"""jaxnum: whole-program numerics & mixed-precision analyzer.

The analyzer suite covers trace safety (ptlint), cost (jaxcost),
policy (jaxplan), locks (lockgraph) and sharding (jaxshard); numerics
was guarded by exactly one shallow convert_element_type check in
jaxpr_audit.py. This module gives precision the same artifact
discipline jaxshard gave sharding: a forward abstract interpreter over
jaxprs that propagates, per value, a numerics state — storage dtype,
the effective ACCUMULATION dtype of every dot/reduce/scan it flows
through, and a worst-case relative-error bound in ulps of the
committed f32 reference — through every equation, and commits the
per-program results to `numplan.json` (tools/jaxnum.py
`--plan write|check`, exit 0/1/2, write refuses unsuppressed findings,
check enforces coverage both directions + exact structural drift).

Rules emitted per program:

  NUM-ACC     sub-f32 accumulation in dot_general / reductions / scan
              carries without preferred_element_type / an explicit f32
              accumulator. The bound grows with the contraction or
              trip length (n * u(acc)), so a 4-layer toy passes while
              a flagship-size contraction fails — the gate scales with
              the model, not with the op count.
  NUM-CAST    lossy round-trips (float down-then-up casts that
              discarded mantissa) and integer narrowing whose operand
              range — inferred from clamp/iota/shape/literal
              provenance — cannot be proven to fit the target.
  NUM-FINITE  exp/log/div/rsqrt reachable with an unclamped operand
              whose interval cannot exclude 0 / overflow — the static
              twin of the runtime core/anomaly.py guard.
  NUM-QUANT   a quantize→dequantize pair (round+clip provenance
              flowing into an int convert and back out) whose derived
              scale cannot meet the registry's declared error budget
              for that program, or that has no declared budget at all.

Error model (deterministic, documented, NO-CANCELLATION: worst-case
relative errors are summed, which is the standard gamma_n bound and
ignores catastrophic cancellation — subtractions of near-equal values
are out of scope for a static bound):

  unit roundoff, in f32 ulps (u32 = 2^-24):
      f64 2^-29   f32 1   f16 2^13   bf16 2^16
  elementwise op        eps_out = sum(eps_in) + u(out)
  dot_general           eps_a + eps_b + n_contract * u(acc)
  reduce_sum            eps_in + (n-1) * u(acc)
  scan carry            eps_T = eps_0 + T * per-trip-delta
  quantize(levels=L)+dequantize: error 0.5/L of the tile fullscale
      (reported both as the program's quant bound and as
      (0.5/L)/2^-24 ulps on the dequantized value)

This module also owns the ONE shared dtype lattice: the
bfloat16-aware `jnp.issubdtype` downcast predicate that used to live
in jaxpr_audit.py (`lossy_float_downcast`) plus its integer-narrowing
extension (`lossy_int_narrowing`) — jaxpr_audit delegates here, so
ml_dtypes types outside numpy's hierarchy are handled in exactly one
place.

The registry reuses jaxcost's program registry (train_step, the five
decode sub-programs, serving prefill/paged/chunk/ragged/chunked-
prefill, the three explicit collectives) and adds
`serving.kv_block_codec` — the int8 KV-block codec
(inference/serving/kv_quant.py) whose derived dequant bound numplan
pins against its declared budget. First consumer: the paged cache's
`kv_cache_dtype="int8"` pool mode ships only because that bound is
committed and runtime-verified (tests/test_kv_quant.py parity gate).
"""
from __future__ import annotations

# ptlint: disable-file=PT-T004  registry builders reuse jaxcost's
# program registry, which constructs jit wrappers for TRACING only
# (one build per analysis run behind lru-cached setup; nothing here
# is a serving/training hot path)

import functools
import json
import math
import os
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "NumState", "NumFinding", "NumReport",
    "analyze_fn", "compute_reports", "registry_names",
    "DEFAULT_PLAN_PATH", "DEFAULT_TOLERANCE", "PLAN_VERSION",
    "write_plan", "check_plan", "diff_plans", "load_plan",
    "unsuppressed_findings",
    "ulps32", "lossy_float_downcast", "lossy_int_narrowing",
]

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
DEFAULT_PLAN_PATH = os.path.join(_REPO, "numplan.json")
PLAN_VERSION = 1
DEFAULT_TOLERANCE = 0.05

#: the committed reference dtype every bound is expressed in ulps of
REF_DTYPE = "float32"
_U32 = 2.0 ** -24          # f32 unit roundoff

#: NUM-ACC fires only at contraction/trip lengths >= this — a toy
#: model's hidden-32 contractions pass, flagship-size ones fail
NUM_ACC_MIN_ELEMS = 64
#: scan bodies are interpreted exactly up to this many trips; longer
#: scans extrapolate the (affine) per-trip error delta linearly
SCAN_EXACT_MAX = 256
#: while carries run to fixpoint; a carry still growing after this
#: many probes is charged this trip count and flagged
WHILE_FIXPOINT_MAX = 32
#: f32 exp overflow threshold: exp(x) is finite iff x < ln(f32 max)
EXP_OVERFLOW = 88.72
#: intervals are derived from captured consts only up to this many
#: elements (bigger consts would make analysis O(model size))
_CONST_INTERVAL_MAX = 65536

_INF = float("inf")


# ------------------------------------------------------- dtype lattice
#
# The one shared dtype table. jnp.issubdtype, not np.issubdtype:
# bfloat16 (ml_dtypes) sits outside numpy's type lattice and is
# exactly the sub-32-bit storage these checks exist to catch.

#: mantissa bits (excluding the implicit leading 1) per float dtype
_MANTISSA = {
    "float64": 52, "float32": 23, "float16": 10, "bfloat16": 7,
    "float8_e4m3fn": 3, "float8_e5m2": 2, "float8_e4m3": 3,
    "float8_e5m2fnuz": 2, "float8_e4m3fnuz": 3,
}


def _dt(dtype_like):
    """np.dtype where possible; opaque dtypes (PRNG keys, extended
    dtypes) pass through untouched and act as non-numeric below."""
    try:
        return np.dtype(dtype_like)
    except TypeError:
        return dtype_like


def _dt_name(dtype_like) -> str:
    d = _dt(dtype_like)
    return d.name if isinstance(d, np.dtype) else str(d)


def is_float(dt) -> bool:
    d = _dt(dt)
    return isinstance(d, np.dtype) and bool(
        jnp.issubdtype(d, jnp.floating))


def is_int(dt) -> bool:
    d = _dt(dt)
    return isinstance(d, np.dtype) and d.kind != "b" and bool(
        jnp.issubdtype(d, jnp.integer))


def unit_roundoff(dt) -> float:
    """Absolute unit roundoff 2^-(mantissa+1); 0 for non-floats."""
    d = _dt(dt)
    if not is_float(d):
        return 0.0
    m = _MANTISSA.get(d.name)
    if m is None:                      # unknown float: use finfo
        m = int(jnp.finfo(d).nmant)
    return 2.0 ** -(m + 1)


def ulps32(dt) -> float:
    """Unit roundoff of `dt` expressed in f32 ulps: u(dt)/u(f32).
    f64 -> 2^-29, f32 -> 1, f16 -> 2^13, bf16 -> 2^16; 0 for ints."""
    return unit_roundoff(dt) / _U32


def lossy_float_downcast(src, dst) -> bool:
    """The historical jaxpr_audit downcast predicate: a float convert
    that drops BELOW 32 bits. The package enables jax_enable_x64, so
    f64 -> f32 converts are everywhere and deliberate — only sub-32-bit
    precision drops are lossy here."""
    src, dst = _dt(src), _dt(dst)
    return (is_float(src) and is_float(dst)
            and src.itemsize >= 4 and dst.itemsize < 4)


def lossy_int_narrowing(src, dst) -> bool:
    """Integer convert to a strictly narrower integer (int64 -> int32
    table/length casts were invisible to the old downcast check)."""
    src, dst = _dt(src), _dt(dst)
    return is_int(src) and is_int(dst) and dst.itemsize < src.itemsize


def int_bounds(dt) -> Tuple[float, float]:
    info = jnp.iinfo(np.dtype(dt))
    return float(info.min), float(info.max)


# ------------------------------------------------------- value state
@dataclass(frozen=True)
class NumState:
    """Per-value numerics state the interpreter propagates.

    eps is the worst-case relative error in f32 ulps under the
    no-cancellation model; [lo, hi] the value interval (clamp/iota/
    literal/const provenance; unbounded when unknown); `rounded` marks
    integral-valued floats (round/floor/ceil outputs — quantization
    codes before their int convert); `was_downcast` marks float values
    that passed through a sub-32-bit storage dtype (NUM-CAST
    round-trip provenance); `qlevels` > 0 marks a quantization code
    (and its dequantized descendants) with that many positive levels.
    """
    dtype: object
    eps: float = 0.0
    lo: float = -_INF
    hi: float = _INF
    rounded: bool = False
    was_downcast: bool = False
    qlevels: int = 0

    def with_(self, **kw) -> "NumState":
        d = {"dtype": self.dtype, "eps": self.eps, "lo": self.lo,
             "hi": self.hi, "rounded": self.rounded,
             "was_downcast": self.was_downcast,
             "qlevels": self.qlevels}
        d.update(kw)
        return NumState(**d)

    @property
    def bounded(self) -> bool:
        return self.lo > -_INF and self.hi < _INF


def _unknown(dtype) -> NumState:
    return NumState(dtype=_dt(dtype))


# ------------------------------------------------------------ findings
@dataclass
class NumFinding:
    """One triaged numerics item; `key` is the suppression key
    committed in numplan.json (grouped rule:primitive:detail, same
    aggregation discipline as jaxshard's implicit-collective keys)."""
    key: str
    rule: str            # NUM-ACC | NUM-CAST | NUM-FINITE | NUM-QUANT
    message: str
    bound_ulps: float = 0.0
    count: int = 1
    example: str = ""
    suppressed: Optional[str] = None

    def to_dict(self) -> dict:
        return {"key": self.key, "rule": self.rule,
                "message": self.message,
                "bound_ulps": _round6(self.bound_ulps),
                "count": self.count, "example": self.example,
                "suppressed": self.suppressed}

    def format(self) -> str:
        tag = "suppressed" if self.suppressed else "UNSUPPRESSED"
        return (f"  [{tag}] {self.rule} {self.key}: {self.message}"
                + (f"  # {self.suppressed}" if self.suppressed else ""))


@dataclass
class NumReport:
    """Per-program numerics report, the unit numplan.json commits."""
    name: str
    ref_dtype: str = REF_DTYPE
    out_dtypes: List[str] = field(default_factory=list)
    acc_dtypes: List[str] = field(default_factory=list)
    max_error_ulps: float = 0.0
    quant: Optional[dict] = None
    findings: List[NumFinding] = field(default_factory=list)
    notes: List[str] = field(default_factory=list)

    def unsuppressed(self) -> List[NumFinding]:
        return [f for f in self.findings if not f.suppressed]

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "ref_dtype": self.ref_dtype,
            "out_dtypes": list(self.out_dtypes),
            "acc_dtypes": list(self.acc_dtypes),
            "max_error_ulps": _round6(self.max_error_ulps),
            "quant": dict(self.quant) if self.quant else None,
            "findings": {f.key: f.to_dict() for f in self.findings},
        }

    def format(self) -> str:
        lines = [f"{self.name}: max_error={self.max_error_ulps:g} "
                 f"ulps({self.ref_dtype}) "
                 f"out={','.join(self.out_dtypes)} "
                 f"acc={','.join(self.acc_dtypes) or '-'}"]
        if self.quant:
            lines.append(
                f"  quant: levels={self.quant['levels']} derived="
                f"{self.quant['derived_rel_err']:g} budget="
                f"{self.quant['budget_rel_err']:g}")
        for f in self.findings:
            lines.append(f.format())
        for n in self.notes[:6]:
            lines.append(f"  note: {n}")
        return "\n".join(lines)


def _round6(x: float) -> float:
    if not math.isfinite(x):
        return 1e30            # committed plans must stay strict JSON
    return float(f"{float(x):.6g}")


#: equations that run a single sub-jaxpr transparently
_TRANSPARENT_CALLS = frozenset({
    "custom_jvp_call", "custom_vjp_call", "custom_vjp_call_jaxpr",
    "remat", "checkpoint", "closed_call", "core_call", "custom_lin",
})

#: pure data-movement primitives: state passes through unchanged
_SHAPE_OPS = frozenset({
    "broadcast_in_dim", "reshape", "transpose", "squeeze",
    "expand_dims", "rev", "slice", "dynamic_slice", "copy",
    "device_put", "stop_gradient", "gather", "real", "bitcast_convert_type",
    "sharding_constraint", "optimization_barrier",
})

#: exact elementwise selections/sign ops: no new rounding error
_EXACT_ELEMENTWISE = frozenset({
    "neg", "abs", "sign", "max", "min", "and", "or", "xor", "not",
    "shift_left", "shift_right_logical", "shift_right_arithmetic",
    "is_finite", "select_n",
})

#: comparison ops: boolean outputs, exact
_CMP = frozenset({"eq", "ne", "lt", "le", "gt", "ge"})


# ----------------------------------------------------- interval helpers
def _ivl_add(a: NumState, b: NumState) -> Tuple[float, float]:
    return a.lo + b.lo, a.hi + b.hi


def _ivl_sub(a: NumState, b: NumState) -> Tuple[float, float]:
    return a.lo - b.hi, a.hi - b.lo


def _ivl_mul(a: NumState, b: NumState) -> Tuple[float, float]:
    cands = []
    for x in (a.lo, a.hi):
        for y in (b.lo, b.hi):
            p = x * y
            if math.isnan(p):      # 0 * inf
                p = 0.0
            cands.append(p)
    return min(cands), max(cands)


def _hull(states: Sequence[NumState]) -> Tuple[float, float]:
    return min(s.lo for s in states), max(s.hi for s in states)


def _contains_zero(s: NumState) -> bool:
    return s.lo <= 0.0 <= s.hi


# ------------------------------------------------------- interpreter
class _Interp:
    """Forward abstract interpretation of numerics state over one
    program's jaxpr (same handler-dispatch skeleton as jaxshard)."""

    def __init__(self, name: str):
        self.name = name
        self.states: Dict[object, NumState] = {}
        self.findings: Dict[str, NumFinding] = {}
        self.acc_dtypes: set = set()
        self.notes: List[str] = []
        self.quant_events: List[dict] = []

    # -------------------------------------------------------- plumbing
    def read(self, atom) -> NumState:
        if _lit(atom):
            return _literal_state(atom)
        got = self.states.get(atom)
        if got is None:
            got = _unknown(atom.aval.dtype)
        return got

    def write(self, var, st: NumState) -> None:
        self.states[var] = st

    def note(self, msg: str) -> None:
        if msg not in self.notes:
            self.notes.append(msg)

    def finding(self, rule: str, key: str, message: str,
                bound: float = 0.0, path: str = "",
                count: int = 1) -> None:
        got = self.findings.get(key)
        if got is None:
            self.findings[key] = NumFinding(
                key=key, rule=rule, message=message, bound_ulps=bound,
                count=count, example=path)
        else:
            got.count += count
            got.bound_ulps = max(got.bound_ulps, bound)

    def _out_dtype(self, eqn):
        return _dt(eqn.outvars[0].aval.dtype)

    # ------------------------------------------------------------ run
    def run(self, jaxpr_like, in_states: Sequence[NumState],
            path: str, mult: int = 1) -> List[NumState]:
        raw = getattr(jaxpr_like, "jaxpr", jaxpr_like)
        consts = getattr(jaxpr_like, "consts", None)
        for i, v in enumerate(getattr(raw, "constvars", ())):
            cval = consts[i] if consts is not None \
                and i < len(consts) else None
            self.write(v, _const_state(v, cval))
        for v, s in zip(raw.invars, in_states):
            self.write(v, s)
        for i, eqn in enumerate(raw.eqns):
            self.eqn(eqn, f"{path}:{i}", mult)
        return [self.read(v) for v in raw.outvars]

    # ------------------------------------------------------- dispatch
    def eqn(self, eqn, path: str, mult: int) -> None:
        name = eqn.primitive.name
        handler = getattr(self, f"_h_{name}", None)
        if handler is not None:
            handler(eqn, path, mult)
            return
        if name in _TRANSPARENT_CALLS:
            self._h_transparent(eqn, path, mult)
            return
        if name in _SHAPE_OPS:
            self._h_passthrough(eqn, path, mult)
            return
        if name in _CMP or name.startswith("random_") \
                or name in ("iota",):
            # handled below / exact producers
            if name == "iota":
                self._h_iota(eqn, path, mult)
            else:
                self._write_exact(eqn)
            return
        if name in _EXACT_ELEMENTWISE:
            self._h_exact_elementwise(eqn, path, mult)
            return
        if name.startswith("reduce_") or name.startswith("arg"):
            self._h_reduce(eqn, path, mult)
            return
        if name.startswith("cum"):
            self._h_cum(eqn, path, mult)
            return
        self._h_default(eqn, path, mult)

    # ------------------------------------------------ generic handlers
    def _h_default(self, eqn, path: str, mult: int) -> None:
        """Unknown/garden-variety elementwise op: worst-case operand
        errors add, plus one rounding of the output; interval and
        provenance are forgotten."""
        ins = [self.read(v) for v in eqn.invars]
        for ov in eqn.outvars:
            dt = _dt(ov.aval.dtype)
            eps = sum(s.eps for s in ins) + ulps32(dt)
            self.write(ov, NumState(
                dtype=dt, eps=eps if is_float(dt) else 0.0,
                was_downcast=any(s.was_downcast for s in ins)))

    def _h_passthrough(self, eqn, path: str, mult: int) -> None:
        src = self.read(eqn.invars[0])
        for ov in eqn.outvars:
            self.write(ov, src.with_(dtype=_dt(ov.aval.dtype)))

    def _write_exact(self, eqn) -> None:
        for ov in eqn.outvars:
            dt = _dt(ov.aval.dtype)
            lo, hi = (0.0, 1.0) if getattr(dt, "kind", "") == "b" \
                else (-_INF, _INF)
            self.write(ov, NumState(dtype=dt, lo=lo, hi=hi))

    def _h_exact_elementwise(self, eqn, path: str, mult: int) -> None:
        name = eqn.primitive.name
        ins = [self.read(v) for v in eqn.invars]
        dt = self._out_dtype(eqn)
        if name == "select_n":
            cases = ins[1:]
            lo, hi = _hull(cases)
            st = NumState(
                dtype=dt, eps=max(s.eps for s in cases), lo=lo, hi=hi,
                rounded=all(s.rounded for s in cases),
                was_downcast=any(s.was_downcast for s in cases),
                qlevels=min((s.qlevels for s in cases
                             if s.qlevels), default=0)
                if all(s.qlevels for s in cases) else 0)
        elif name == "neg":
            s = ins[0]
            st = s.with_(lo=-s.hi, hi=-s.lo, dtype=dt)
        elif name == "abs":
            s = ins[0]
            lo = 0.0 if _contains_zero(s) else min(abs(s.lo), abs(s.hi))
            st = s.with_(lo=lo, hi=max(abs(s.lo), abs(s.hi)), dtype=dt)
        elif name == "max":
            a, b = ins[0], ins[1]
            st = NumState(dtype=dt, eps=max(a.eps, b.eps),
                          lo=max(a.lo, b.lo), hi=max(a.hi, b.hi),
                          rounded=a.rounded and b.rounded,
                          was_downcast=a.was_downcast or b.was_downcast)
        elif name == "min":
            a, b = ins[0], ins[1]
            st = NumState(dtype=dt, eps=max(a.eps, b.eps),
                          lo=min(a.lo, b.lo), hi=min(a.hi, b.hi),
                          rounded=a.rounded and b.rounded,
                          was_downcast=a.was_downcast or b.was_downcast)
        else:
            eps = max((s.eps for s in ins), default=0.0)
            st = NumState(dtype=dt, eps=eps if is_float(dt) else 0.0)
        for ov in eqn.outvars:
            self.write(ov, st)

    # --------------------------------------------------- arithmetic
    def _binop(self, eqn, ivl_fn) -> NumState:
        a, b = self.read(eqn.invars[0]), self.read(eqn.invars[1])
        dt = self._out_dtype(eqn)
        lo, hi = ivl_fn(a, b)
        return NumState(
            dtype=dt,
            eps=(a.eps + b.eps + ulps32(dt)) if is_float(dt) else 0.0,
            lo=lo, hi=hi,
            was_downcast=a.was_downcast or b.was_downcast)

    def _h_add(self, eqn, path, mult):
        st = self._binop(eqn, _ivl_add)
        a, b = self.read(eqn.invars[0]), self.read(eqn.invars[1])
        self.write(eqn.outvars[0],
                   st.with_(rounded=a.rounded and b.rounded))

    def _h_sub(self, eqn, path, mult):
        st = self._binop(eqn, _ivl_sub)
        a, b = self.read(eqn.invars[0]), self.read(eqn.invars[1])
        self.write(eqn.outvars[0],
                   st.with_(rounded=a.rounded and b.rounded))

    def _h_mul(self, eqn, path, mult):
        st = self._binop(eqn, _ivl_mul)
        a, b = self.read(eqn.invars[0]), self.read(eqn.invars[1])
        # scale * quantization-code keeps the quant provenance: this
        # is the dequant multiply
        q = a.qlevels or b.qlevels
        self.write(eqn.outvars[0], st.with_(qlevels=q))

    def _h_div(self, eqn, path, mult):
        a, b = self.read(eqn.invars[0]), self.read(eqn.invars[1])
        dt = self._out_dtype(eqn)
        if is_float(dt) and _contains_zero(b):
            self.finding(
                "NUM-FINITE", f"finite:div:{self.name_of(eqn)}",
                "division whose denominator interval cannot exclude 0 "
                "(unclamped operand; static twin of the runtime "
                "core/anomaly.py guard)", path=path, count=mult)
        if b.lo > 0 or b.hi < 0:
            cands = [a.lo / b.lo, a.lo / b.hi, a.hi / b.lo, a.hi / b.hi]
            cands = [0.0 if math.isnan(c) else c for c in cands]
            lo, hi = min(cands), max(cands)
        else:
            lo, hi = -_INF, _INF
        self.write(eqn.outvars[0], NumState(
            dtype=dt,
            eps=(a.eps + b.eps + ulps32(dt)) if is_float(dt) else 0.0,
            lo=lo, hi=hi,
            was_downcast=a.was_downcast or b.was_downcast))

    def name_of(self, eqn) -> str:
        return eqn.primitive.name

    def _h_exp(self, eqn, path, mult):
        s = self.read(eqn.invars[0])
        dt = self._out_dtype(eqn)
        if s.hi >= EXP_OVERFLOW:
            self.finding(
                "NUM-FINITE", "finite:exp",
                f"exp of an operand whose interval reaches "
                f"{EXP_OVERFLOW} (f32 overflow): range analysis "
                f"cannot exclude inf without an upstream clamp",
                path=path, count=mult)
        lo = 0.0 if s.lo == -_INF else math.exp(min(s.lo, 700.0))
        hi = _INF if s.hi == _INF else math.exp(min(s.hi, 700.0))
        self.write(eqn.outvars[0], NumState(
            dtype=dt, eps=s.eps + ulps32(dt), lo=lo, hi=hi,
            was_downcast=s.was_downcast))

    def _h_log(self, eqn, path, mult):
        self._log_like(eqn, path, mult, "log", floor=0.0)

    def _h_log1p(self, eqn, path, mult):
        self._log_like(eqn, path, mult, "log1p", floor=-1.0)

    def _h_rsqrt(self, eqn, path, mult):
        self._log_like(eqn, path, mult, "rsqrt", floor=0.0)

    def _log_like(self, eqn, path, mult, what, floor):
        s = self.read(eqn.invars[0])
        dt = self._out_dtype(eqn)
        if s.lo <= floor:
            self.finding(
                "NUM-FINITE", f"finite:{what}",
                f"{what} of an operand whose interval cannot exclude "
                f"{floor} (unclamped operand; static twin of the "
                f"runtime core/anomaly.py guard)",
                path=path, count=mult)
        self.write(eqn.outvars[0], NumState(
            dtype=dt, eps=s.eps + ulps32(dt),
            was_downcast=s.was_downcast))

    def _h_sqrt(self, eqn, path, mult):
        s = self.read(eqn.invars[0])
        dt = self._out_dtype(eqn)
        lo = math.sqrt(max(s.lo, 0.0)) if s.lo > -_INF else 0.0
        hi = math.sqrt(s.hi) if 0 <= s.hi < _INF else _INF
        self.write(eqn.outvars[0], NumState(
            dtype=dt, eps=s.eps + ulps32(dt), lo=lo, hi=hi,
            was_downcast=s.was_downcast))

    def _h_tanh(self, eqn, path, mult):
        self._bounded_unary(eqn, -1.0, 1.0)

    def _h_logistic(self, eqn, path, mult):
        self._bounded_unary(eqn, 0.0, 1.0)

    def _h_erf(self, eqn, path, mult):
        self._bounded_unary(eqn, -1.0, 1.0)

    def _bounded_unary(self, eqn, lo, hi):
        s = self.read(eqn.invars[0])
        dt = self._out_dtype(eqn)
        self.write(eqn.outvars[0], NumState(
            dtype=dt, eps=s.eps + ulps32(dt), lo=lo, hi=hi,
            was_downcast=s.was_downcast))

    def _h_integer_pow(self, eqn, path, mult):
        self._pow_like(eqn, int(eqn.params.get("y", 2)))

    def _pow_like(self, eqn, y):
        s = self.read(eqn.invars[0])
        dt = self._out_dtype(eqn)
        lo, hi = -_INF, _INF
        if s.bounded:
            cands = [s.lo ** y, s.hi ** y]
            lo, hi = min(cands), max(cands)
            if y % 2 == 0:
                lo = 0.0 if _contains_zero(s) else min(cands)
        self.write(eqn.outvars[0], NumState(
            dtype=dt, eps=s.eps * max(abs(y), 1) + ulps32(dt),
            lo=lo, hi=hi, was_downcast=s.was_downcast))

    def _h_square(self, eqn, path, mult):
        # square_p carries no "y" param; NEVER write one into
        # eqn.params — jaxprs are shared via jax's tracing caches, and
        # square's lowering rejects the stray kwarg at compile time
        self._pow_like(eqn, 2)

    # ------------------------------------------------ rounding / clamp
    def _h_round(self, eqn, path, mult):
        s = self.read(eqn.invars[0])
        self.write(eqn.outvars[0], s.with_(
            dtype=self._out_dtype(eqn), rounded=True))

    _h_floor = _h_round
    _h_ceil = _h_round

    def _h_clamp(self, eqn, path, mult):
        lo_s = self.read(eqn.invars[0])
        x = self.read(eqn.invars[1])
        hi_s = self.read(eqn.invars[2])
        dt = self._out_dtype(eqn)
        self.write(eqn.outvars[0], x.with_(
            dtype=dt, lo=max(x.lo, lo_s.lo), hi=min(x.hi, hi_s.hi)))

    def _h_iota(self, eqn, path, mult):
        ov = eqn.outvars[0]
        dt = _dt(ov.aval.dtype)
        dim = eqn.params.get("dimension", 0)
        n = ov.aval.shape[dim] if ov.aval.shape else 1
        self.write(ov, NumState(dtype=dt, lo=0.0, hi=float(n - 1),
                                rounded=True))

    # -------------------------------------------------------- converts
    def _h_convert_element_type(self, eqn, path, mult):
        s = self.read(eqn.invars[0])
        src = _dt(s.dtype)
        dst = _dt(eqn.params.get("new_dtype",
                                      eqn.outvars[0].aval.dtype))
        st = s.with_(dtype=dst)
        if is_float(src) and is_float(dst):
            if ulps32(dst) > ulps32(src):          # losing mantissa
                st = st.with_(eps=s.eps + ulps32(dst),
                              was_downcast=st.was_downcast
                              or dst.itemsize < 4 <= src.itemsize)
            elif s.was_downcast and dst.itemsize >= 4:
                # down-then-up round trip: the mantissa is already
                # gone; the upcast only hides it
                self.finding(
                    "NUM-CAST", f"cast:roundtrip:{src.name}->{dst.name}",
                    f"lossy float round-trip: value was downcast below "
                    f"32 bits and is converted back up to {dst.name} "
                    f"— the discarded mantissa does not come back",
                    bound=s.eps, path=path, count=mult)
                st = st.with_(was_downcast=False)
        elif is_int(src) and is_int(dst):
            if lossy_int_narrowing(src, dst):
                lo, hi = int_bounds(dst)
                if not (s.lo >= lo and s.hi <= hi):
                    self.finding(
                        "NUM-CAST", f"cast:int:{src.name}->{dst.name}",
                        f"integer narrowing {src.name} -> {dst.name} "
                        f"whose operand range "
                        f"[{_fmt_b(s.lo)}, {_fmt_b(s.hi)}] cannot be "
                        f"proven to fit", path=path, count=mult)
        elif is_float(src) and is_int(dst):
            if s.rounded and s.bounded:
                levels = int(max(abs(s.lo), abs(s.hi)))
                ilo, ihi = int_bounds(dst)
                if levels > 0 and s.lo >= ilo and s.hi <= ihi:
                    # a quantize event: round+clip provenance entering
                    # integer storage
                    self.quant_events.append(
                        {"levels": levels, "path": path,
                         "dtype": dst.name, "dequantized": False})
                    st = st.with_(qlevels=levels)
        elif is_int(src) and is_float(dst):
            if s.qlevels:
                for ev in self.quant_events:
                    if ev["levels"] == s.qlevels:
                        ev["dequantized"] = True
                # the dequantized value's error is the quant bound,
                # relative to the tile fullscale, in f32 ulps
                st = st.with_(eps=(0.5 / s.qlevels) / _U32)
            elif s.bounded:
                exact = 2.0 ** (_MANTISSA.get(dst.name, 23) + 1)
                if max(abs(s.lo), abs(s.hi)) > exact:
                    st = st.with_(eps=s.eps + ulps32(dst))
        for ov in eqn.outvars:
            self.write(ov, st)

    # ---------------------------------------------------- accumulation
    def _h_dot_general(self, eqn, path, mult):
        a, b = self.read(eqn.invars[0]), self.read(eqn.invars[1])
        lhs = eqn.invars[0].aval
        (lc, _rc), _ = eqn.params["dimension_numbers"]
        n = 1
        for d in lc:
            n *= int(lhs.shape[d])
        out_dt = self._out_dtype(eqn)
        acc = eqn.params.get("preferred_element_type") or out_dt
        acc = _dt(acc)
        self.acc_dtypes.add(acc.name)
        u_acc = ulps32(acc)
        if u_acc > 1.0 and n >= NUM_ACC_MIN_ELEMS:
            self.finding(
                "NUM-ACC", f"acc:dot_general:{acc.name}",
                f"dot_general accumulates {n} products in {acc.name} "
                f"(error bound {n * u_acc:g} ulps grows with the "
                f"contraction); set preferred_element_type=float32 "
                f"or accumulate explicitly in f32",
                bound=n * u_acc, path=path, count=mult)
        eps = a.eps + b.eps + n * u_acc + ulps32(out_dt)
        self.write(eqn.outvars[0], NumState(
            dtype=out_dt, eps=eps if is_float(out_dt) else 0.0,
            was_downcast=a.was_downcast or b.was_downcast))

    def _h_reduce(self, eqn, path, mult):
        name = eqn.primitive.name
        s = self.read(eqn.invars[0])
        ov = eqn.outvars[0]
        dt = _dt(ov.aval.dtype)
        axes = eqn.params.get("axes", ())
        n = 1
        ishape = getattr(eqn.invars[0].aval, "shape", ())
        for d in axes:
            n *= int(ishape[d])
        if name in ("reduce_max", "reduce_min"):
            self.write(ov, s.with_(dtype=dt))
            return
        if name in ("reduce_and", "reduce_or", "reduce_xor"):
            self.write(ov, NumState(dtype=dt, lo=0.0, hi=1.0))
            return
        if name.startswith("arg"):
            hi = float(max(n - 1, 0))
            self.write(ov, NumState(dtype=dt, lo=0.0, hi=hi,
                                    rounded=True))
            return
        if name == "reduce_sum":
            self.acc_dtypes.add(dt.name)
            u_acc = ulps32(dt)
            if u_acc > 1.0 and n >= NUM_ACC_MIN_ELEMS:
                self.finding(
                    "NUM-ACC", f"acc:reduce_sum:{dt.name}",
                    f"reduce_sum over {n} elements accumulates in "
                    f"{dt.name} (error bound {(n - 1) * u_acc:g} "
                    f"ulps); cast to f32 before the reduction",
                    bound=(n - 1) * u_acc, path=path, count=mult)
            lo = min(n * s.lo, s.lo)
            hi = max(n * s.hi, s.hi)
            self.write(ov, NumState(
                dtype=dt,
                eps=(s.eps + (n - 1) * u_acc) if is_float(dt) else 0.0,
                lo=lo, hi=hi, was_downcast=s.was_downcast))
            return
        if name == "reduce_prod":
            self.acc_dtypes.add(dt.name)
            self.write(ov, NumState(
                dtype=dt,
                eps=(n * s.eps + (n - 1) * ulps32(dt))
                if is_float(dt) else 0.0,
                was_downcast=s.was_downcast))
            return
        self._h_default(eqn, path, mult)

    def _h_cum(self, eqn, path, mult):
        # cumsum/cumprod/cummax...: worst row accumulates like the
        # full reduction
        name = eqn.primitive.name
        s = self.read(eqn.invars[0])
        ov = eqn.outvars[0]
        dt = _dt(ov.aval.dtype)
        axis = eqn.params.get("axis", 0)
        n = int(getattr(ov.aval, "shape", (1,))[axis]) \
            if getattr(ov.aval, "shape", ()) else 1
        if name in ("cummax", "cummin"):
            self.write(ov, s.with_(dtype=dt))
            return
        u_acc = ulps32(dt)
        if name == "cumsum":
            self.acc_dtypes.add(dt.name)
            if u_acc > 1.0 and n >= NUM_ACC_MIN_ELEMS:
                self.finding(
                    "NUM-ACC", f"acc:cumsum:{dt.name}",
                    f"cumsum over {n} elements accumulates in "
                    f"{dt.name}", bound=(n - 1) * u_acc, path=path,
                    count=mult)
        self.write(ov, NumState(
            dtype=dt,
            eps=(s.eps + (n - 1) * u_acc) if is_float(dt) else 0.0,
            was_downcast=s.was_downcast))

    # -------------------------------------------------- control flow
    def _h_pjit(self, eqn, path, mult):
        inner = eqn.params["jaxpr"]
        ins = [self.read(v) for v in eqn.invars]
        outs = self.run(inner, ins, f"{path}/pjit", mult)
        for ov, st in zip(eqn.outvars, outs):
            self.write(ov, st)

    def _h_shard_map(self, eqn, path, mult):
        inner = eqn.params["jaxpr"]
        ins = [self.read(v) for v in eqn.invars]
        outs = self.run(inner, ins, f"{path}/shard_map", mult)
        for ov, st in zip(eqn.outvars, outs):
            self.write(ov, st)

    def _h_transparent(self, eqn, path, mult):
        inner = None
        for key in ("call_jaxpr", "fun_jaxpr", "jaxpr"):
            cand = eqn.params.get(key)
            if cand is not None and (hasattr(cand, "jaxpr")
                                     or hasattr(cand, "eqns")):
                inner = cand
                break
        if inner is None:
            for val in eqn.params.values():
                if hasattr(val, "jaxpr") or hasattr(val, "eqns"):
                    inner = val
                    break
        if inner is None:
            self._h_default(eqn, path, mult)
            return
        ins = [self.read(v) for v in eqn.invars]
        raw = getattr(inner, "jaxpr", inner)
        ins = ins[:len(raw.invars)] if len(ins) >= len(raw.invars) \
            else ins + [_unknown(v.aval.dtype)
                        for v in raw.invars[len(ins):]]
        outs = self.run(inner, ins,
                        f"{path}/{eqn.primitive.name}", mult)
        for ov, st in zip(eqn.outvars, outs):
            self.write(ov, st)

    def _h_scan(self, eqn, path, mult):
        p = eqn.params
        T = int(p.get("length", 1))
        n_consts = int(p.get("num_consts", 0))
        n_carry = int(p.get("num_carry", 0))
        inner = p["jaxpr"]
        ins = [self.read(v) for v in eqn.invars]
        consts = ins[:n_consts]
        carry = list(ins[n_consts:n_consts + n_carry])
        xs = [s.with_() for s in ins[n_consts + n_carry:]]
        carry0_eps = [s.eps for s in carry]
        ys: List[NumState] = []
        trips = min(T, SCAN_EXACT_MAX)
        prev_eps = carry0_eps
        for _t in range(trips):
            outs = self.run(inner, consts + carry + xs,
                            f"{path}/scan", mult)
            carry = list(outs[:n_carry])
            ys = outs[n_carry:]
            prev2, prev_eps = prev_eps, [s.eps for s in carry]
            if prev_eps == prev2:
                break                       # carry error fixpoint
        if T > trips:
            # extrapolate the affine per-trip delta for the tail
            deltas = [cur - prev
                      for cur, prev in zip(prev_eps, prev2)]
            carry = [s.with_(eps=s.eps + max(d, 0.0) * (T - trips))
                     for s, d in zip(carry, deltas)]
            self.note(f"scan at {path}: {T} trips, interpreted "
                      f"{trips} exactly then extrapolated linearly")
        for st, e0 in zip(carry, carry0_eps):
            dt = _dt(st.dtype)
            u = ulps32(dt)
            if is_float(dt) and u > 1.0 and st.eps > e0 \
                    and T >= NUM_ACC_MIN_ELEMS:
                self.finding(
                    "NUM-ACC", f"acc:scan:{dt.name}",
                    f"scan carry accumulates in {dt.name} over {T} "
                    f"trips (error bound grows {st.eps - e0:g} ulps "
                    f"across the loop); carry an f32 accumulator",
                    bound=st.eps, path=path, count=mult)
        for ov, st in zip(eqn.outvars, carry + list(ys)):
            self.write(ov, st)

    def _h_while(self, eqn, path, mult):
        p = eqn.params
        cn = int(p.get("cond_nconsts", 0))
        bn = int(p.get("body_nconsts", 0))
        body = p["body_jaxpr"]
        ins = [self.read(v) for v in eqn.invars]
        bconsts = ins[cn:cn + bn]
        carry = list(ins[cn + bn:])
        prev_eps = [s.eps for s in carry]
        converged = False
        for _t in range(WHILE_FIXPOINT_MAX):
            outs = self.run(body, bconsts + carry,
                            f"{path}/while", mult)
            carry = [st.with_(lo=min(st.lo, old.lo),
                              hi=max(st.hi, old.hi))
                     for st, old in zip(outs, carry)]
            cur = [s.eps for s in carry]
            if cur == prev_eps:
                converged = True
                break
            prev_eps = cur
        if not converged:
            self.note(f"while at {path}: carry error still growing "
                      f"after {WHILE_FIXPOINT_MAX} probes; bound is "
                      f"a floor, not a ceiling")
            for st in carry:
                dt = _dt(st.dtype)
                if is_float(dt) and ulps32(dt) > 1.0:
                    self.finding(
                        "NUM-ACC", f"acc:while:{dt.name}",
                        f"while carry accumulates in {dt.name} with "
                        f"an unbounded trip count",
                        bound=st.eps, path=path, count=mult)
        for ov, st in zip(eqn.outvars, carry):
            self.write(ov, st)

    def _h_cond(self, eqn, path, mult):
        branches = eqn.params["branches"]
        ins = [self.read(v) for v in eqn.invars[1:]]
        per_branch = [self.run(br, ins, f"{path}/cond[{i}]", mult)
                      for i, br in enumerate(branches)]
        for j, ov in enumerate(eqn.outvars):
            cases = [outs[j] for outs in per_branch]
            lo, hi = _hull(cases)
            self.write(ov, NumState(
                dtype=_dt(ov.aval.dtype),
                eps=max(s.eps for s in cases), lo=lo, hi=hi,
                rounded=all(s.rounded for s in cases),
                was_downcast=any(s.was_downcast for s in cases)))

    # --------------------------------------------- structured updates
    def _h_concatenate(self, eqn, path, mult):
        ins = [self.read(v) for v in eqn.invars]
        dt = self._out_dtype(eqn)
        lo, hi = _hull(ins)
        self.write(eqn.outvars[0], NumState(
            dtype=dt, eps=max(s.eps for s in ins), lo=lo, hi=hi,
            rounded=all(s.rounded for s in ins),
            was_downcast=any(s.was_downcast for s in ins)))

    def _h_pad(self, eqn, path, mult):
        x, pad = self.read(eqn.invars[0]), self.read(eqn.invars[1])
        dt = self._out_dtype(eqn)
        lo, hi = _hull([x, pad])
        self.write(eqn.outvars[0], x.with_(dtype=dt, lo=lo, hi=hi))

    def _h_dynamic_update_slice(self, eqn, path, mult):
        x, upd = self.read(eqn.invars[0]), self.read(eqn.invars[1])
        dt = self._out_dtype(eqn)
        lo, hi = _hull([x, upd])
        self.write(eqn.outvars[0], NumState(
            dtype=dt, eps=max(x.eps, upd.eps), lo=lo, hi=hi,
            rounded=x.rounded and upd.rounded,
            was_downcast=x.was_downcast or upd.was_downcast,
            qlevels=x.qlevels if x.qlevels == upd.qlevels else 0))

    def _h_scatter(self, eqn, path, mult):
        self._h_dynamic_update_slice_like(eqn)

    _h_scatter_add = _h_scatter

    def _h_dynamic_update_slice_like(self, eqn):
        x, upd = self.read(eqn.invars[0]), self.read(eqn.invars[-1])
        dt = self._out_dtype(eqn)
        self.write(eqn.outvars[0], NumState(
            dtype=dt, eps=max(x.eps, upd.eps) + (
                ulps32(dt) if eqn.primitive.name.endswith("add")
                else 0.0),
            was_downcast=x.was_downcast or upd.was_downcast))


# ------------------------------------------------------------- helpers
def _lit(atom) -> bool:
    return type(atom).__name__ == "Literal" or hasattr(atom, "val")


def _literal_state(atom) -> NumState:
    dt = _dt(atom.aval.dtype)
    try:
        v = float(np.asarray(atom.val).reshape(()))
    except Exception:
        return _unknown(dt)
    rounded = math.isfinite(v) and float(v).is_integer()
    return NumState(dtype=dt, lo=v, hi=v, rounded=rounded)


def _const_state(var, cval) -> NumState:
    dt = _dt(var.aval.dtype)
    if cval is None:
        return _unknown(dt)
    try:
        arr = np.asarray(cval)
        if arr.size == 0 or arr.size > _CONST_INTERVAL_MAX \
                or arr.dtype.kind not in "ifu" \
                or arr.dtype.name == "bfloat16":
            return _unknown(dt)
        lo, hi = float(arr.min()), float(arr.max())
        if not (math.isfinite(lo) and math.isfinite(hi)):
            return _unknown(dt)
        rounded = bool(np.all(arr == np.round(
            arr.astype(np.float64)))) if arr.dtype.kind == "f" else True
        return NumState(dtype=dt, lo=lo, hi=hi, rounded=rounded)
    except Exception:
        return _unknown(dt)


def _fmt_b(x: float) -> str:
    return "inf" if x == _INF else "-inf" if x == -_INF else f"{x:g}"


# ------------------------------------------------------------- analyze
def analyze_fn(fn, *args, name: str,
               static_argnums: Sequence[int] = (),
               suppress: Optional[Dict[str, str]] = None,
               quant_budget: Optional[float] = None) -> NumReport:
    """Trace `fn` with the example args and abstract-interpret its
    numerics. `suppress` maps finding keys to triage reasons;
    `quant_budget` is the program's declared quantization error budget
    (relative fullscale), checked against the derived bound."""
    closed = jax.make_jaxpr(fn, static_argnums=tuple(static_argnums))(
        *args)
    interp = _Interp(name)
    flat_in = closed.jaxpr.invars
    in_states = [_unknown(v.aval.dtype) for v in flat_in]
    outs = interp.run(closed, in_states, name)

    report = NumReport(name=name)
    report.out_dtypes = [_dt_name(s.dtype) for s in outs]
    report.acc_dtypes = sorted(interp.acc_dtypes)
    float_eps = [s.eps for s in outs if is_float(s.dtype)]
    report.max_error_ulps = max(float_eps, default=0.0)
    report.notes = list(interp.notes)

    # ---- NUM-QUANT: derived bound vs the declared budget
    events = interp.quant_events
    if events:
        levels = min(ev["levels"] for ev in events)
        derived = 0.5 / levels
        report.quant = {
            "levels": levels,
            "derived_rel_err": _round6(derived),
            "budget_rel_err": _round6(quant_budget)
            if quant_budget is not None else None,
        }
        if quant_budget is None:
            interp.finding(
                "NUM-QUANT", "quant:undeclared",
                f"quantize→dequantize pair found (levels={levels}, "
                f"derived error {derived:g} fullscale) but the "
                f"registry declares no error budget for this program",
                bound=derived / _U32, path=events[0]["path"])
        elif derived > quant_budget * (1 + 1e-9):
            interp.finding(
                "NUM-QUANT", "quant:budget",
                f"derived quantization error {derived:g} exceeds the "
                f"declared budget {quant_budget:g} (levels={levels})",
                bound=derived / _U32, path=events[0]["path"])
        if not any(ev["dequantized"] for ev in events):
            report.notes.append(
                "quantize without a matching dequantize: codes leave "
                "the program still encoded")
    elif quant_budget is not None:
        interp.finding(
            "NUM-QUANT", "quant:missing",
            f"the registry declares a quantization error budget "
            f"({quant_budget:g}) but no quantize→dequantize pair was "
            f"found in the program")

    report.findings = [interp.findings[k]
                       for k in sorted(interp.findings)]
    _apply_suppressions(report, suppress or {})
    return report


def _apply_suppressions(report: NumReport,
                        suppress: Dict[str, str]) -> None:
    used = set()
    for f in report.findings:
        reason = suppress.get(f.key)
        if reason:
            f.suppressed = reason
            used.add(f.key)
    for key in sorted(set(suppress) - used):
        report.notes.append(
            f"unused suppression {key!r} (finding no longer emitted "
            f"— drop it from the registry)")


# ------------------------------------------------------------ registry
@dataclass(frozen=True)
class _NumProgram:
    name: str
    build: Callable          # () -> (fn, args, static_argnums)
    suppress: Dict[str, str] = field(default_factory=dict)
    quant_budget: Optional[float] = None


#: first-run triage: every finding the registry programs emit today,
#: each with the reason it is acceptable. The suppression IS the
#: review record — remove the root cause and the plan check will flag
#: the suppression as unused.
_SOFTMAX_EXP = ("softmax computes exp(x - max(x)) <= exp(0): the "
                "shared-max subtraction is a relational fact interval "
                "analysis cannot see; the runtime core/anomaly.py "
                "guard covers the residual risk")
_SOFTMAX_DIV = ("softmax denominator sum(exp(x - max(x))) >= 1 "
                "relationally (the max element contributes exp(0)); "
                "intervals lose the shared-max relation")
_CE_LOG = ("cross_entropy uses log-sum-exp: the log operand "
           "sum(exp(x - max(x))) >= 1 relationally (the max element "
           "contributes exp(0)); intervals lose the shared-max "
           "relation (nn/functional/loss.py lse)")
_LOGPROB = ("token-logprob tracking uses jax.nn.log_softmax, whose "
            "log operand sum(exp(x - max(x))) >= 1 relationally "
            "(models/generation.py decode_chunk sampler)")
_LABEL_NARROW = ("cross_entropy reshapes int64 label inputs (x64 mode "
                 "default) to int32 for the logprob gather; labels "
                 "are program inputs with no static range, but XLA "
                 "gather clamps out-of-range indices and the "
                 "vocab-size contract bounds them at runtime")

_SUPPRESS: Dict[str, Dict[str, str]] = {
    "train_step": {
        "finite:exp": _SOFTMAX_EXP,
        "finite:div:div": _SOFTMAX_DIV,
        "finite:log": _CE_LOG,
        "cast:int:int64->int32": _LABEL_NARROW,
    },
    "decode.qkv": {},
    "decode.attn": {
        "finite:exp": _SOFTMAX_EXP,
        "finite:div:div": _SOFTMAX_DIV,
    },
    "serving.prefill": {
        "finite:exp": _SOFTMAX_EXP,
        "finite:div:div": _SOFTMAX_DIV,
    },
    "serving.paged_decode": {
        "finite:exp": _SOFTMAX_EXP,
        "finite:div:div": _SOFTMAX_DIV,
    },
    "serving.decode_chunk": {
        "finite:exp": _SOFTMAX_EXP,
        "finite:div:div": _SOFTMAX_DIV,
        "finite:log": _LOGPROB,
    },
    "serving.chunked_prefill": {
        "finite:exp": _SOFTMAX_EXP,
        "finite:div:div": _SOFTMAX_DIV,
        "finite:log": _LOGPROB,
    },
    "serving.ragged_attention": {
        "finite:exp": _SOFTMAX_EXP,
        "finite:div:div": _SOFTMAX_DIV,
    },
    "serving.kv_block_codec": {
        "finite:div:div": (
            "the codec divides by where(scale > 0, scale, 1): the "
            "select guard excludes 0 relationally, but the interval "
            "hull of {scale, 1.0} still contains 0; an all-zero tile "
            "encodes to exact zeros either way "
            "(inference/serving/kv_quant.py _safe)"),
    },
    "collective.ring_attention": {
        "finite:exp": _SOFTMAX_EXP,
        "finite:div:div": _SOFTMAX_DIV,
    },
    "collective.ulysses_attention": {
        "finite:exp": _SOFTMAX_EXP,
        "finite:div:div": _SOFTMAX_DIV,
    },
}


def _kv_codec_build():
    from ..inference.serving import kv_quant
    x = jnp.zeros((4, 16, 4, 8), jnp.float32)
    return kv_quant.kv_block_roundtrip, (x,), ()


def registry_names() -> List[str]:
    from .jaxcost import registry_names as cost_names
    return list(cost_names()) + ["serving.kv_block_codec"]


def _build_num_programs(names: Optional[Sequence[str]] = None
                        ) -> List[_NumProgram]:
    from .jaxcost import _build_programs, registry_names as cost_names
    known = set(cost_names()) | {"serving.kv_block_codec"}
    if names is not None:
        unknown = sorted(set(names) - known)
        if unknown:
            raise KeyError(
                f"unknown program(s): {', '.join(unknown)}; known: "
                f"{', '.join(sorted(known))}")
    want_codec = names is None or "serving.kv_block_codec" in names
    cost_wanted = None if names is None else [
        n for n in names if n != "serving.kv_block_codec"]
    out: List[_NumProgram] = []
    if cost_wanted is None or cost_wanted:
        for p in _build_programs(cost_wanted):
            out.append(_NumProgram(
                name=p.name,
                build=(lambda p=p: (p.fn, p.args, p.static_argnums)),
                suppress=_SUPPRESS.get(p.name, {})))
    if want_codec:
        from ..inference.serving.kv_quant import KV_INT8_REL_ERR
        out.append(_NumProgram(
            name="serving.kv_block_codec", build=_kv_codec_build,
            suppress=_SUPPRESS.get("serving.kv_block_codec", {}),
            quant_budget=KV_INT8_REL_ERR))
    return out


def compute_reports(names: Optional[Sequence[str]] = None
                    ) -> Dict[str, NumReport]:
    """Analyze every (selected) registry program."""
    reports: Dict[str, NumReport] = {}
    for prog in _build_num_programs(names):
        fn, args, static = prog.build()
        reports[prog.name] = analyze_fn(
            fn, *args, name=prog.name, static_argnums=static,
            suppress=prog.suppress, quant_budget=prog.quant_budget)
    return reports


# ------------------------------------------------------------ plan I/O
def _plan_payload(reports: Dict[str, NumReport]) -> dict:
    return {
        "version": PLAN_VERSION,
        "tolerance": DEFAULT_TOLERANCE,
        "ref_dtype": REF_DTYPE,
        "programs": {name: rep.to_dict()
                     for name, rep in sorted(reports.items())},
    }


def write_plan(path: str, reports: Dict[str, NumReport]) -> dict:
    payload = _plan_payload(reports)
    with open(path, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    return payload


@functools.lru_cache(maxsize=16)
def _load_plan_cached(path: str, mtime_ns: int) -> dict:
    with open(path) as f:
        return json.load(f)


def load_plan(path: str = DEFAULT_PLAN_PATH) -> Optional[dict]:
    """Committed precision plan, or None when missing. stdlib-only."""
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        return None
    return _load_plan_cached(path, mtime)


def _num_drift(cur, ref, tol: float) -> bool:
    lo, hi = sorted((float(cur), float(ref)))
    return hi - lo > tol * max(hi, 1.0)


def diff_plans(committed: dict, current: dict,
               tolerance: Optional[float] = None) -> List[str]:
    """Violations between a committed plan and a freshly computed one:
    coverage both directions, structural drift (dtypes, finding keys,
    quant levels) exact, error bounds within tolerance."""
    tol = tolerance if tolerance is not None else float(
        committed.get("tolerance", DEFAULT_TOLERANCE))
    out: List[str] = []
    if committed.get("ref_dtype", REF_DTYPE) != \
            current.get("ref_dtype", REF_DTYPE):
        out.append(f"reference dtype drift "
                   f"{committed.get('ref_dtype')} -> "
                   f"{current.get('ref_dtype')}")
    cp = committed.get("programs", {})
    np_ = current.get("programs", {})
    for name in sorted(set(cp) - set(np_)):
        out.append(f"{name}: committed but no longer in the registry")
    for name in sorted(set(np_) - set(cp)):
        out.append(f"{name}: registry program missing from the "
                   f"committed plan")
    for name in sorted(set(cp) & set(np_)):
        a, b = cp[name], np_[name]
        for fieldname in ("ref_dtype", "out_dtypes", "acc_dtypes"):
            if a.get(fieldname) != b.get(fieldname):
                out.append(f"{name}: {fieldname} drift "
                           f"{a.get(fieldname)} -> {b.get(fieldname)}")
        if _num_drift(b.get("max_error_ulps", 0),
                      a.get("max_error_ulps", 0), tol):
            out.append(
                f"{name}: max_error_ulps drifted "
                f"{a.get('max_error_ulps', 0):g} -> "
                f"{b.get('max_error_ulps', 0):g} (> {tol:.0%})")
        qa, qb = a.get("quant"), b.get("quant")
        if (qa is None) != (qb is None):
            out.append(f"{name}: quantization pattern "
                       f"{'appeared' if qb else 'disappeared'}")
        elif qa is not None:
            if qa.get("levels") != qb.get("levels"):
                out.append(f"{name}: quant levels drift "
                           f"{qa.get('levels')} -> {qb.get('levels')}")
            for k in ("derived_rel_err", "budget_rel_err"):
                va, vb = qa.get(k), qb.get(k)
                if (va is None) != (vb is None) or (
                        va is not None and _num_drift(vb, va, tol)):
                    out.append(f"{name}: quant {k} drifted "
                               f"{va} -> {vb}")
        af, bf = a.get("findings", {}), b.get("findings", {})
        if sorted(af) != sorted(bf):
            out.append(f"{name}: finding keys drifted "
                       f"{sorted(af)} -> {sorted(bf)}")
        else:
            for key in af:
                sa = af[key].get("suppressed")
                sb = bf[key].get("suppressed")
                if bool(sa) != bool(sb):
                    out.append(f"{name}: finding {key} suppression "
                               f"changed ({bool(sa)} -> {bool(sb)})")
                elif _num_drift(bf[key].get("bound_ulps", 0),
                                af[key].get("bound_ulps", 0), tol):
                    out.append(
                        f"{name}: finding {key} bound drifted "
                        f"{af[key].get('bound_ulps', 0):g} -> "
                        f"{bf[key].get('bound_ulps', 0):g}")
    return out


def unsuppressed_findings(reports: Dict[str, NumReport]) -> List[str]:
    out = []
    for name, rep in sorted(reports.items()):
        for f in rep.unsuppressed():
            out.append(f"{name}: {f.key}: {f.message}")
    return out


def check_plan(path: str = DEFAULT_PLAN_PATH,
               reports: Optional[Dict[str, NumReport]] = None,
               ) -> List[str]:
    """Violations of the committed plan: missing/stale file, version
    drift, structural/numeric drift vs a fresh analysis, and any
    unsuppressed finding."""
    committed = load_plan(path)
    if committed is None:
        return [f"no committed precision plan at {path} — run "
                f"tools/jaxnum.py --plan write"]
    if committed.get("version") != PLAN_VERSION:
        return [f"plan version {committed.get('version')} != analyzer "
                f"version {PLAN_VERSION} — re-write the plan"]
    if reports is None:
        reports = compute_reports()
    out = unsuppressed_findings(reports)
    out += diff_plans(committed, _plan_payload(reports))
    return out


def committed_codec_bound(path: str = DEFAULT_PLAN_PATH
                          ) -> Optional[float]:
    """The int8 KV codec's committed worst-case dequant error
    (relative fullscale) from numplan.json — the runtime parity tests
    gate against THIS number, so a loosened codec cannot pass without
    re-committing the plan. None when no plan is committed."""
    plan = load_plan(path)
    if not plan:
        return None
    entry = plan.get("programs", {}).get("serving.kv_block_codec")
    if not entry or not entry.get("quant"):
        return None
    return float(entry["quant"]["derived_rel_err"])
