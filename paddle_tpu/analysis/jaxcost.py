"""jaxcost: static FLOP / bytes / peak-memory analyzer for jaxprs.

PR 4's trace-time auditor checks what a compiled program DOES (host
callbacks, const bloat, downcasts); this module checks what it COSTS —
without running it. An abstract interpreter walks the jaxpr (the same
`_sub_jaxprs` traversal the auditor uses) and computes, per program:

- **flops** — per-primitive cost table: matmuls/convs from their
  contraction geometry, transcendentals at 8 flops/element, reductions
  at one flop per input element, data movement at zero, everything
  else conservatively at one flop per output element;
- **bytes_read / bytes_written** — operand and result bytes per
  equation (literals are inlined and free);
- **comm_bytes** — collective wire volume: ring all-reduce moves ~2x
  its payload (reduce-scatter + all-gather phases), all_gather is
  charged its output, permutes/all_to_all their input;
- **peak_bytes** — linear-scan liveness (`liveness.py`): buffers are
  freed after their last read, loop carries double-reside at iteration
  boundaries, sub-programs contribute their transient overshoot;
- **donation audit** — arguments that die after their last read AND
  have an aval-matched output produced no earlier are donation
  candidates: not listing them in `donate_argnums` costs a full extra
  residency of their bytes.

Control flow: `scan` bodies are multiplied by their static trip count
(`fori_loop` with static bounds lowers to scan, so ring attention's
rotation is counted exactly), `while` bodies are counted ONCE with a
note (trip count is not static), `cond` takes the per-metric max over
branches, `pjit`/`shard_map`/custom_* recurse transparently. Inside
`shard_map` the avals are per-device, so collective programs report
per-device cost — the quantity weak scaling holds constant.

The numbers are a deterministic MODEL, not a measurement: XLA fusion
changes bytes in its favor and the flop table rounds transcendentals,
so absolute values are first-order. What makes them useful is that
they are exactly reproducible from the IR — `jaxcost_budget.json`
pins them per registered program and `tools/jaxcost.py --budget
check` fails when a code change moves any metric more than 5%, the
same regression contract as ptlint's baseline.

Registered programs (`registry_names()`): jit.TrainStep on the tiny
deterministic GPT ptlint audits, the five decode sub-programs shared
by dense generate() and paged serving (models/generation.py), the
serving prefill + paged-attention decode step, and the distributed
collective paths (ring/ulysses attention, the psum tree) on a 4-device
mesh.
"""
from __future__ import annotations

import functools
import json
from dataclasses import dataclass, field, replace
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .hlo_bytes import shape_bytes  # noqa: F401  (one byte-accounting table)
from .jaxpr_audit import _sub_jaxprs
from .liveness import aval_bytes, peak_live_bytes, var_bytes

__all__ = ["ProgramCost", "analyze_jaxpr", "estimate_fn",
           "estimate_train_step", "estimate_decode_step",
           "DonationFinding", "leaf_argnums", "audit_donation",
           "registry_names", "compute_costs",
           "collect_donation_findings", "write_budget", "check_budget",
           "DEFAULT_TOLERANCE", "shape_bytes"]

# --------------------------------------------------------------- cost tables
#: pure data movement / bookkeeping: no arithmetic charged
_ZERO_FLOP = frozenset({
    "reshape", "transpose", "broadcast_in_dim", "slice", "dynamic_slice",
    "dynamic_update_slice", "concatenate", "pad", "gather", "scatter",
    "squeeze", "expand_dims", "rev", "iota", "copy", "copy_p",
    "convert_element_type", "bitcast_convert_type", "stop_gradient",
    "select_n", "split", "device_put", "sharding_constraint", "pbroadcast",
    "axis_index", "real", "imag", "is_finite", "sign",
})
#: one table entry = 8 flops per output element (polynomial approx cost)
_TRANSCENDENTAL = frozenset({
    "exp", "exp2", "expm1", "log", "log2", "log1p", "tanh", "sinh",
    "cosh", "tan", "sin", "cos", "asin", "acos", "atan", "atan2",
    "asinh", "acosh", "atanh", "erf", "erfc", "erf_inv", "logistic",
    "pow", "integer_pow", "sqrt", "rsqrt", "cbrt", "digamma", "lgamma",
    "threefry2x32",
})
_TRANSCENDENTAL_FLOPS = 8
#: reductions cost one flop per INPUT element
_REDUCTION_PREFIXES = ("reduce_", "cum", "arg")

#: collectives: wire bytes per equation. Ring all-reduce moves
#: 2*(N-1)/N * payload per device (~2x); gathers are charged their
#: output; permutes / all-to-all / scatters their input.
_COMM_TWICE_IN = frozenset({"psum", "psum2", "pmax", "pmin", "pmax2",
                            "pmin2", "pmean"})
_COMM_OUT = frozenset({"all_gather", "all_gather_invariant"})
_COMM_IN = frozenset({"reduce_scatter", "psum_scatter", "ppermute",
                      "pshuffle", "all_to_all"})


def _elems(v) -> int:
    aval = getattr(v, "aval", None)
    n = 1
    for d in getattr(aval, "shape", ()):
        n *= int(d)
    return n


def _leaf_flops(eqn) -> int:
    name = eqn.primitive.name
    out_elems = sum(_elems(v) for v in eqn.outvars)
    if name in _ZERO_FLOP or name in _COMM_TWICE_IN or name in _COMM_OUT \
            or name in _COMM_IN:
        return 0
    if name == "dot_general":
        (lhs_c, _), _ = eqn.params["dimension_numbers"]
        lhs_shape = eqn.invars[0].aval.shape
        csize = 1
        for d in lhs_c:
            csize *= int(lhs_shape[d])
        return 2 * out_elems * csize
    if name == "conv_general_dilated":
        dn = eqn.params["dimension_numbers"]
        rhs = eqn.invars[1].aval
        out_feature = int(rhs.shape[dn.rhs_spec[0]])
        kernel_elems = _elems(eqn.invars[1]) // max(out_feature, 1)
        return 2 * out_elems * kernel_elems
    if name in _TRANSCENDENTAL:
        return _TRANSCENDENTAL_FLOPS * out_elems
    if name.startswith(_REDUCTION_PREFIXES):
        return sum(_elems(v) for v in eqn.invars
                   if not hasattr(v, "val"))
    return out_elems  # conservative default: 1 flop / output element


def _leaf_comm(eqn) -> int:
    name = eqn.primitive.name
    in_bytes = sum(var_bytes(v) for v in eqn.invars)
    if name in _COMM_TWICE_IN:
        return 2 * in_bytes
    if name in _COMM_OUT:
        return sum(var_bytes(v) for v in eqn.outvars)
    if name in _COMM_IN:
        return in_bytes
    return 0


# ------------------------------------------------------------------ analyzer
@dataclass
class ProgramCost:
    name: str
    flops: int = 0
    bytes_read: int = 0
    bytes_written: int = 0
    comm_bytes: int = 0
    peak_bytes: int = 0
    peak_at: str = ""
    #: primitive -> {count, flops, bytes, comm_bytes}; counts are DYNAMIC
    #: instances (a scan body eqn counts once per trip)
    by_primitive: Dict[str, Dict[str, int]] = field(default_factory=dict)
    notes: Tuple[str, ...] = ()

    def to_dict(self) -> dict:
        return {"name": self.name, "flops": self.flops,
                "bytes_read": self.bytes_read,
                "bytes_written": self.bytes_written,
                "comm_bytes": self.comm_bytes,
                "peak_bytes": self.peak_bytes, "peak_at": self.peak_at,
                "by_primitive": self.by_primitive,
                "notes": list(self.notes)}

    def format(self, top_n: int = 8) -> str:
        lines = [f"{self.name}: {self.flops:,} flops, "
                 f"read {self.bytes_read:,} B, "
                 f"written {self.bytes_written:,} B, "
                 f"comm {self.comm_bytes:,} B, "
                 f"peak {self.peak_bytes:,} B (at {self.peak_at})"]
        ranked = sorted(self.by_primitive.items(),
                        key=lambda kv: -(kv[1]["flops"] + kv[1]["bytes"]))
        for pname, row in ranked[:top_n]:
            lines.append(f"    {pname:<24} x{row['count']:<6} "
                         f"{row['flops']:>14,} flops "
                         f"{row['bytes']:>14,} B"
                         + (f" {row['comm_bytes']:>12,} B comm"
                            if row["comm_bytes"] else ""))
        for n in self.notes:
            lines.append(f"    note: {n}")
        return "\n".join(lines)


class _Tally:
    __slots__ = ("flops", "read", "written", "comm", "by_prim", "notes")

    def __init__(self):
        self.flops = 0
        self.read = 0
        self.written = 0
        self.comm = 0
        self.by_prim: Dict[str, Dict[str, int]] = {}
        self.notes: List[str] = []

    def charge(self, pname, mult, flops, read, written, comm):
        self.flops += mult * flops
        self.read += mult * read
        self.written += mult * written
        self.comm += mult * comm
        row = self.by_prim.setdefault(
            pname, {"count": 0, "flops": 0, "bytes": 0, "comm_bytes": 0})
        row["count"] += mult
        row["flops"] += mult * flops
        row["bytes"] += mult * (read + written)
        row["comm_bytes"] += mult * comm

    def absorb(self, other: "_Tally", mult: int = 1):
        self.flops += mult * other.flops
        self.read += mult * other.read
        self.written += mult * other.written
        self.comm += mult * other.comm
        for pname, row in other.by_prim.items():
            mine = self.by_prim.setdefault(
                pname,
                {"count": 0, "flops": 0, "bytes": 0, "comm_bytes": 0})
            for k in mine:
                mine[k] += mult * row[k]
        self.notes.extend(other.notes)


def _tally(jaxpr_like, out: _Tally, mult: int, path: str) -> None:
    raw = jaxpr_like.jaxpr if hasattr(jaxpr_like, "jaxpr") else jaxpr_like
    for eqn in raw.eqns:
        pname = eqn.primitive.name
        subs = list(_sub_jaxprs(eqn))
        if not subs:
            read = sum(var_bytes(v) for v in eqn.invars)
            written = sum(var_bytes(v) for v in eqn.outvars)
            out.charge(pname, mult, _leaf_flops(eqn), read, written,
                       _leaf_comm(eqn))
            continue
        # control flow charges only its children (the eqn's own in/out
        # bytes are the body's, already counted inside)
        if pname == "cond":
            branches = []
            for label, sub in subs:
                t = _Tally()
                _tally(sub, t, 1, f"{path}/{pname}.{label}")
                branches.append(t)
            heavy = max(branches,
                        key=lambda t: (t.flops, t.read + t.written))
            # per-metric max over branches (conservative); by_primitive
            # attribution follows the heaviest branch
            out.flops += mult * max(t.flops for t in branches)
            out.read += mult * max(t.read for t in branches)
            out.written += mult * max(t.written for t in branches)
            out.comm += mult * max(t.comm for t in branches)
            for bp, row in heavy.by_prim.items():
                mine = out.by_prim.setdefault(
                    bp, {"count": 0, "flops": 0, "bytes": 0,
                         "comm_bytes": 0})
                for k in mine:
                    mine[k] += mult * row[k]
            out.notes.extend(heavy.notes)
            continue
        m = 1
        if pname == "scan":
            m = int(eqn.params.get("length", 1))
        elif pname == "while":
            out.notes.append(
                f"{path}: 'while' body counted once (trip count is not "
                f"static); totals are a lower bound there")
        for label, sub in subs:
            _tally(sub, out, mult * m, f"{path}/{pname}.{label}")


def analyze_jaxpr(jaxpr_like, name: str = "<jaxpr>") -> ProgramCost:
    """Full static cost of one (Closed)Jaxpr."""
    t = _Tally()
    _tally(jaxpr_like, t, 1, name)
    rep = peak_live_bytes(jaxpr_like, name=name)
    # drop duplicate notes, keep first-seen order
    notes = tuple(dict.fromkeys(t.notes))
    return ProgramCost(name=name, flops=t.flops, bytes_read=t.read,
                       bytes_written=t.written, comm_bytes=t.comm,
                       peak_bytes=rep.peak_bytes, peak_at=rep.where,
                       by_primitive=t.by_prim, notes=notes)


def estimate_fn(fn, *args, static_argnums: Sequence[int] = (),
                name: Optional[str] = None) -> ProgramCost:
    """Trace `fn` on the example args and analyze the result. Accepts
    jax.ShapeDtypeStruct leaves, so big programs can be estimated
    without materializing their buffers."""
    label = name or getattr(fn, "__name__", repr(fn))
    closed = jax.make_jaxpr(
        fn, static_argnums=tuple(static_argnums))(*args)
    return analyze_jaxpr(closed, name=label)


# ----------------------------------------------------------- donation audit
@dataclass(frozen=True)
class DonationFinding:
    program: str
    argnum: int
    nbytes: int
    n_leaves: int
    suppressed: Optional[str] = None  # reason, if intentionally undonated

    @property
    def message(self) -> str:
        return (f"{self.program}: argument {self.argnum} — "
                f"{self.nbytes:,} bytes across {self.n_leaves} array(s) "
                f"dead after their last read with aval-matched outputs; "
                f"add argnum {self.argnum} to donate_argnums to drop a "
                f"full extra residency")

    def format(self) -> str:
        tail = f"  (suppressed: {self.suppressed})" if self.suppressed \
            else ""
        return f"[donation] {self.message}{tail}"


def leaf_argnums(args, static_argnums: Sequence[int] = ()) -> List[int]:
    """argnum of every flattened dynamic-arg leaf, in jaxpr invar order."""
    static = set(static_argnums)
    out: List[int] = []
    for i, a in enumerate(args):
        if i in static:
            continue
        out.extend([i] * len(jax.tree_util.tree_leaves(a)))
    return out


#: below this many matched bytes per argnum the finding is noise (loop
#: counters, lr scalars, per-token activations)
DONATION_MIN_BYTES = 1024


def audit_donation(fn, *args, name: str,
                   donate_argnums: Sequence[int] = (),
                   static_argnums: Sequence[int] = (),
                   min_bytes: int = DONATION_MIN_BYTES,
                   suppress: Optional[Dict[int, str]] = None,
                   ) -> List[DonationFinding]:
    """Flag arguments that could be donated but are not.

    An argnum is a candidate when its leaves (a) are read, (b) are not
    returned unchanged (no passthrough aliasing), and (c) can each be
    greedily matched to a distinct non-passthrough output of identical
    shape+dtype produced at-or-after the leaf's last read — exactly the
    conditions under which XLA's input-output aliasing reuses the
    buffer. Aggregated bytes under `min_bytes` are dropped as noise.
    `suppress` maps argnum -> reason for intentional non-donation; the
    finding is still reported, marked suppressed."""
    suppress = suppress or {}
    closed = jax.make_jaxpr(
        fn, static_argnums=tuple(static_argnums))(*args)
    raw = closed.jaxpr
    owner = leaf_argnums(args, static_argnums)
    if len(owner) != len(raw.invars):
        raise ValueError(
            f"{name}: {len(raw.invars)} jaxpr invars but "
            f"{len(owner)} example-arg leaves — static_argnums "
            f"mismatch?")

    last_read: Dict[object, int] = {}
    produced_at: Dict[object, int] = {}
    for i, eqn in enumerate(raw.eqns):
        for v in eqn.invars:
            if not hasattr(v, "val"):
                last_read[v] = i
        for v in eqn.outvars:
            produced_at[v] = i

    invar_set = set(raw.invars)
    outputs = []  # (shape, dtype, produced_at) of non-passthrough outvars
    for v in raw.outvars:
        if hasattr(v, "val") or v in invar_set:
            continue
        aval = getattr(v, "aval", None)
        outputs.append([tuple(getattr(aval, "shape", ())),
                        getattr(aval, "dtype", None),
                        produced_at.get(v, len(raw.eqns)), False])

    findings: List[DonationFinding] = []
    donated = set(donate_argnums)
    per_argnum: Dict[int, List[object]] = {}
    for v, a in zip(raw.invars, owner):
        per_argnum.setdefault(a, []).append(v)
    for argnum in sorted(per_argnum):
        if argnum in donated:
            continue
        cands = [v for v in per_argnum[argnum]
                 if v in last_read and v not in set(raw.outvars)]
        cands.sort(key=lambda v: last_read[v])
        matched_bytes, matched = 0, 0
        for v in cands:
            aval = getattr(v, "aval", None)
            key = (tuple(getattr(aval, "shape", ())),
                   getattr(aval, "dtype", None))
            for out in outputs:
                if not out[3] and (out[0], out[1]) == key \
                        and out[2] >= last_read[v]:
                    out[3] = True
                    matched_bytes += aval_bytes(aval)
                    matched += 1
                    break
        if matched_bytes >= min_bytes:
            findings.append(DonationFinding(
                program=name, argnum=argnum, nbytes=matched_bytes,
                n_leaves=matched, suppressed=suppress.get(argnum)))
    return findings


# ------------------------------------------------------- high-level helpers
def estimate_train_step(step, *batch,
                        name: str = "train_step") -> ProgramCost:
    """Static cost of a jit.TrainStep's full program (fwd+bwd+optimizer)
    against an example batch — same argument assembly as dispatch."""
    from .jaxpr_audit import train_step_args
    return estimate_fn(step._raw_step, *train_step_args(step, *batch),
                       name=name)


def estimate_decode_step(params, geom, batch: int,
                         dtype=None,
                         name: str = "decode_step") -> ProgramCost:
    """Static cost of ONE full dense decode step (embed + L x (qkv +
    cache write + attn) + head). The KV cache is traced as
    ShapeDtypeStructs so flagship-sized caches cost nothing to model."""
    from ..models import generation as g
    L, H, D, S = geom
    if dtype is None:
        dtype = params["wte.weight"].dtype
    leaf = jax.ShapeDtypeStruct((batch, H, S, D), dtype)
    cache = tuple((leaf, leaf) for _ in range(L))
    tok = jax.ShapeDtypeStruct((batch,), jnp.int32)
    pos = jax.ShapeDtypeStruct((), jnp.int32)

    def run(p, c, t, q):
        return g.decode_step(p, c, t, q, geom)

    return estimate_fn(run, params, cache, tok, pos, name=name)


# ----------------------------------------------------------------- registry
@dataclass(frozen=True)
class _Program:
    name: str
    fn: Callable
    args: tuple
    static_argnums: Tuple[int, ...] = ()
    donate_argnums: Tuple[int, ...] = ()
    #: argnum -> reason for findings that are intentional
    suppress: Dict[int, str] = field(default_factory=dict)
    #: False for library functions whose donation is the CALLER's jit
    #: decision (shard_map'd collectives)
    donation_applies: bool = True


@functools.lru_cache(maxsize=1)
def _tiny_gpt():
    """The deterministic tiny-GPT recipe ptlint's --audit uses; every
    registry program keys off this geometry so budget numbers are
    stable across machines."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from ..models import generation
    from ..models.gpt import GPT, GPTConfig

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=24)
    model = GPT(cfg)
    geom = (cfg.num_layers, cfg.num_heads,
            cfg.hidden_size // cfg.num_heads, cfg.max_seq_len)

    def loss_fn(m, x, y):
        logits = m(x)
        return F.cross_entropy(
            logits.reshape([-1, cfg.vocab_size]), y.reshape([-1]))

    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    step = paddle.jit.TrainStep(model, loss_fn, opt)
    params = generation.extract_params(model)
    return model, cfg, geom, params, step


def _train_step_programs() -> List[_Program]:
    import paddle_tpu as paddle
    from .jaxpr_audit import train_step_args
    _, _, _, _, step = _tiny_gpt()
    x = paddle.to_tensor([[1, 2, 3, 4]], dtype="int64")
    y = paddle.to_tensor([[2, 3, 4, 5]], dtype="int64")
    return [_Program("train_step", step._raw_step,
                     tuple(train_step_args(step, x, y)),
                     donate_argnums=step._donate_argnums)]


def _decode_sub_programs() -> List[_Program]:
    from .jaxpr_audit import decode_programs
    _, _, geom, params, _ = _tiny_gpt()
    out = []
    for pname, fn, args, static in decode_programs(params, geom):
        # _cache_write is the one donated decode sub-program: every
        # caller rebinds kc/vc to the returned pair (decode_step,
        # ServingPredictor) so the old cache is reusable in place
        donate = (0, 1) if pname == "cache_write" else ()
        out.append(_Program(f"decode.{pname}",
                            getattr(fn, "__wrapped__", fn), tuple(args),
                            static_argnums=tuple(static),
                            donate_argnums=donate))
    return out


def _serving_programs() -> List[_Program]:
    from ..inference.serving.attention import (PACK_COLS,
                                               fused_decode_chunk,
                                               paged_decode_step)
    from ..models import generation as g
    from ..ops.pallas.ragged_paged_attention import \
        ragged_attention_reference
    _, cfg, geom, params, _ = _tiny_gpt()
    L, H, D, S = geom
    dtype = params["wte.weight"].dtype
    ids = jnp.zeros((2, 8), jnp.int32)
    prefill = _Program("serving.prefill",
                       getattr(g.prefill, "__wrapped__", g.prefill),
                       (params, ids, geom), static_argnums=(2,))
    # paged pool geometry: MB * block_size == max_seq_len so the
    # gathered context has the dense cache layout (parity contract)
    bs, nb, N = 4, 8, 2
    MB = S // bs
    pool = jnp.zeros((nb, bs, H, D), dtype)
    pools = tuple((pool, pool) for _ in range(L))
    tokens = jnp.zeros((N,), jnp.int32)
    positions = jnp.zeros((N,), jnp.int32)
    tables = jnp.zeros((N, MB), jnp.int32)
    slots = jnp.zeros((N,), jnp.int32)
    paged = _Program(
        "serving.paged_decode", paged_decode_step,
        (params, pools, tokens, positions, tables, slots, slots, geom),
        static_argnums=(7,),
        suppress={1: "engine crash recovery re-reads the pre-step pools "
                     "to rebuild survivors after a poisoned step "
                     "(LLMEngine watchdog); donating them would delete "
                     "the rollback copy"})
    # the fused k-token chunk (the engine's steady-state decode path):
    # cost scales ~k x the single paged step — the scan body is
    # multiplied by its static trip count — and the pools ARE donated
    # here (the scan carries them; the engine rebinds cache.pools from
    # the return value, and chunk-granular recovery re-prefills from
    # host token logs instead of re-reading pre-step pools)
    # NOTE: there is no per-bucket compile-count axis here anymore — the
    # ragged default pads every batch to the ONE fixed max_num_seqs
    # width, so these budgets each cover every batch mix (pinned by the
    # compile-count test in tests/test_serving_ragged.py).
    K = 8
    packed = jnp.zeros((N, PACK_COLS + K + MB), jnp.int32)
    chunk = _Program(
        "serving.decode_chunk",
        getattr(fused_decode_chunk, "__wrapped__", fused_decode_chunk),
        (params, pools, packed, geom, K, "ragged"),
        static_argnums=(3, 4, 5), donate_argnums=(1,))
    # the ragged paged-attention program: the lax.scan reference is the
    # kernel's cost-faithful twin (same block-streamed flash update the
    # pallas kernel executes per row), so the committed budget bounds
    # the kernel's FLOP/bytes envelope without tracing pallas_call
    q1 = jnp.zeros((N, H, D), dtype)
    lens = jnp.zeros((N,), jnp.int32)
    ragged = _Program(
        "serving.ragged_attention",
        getattr(ragged_attention_reference, "__wrapped__",
                ragged_attention_reference),
        (q1, pool, pool, tables, lens))
    # chunked prefill rides the SAME fused scan (prompt tokens feed the
    # body; no extra dispatch): registering it separately pins that the
    # prompt-feed path adds no cost axis over plain decode — the two
    # budgets must stay identical
    pf_packed = jnp.zeros((N, PACK_COLS + K + MB), jnp.int32)
    chunked_prefill = _Program(
        "serving.chunked_prefill",
        getattr(fused_decode_chunk, "__wrapped__", fused_decode_chunk),
        (params, pools, pf_packed, geom, K, "ragged"),
        static_argnums=(3, 4, 5), donate_argnums=(1,))
    return [prefill, paged, chunk, ragged, chunked_prefill]


def _collective_programs() -> List[_Program]:
    import numpy as np
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P
    from ..parallel.ring_attention import (ring_attention,
                                           ulysses_attention)

    devs = jax.devices()
    if len(devs) < 4:
        raise RuntimeError(
            "collective registry programs need >= 4 devices; run under "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 (the "
            "jaxcost CLI and tests/conftest.py both set this)")
    mesh = Mesh(np.asarray(devs[:4]), ("sp",))
    B, H, T, D = 1, 4, 32, 8
    q = jnp.zeros((B, H, T, D), jnp.float32)
    # ptlint: disable=PT-S001  this IS the committed layout: the
    # collective.* registry programs define the byte budget that
    # jaxcost_budget.json and shardplan.json both pin (the jaxshard
    # registry mirrors these literals so the cross-artifact check
    # compares like with like)
    spec = P(None, None, "sp", None)

    ring = shard_map(lambda a, b, c: ring_attention(a, b, c, "sp"),
                     mesh=mesh, in_specs=(spec,) * 3, out_specs=spec)
    uly = shard_map(lambda a, b, c: ulysses_attention(a, b, c, "sp"),
                    mesh=mesh, in_specs=(spec,) * 3, out_specs=spec)

    # the grad-all-reduce shape: per-leaf psum over the dp axis (what
    # ShardedTrainStep's gradient sync lowers to)
    def psum_tree(grads):
        return jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, "dp"), grads)

    dmesh = Mesh(np.asarray(devs[:4]), ("dp",))
    tree = {"w": jnp.zeros((8, 8), jnp.float32),
            "b": jnp.zeros((4,), jnp.float32)}
    pt = shard_map(psum_tree, mesh=dmesh,
                   # ptlint: disable=PT-S001  committed registry layout
                   in_specs=({"w": P("dp", None), "b": P("dp")},),
                   # ptlint: disable=PT-S001  committed registry layout
                   out_specs={"w": P(None, None), "b": P(None)},
                   check_rep=False)
    return [
        _Program("collective.ring_attention", ring, (q, q, q),
                 donation_applies=False),
        _Program("collective.ulysses_attention", uly, (q, q, q),
                 donation_applies=False),
        _Program("collective.psum_tree", pt, (tree,),
                 donation_applies=False),
    ]


_GROUPS: Tuple[Tuple[str, Callable], ...] = (
    ("train_step", _train_step_programs),
    ("decode.", _decode_sub_programs),
    ("serving.", _serving_programs),
    ("collective.", _collective_programs),
)

_REGISTRY_NAMES = (
    "train_step",
    "decode.token_embed", "decode.qkv", "decode.cache_write",
    "decode.attn", "decode.head",
    "serving.prefill", "serving.paged_decode", "serving.decode_chunk",
    "serving.ragged_attention", "serving.chunked_prefill",
    "collective.ring_attention", "collective.ulysses_attention",
    "collective.psum_tree",
)


def registry_names() -> List[str]:
    return list(_REGISTRY_NAMES)


def _build_programs(names: Optional[Sequence[str]] = None
                    ) -> List[_Program]:
    if names is not None:
        unknown = sorted(set(names) - set(_REGISTRY_NAMES))
        if unknown:
            raise KeyError(
                f"unknown program(s): {', '.join(unknown)}; known: "
                f"{', '.join(_REGISTRY_NAMES)}")
    wanted = set(names) if names is not None else None
    out: List[_Program] = []
    for prefix, builder in _GROUPS:
        if wanted is not None and not any(n.startswith(prefix)
                                          for n in wanted):
            continue
        for prog in builder():
            if wanted is None or prog.name in wanted:
                out.append(prog)
    return out


def compute_costs(names: Optional[Sequence[str]] = None
                  ) -> Dict[str, ProgramCost]:
    """Static cost of every (selected) registered program."""
    return {p.name: estimate_fn(p.fn, *p.args,
                                static_argnums=p.static_argnums,
                                name=p.name)
            for p in _build_programs(names)}


def collect_donation_findings(names: Optional[Sequence[str]] = None
                              ) -> List[DonationFinding]:
    """Donation audit over every (selected) registered program where
    donation is that program's own decision (skips shard_map'd library
    collectives — their donation belongs to the caller's jit)."""
    findings: List[DonationFinding] = []
    for p in _build_programs(names):
        if not p.donation_applies:
            continue
        findings.extend(audit_donation(
            p.fn, *p.args, name=p.name,
            donate_argnums=p.donate_argnums,
            static_argnums=p.static_argnums, suppress=p.suppress))
    return findings


# ------------------------------------------------------------------- budget
DEFAULT_TOLERANCE = 0.05
BUDGET_METRICS = ("flops", "peak_bytes", "comm_bytes")


def write_budget(path: str, costs: Dict[str, ProgramCost],
                 tolerance: float = DEFAULT_TOLERANCE) -> None:
    payload = {
        "version": 1,
        "tolerance": tolerance,
        "programs": {
            name: {m: getattr(c, m) for m in BUDGET_METRICS}
            for name, c in sorted(costs.items())},
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")


def check_budget(path: str, costs: Dict[str, ProgramCost],
                 require_full_coverage: bool = True) -> List[str]:
    """Violations list (empty = within budget). A program is over
    budget when any metric exceeds its committed value by more than
    the file's tolerance. With `require_full_coverage`, programs
    missing from either side are violations too — a silently dropped
    program is how regressions hide."""
    with open(path) as f:
        payload = json.load(f)
    tol = float(payload.get("tolerance", DEFAULT_TOLERANCE))
    budget = payload.get("programs", {})
    violations: List[str] = []
    for name in sorted(costs):
        ref = budget.get(name)
        if ref is None:
            violations.append(
                f"{name}: not in budget file (intentional new program? "
                f"re-baseline with --budget write)")
            continue
        for metric in BUDGET_METRICS:
            cur = int(getattr(costs[name], metric))
            bud = int(ref.get(metric, 0))
            if cur > bud * (1.0 + tol):
                over = (cur / bud - 1.0) * 100 if bud else float("inf")
                violations.append(
                    f"{name}: {metric} {cur:,} exceeds budget {bud:,} "
                    f"by {over:.1f}% (tolerance {tol:.0%})")
    if require_full_coverage:
        for name in sorted(set(budget) - set(costs)):
            violations.append(
                f"{name}: in budget file but not produced by this run "
                f"(program removed? re-baseline with --budget write)")
    return violations
