"""Linear-scan liveness over jaxprs -> peak live-buffer bytes.

XLA frees a buffer after its last read, so the peak residency of a
program is NOT the sum of everything it ever allocates — it is the
maximum, over equations, of

    (bytes live across the eqn) + (bytes the eqn writes)
    + (transient extra of any sub-program the eqn runs).

This module computes that maximum by a single linear scan:

1. build a last-use map (eqn index of the final read of every var;
   jaxpr outvars are pinned live to the end),
2. walk equations in order, charging each eqn's outputs on top of the
   current live set, releasing inputs after their last use.

Sub-jaxprs (pjit bodies, scan/while carries, cond branches) recurse via
`jaxpr_audit._sub_jaxprs` — the same traversal the trace-time auditor
uses. A sub-program's contribution is its TRANSIENT requirement
`max(0, sub_peak - sub_entry)`: its inputs are already counted live in
the parent frame. For scan/while bodies the body invars are pinned live
through the whole body (`pin_invars`) because at every iteration
boundary the old carry coexists with the freshly produced one.

Accounting conventions (deterministic, documented, testable):

- literals cost 0 (inlined scalars);
- captured consts are pinned live for the whole program (they are owned
  by the executable);
- dropped outputs (DropVar) are charged at their producing eqn and
  released immediately;
- inside `shard_map` bodies avals are per-device, so programs built
  around shard_map report per-device residency for the mapped region.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict

from .jaxpr_audit import _sub_jaxprs

__all__ = ["aval_bytes", "var_bytes", "PeakReport", "peak_live_bytes"]

#: primitives whose body invars stay live for the whole body: the loop
#: carry is read at the top of every iteration while the new carry is
#: being produced, so old and new coexist.
_PIN_BODY = frozenset({"scan", "while"})


def aval_bytes(aval) -> int:
    """Size in bytes of one abstract value (0 for shapeless avals)."""
    dtype = getattr(aval, "dtype", None)
    itemsize = int(getattr(dtype, "itemsize", 0) or 0)
    n = 1
    for d in getattr(aval, "shape", ()):
        n *= int(d)
    return itemsize * n


def _is_literal(v) -> bool:
    return type(v).__name__ == "Literal" or hasattr(v, "val")


def var_bytes(v) -> int:
    """Bytes of a jaxpr var; literals are inlined and cost nothing."""
    if _is_literal(v):
        return 0
    return aval_bytes(getattr(v, "aval", None))


@dataclass(frozen=True)
class PeakReport:
    peak_bytes: int    # max simultaneously-live bytes
    where: str         # "<name>:<eqn idx>:<primitive>" or "<name>:entry"
    entry_bytes: int   # bytes live at program entry (invars + consts)


def peak_live_bytes(jaxpr_like, name: str = "<jaxpr>",
                    pin_invars: bool = False,
                    bytes_fn=None) -> PeakReport:
    """Peak live-buffer bytes of a (Closed)Jaxpr by linear-scan
    liveness. `pin_invars` keeps every invar live to the end (used for
    scan/while bodies — loop-carry double residency). `bytes_fn`
    overrides the per-var byte charge (default `var_bytes`): jaxshard
    passes bytes/shard_factor to turn the global peak into a per-device
    peak without duplicating the scan."""
    if bytes_fn is None:
        bytes_fn = var_bytes
    closed = jaxpr_like if hasattr(jaxpr_like, "jaxpr") else None
    raw = closed.jaxpr if closed is not None else jaxpr_like
    eqns = list(raw.eqns)
    end = len(eqns)  # sentinel: live to the end of the program

    last_use: Dict[object, int] = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if not _is_literal(v):
                last_use[v] = i
    for v in raw.outvars:
        if not _is_literal(v):
            last_use[v] = end
    for v in raw.constvars:
        last_use[v] = end
    if pin_invars:
        for v in raw.invars:
            last_use[v] = end

    live: Dict[object, int] = {}
    entry = 0
    for v in list(raw.constvars) + list(raw.invars):
        b = bytes_fn(v)
        entry += b
        if v in last_use and v not in live:
            live[v] = b
    live_total = sum(live.values())

    peak, where = entry, f"{name}:entry"
    for i, eqn in enumerate(eqns):
        out_b = sum(bytes_fn(v) for v in eqn.outvars)
        inner_extra = 0
        pin = eqn.primitive.name in _PIN_BODY
        for label, sub in _sub_jaxprs(eqn):
            rep = peak_live_bytes(
                sub, name=f"{name}/{eqn.primitive.name}.{label}",
                pin_invars=pin, bytes_fn=bytes_fn)
            inner_extra = max(inner_extra,
                              max(0, rep.peak_bytes - rep.entry_bytes))
        cur = live_total + out_b + inner_extra
        if cur > peak:
            peak, where = cur, f"{name}:{i}:{eqn.primitive.name}"
        for v in eqn.outvars:
            lu = last_use.get(v)
            if lu is not None and lu > i and v not in live:
                b = bytes_fn(v)
                live[v] = b
                live_total += b
        for v in [u for u, lu in last_use.items()
                  if lu == i and u in live]:
            live_total -= live.pop(v)

    return PeakReport(peak_bytes=peak, where=where, entry_bytes=entry)
