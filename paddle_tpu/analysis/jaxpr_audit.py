"""Trace-time jaxpr auditor: inspect what actually got staged.

The AST rules catch what the SOURCE says; this module checks what the
COMPILER sees. After tracing an entry point to a jaxpr it walks every
equation (recursing through pjit/scan/cond/while sub-jaxprs) for:

- forbidden primitives ("callbacks"): host callbacks (pure_callback,
  io_callback, debug_callback, ...) — each one is a device→host round
  trip buried in the hot program;
- oversized captured constants ("consts"): closure-captured arrays are
  baked into the executable and re-uploaded per compile; big ones mean
  someone closed over parameters instead of passing them as arguments;
- unintended dtype downcasts ("downcasts"): convert_element_type from a
  >=32-bit float to a sub-32-bit float. NOTE the package enables
  jax_enable_x64, so f64→f32 converts are everywhere and deliberate —
  only precision drops BELOW 32 bits are flagged. The dtype predicate
  itself lives in analysis/jaxnum.py (`lossy_float_downcast`) — ONE
  bfloat16-aware lattice shared with the whole-program numerics
  analyzer;
- integer narrowing ("int_narrowing", opt-in): convert_element_type to
  a strictly narrower integer (int64→int32 table/length casts). Not in
  DEFAULT_CHECKS because gather-index casts (`lab.astype(int32)`) are
  deliberate and this trace-level check has no value-range analysis to
  tell them apart — jaxnum's NUM-CAST rule is the range-aware version,
  and numplan.json is where its findings are triaged and gated.

Entry points: `audit_fn` on any callable, `audit_train_step` on a
jit.TrainStep, `audit_decode_programs` on the four decode sub-programs
that serve both the dense and paged paths (models/generation.py).
bench.py calls these before timing so a perf run fails loudly instead
of quietly timing a host round-trip.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# the shared dtype lattice: jaxnum owns the bfloat16-aware downcast /
# narrowing predicates (module-level import is cycle-safe — jaxnum's
# registry imports run lazily inside its builder functions)
from . import jaxnum as _lattice

__all__ = ["AuditIssue", "JaxprAuditError", "FORBIDDEN_PRIMITIVES",
           "audit_jaxpr", "audit_fn", "audit_train_step",
           "audit_decode_programs", "assert_clean",
           "train_step_args", "decode_programs"]

#: primitives that smuggle host work into a compiled program
FORBIDDEN_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call", "host_callback", "device_get", "host_local_array",
})

DEFAULT_CHECKS = ("callbacks", "consts", "downcasts")
#: every check audit_jaxpr knows; "int_narrowing" is opt-in (see the
#: module docstring for why)
ALL_CHECKS = ("callbacks", "consts", "downcasts", "int_narrowing")
#: one closure-captured array bigger than this means someone baked
#: state into the executable instead of passing it as an argument
DEFAULT_MAX_CONST_BYTES = 1 << 20


@dataclass(frozen=True)
class AuditIssue:
    kind: str        # "callback" | "const" | "downcast" | "int_narrowing"
    where: str       # entry-point name (+ sub-jaxpr path)
    message: str

    def format(self) -> str:
        return f"[{self.kind}] {self.where}: {self.message}"


class JaxprAuditError(RuntimeError):
    def __init__(self, issues: Sequence[AuditIssue]):
        self.issues = list(issues)
        lines = "\n  ".join(i.format() for i in self.issues)
        super().__init__(
            f"jaxpr audit failed with {len(self.issues)} issue(s):\n"
            f"  {lines}")


def _sub_jaxprs(eqn) -> Iterable[Tuple[str, object]]:
    """Yield (label, jaxpr-like) for every sub-program an equation
    carries (pjit bodies, scan/while carries, cond branches, ...)."""
    for key, val in eqn.params.items():
        vals = val if isinstance(val, (list, tuple)) else [val]
        for i, v in enumerate(vals):
            if hasattr(v, "jaxpr") or hasattr(v, "eqns"):
                label = key if len(vals) == 1 else f"{key}[{i}]"
                yield label, v


def _iter_eqns(jaxpr_like, path: str):
    """DFS over equations; yields (eqn, path). Accepts ClosedJaxpr or
    raw Jaxpr; also yields each ClosedJaxpr met (for const checks)."""
    closed = jaxpr_like if hasattr(jaxpr_like, "jaxpr") else None
    raw = closed.jaxpr if closed is not None else jaxpr_like
    yield ("__closed__", closed, path)
    for eqn in raw.eqns:
        yield ("__eqn__", eqn, path)
        for label, sub in _sub_jaxprs(eqn):
            sub_path = f"{path}/{eqn.primitive.name}.{label}"
            yield from _iter_eqns(sub, sub_path)


def _nbytes(x) -> int:
    try:
        return int(np.asarray(jax.core.get_aval(x).dtype.itemsize)
                   * np.prod(jax.core.get_aval(x).shape, dtype=np.int64))
    except Exception:
        arr = np.asarray(x)
        return int(arr.nbytes)


def _dtype_of(var):
    aval = getattr(var, "aval", None)
    return getattr(aval, "dtype", None)


def _is_literal(var) -> bool:
    return type(var).__name__ == "Literal" or hasattr(var, "val")


def audit_jaxpr(jaxpr_like, name: str = "<jaxpr>",
                checks: Sequence[str] = DEFAULT_CHECKS,
                max_const_bytes: int = DEFAULT_MAX_CONST_BYTES
                ) -> List[AuditIssue]:
    """Audit one (Closed)Jaxpr; returns the list of issues (empty =
    clean). `checks` selects from {"callbacks", "consts", "downcasts"}."""
    checks = set(checks)
    issues: List[AuditIssue] = []
    for tag, obj, path in _iter_eqns(jaxpr_like, name):
        if tag == "__closed__":
            if obj is None or "consts" not in checks:
                continue
            for c in getattr(obj, "consts", []):
                n = _nbytes(c)
                if n > max_const_bytes:
                    shape = tuple(getattr(jax.core.get_aval(c), "shape",
                                          ()))
                    issues.append(AuditIssue(
                        "const", path,
                        f"captured constant of {n} bytes (shape {shape})"
                        f" baked into the executable (> "
                        f"{max_const_bytes}); pass it as an argument "
                        f"instead of closing over it"))
            continue
        eqn = obj
        pname = eqn.primitive.name
        if "callbacks" in checks and pname in FORBIDDEN_PRIMITIVES:
            issues.append(AuditIssue(
                "callback", path,
                f"forbidden primitive '{pname}' — a host round-trip "
                f"inside the compiled program"))
        if pname == "convert_element_type" and (
                "downcasts" in checks or "int_narrowing" in checks):
            invar = eqn.invars[0]
            if _is_literal(invar):
                continue  # literal converts are free trace-time consts
            src = _dtype_of(invar)
            dst = eqn.params.get("new_dtype")
            if src is None or dst is None:
                continue
            src = np.dtype(src)
            dst = np.dtype(dst)
            if "downcasts" in checks and \
                    _lattice.lossy_float_downcast(src, dst):
                issues.append(AuditIssue(
                    "downcast", path,
                    f"float downcast {src.name} -> {dst.name}: "
                    f"sub-32-bit precision entered the program; if "
                    f"intentional, audit with checks excluding "
                    f"'downcasts'"))
            if "int_narrowing" in checks and \
                    _lattice.lossy_int_narrowing(src, dst):
                issues.append(AuditIssue(
                    "int_narrowing", path,
                    f"integer narrowing {src.name} -> {dst.name}: "
                    f"values past 2^{8 * dst.itemsize - 1} wrap; "
                    f"jaxnum's NUM-CAST rule proves or refutes the "
                    f"range — prefer gating via numplan.json"))
    return issues


def audit_fn(fn, *args, name: Optional[str] = None,
             static_argnums: Sequence[int] = (),
             checks: Sequence[str] = DEFAULT_CHECKS,
             max_const_bytes: int = DEFAULT_MAX_CONST_BYTES,
             ) -> List[AuditIssue]:
    """Trace `fn` with the example args and audit the result. Works on
    plain callables and jitted wrappers alike (jit bodies show up as
    pjit sub-jaxprs and are recursed into)."""
    label = name or getattr(fn, "__name__", repr(fn))
    closed = jax.make_jaxpr(fn, static_argnums=tuple(static_argnums))(
        *args)
    return audit_jaxpr(closed, name=label, checks=checks,
                       max_const_bytes=max_const_bytes)


def assert_clean(issues: Sequence[AuditIssue]) -> None:
    if issues:
        raise JaxprAuditError(issues)


# ----------------------------------------------------------- entry points
def decode_programs(params, geom, batch: int = 2):
    """[(name, fn, example_args, static_argnums), ...] for the five
    top-level jitted decode sub-programs every decode path (dense
    generate() AND paged serving) compiles: _token_embed, _decode_qkv,
    _cache_write, _decode_attn, _decode_head. `params`/`geom` as for
    models.generation (geom = (L, H, D, S)). Shared by the trace-time
    audit below and jaxcost's cost/donation registry."""
    from ..models import generation as g

    L, H, D, S = geom
    C = H * D
    dtype = jnp.asarray(params["wte.weight"]).dtype
    B = batch
    tokens = jnp.zeros((B,), jnp.int32)
    positions = jnp.zeros((B,), jnp.int32)
    x = jnp.zeros((B, 1, C), dtype)
    q = jnp.zeros((B, H, 1, D), dtype)
    kc = jnp.zeros((B, H, S, D), dtype)
    vc = jnp.zeros((B, H, S, D), dtype)
    k_new = jnp.zeros((B, H, 1, D), dtype)
    v_new = jnp.zeros((B, H, 1, D), dtype)
    pos = jnp.zeros((), jnp.int32)
    return [
        ("token_embed", g._token_embed,
         (params, tokens, positions), ()),
        ("qkv", g._decode_qkv, (params, 0, x, geom), (1, 3)),
        ("cache_write", g._cache_write,
         (kc, vc, k_new, v_new, pos), ()),
        ("attn", g._decode_attn,
         (params, 0, x, q, kc, vc, positions, geom), (1, 7)),
        ("head", g._decode_head, (params, x), ()),
    ]


def audit_decode_programs(params, geom,
                          batch: int = 2,
                          checks: Sequence[str] = DEFAULT_CHECKS,
                          max_const_bytes: int = DEFAULT_MAX_CONST_BYTES,
                          ) -> List[AuditIssue]:
    """Audit the decode sub-programs (see `decode_programs`)."""
    issues: List[AuditIssue] = []
    for name, fn, args, static in decode_programs(params, geom, batch):
        issues += audit_fn(fn, *args, name=f"decode.{name}",
                           static_argnums=static, checks=checks,
                           max_const_bytes=max_const_bytes)
    return issues


def train_step_args(step, *batch):
    """Assemble the example argument tuple for a jit.TrainStep's raw
    step — the same assembly as TrainStep._dispatch, without running
    anything. Shared by the trace-time audit and jaxcost."""
    from ..core.tensor import Tensor

    params_t, frozen_t, buffers_t = step._collect_state()
    params = {k: p._value for k, p in params_t}
    frozen = {k: p._value for k, p in frozen_t}
    buffers = {k: b._value for k, b in buffers_t}
    opt_state = step._opt_state
    if opt_state is None:
        opt_state = step.optimizer.init_opt_state(params)
    lr = jnp.asarray(float(step.optimizer.get_lr()), jnp.float32)
    key_root = step._key_root
    if key_root is None:
        key_root = jax.random.PRNGKey(0)
    rng_ctr = jnp.asarray(1, jnp.uint32)
    arr = [a._value if isinstance(a, Tensor) else jnp.asarray(a)
           for a in batch]
    return (params, frozen, buffers, opt_state, lr, key_root, rng_ctr,
            *arr)


def audit_train_step(step, *batch,
                     checks: Sequence[str] = DEFAULT_CHECKS,
                     max_const_bytes: int = DEFAULT_MAX_CONST_BYTES,
                     ) -> List[AuditIssue]:
    """Audit a jit.TrainStep's full compiled program (fwd + bwd +
    optimizer) against an example batch, mirroring the argument
    assembly of TrainStep._dispatch without running the step."""
    return audit_fn(step._raw_step, *train_step_args(step, *batch),
                    name=type(step).__name__, checks=checks,
                    max_const_bytes=max_const_bytes)
