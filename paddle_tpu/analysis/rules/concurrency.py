"""Lock-discipline lint (PT-C001): `_GUARDED_BY`-annotated fields must
only be touched while holding their lock.

A class opts in by declaring, as a class attribute, a dict literal
mapping field names to the lock attribute that guards them:

    class LLMEngine:
        _GUARDED_BY = {
            "_requests": "_lock",
            "_pending_outputs": "_lock",
        }

Inside that class, every read or write of ``self.<field>`` for a field
in the map must be lexically inside ``with self.<lock>:`` (or a with
statement over a local alias of it), OR inside a method decorated
``@holds_lock("<lock>")`` (the runtime no-op from paddle_tpu.analysis
— a promise that every caller takes the lock first). ``__init__`` is
exempt: construction happens before the object is shared.

The check is lexical, per-method, and intra-class — it does not chase
aliases of self or cross-class access. That keeps it sound on the
serving engine's actual shape (public entry points lock, helpers are
annotated) without a whole-program escape analysis.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from ..ast_core import Finding, ModuleContext, Rule

__all__ = ["LockDisciplineRule", "CONCURRENCY_RULES"]

CONCURRENCY_RULES = {
    "PT-C001": ("error",
                "access to a _GUARDED_BY field without holding its lock"),
}

_HOLDS_NAMES = {"holds_lock", "analysis.holds_lock"}
_EXEMPT_METHODS = {"__init__", "__new__", "__repr__", "__del__"}


def _dotted(node) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _guarded_map(cls: ast.ClassDef) -> Dict[str, str]:
    """Extract the `_GUARDED_BY = {...}` dict literal, if any."""
    for stmt in cls.body:
        targets = []
        value = None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "_GUARDED_BY" \
                    and isinstance(value, ast.Dict):
                out: Dict[str, str] = {}
                for k, v in zip(value.keys, value.values):
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, str) \
                            and isinstance(v, ast.Constant) \
                            and isinstance(v.value, str):
                        out[k.value] = v.value
                return out
    return {}


def _held_by_decorator(fn: ast.FunctionDef) -> Set[str]:
    """Locks promised held via @holds_lock("_lock", ...)."""
    held: Set[str] = set()
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call):
            name = _dotted(dec.func)
            if name and name.split(".")[-1] == "holds_lock":
                for a in dec.args:
                    if isinstance(a, ast.Constant) \
                            and isinstance(a.value, str):
                        held.add(a.value)
    return held


class LockDisciplineRule(Rule):
    ids = tuple(CONCURRENCY_RULES)

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                guarded = _guarded_map(node)
                if guarded:
                    self._check_class(ctx, node, guarded, findings)
        return findings

    def _check_class(self, ctx: ModuleContext, cls: ast.ClassDef,
                     guarded: Dict[str, str],
                     findings: List[Finding]):
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if stmt.name in _EXEMPT_METHODS:
                    continue
                held0 = _held_by_decorator(stmt)
                self._scan(ctx, cls, stmt, stmt.body, guarded,
                           held0, findings, {})

    def _scan(self, ctx: ModuleContext, cls: ast.ClassDef,
              method: ast.FunctionDef, body: List[ast.stmt],
              guarded: Dict[str, str], held: Set[str],
              findings: List[Finding],
              aliases: Optional[Dict[str, str]] = None):
        """Walk statements tracking the set of held locks lexically.
        `aliases` maps local names to the lock attr they alias
        (`lk = self._lock; l2 = lk` makes both keys map to '_lock')."""
        aliases = {} if aliases is None else aliases
        for stmt in body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                # track lock aliases through chains of any length; a
                # non-alias assignment to the same name shadows it
                lock = self._lock_of(stmt.value, aliases) \
                    if isinstance(stmt.value, (ast.Name, ast.Attribute)) \
                    else None
                if lock is not None and lock in set(guarded.values()):
                    aliases[stmt.targets[0].id] = lock
                else:
                    aliases.pop(stmt.targets[0].id, None)
                self._check_expr(ctx, method, stmt.value, guarded, held,
                                 findings)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                newly = set()
                for item in stmt.items:
                    lock = self._lock_of(item.context_expr, aliases)
                    if lock is not None:
                        newly.add(lock)
                    # the with-item expression itself (e.g. self._lock)
                    # is a lock attribute, not guarded data — no check
                self._scan(ctx, cls, method, stmt.body, guarded,
                           held | newly, findings, aliases)
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested def: runs later, with no lock guarantee
                self._scan(ctx, cls, method, stmt.body, guarded,
                           _held_by_decorator(stmt), findings, {})
                continue
            if isinstance(stmt, ast.Try):
                for blk in (stmt.body, stmt.orelse, stmt.finalbody):
                    self._scan(ctx, cls, method, blk, guarded, held,
                               findings, aliases)
                for h in stmt.handlers:
                    if h.type is not None:
                        self._check_expr(ctx, method, h.type, guarded,
                                         held, findings)
                    self._scan(ctx, cls, method, h.body, guarded, held,
                               findings, aliases)
                continue
            # compound statements: recurse into sub-blocks with the
            # same held set, and check expressions hanging off them
            for field_name, value in ast.iter_fields(stmt):
                if isinstance(value, list) and value and \
                        isinstance(value[0], ast.stmt):
                    self._scan(ctx, cls, method, value, guarded,
                               held, findings, aliases)
                elif isinstance(value, list):
                    for v in value:
                        if isinstance(v, ast.AST):
                            self._check_expr(ctx, method, v, guarded,
                                             held, findings)
                elif isinstance(value, ast.AST):
                    self._check_expr(ctx, method, value, guarded,
                                     held, findings)

    def _lock_of(self, expr,
                 aliases: Optional[Dict[str, str]] = None
                 ) -> Optional[str]:
        """`with self._lock:` → '_lock' (also unwraps common wrappers
        like `self._lock.acquire_timeout(...)` call expressions and
        local aliases recorded by _scan)."""
        if isinstance(expr, ast.Name) and aliases:
            return aliases.get(expr.id)
        name = _dotted(expr)
        if name and name.startswith("self."):
            return name[len("self."):]
        if isinstance(expr, ast.Call):
            return self._lock_of(expr.func, aliases)
        if isinstance(expr, ast.Attribute):
            return self._lock_of(expr.value, aliases)
        return None

    def _check_expr(self, ctx: ModuleContext, method: ast.FunctionDef,
                    expr: ast.AST, guarded: Dict[str, str],
                    held: Set[str], findings: List[Finding]):
        for node in ast.walk(expr):
            if isinstance(node, ast.Lambda):
                continue
            if not isinstance(node, ast.Attribute):
                continue
            if not (isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                continue
            lock = guarded.get(node.attr)
            if lock is None or lock in held:
                continue
            findings.append(ctx.finding(
                "PT-C001", node,
                f"'self.{node.attr}' is _GUARDED_BY '{lock}' but "
                f"'{method.name}' accesses it without holding the lock; "
                f"wrap in `with self.{lock}:` or mark the method "
                f"@holds_lock(\"{lock}\") and lock in every caller",
                severity="error"))
