"""Sharding-policy rules: PartitionSpec layouts belong to the shard
plan, not to call sites.

PR 19 introduced jaxshard (analysis/jaxshard.py): per-program sharding
layouts are abstract-interpreted, triaged, and committed to
shardplan.json. A literal `P(...)` handed straight to a sharding
consumer (`with_sharding_constraint`, `NamedSharding`, `shard_map`
in/out specs, jit in/out shardings, `device_put`) forks that policy at
the call site — the plan gate keeps passing while the program lays
tensors out some other way. And a mesh-axis name that no enclosing
mesh defines ("tpx" for "tp") silently no-ops: GSPMD treats the dim as
unsharded and the program replicates. Two rules make both visible:

  PT-S001  literal PartitionSpec at a sharding call site (route the
           layout through the committed shard plan, or suppress with
           a reason)
  PT-S002  mesh-axis name used in a spec but absent from every mesh
           the enclosing module can build

Taint-style propagation (same discipline as the trace-safety rules):
`spec = P(None, None, "sp", None)` followed by
`shard_map(..., in_specs=(spec,))` fires PT-S001 at the ASSIGNMENT —
the layout decision — so the suppression reason lives where the spec
is chosen. Bare `P()` (replicated) is exempt: replication is the
absence of a layout decision. As with PT-T009, the suppression IS the
workflow: the sanctioned plumbing layers (parallel/mesh.py,
parallel/api.py, distributed/tp_layers.py) and the jaxshard registry
itself carry `# ptlint: disable=PT-S001` comments explaining why they
are the mechanism rather than a policy fork.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..ast_core import Finding, ModuleContext, Rule
from .trace_safety import _dotted

__all__ = ["ShardingPolicyRule", "SHARDING_RULES"]

SHARDING_RULES = {
    "PT-S001": ("error",
                "literal PartitionSpec at a sharding call site (bypass "
                "of the committed shard plan)"),
    "PT-S002": ("error",
                "mesh-axis name used in a PartitionSpec but absent "
                "from every mesh the module can build"),
}

#: the canonical mesh vocabulary: parallel/mesh.py build_mesh axes.
#: Modules that construct no mesh of their own (they run under the
#: global mesh) are checked against this set.
_BUILD_MESH_AXES = frozenset({"dp", "pp", "sharding", "sp", "ep", "tp"})

#: callee tails that consume a layout
_CONSUMER_TAILS = frozenset({
    "with_sharding_constraint", "NamedSharding", "shard_map",
    "device_put", "named_sharding",
})
#: keywords that consume a layout on ANY call (jit, shard_map, ...)
_CONSUMER_KWARGS = frozenset({
    "in_shardings", "out_shardings", "in_specs", "out_specs",
    "sharding", "shardings", "device",
})


def _is_pspec_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = _dotted(node.func)
    if name is None:
        return False
    return name.split(".")[-1] in ("P", "PartitionSpec")


def _nonempty_pspec(node: ast.Call) -> bool:
    """Bare P() carries no layout decision; P(None) and friends do
    (an explicit every-dim-replicated pin is still a decision).
    `P(*spec)` is exempt too: a starred forward passes on a spec the
    call site did not choose — the decision lives upstream."""
    args = [a for a in node.args if not isinstance(a, ast.Starred)]
    return bool(args or node.keywords)


def _spec_axis_names(node: ast.Call) -> Iterable[Tuple[str, ast.AST]]:
    """String mesh-axis names inside one P(...) literal, with the node
    carrying each (axes may sit inside per-dim tuples)."""
    def walk(n):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            yield n.value, n
        elif isinstance(n, (ast.Tuple, ast.List)):
            for e in n.elts:
                yield from walk(e)

    for a in node.args:
        yield from walk(a)
    for kw in node.keywords:
        if kw.arg is None:
            continue
        yield from walk(kw.value)


def _module_mesh_axes(tree: ast.Module) -> Tuple[Set[str], bool]:
    """(axis names of every mesh this module builds, found_any).
    Recognizes `Mesh(devs, ("a", "b"))` / `Mesh(..., axis_names=...)`
    literals and `build_mesh(dp=4, tp=2)` kwarg names."""
    axes: Set[str] = set()
    found = False

    def strings(n) -> Iterable[str]:
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            yield n.value
        elif isinstance(n, (ast.Tuple, ast.List)):
            for e in n.elts:
                yield from strings(e)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func)
        tail = name.split(".")[-1] if name else ""
        if tail == "Mesh":
            cands = list(node.args[1:2]) + [
                kw.value for kw in node.keywords
                if kw.arg == "axis_names"]
            for c in cands:
                got = set(strings(c))
                if got:
                    axes |= got
                    found = True
        elif tail == "build_mesh":
            got = {kw.arg for kw in node.keywords
                   if kw.arg and kw.arg != "devices"}
            if got:
                axes |= got & _BUILD_MESH_AXES
                found = True
    return axes, found


class ShardingPolicyRule(Rule):
    """PT-S001 (literal spec at a consumer) + PT-S002 (unknown axis)."""

    ids = tuple(SHARDING_RULES)

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        mesh_axes, found_mesh = _module_mesh_axes(ctx.tree)
        # a module that builds its own mesh may also still run pieces
        # under the global build_mesh mesh — the union is the set of
        # names that can possibly bind
        known_axes = mesh_axes | _BUILD_MESH_AXES

        # ---- PT-S002: every axis name in every spec literal
        sev2 = SHARDING_RULES["PT-S002"][0]
        for node in ast.walk(ctx.tree):
            if not _is_pspec_call(node):
                continue
            for axis, anchor in _spec_axis_names(node):
                if axis not in known_axes:
                    where = ("meshes built here define "
                             f"{sorted(mesh_axes)}" if found_mesh
                             else "no mesh is built in this module; "
                                  "build_mesh axes are "
                                  f"{sorted(_BUILD_MESH_AXES)}")
                    findings.append(ctx.finding(
                        "PT-S002", anchor,
                        f"axis {axis!r} is not a mesh axis any "
                        f"enclosing mesh defines ({where}) — GSPMD "
                        f"silently treats the dim as unsharded",
                        severity=sev2))

        # ---- PT-S001: spec literals consumed by sharding call sites
        sev1 = SHARDING_RULES["PT-S001"][0]
        emitted: Set[int] = set()

        def emit(anchor, how: str):
            if id(anchor) in emitted:
                return
            emitted.add(id(anchor))
            findings.append(ctx.finding(
                "PT-S001", anchor,
                f"literal PartitionSpec {how}: layouts are planned "
                f"and committed (analysis/jaxshard.py -> "
                f"shardplan.json); consume the plan's layout or "
                f"suppress with a reason", severity=sev1))

        # taint sources: name = <expr containing a nonempty P literal>,
        # recorded per enclosing function scope (module counts as one)
        scopes: List[Tuple[ast.AST, List[ast.stmt]]] = [
            (ctx.tree, list(ctx.tree.body))]
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append((node, list(node.body)))

        for scope, _body in scopes:
            tainted: Dict[str, ast.AST] = {}
            for node in _scope_walk(scope):
                if isinstance(node, ast.Assign):
                    lits = [n for n in ast.walk(node.value)
                            if _is_pspec_call(n) and _nonempty_pspec(n)]
                    if lits:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                tainted[t.id] = node
            if not tainted:
                continue
            for node in _scope_walk(scope):
                if not isinstance(node, ast.Call):
                    continue
                if not _is_consumer(node):
                    continue
                for arg in _consumed_exprs(node):
                    for n in ast.walk(arg):
                        if isinstance(n, ast.Name) and n.id in tainted:
                            emit(tainted[n.id],
                                 f"assigned here reaches "
                                 f"{_callee_label(node)}")

        # direct literals inside a consumer's arguments
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not _is_consumer(node):
                continue
            for arg in _consumed_exprs(node):
                for n in ast.walk(arg):
                    if _is_pspec_call(n) and _nonempty_pspec(n):
                        emit(n, f"passed to {_callee_label(node)}")
        return findings


def _scope_walk(scope: ast.AST):
    """Walk a function scope WITHOUT descending into nested defs (each
    nested def is its own scope entry)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _is_consumer(call: ast.Call) -> bool:
    name = _dotted(call.func)
    tail = name.split(".")[-1] if name else ""
    if tail in _CONSUMER_TAILS:
        return True
    return any(kw.arg in _CONSUMER_KWARGS for kw in call.keywords)


def _consumed_exprs(call: ast.Call) -> Iterable[ast.AST]:
    name = _dotted(call.func)
    tail = name.split(".")[-1] if name else ""
    if tail in _CONSUMER_TAILS:
        yield from call.args
    for kw in call.keywords:
        if kw.arg in _CONSUMER_KWARGS or tail in _CONSUMER_TAILS:
            yield kw.value


def _callee_label(call: ast.Call) -> str:
    name = _dotted(call.func)
    if name:
        return f"'{name}(...)'"
    kws = [kw.arg for kw in call.keywords if kw.arg in _CONSUMER_KWARGS]
    return f"a call with {'/'.join(kws) or 'sharding'} keywords"
