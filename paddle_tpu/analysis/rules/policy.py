"""Policy-centralization rule: remat and donation decisions belong to
the planner, not to call sites.

PR 8 introduced jaxplan (analysis/jaxplan.py): remat policy and
donate_argnums are *planned* from the static cost model and committed
to jaxplan.json, then consumed via `use_recompute="auto"` and
`jaxplan.planned_donation(...)`. A hand-set `use_recompute=True`, a
manual `jax.checkpoint(...)`, or a literal `donate_argnums=(...)` on a
jit construction silently forks that policy — the plan gate keeps
passing while the program runs something else. Such sites are legal
only with a reasoned suppression, so every divergence from the planner
is visible and justified in place:

  PT-T009  hand-set remat/donation policy at a call site (use the
           planner, or suppress with a reason)

The suppression IS the workflow: the sanctioned implementation layer
(fleet.utils.recompute — the primitive the planner itself lowers to)
and structural remat (pipeline microbatching) carry
`# ptlint: disable=PT-T009` comments explaining why they are the
mechanism rather than a policy fork.
"""
from __future__ import annotations

import ast
from typing import Iterable, List

from ..ast_core import Finding, ModuleContext, Rule
from .trace_safety import _dotted, _is_jit_callee, _jit_partial

__all__ = ["PolicyCentralizationRule", "POLICY_RULES"]

POLICY_RULES = {
    "PT-T009": ("error",
                "hand-set remat/donation policy at a call site (bypass "
                "of the jaxplan planner)"),
}

# remat entry points whose direct use hard-codes a remat decision
_REMAT_CALLEES = {"jax.checkpoint", "jax.remat"}


class PolicyCentralizationRule(Rule):
    """Module-wide scan for hand-set remat/donation policy (PT-T009)."""

    ids = tuple(POLICY_RULES)

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        sev = POLICY_RULES["PT-T009"][0]

        def emit(node, message):
            findings.append(
                ctx.finding("PT-T009", node, message, severity=sev))

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                name = _dotted(node.func)
                # manual jax.checkpoint/jax.remat
                if name in _REMAT_CALLEES:
                    emit(node,
                         f"manual '{name}(...)': remat policy is chosen "
                         f"by the planner (analysis/jaxplan.py, "
                         f"use_recompute='auto'); route through the "
                         f"planned policy or suppress with a reason")
                # hand-set use_recompute=True at a construction site
                for kw in node.keywords:
                    if kw.arg == "use_recompute" \
                            and isinstance(kw.value, ast.Constant) \
                            and kw.value.value is True:
                        emit(kw.value,
                             "use_recompute=True hard-codes remat on; "
                             "use 'auto' (committed plan) or an explicit "
                             "planner policy string, or suppress with a "
                             "reason")
                # literal donate_argnums on a jit construction
                if _is_jit_callee(name) or _jit_partial(node) is not None:
                    for kw in node.keywords:
                        if kw.arg == "donate_argnums" and isinstance(
                                kw.value,
                                (ast.Tuple, ast.List, ast.Constant)):
                            emit(kw.value,
                                 "literal donate_argnums on a jit "
                                 "construction: donation sets are "
                                 "planned (jaxplan.planned_donation) "
                                 "and audited; consume the plan or "
                                 "suppress with a reason")
            # hand-set cfg.use_recompute = True after construction
            elif isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Constant) \
                    and node.value.value is True:
                for t in node.targets:
                    if isinstance(t, ast.Attribute) \
                            and t.attr == "use_recompute":
                        emit(node,
                             "use_recompute=True hard-codes remat on; "
                             "use 'auto' (committed plan) or suppress "
                             "with a reason")
        return findings
