"""ptlint rule registry.

RULE_CATALOG maps every rule id to (severity, one-line description);
docs/static_analysis.md is the narrative catalog. default_rules() is
what the engine and CLI run when no explicit rule set is given.
"""
from __future__ import annotations

from .concurrency import CONCURRENCY_RULES, LockDisciplineRule
from .lockorder import LOCKORDER_RULES, LockOrderRule
from .numerics import NUMERICS_RULES, NumericsCastRule
from .policy import POLICY_RULES, PolicyCentralizationRule
from .sharding import SHARDING_RULES, ShardingPolicyRule
from .trace_safety import TRACE_RULES, TraceSafetyRule

__all__ = ["RULE_CATALOG", "default_rules", "TraceSafetyRule",
           "LockDisciplineRule", "LockOrderRule",
           "PolicyCentralizationRule", "ShardingPolicyRule",
           "NumericsCastRule"]

RULE_CATALOG = {**TRACE_RULES, **CONCURRENCY_RULES, **LOCKORDER_RULES,
                **POLICY_RULES, **SHARDING_RULES, **NUMERICS_RULES}


def default_rules():
    return [TraceSafetyRule(), LockDisciplineRule(), LockOrderRule(),
            PolicyCentralizationRule(), ShardingPolicyRule(),
            NumericsCastRule()]
