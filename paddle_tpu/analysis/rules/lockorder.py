"""Per-module front end for the lockgraph analyzer (PT-C002..C004).

The real analysis is whole-program (paddle_tpu/analysis/lockgraph.py,
driven by tools/lockgraph.py against the committed lockgraph.json); this
Rule runs the same engine over ONE module at a time so the three rules
participate in the ordinary ptlint pipeline — fixtures, suppressions,
baseline, `--select PT-C003` — without the CLI.

In single-module mode the declared order comes from a module-level

    _LOCK_ORDER = ["Outer._lock", "Inner._lock", ...]

literal (outermost first), which is how the tests/data/ptlint fixtures
declare theirs. A module with no such literal is checked for blocking
calls and callback escapes (PT-C003/PT-C004 need no declared order) and
for acquisition CYCLES, but edges cannot invert an order that was never
declared — so repo modules without the literal stay quiet on PT-C002
and the committed lockgraph.json remains the single source of truth for
the fleet-wide order.
"""
from __future__ import annotations

from typing import Iterable, List

from ..ast_core import Finding, ModuleContext, Rule
from ..lockgraph import (LOCKGRAPH_RULES, LockGraphProgram, LockModel,
                         _infile_order)

__all__ = ["LockOrderRule", "LOCKORDER_RULES"]

LOCKORDER_RULES = dict(LOCKGRAPH_RULES)


class LockOrderRule(Rule):
    ids = tuple(LOCKORDER_RULES)

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        order = _infile_order(ctx.tree)
        prog = LockGraphProgram()
        prog.add_module(ctx.path, ctx.source, tree=ctx.tree)
        model = LockModel(order=order)
        findings: List[Finding] = prog.analyze(model)
        if not order:
            # no declared order -> every edge would be "undeclared";
            # keep only rank-independent findings (cycles, blocking,
            # callbacks) so undeclared modules aren't noise
            findings = [f for f in findings
                        if f.rule != "PT-C002"
                        or "cycle" in f.message]
        return findings
