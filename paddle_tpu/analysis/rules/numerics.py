"""Numerics-policy rule: lossy storage casts belong to the sanctioned
cast helpers, not to call sites.

PR 20 introduced jaxnum (analysis/jaxnum.py): per-program numerics are
abstract-interpreted, error bounds derived, findings triaged, and the
result committed to numplan.json. A literal sub-32-bit `astype` /
`dtype=` at an arbitrary call site forks that policy the same way a
literal PartitionSpec forks the shard plan: the committed precision
plan keeps passing while some tensor quietly loses mantissa (or wraps)
outside any analyzed program.

  PT-N001  literal lossy dtype (`float16`/`bfloat16`/`int8`/...)
           consumed by `.astype(...)` or a `dtype=` keyword outside a
           sanctioned cast helper (route the cast through amp
           (amp/auto_cast.py, static/amp.py), the quantization ops
           (ops/quant_ops.py), or the KV codec
           (inference/serving/kv_quant.py) — or suppress with a
           reason)

Taint-style propagation (the PT-S001 discipline): `dt = jnp.bfloat16`
followed by `x.astype(dt)` fires at the ASSIGNMENT — the precision
decision — so the suppression reason lives where the dtype is chosen.
32-bit-and-wider dtypes (`float32`, `int32`, `float64`, ...) are
exempt: the package runs with jax_enable_x64, so down-to-32 converts
are the deliberate norm (the same boundary as jaxnum's
`lossy_float_downcast`). The sanctioned helpers themselves carry
`# ptlint: disable=PT-N001` comments explaining why they are the
mechanism rather than a policy fork.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from ..ast_core import Finding, ModuleContext, Rule
from .trace_safety import _dotted

__all__ = ["NumericsCastRule", "NUMERICS_RULES"]

NUMERICS_RULES = {
    "PT-N001": ("error",
                "literal lossy dtype at an astype/dtype= call site "
                "(bypass of the committed precision plan)"),
}

#: sub-32-bit storage names — the same boundary jaxnum's
#: lossy_float_downcast / lossy_int_narrowing draw (duplicated as
#: strings because the lint core is stdlib-only and cannot import the
#: jax-backed lattice)
_LOSSY_DTYPES = frozenset({
    "float16", "bfloat16", "half", "int8", "uint8", "int16", "uint16",
})


def _lossy_name(name: str) -> bool:
    return name in _LOSSY_DTYPES or name.startswith("float8")


def _is_lossy_literal(node: ast.AST) -> bool:
    """A dtype literal that names sub-32-bit storage: a string
    constant ("bfloat16") or a dotted attribute whose tail is one
    (jnp.bfloat16, np.float16, ml_dtypes.float8_e4m3fn)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return _lossy_name(node.value)
    if isinstance(node, (ast.Attribute, ast.Name)):
        name = _dotted(node)
        if name:
            return _lossy_name(name.split(".")[-1])
    return False


def _lossy_literals(expr: ast.AST) -> List[ast.AST]:
    return [n for n in ast.walk(expr) if _is_lossy_literal(n)]


def _consumed_exprs(call: ast.Call) -> Iterable[ast.AST]:
    """The expressions a call consumes as a dtype: every argument of
    an `.astype(...)` method call, and any `dtype=` keyword."""
    if isinstance(call.func, ast.Attribute) and \
            call.func.attr == "astype":
        yield from call.args
    for kw in call.keywords:
        if kw.arg == "dtype":
            yield kw.value


def _callee_label(call: ast.Call) -> str:
    if isinstance(call.func, ast.Attribute) and \
            call.func.attr == "astype":
        return "'.astype(...)'"
    name = _dotted(call.func)
    return f"'{name}(dtype=...)'" if name else "a dtype= keyword"


class NumericsCastRule(Rule):
    """PT-N001: literal lossy dtype reaching an astype/dtype= site."""

    ids = tuple(NUMERICS_RULES)

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        sev = NUMERICS_RULES["PT-N001"][0]
        emitted: Set[int] = set()

        def emit(anchor, how: str):
            if id(anchor) in emitted:
                return
            emitted.add(id(anchor))
            findings.append(ctx.finding(
                "PT-N001", anchor,
                f"literal lossy dtype {how}: sub-32-bit precision is "
                f"planned and committed (analysis/jaxnum.py -> "
                f"numplan.json); route the cast through a sanctioned "
                f"helper (amp, ops/quant_ops.py, kv_quant.py) or "
                f"suppress with a reason", severity=sev))

        # taint sources: name = <expr containing a lossy dtype
        # literal>, recorded per enclosing function scope (module
        # counts as one scope)
        scopes: List[ast.AST] = [ctx.tree]
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append(node)

        for scope in scopes:
            tainted: Dict[str, ast.AST] = {}
            for node in _scope_walk(scope):
                if isinstance(node, ast.Assign) and \
                        _lossy_literals(node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            tainted[t.id] = node
            if not tainted:
                continue
            for node in _scope_walk(scope):
                if not isinstance(node, ast.Call):
                    continue
                for arg in _consumed_exprs(node):
                    for n in ast.walk(arg):
                        if isinstance(n, ast.Name) and n.id in tainted:
                            emit(tainted[n.id],
                                 f"assigned here reaches "
                                 f"{_callee_label(node)}")

        # direct literals inside a consumer's dtype expressions
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            for arg in _consumed_exprs(node):
                for n in _lossy_literals(arg):
                    emit(n, f"passed to {_callee_label(node)}")
        return findings


def _scope_walk(scope: ast.AST):
    """Walk a function scope WITHOUT descending into nested defs (each
    nested def is its own scope entry)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))
