"""Trace-safety rules: keep jitted programs pure and recompile-free.

The defect class: jax.jit hard-stages Python at trace time, so Python
constructs that LOOK innocent change meaning under trace — a branch on
a traced value either throws (ConcretizationTypeError) or silently
specializes; `.item()`/`np.asarray` forces a device→host sync inside
the hot program; list/global mutation runs ONCE at trace time and then
never again; a `jax.jit(...)` constructed per call throws the compile
cache away every step ("Operator Fusion in XLA", PAPERS.md, measures
how much semantics/perf ride on stable compiled programs).

Rules (catalog in docs/static_analysis.md):

  PT-T001  tracer-dependent Python branching (if/while/assert/ternary
           on a value derived from traced arguments)
  PT-T002  host materialization under trace (.item()/.tolist()/
           .numpy()/float()/int()/bool()/np.* on traced values,
           jax.device_get)
  PT-T003  Python side effects under trace (mutating closure/global/
           self state from inside a traced function)
  PT-T004  jit constructed inside a function or loop body (recompile
           churn; exempt: module scope, `self.attr = jax.jit(...)`
           one-time bindings, lru_cache-memoized factories)
  PT-T005  unhashable static args (static_argnums/static_argnames
           pointing at list/dict/set parameters or call sites)
  PT-T006  host RNG under trace (np.random.* / stdlib random.* inside
           a traced scope — trace-time constants, NOT per-call
           randomness; use jax.random with a threaded key)
  PT-T007  per-iteration host sync in a HOST-side loop
           (.block_until_ready() / jax.device_get / np.asarray of a
           device value inside for/while — each iteration stalls the
           dispatch pipeline; hoist the sync out of the loop or batch
           the transfers)

Scope marking is lexical and conservative: a function is "traced" when
it is decorated with jax.jit (directly or via functools.partial), is
passed by name to jax.jit / jax.vmap / grad / lax control flow, or is
bound to `self.attr` and jitted through that attribute — plus every
def nested inside one. Taint starts at the traced function's
parameters (minus static_argnums/static_argnames) and flows through
assignments; shape/dtype metadata (`x.shape`, `x.ndim`, `x.dtype`,
`len(x)`, `isinstance(x, ...)`) is static under jax tracing and
deliberately does NOT taint, so shape-polymorphic branching stays
legal. Cross-module calls are not followed — helpers called FROM a
traced scope with tainted values are each rule's blind spot, kept so
the zero-findings gate stays free of false positives.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..ast_core import Finding, ModuleContext, Rule

__all__ = ["TraceSafetyRule", "TRACE_RULES"]

TRACE_RULES = {
    "PT-T001": ("error",
                "tracer-dependent Python branching inside a jitted scope"),
    "PT-T002": ("error",
                "host materialization of a traced value inside a jitted "
                "scope"),
    "PT-T003": ("warning",
                "Python side effect (closure/global/attribute mutation) "
                "inside a jitted scope"),
    "PT-T004": ("warning",
                "jax.jit constructed inside a function or loop body "
                "(recompile churn)"),
    "PT-T005": ("error",
                "unhashable value routed through static_argnums/"
                "static_argnames"),
    "PT-T006": ("error",
                "host RNG (np.random/stdlib random) inside a jitted "
                "scope"),
    "PT-T007": ("warning",
                "per-iteration host sync (.block_until_ready/device_get/"
                "np.asarray of a device value) inside a host-side loop"),
}

# attribute reads that are static under jax tracing (never taint)
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "weak_type",
                 "sharding", "itemsize", "nbytes"}
# calls whose result is static under jax tracing
_STATIC_CALLS = {"len", "isinstance", "type", "getattr", "hasattr", "id",
                 "repr", "str", "issubclass", "callable", "range",
                 "enumerate", "zip"}
# host materialization method names (device → host sync under trace)
_HOST_METHODS = {"item", "tolist", "numpy", "block_until_ready",
                 "copy_to_host_async"}
_HOST_BUILTINS = {"float", "int", "bool", "complex"}
# in-place mutators for the side-effect rule
_MUTATORS = {"append", "extend", "insert", "add", "update", "pop",
             "popitem", "remove", "discard", "clear", "setdefault",
             "sort", "reverse", "appendleft", "popleft", "extendleft"}
_MEMO_DECORATORS = {"lru_cache", "cache", "functools.lru_cache",
                    "functools.cache"}


def _dotted(node) -> Optional[str]:
    """Best-effort dotted name of an expression ('jax.lax.scan',
    'self._step_fn'); None when it isn't a plain name chain."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _is_jit_callee(name: Optional[str]) -> bool:
    return name in ("jax.jit", "jit") or (
        name is not None and name.endswith(".jit"))


def _is_trace_wrapper(name: Optional[str]) -> bool:
    """Callables whose function argument gets traced."""
    if name is None:
        return False
    if _is_jit_callee(name):
        return True
    tail = name.split(".")[-1]
    return tail in ("vmap", "pmap", "grad", "value_and_grad", "make_jaxpr",
                    "checkpoint", "remat", "scan", "cond", "while_loop",
                    "fori_loop", "switch", "map", "associative_scan",
                    "custom_jvp", "custom_vjp", "shard_map")


def _jit_partial(call: ast.Call) -> Optional[ast.Call]:
    """For `functools.partial(jax.jit, ...)` returns the partial call."""
    name = _dotted(call.func)
    if name in ("functools.partial", "partial") and call.args:
        if _is_jit_callee(_dotted(call.args[0])):
            return call
    return None


def _static_names_from_call(call: ast.Call, fn: ast.FunctionDef
                            ) -> Set[str]:
    """Resolve static_argnums/static_argnames of a jit construction to
    parameter NAMES of the target def."""
    statics: Set[str] = set()
    posargs = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            for n in _int_values(kw.value):
                if 0 <= n < len(posargs):
                    statics.add(posargs[n])
        elif kw.arg == "static_argnames":
            for s in _str_values(kw.value):
                statics.add(s)
    return statics


def _int_values(node) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            out.extend(_int_values(e))
        return out
    return []


def _str_values(node) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            out.extend(_str_values(e))
        return out
    return []


class _FuncInfo:
    def __init__(self, node: ast.FunctionDef, parent: Optional["_FuncInfo"],
                 cls: Optional[ast.ClassDef]):
        self.node = node
        self.parent = parent
        self.cls = cls
        self.traced = False
        self.static_params: Set[str] = set()
        self.children: List["_FuncInfo"] = []
        # names bound anywhere in this def (params, assigns, for/with
        # targets, nested defs, imports) — the side-effect rule's notion
        # of "local"
        self.local_names: Set[str] = _bound_names(node)
        self.memoized = any(
            _dotted(d) in _MEMO_DECORATORS
            or (isinstance(d, ast.Call) and _dotted(d.func)
                in _MEMO_DECORATORS)
            for d in node.decorator_list)


def _bound_names(fn: ast.FunctionDef) -> Set[str]:
    names: Set[str] = set()
    a = fn.args
    for arg in (a.posonlyargs + a.args + a.kwonlyargs):
        names.add(arg.arg)
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)

    class V(ast.NodeVisitor):
        def _target(self, t):
            if isinstance(t, ast.Name):
                names.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    self._target(e)
            elif isinstance(t, ast.Starred):
                self._target(t.value)

        def visit_Assign(self, node):
            for t in node.targets:
                self._target(t)
            self.generic_visit(node)

        def visit_AnnAssign(self, node):
            self._target(node.target)
            self.generic_visit(node)

        def visit_AugAssign(self, node):
            self._target(node.target)
            self.generic_visit(node)

        def visit_NamedExpr(self, node):
            self._target(node.target)
            self.generic_visit(node)

        def visit_For(self, node):
            self._target(node.target)
            self.generic_visit(node)

        def visit_withitem(self, node):
            if node.optional_vars is not None:
                self._target(node.optional_vars)
            self.generic_visit(node)

        def visit_FunctionDef(self, node):
            names.add(node.name)
            # do not recurse: nested defs bind their own scope

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            pass

        def visit_comprehension(self, node):
            self._target(node.target)
            self.generic_visit(node)

    for stmt in fn.body:
        V().visit(stmt)
    return names


class TraceSafetyRule(Rule):
    """One analysis pass per module emitting PT-T001..PT-T007."""

    ids = tuple(TRACE_RULES)

    # ------------------------------------------------------------- driver
    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        self.ctx = ctx
        self.findings: List[Finding] = []
        self.funcs: Dict[ast.FunctionDef, _FuncInfo] = {}
        self._index_functions(ctx.tree)
        self._mark_traced_roots(ctx.tree)
        self._check_jit_construction(ctx.tree)      # PT-T004 / PT-T005
        self._check_static_defaults()               # PT-T005 on defaults
        self._check_callsite_statics()              # PT-T005 at call sites
        for info in self.funcs.values():
            if info.traced and (info.parent is None
                                or not info.parent.traced):
                self._check_traced_unit(info)       # PT-T001/2/3/6
        self._check_host_loop_syncs(ctx.tree)       # PT-T007
        return self.findings

    def _emit(self, rule_id: str, node, message: str):
        sev = TRACE_RULES[rule_id][0]
        self.findings.append(
            self.ctx.finding(rule_id, node, message, severity=sev))

    # -------------------------------------------------------- function map
    def _index_functions(self, tree: ast.Module):
        rule = self

        class V(ast.NodeVisitor):
            def __init__(self):
                self.stack: List[_FuncInfo] = []
                self.cls: List[ast.ClassDef] = []

            def visit_ClassDef(self, node):
                self.cls.append(node)
                self.generic_visit(node)
                self.cls.pop()

            def visit_FunctionDef(self, node):
                info = _FuncInfo(node,
                                 self.stack[-1] if self.stack else None,
                                 self.cls[-1] if self.cls else None)
                if info.parent is not None:
                    info.parent.children.append(info)
                rule.funcs[node] = info
                self.stack.append(info)
                self.generic_visit(node)
                self.stack.pop()

            visit_AsyncFunctionDef = visit_FunctionDef

        V().visit(tree)

    def _resolve_def(self, name: Optional[str],
                     cls: Optional[ast.ClassDef]) -> Optional[_FuncInfo]:
        """Resolve a plain / `self.attr` name to a def in this module.
        `self.attr` is resolved through `self.attr = local_def`
        rebindings collected per class."""
        if name is None:
            return None
        if name.startswith("self."):
            attr = name[len("self."):]
            target = self._self_aliases.get((cls, attr))
            if target is not None:
                return target
            if cls is not None:
                for stmt in cls.body:
                    if isinstance(stmt, ast.FunctionDef) \
                            and stmt.name == attr:
                        return self.funcs.get(stmt)
            return None
        if "." in name:
            return None
        for info in self.funcs.values():
            if info.node.name == name:
                return info
        return None

    def _mark_traced_roots(self, tree: ast.Module):
        # pass 0: collect `self.attr = <local def>` aliases per class
        self._self_aliases: Dict[Tuple[Optional[ast.ClassDef], str],
                                 _FuncInfo] = {}
        for info in self.funcs.values():
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Assign):
                    continue
                src = self._resolve_def(_dotted(node.value), info.cls)
                if src is None:
                    continue
                for t in node.targets:
                    nm = _dotted(t)
                    if nm and nm.startswith("self."):
                        self._self_aliases[(info.cls,
                                            nm[len("self."):])] = src

        # pass 1: decorators
        for info in self.funcs.values():
            for dec in info.node.decorator_list:
                if _is_jit_callee(_dotted(dec)):
                    info.traced = True
                elif isinstance(dec, ast.Call):
                    if _is_jit_callee(_dotted(dec.func)):
                        info.traced = True
                        info.static_params |= _static_names_from_call(
                            dec, info.node)
                    else:
                        p = _jit_partial(dec)
                        if p is not None:
                            info.traced = True
                            info.static_params |= _static_names_from_call(
                                p, info.node)

        # pass 2: functions passed by name to jit / trace wrappers
        class V(ast.NodeVisitor):
            def __init__(self, rule):
                self.rule = rule
                self.cls: List[ast.ClassDef] = []

            def visit_ClassDef(self, node):
                self.cls.append(node)
                self.generic_visit(node)
                self.cls.pop()

            def visit_Call(self, node):
                name = _dotted(node.func)
                cls = self.cls[-1] if self.cls else None
                if _is_trace_wrapper(name):
                    for i, arg in enumerate(node.args):
                        target = self.rule._resolve_def(_dotted(arg), cls)
                        if target is None:
                            continue
                        target.traced = True
                        if i == 0 and _is_jit_callee(name):
                            target.static_params |= \
                                _static_names_from_call(node, target.node)
                self.generic_visit(node)

        V(self).visit(tree)

    # ---------------------------------------------- PT-T004 / PT-T005
    def _enclosing_chain(self, tree):
        """Yields (call_node, enclosing_def_or_None, in_loop, target) for
        every jit construction in the module. `target` is the Assign
        target's dotted name when the call is an assignment RHS."""
        rule = self

        class V(ast.NodeVisitor):
            def __init__(self):
                self.def_stack: List[_FuncInfo] = []
                self.loop_depth = 0
                self.assign_target: List[Optional[str]] = [None]
                self.out = []

            def visit_FunctionDef(self, node):
                self.def_stack.append(rule.funcs[node])
                # decorators evaluate in the ENCLOSING scope
                saved, self.def_stack = self.def_stack, self.def_stack[:-1]
                for d in node.decorator_list:
                    self.visit(d)
                self.def_stack = saved
                for item in node.body:
                    self.visit(item)
                self.def_stack.pop()

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_For(self, node):
                self.loop_depth += 1
                self.generic_visit(node)
                self.loop_depth -= 1

            visit_While = visit_For
            visit_AsyncFor = visit_For

            def visit_Assign(self, node):
                tname = _dotted(node.targets[0]) \
                    if len(node.targets) == 1 else None
                self.assign_target.append(tname)
                self.visit(node.value)
                self.assign_target.pop()
                for t in node.targets:
                    self.visit(t)

            def visit_Call(self, node):
                if _is_jit_callee(_dotted(node.func)) \
                        or _jit_partial(node) is not None:
                    self.out.append(
                        (node,
                         self.def_stack[-1] if self.def_stack else None,
                         self.loop_depth > 0,
                         self.assign_target[-1]))
                self.assign_target.append(None)
                self.generic_visit(node)
                self.assign_target.pop()

        v = V()
        v.visit(tree)
        return v.out

    def _check_jit_construction(self, tree: ast.Module):
        for call, encl, in_loop, target in self._enclosing_chain(tree):
            # ---- PT-T005 on the construction itself
            self._check_static_hashability(call, encl)
            # ---- PT-T004
            if in_loop:
                self._emit("PT-T004", call,
                           "jax.jit constructed inside a loop: every "
                           "iteration builds a fresh compile cache "
                           "(recompile churn); hoist the jit out of the "
                           "loop")
                continue
            if encl is None:
                continue                      # module scope: fine
            if target is not None and target.startswith("self."):
                continue                      # one-time instance binding
            if any(f.memoized for f in self._chain(encl)):
                continue                      # lru_cache factory
            self._emit("PT-T004", call,
                       f"jax.jit constructed inside function "
                       f"'{encl.node.name}': each call recompiles from "
                       f"scratch; hoist to module scope, memoize the "
                       f"factory (functools.lru_cache), or bind once to "
                       f"an instance attribute")

    def _chain(self, info: Optional[_FuncInfo]):
        while info is not None:
            yield info
            info = info.parent

    def _check_static_hashability(self, call: ast.Call,
                                  encl: Optional[_FuncInfo]):
        # resolve the jitted target def (jax.jit(f, ...) or partial deco)
        target: Optional[_FuncInfo] = None
        if call.args and _is_jit_callee(_dotted(call.func)):
            target = self._resolve_def(
                _dotted(call.args[0]), encl.cls if encl else None)
        statics: Set[str] = set()
        if target is not None:
            statics = _static_names_from_call(call, target.node)
        if not statics or target is None:
            return
        target.static_params |= statics

    def _check_static_defaults(self):
        """Unhashable defaults on static parameters, for every def whose
        static_params were discovered (decorator, partial, or jit(f,...)
        form alike)."""
        for info in self.funcs.values():
            statics = info.static_params
            if not statics:
                continue
            defaults = info.node.args.defaults
            posargs = (info.node.args.posonlyargs + info.node.args.args)
            offset = len(posargs) - len(defaults)
            for i, d in enumerate(defaults):
                pname = posargs[offset + i].arg
                if pname in statics and isinstance(
                        d, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                            ast.DictComp, ast.SetComp)):
                    self._emit("PT-T005", d,
                               f"static parameter '{pname}' of "
                               f"'{info.node.name}' defaults to an "
                               f"unhashable {type(d).__name__.lower()}; "
                               f"static args are jit cache keys and must "
                               f"hash (use a tuple)")

    def _check_callsite_statics(self):
        """Direct calls to known-jitted defs with unhashable literals in
        static positions (checked module-wide, not just traced scopes)."""
        for node in ast.walk(self.ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            target = self._resolve_def(_dotted(node.func), None)
            if target is None or not target.static_params:
                continue
            posargs = [a.arg for a in (target.node.args.posonlyargs
                                       + target.node.args.args)]
            for i, arg in enumerate(node.args):
                if i < len(posargs) and posargs[i] in target.static_params:
                    if isinstance(arg, (ast.List, ast.Dict, ast.Set)):
                        self._emit(
                            "PT-T005", arg,
                            f"call to jitted '{target.node.name}' passes "
                            f"an unhashable {type(arg).__name__.lower()} "
                            f"for static parameter '{posargs[i]}'; every "
                            f"call would fail or recompile — pass a "
                            f"tuple/frozen value")
            for kw in node.keywords:
                if kw.arg in target.static_params and isinstance(
                        kw.value, (ast.List, ast.Dict, ast.Set)):
                    self._emit(
                        "PT-T005", kw.value,
                        f"call to jitted '{target.node.name}' passes an "
                        f"unhashable {type(kw.value).__name__.lower()} "
                        f"for static parameter '{kw.arg}'")

    # ------------------------------------------------- traced-unit checks
    def _check_traced_unit(self, root: _FuncInfo):
        """Taint + purity checks over one maximal traced subtree."""
        unit: List[_FuncInfo] = []

        def collect(info):
            unit.append(info)
            for c in info.children:
                collect(c)

        collect(root)

        tainted: Set[str] = set()
        for info in unit:
            statics = info.static_params if info is root else set()
            for name in _param_names(info.node):
                if name not in statics and name != "self":
                    tainted.add(name)

        # fixed-point assignment propagation over the unit's statements
        stmts: List[ast.stmt] = []
        for info in unit:
            stmts.extend(info.node.body)
        for _ in range(10):
            before = len(tainted)
            for stmt in stmts:
                self._propagate(stmt, tainted)
            if len(tainted) == before:
                break

        for info in unit:
            self._scan_body(info, tainted)

    def _propagate(self, node, tainted: Set[str]):
        for n in ast.walk(node):
            if isinstance(n, ast.Assign):
                hot = self._taints(n.value, tainted)
                for t in n.targets:
                    self._mark(t, tainted, hot)
            elif isinstance(n, ast.AnnAssign) and n.value is not None:
                self._mark(n.target, tainted,
                           self._taints(n.value, tainted))
            elif isinstance(n, ast.AugAssign):
                if self._taints(n.value, tainted):
                    self._mark(n.target, tainted, True)
            elif isinstance(n, ast.NamedExpr):
                self._mark(n.target, tainted,
                           self._taints(n.value, tainted))
            elif isinstance(n, ast.For):
                if self._taints(n.iter, tainted):
                    self._mark(n.target, tainted, True)
            elif isinstance(n, ast.withitem):
                if n.optional_vars is not None and \
                        self._taints(n.context_expr, tainted):
                    self._mark(n.optional_vars, tainted, True)
            elif isinstance(n, ast.comprehension):
                if self._taints(n.iter, tainted):
                    self._mark(n.target, tainted, True)

    def _mark(self, target, tainted: Set[str], hot: bool):
        if isinstance(target, ast.Name):
            if hot:
                tainted.add(target.id)
            else:
                tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._mark(e, tainted, hot)
        elif isinstance(target, ast.Starred):
            self._mark(target.value, tainted, hot)
        # attribute/subscript stores do not (un)taint names

    def _taints(self, node, tainted: Set[str]) -> bool:
        """Is this expression derived from a traced value? Static
        metadata (shape/dtype/len/isinstance) breaks the chain."""
        if node is None or isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            return node.id in tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self._taints(node.value, tainted)
        if isinstance(node, ast.Subscript):
            return self._taints(node.value, tainted) \
                or self._taints(node.slice, tainted)
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name in _STATIC_CALLS:
                return False
            if any(self._taints(a, tainted) for a in node.args):
                return True
            if any(self._taints(k.value, tainted) for k in node.keywords):
                return True
            return self._taints(node.func, tainted)
        if isinstance(node, ast.Lambda):
            return False
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return False
        if isinstance(node, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            # identity checks (`x is None`) compare Python object
            # identity, decided at trace time — never a tracer read
            return False
        return any(self._taints(c, tainted)
                   for c in ast.iter_child_nodes(node))

    def _scan_body(self, info: _FuncInfo, tainted: Set[str]):
        """PT-T001 / PT-T002 / PT-T003 / PT-T006 over one def's own
        statements (nested defs are scanned as their own infos)."""
        local = info.local_names

        for node in _walk_own(info.node):
            # ---- PT-T001: control flow on traced values
            if isinstance(node, (ast.If, ast.While, ast.IfExp)):
                if self._taints(node.test, tainted):
                    self._emit(
                        "PT-T001", node,
                        f"branching on a traced value in jitted scope "
                        f"'{info.node.name}': Python control flow is "
                        f"staged at trace time — use jnp.where / "
                        f"lax.cond / lax.select")
            elif isinstance(node, ast.Assert):
                if self._taints(node.test, tainted):
                    self._emit(
                        "PT-T001", node,
                        f"assert on a traced value in jitted scope "
                        f"'{info.node.name}' forces concretization; use "
                        f"checkify or move validation out of the jit")

            # ---- PT-T002 / PT-T006: host calls
            elif isinstance(node, ast.Call):
                self._check_call(node, info, tainted)

            # ---- PT-T003: mutating method call as a bare statement
            # (value-discarded — `xs.append(x)`; a USED result like
            # `lg = jnp.sort(...)` is a pure-function idiom, not a
            # mutation)
            elif isinstance(node, ast.Expr) \
                    and isinstance(node.value, ast.Call):
                self._check_mutator(node.value, info, local)

            # ---- PT-T003: side effects
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                kind = "global" if isinstance(node, ast.Global) else \
                    "nonlocal"
                self._emit(
                    "PT-T003", node,
                    f"'{kind} {', '.join(node.names)}' inside jitted "
                    f"scope '{info.node.name}': the write happens once "
                    f"at trace time, not per call — thread state "
                    f"through the carry/return instead",
                )
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    self._check_store(t, info, local)

    def _check_call(self, node: ast.Call, info: _FuncInfo,
                    tainted: Set[str]):
        name = _dotted(node.func)
        args_hot = any(self._taints(a, tainted) for a in node.args)

        # PT-T006: host RNG — trace-time constant, not per-call noise
        if name and (name.startswith("np.random.")
                     or name.startswith("numpy.random.")
                     or name.startswith("random.")):
            self._emit(
                "PT-T006", node,
                f"host RNG '{name}' inside jitted scope "
                f"'{info.node.name}': it draws ONCE at trace time and "
                f"is baked into the program as a constant — use "
                f"jax.random with an explicitly threaded key")
            return

        # PT-T002: host materialization
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _HOST_METHODS \
                and self._taints(node.func.value, tainted):
            self._emit(
                "PT-T002", node,
                f".{node.func.attr}() on a traced value in jitted scope "
                f"'{info.node.name}' forces a device→host sync inside "
                f"the compiled program")
        elif name in _HOST_BUILTINS and args_hot:
            self._emit(
                "PT-T002", node,
                f"{name}() on a traced value in jitted scope "
                f"'{info.node.name}' concretizes the tracer (host "
                f"sync); keep it as a jnp scalar")
        elif name and (name.startswith("np.") or name.startswith("numpy.")
                       ) and args_hot:
            self._emit(
                "PT-T002", node,
                f"'{name}' on a traced value in jitted scope "
                f"'{info.node.name}' materializes to host numpy; use "
                f"the jnp equivalent")
        elif name in ("jax.device_get", "device_get") and node.args:
            self._emit(
                "PT-T002", node,
                f"jax.device_get inside jitted scope "
                f"'{info.node.name}' is a host transfer in the hot "
                f"program")

    def _check_store(self, target, info: _FuncInfo, local: Set[str]):
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._check_store(e, info, local)
            return
        if isinstance(target, ast.Attribute):
            base = _dotted(target.value)
            root_name = (base or "").split(".")[0]
            if root_name == "self" or (root_name and
                                       root_name not in local):
                self._emit(
                    "PT-T003", target,
                    f"attribute store '{_dotted(target)} = ...' inside "
                    f"jitted scope '{info.node.name}' mutates state "
                    f"that outlives the trace (runs once, at trace "
                    f"time); return the new value instead")
        elif isinstance(target, ast.Subscript):
            base = _dotted(target.value)
            root_name = (base or "").split(".")[0]
            if root_name and root_name != "self" \
                    and root_name not in local:
                self._emit(
                    "PT-T003", target,
                    f"subscript store into closure/global "
                    f"'{base}' inside jitted scope "
                    f"'{info.node.name}' is a trace-time side effect")

    def _check_mutator(self, node: ast.Call, info: _FuncInfo,
                       local: Set[str]):
        """PT-T003 for mutating method calls on closure/instance names."""
        if not isinstance(node.func, ast.Attribute):
            return
        if node.func.attr not in _MUTATORS:
            return
        base = _dotted(node.func.value)
        root_name = (base or "").split(".")[0]
        if not root_name:
            return
        if root_name == "self" or root_name not in local:
            self._emit(
                "PT-T003", node,
                f"'{base}.{node.func.attr}(...)' inside jitted scope "
                f"'{info.node.name}' mutates closure/instance state at "
                f"trace time only; thread it through the return value")

    # --------------------------------------------------------- PT-T007
    def _check_host_loop_syncs(self, tree: ast.Module):
        """PT-T007: per-iteration device→host syncs in HOST loops.

        Traced scopes are PT-T002's territory; this pass covers the
        complement — module-level code and non-traced defs. For each
        OUTERMOST for/while it flags calls that force a sync every
        iteration: `.block_until_ready()`, `jax.block_until_ready(...)`,
        `jax.device_get(...)`, and `np.asarray/np.array` whose argument
        is device-derived (a direct non-numpy call, or a name the loop
        itself assigns from one). One sync per loop body is one pipeline
        stall per iteration — hoist it past the loop or batch the
        transfers.
        """
        rule = self

        def in_traced_scope(info: Optional[_FuncInfo]) -> bool:
            while info is not None:
                if info.traced:
                    return True
                info = info.parent
            return False

        loops: List[ast.stmt] = []

        class V(ast.NodeVisitor):
            def __init__(self):
                self.loop_depth = [0]   # one counter per def scope

            def visit_FunctionDef(self, node):
                info = rule.funcs.get(node)
                if in_traced_scope(info):
                    return              # traced unit: PT-T002 covers it
                self.loop_depth.append(0)
                self.generic_visit(node)
                self.loop_depth.pop()

            visit_AsyncFunctionDef = visit_FunctionDef

            def _loop(self, node):
                if self.loop_depth[-1] == 0:
                    loops.append(node)
                self.loop_depth[-1] += 1
                self.generic_visit(node)
                self.loop_depth[-1] -= 1

            visit_For = _loop
            visit_While = _loop

        V().visit(tree)
        for loop in loops:
            self._check_one_host_loop(loop)

    def _check_one_host_loop(self, loop):
        computed = _loop_device_names(loop)
        for node in _walk_loop(loop):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "block_until_ready" \
                    and not node.args:
                base = _dotted(node.func.value) or "<expr>"
                self._emit(
                    "PT-T007", node,
                    f"'{base}.block_until_ready()' inside a host loop "
                    f"syncs every iteration; hoist it after the loop")
            elif name in ("jax.block_until_ready", "block_until_ready") \
                    and node.args:
                self._emit(
                    "PT-T007", node,
                    f"'{name}(...)' inside a host loop syncs every "
                    f"iteration; hoist it after the loop")
            elif name in ("jax.device_get", "device_get"):
                self._emit(
                    "PT-T007", node,
                    f"'{name}(...)' inside a host loop transfers "
                    f"device→host every iteration; batch the transfers "
                    f"or move the computation on-device")
            elif name in ("np.asarray", "np.array", "numpy.asarray",
                          "numpy.array") and node.args:
                if _device_derived(node.args[0], computed):
                    self._emit(
                        "PT-T007", node,
                        f"'{name}(...)' of a device value inside a host "
                        f"loop forces a device→host sync every "
                        f"iteration; keep the value on-device or batch "
                        f"the transfers")


def _param_names(fn: ast.FunctionDef) -> List[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def _walk_own(fn: ast.FunctionDef):
    """ast.walk limited to fn's own body — nested defs are excluded
    (they are scanned as their own _FuncInfo units)."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in node.decorator_list:
                stack.extend(ast.walk(d))
            continue
        if isinstance(node, ast.Lambda):
            continue
        stack.extend(ast.iter_child_nodes(node))


# ------------------------------------------------------ PT-T007 helpers
# numpy roots: calls under these are host-side producers, never device
_NUMPY_ROOTS = ("np", "numpy")


def _is_numpy_rooted(name: Optional[str]) -> bool:
    return name is not None and name.split(".")[0] in _NUMPY_ROOTS


def _walk_loop(loop):
    """Walk a loop's body/orelse, skipping nested defs and lambdas
    (their bodies run when called, not per loop iteration here)."""
    stack = list(loop.body) + list(getattr(loop, "orelse", []))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _is_device_call(call: ast.Call) -> bool:
    """A call that plausibly returns a device array: anything that is
    not numpy-rooted and not a static builtin. Method chains like
    `self._decode.call(...)` count (dotted resolves, root isn't np)."""
    name = _dotted(call.func)
    if name is None:
        # method on a call result (np.asarray(v).ravel()) inherits the
        # inner call's classification; bare call-of-call (jit(f)(x))
        # stays device
        if isinstance(call.func, ast.Attribute) \
                and isinstance(call.func.value, ast.Call):
            return _is_device_call(call.func.value)
        return True
    if name in _STATIC_CALLS or name in _HOST_BUILTINS:
        return False
    return not _is_numpy_rooted(name)


def _loop_device_names(loop) -> Set[str]:
    """Names the loop body assigns from expressions containing a
    device-producing call — candidates for np.asarray sync flags."""
    names: Set[str] = set()

    def targets_of(t, out: Set[str]):
        if isinstance(t, ast.Name):
            out.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                targets_of(e, out)
        elif isinstance(t, ast.Starred):
            targets_of(t.value, out)

    for node in _walk_loop(loop):
        value, tgts = None, []
        if isinstance(node, ast.Assign):
            value, tgts = node.value, node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            value, tgts = node.value, [node.target]
        elif isinstance(node, ast.NamedExpr):
            value, tgts = node.value, [node.target]
        if value is None:
            continue
        if any(isinstance(n, ast.Call) and _is_device_call(n)
               for n in ast.walk(value)):
            for t in tgts:
                targets_of(t, names)
    return names


def _device_derived(expr, loop_device_names: Set[str]) -> bool:
    """Does `expr` plausibly hold a device value? True when it contains
    a device-producing call or a name the loop assigned from one."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Call) and _is_device_call(n):
            return True
        if isinstance(n, ast.Name) and n.id in loop_device_names:
            return True
    return False
