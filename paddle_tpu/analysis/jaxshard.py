"""jaxshard: whole-program static SPMD/sharding analyzer.

jaxcost charges collective bytes only where the program says `psum`;
under GSPMD most collectives are IMPLICIT — XLA inserts them wherever
the sharding it propagated for an operand disagrees with what an
equation needs. This module makes those insertions visible *before*
compilation: an abstract interpreter over jaxprs that propagates
NamedSharding / PartitionSpec annotations (pjit in/out shardings,
`with_sharding_constraint` sites, shard_map specs) through every
equation, inferring each intermediate's sharding and flagging where XLA
must reshard. Lineage: "Automatic Cross-Replica Sharding of Weight
Update in Data-Parallel Training" (PAPERS.md) — sharding decisions are
derived and checked from the program, not hand-tuned.

Per program the analyzer emits:

- resharding edges, with wire bytes charged PER MESH AXIS (the byte
  model extends jaxcost's collective table — see below);
- accidental full replication: a transition that rematerializes a
  >= 1 MiB tensor fully replicated on every device;
- donation defeated by sharding: a donated input whose aval-matched
  output either carries a different final sharding (aliasing is
  layout-impossible) or is produced through a resharding edge (XLA
  materializes a gathered copy before writing the aliased buffer);
- per-device peak live bytes: the liveness peak with every buffer
  divided by its true shard factor, checked against the jaxplan HBM
  envelope.

Byte model (deterministic; global-payload semantics, consistent with
jaxcost's per-equation table so the two artifacts cross-check):

    implicit psum (partial resolution)   2 x global result bytes / axis
    implicit all_gather (unshard a dim)  1 x global result bytes / axis
    implicit reshard (axis moves dims)   1 x global result bytes / axis
    replicated -> sharded (slice)        0   (each device keeps a slice)
    explicit collective in shard_map     exactly jaxcost's charge
                                         (2x-in / out / in), split over
                                         the equation's named axes

Partial sums are resolved EAGERLY: a dot_general contracting a sharded
dimension charges its psum at the dot itself (XLA may defer the reduce,
but the dot is where the partial value is born, and eager resolution
keeps the model one-pass deterministic). Mesh axes of size 1 are
dropped when specs are normalized, so `build_mesh(dp=4)` meshes do not
produce phantom edges on the five size-1 axes.

The registry (>= 8 programs: the fsdp x tp training step, dp training,
the ring/ulysses/psum_tree explicit collectives shared with jaxcost,
and the TP serving decode sub-programs) commits its reports to
`shardplan.json` with the same write/check/tolerance discipline as
jaxcost_budget.json / jaxplan.json: 5% byte tolerance, structural
drift exact, full coverage both directions, and every finding must
carry a triage reason (suppression) before the plan can be written.
CLI: tools/jaxshard.py (`--plan write|check`, exit 0/1/2).
"""
from __future__ import annotations

# ptlint: disable-file=PT-T004  registry builders construct jax.jit
# wrappers for TRACING only (analyze_jit needs the pjit equation's
# in/out shardings); each builds at most once per analysis run behind
# lru-cached setup and nothing here is a serving/training hot path

import functools
import json
import os
from dataclasses import dataclass, field, replace
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .liveness import aval_bytes, peak_live_bytes, var_bytes

__all__ = [
    "ShardReport", "ReshardEdge", "ShardFinding",
    "analyze_jit", "compute_reports", "registry_names",
    "DEFAULT_PLAN_PATH", "DEFAULT_TOLERANCE", "PLAN_VERSION",
    "write_plan", "check_plan", "diff_plans", "load_plan",
    "crosscheck_with_budget", "committed_shard_factors",
]

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
DEFAULT_PLAN_PATH = os.path.join(_REPO, "shardplan.json")
PLAN_VERSION = 1
DEFAULT_TOLERANCE = 0.05

#: implicit edges below this wire-byte total never become findings
#: (scalar loss psums etc. are charged but not triaged)
IMPLICIT_MIN_BYTES = 1024
#: "accidental full replication" findings start here
REPLICATION_MIN_BYTES = 1 << 20

# jaxcost's collective byte table (kept in sync by the cross-artifact
# check in tools/jaxcost.py): all-reduce family 2x input, gathers their
# output, permutes / all-to-all / scatters their input.
_COMM_TWICE_IN = frozenset({"psum", "psum2", "pmax", "pmin", "pmax2",
                            "pmin2", "pmean"})
_COMM_OUT = frozenset({"all_gather", "all_gather_invariant"})
_COMM_IN = frozenset({"reduce_scatter", "psum_scatter", "ppermute",
                      "pshuffle", "all_to_all"})

#: equations that run a sub-jaxpr transparently (same operand order)
_TRANSPARENT_CALLS = frozenset({
    "custom_jvp_call", "custom_vjp_call", "custom_vjp_call_jaxpr",
    "remat", "checkpoint", "closed_call", "core_call", "custom_lin",
})


# ------------------------------------------------------------------ specs
#
# A normalized spec is a tuple with one entry per array dim:
#   None            unsharded on that dim
#   ("tp",)         sharded over mesh axis tp
#   ("dp", "sh")    sharded over two axes (major to minor)
# Axes whose mesh size is 1 are dropped at normalization time.

def _replicated(ndim: int) -> tuple:
    return (None,) * ndim


def _norm_entry(entry, sizes: Dict[str, int]):
    if entry is None:
        return None
    names = entry if isinstance(entry, (tuple, list)) else (entry,)
    kept = tuple(str(a) for a in names if sizes.get(str(a), 1) > 1)
    return kept or None


def _spec_of_pspec(pspec, ndim: int, sizes: Dict[str, int],
                   unconstrained=frozenset()) -> tuple:
    """PartitionSpec -> normalized per-dim tuple. `unconstrained` dims
    come out as None (caller keeps the incoming sharding there)."""
    entries = tuple(pspec) + (None,) * (ndim - len(tuple(pspec)))
    out = []
    for d, e in enumerate(entries[:ndim]):
        if d in unconstrained or _is_unconstrained(e):
            out.append(None)
        else:
            out.append(_norm_entry(e, sizes))
    return tuple(out)


def _is_unconstrained(entry) -> bool:
    from jax.sharding import PartitionSpec as P
    return entry is P.UNCONSTRAINED


def _spec_str(spec) -> str:
    def one(e):
        return "-" if not e else "+".join(e)
    return "[" + ",".join(one(e) for e in spec) + "]"


def _spec_axes(spec) -> Tuple[str, ...]:
    out = []
    for e in spec:
        for a in e or ():
            if a not in out:
                out.append(a)
    return tuple(out)


def _shard_factor(spec, sizes: Dict[str, int]) -> int:
    f = 1
    for a in _spec_axes(spec):
        f *= sizes.get(a, 1)
    return f


def _mesh_sizes(mesh) -> Dict[str, int]:
    return {str(k): int(v) for k, v in dict(mesh.shape).items()
            if int(v) > 1}


# ------------------------------------------------------------------ report
@dataclass(frozen=True)
class ReshardEdge:
    """One place GSPMD must move data. `axes -> bytes` is the per-axis
    wire charge (already multiplied by loop trip counts)."""
    path: str
    primitive: str
    kind: str                      # psum | all_gather | reshard
    axis_bytes: Dict[str, int]
    tensor_bytes: int
    src: str
    dst: str

    def to_dict(self) -> dict:
        return {"path": self.path, "primitive": self.primitive,
                "kind": self.kind, "axis_bytes": dict(self.axis_bytes),
                "tensor_bytes": self.tensor_bytes,
                "src": self.src, "dst": self.dst}


@dataclass
class ShardFinding:
    """One triaged item. Aggregated implicit-collective groups,
    replication sites, donation defeats and envelope breaches all
    share this shape; `key` is the suppression key committed in
    shardplan.json."""
    key: str
    kind: str          # implicit | replication | donation | envelope
    message: str
    nbytes: int = 0
    count: int = 1
    example: str = ""
    suppressed: Optional[str] = None

    def to_dict(self) -> dict:
        return {"key": self.key, "kind": self.kind,
                "message": self.message, "nbytes": self.nbytes,
                "count": self.count, "example": self.example,
                "suppressed": self.suppressed}

    def format(self) -> str:
        tag = "suppressed" if self.suppressed else "UNSUPPRESSED"
        return (f"  [{tag}] {self.key}: {self.message}"
                + (f"  # {self.suppressed}" if self.suppressed else ""))


@dataclass
class ShardReport:
    name: str
    mesh: Dict[str, int]
    edges: List[ReshardEdge] = field(default_factory=list)
    implicit_axis_bytes: Dict[str, int] = field(default_factory=dict)
    explicit_axis_bytes: Dict[str, int] = field(default_factory=dict)
    findings: List[ShardFinding] = field(default_factory=list)
    per_device_peak_bytes: int = 0
    peak_where: str = ""
    envelope_bytes: int = 0
    notes: List[str] = field(default_factory=list)

    @property
    def comm_bytes_total(self) -> int:
        return (sum(self.implicit_axis_bytes.values())
                + sum(self.explicit_axis_bytes.values()))

    def unsuppressed(self) -> List[ShardFinding]:
        return [f for f in self.findings if not f.suppressed]

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "mesh": dict(sorted(self.mesh.items())),
            "edge_count": len(self.edges),
            "implicit_axis_bytes": dict(
                sorted(self.implicit_axis_bytes.items())),
            "explicit_axis_bytes": dict(
                sorted(self.explicit_axis_bytes.items())),
            "comm_bytes_total": self.comm_bytes_total,
            "per_device_peak_bytes": self.per_device_peak_bytes,
            "peak_where": self.peak_where,
            "envelope_ok": self.per_device_peak_bytes
            <= self.envelope_bytes,
            "findings": {f.key: f.to_dict() for f in self.findings},
        }

    def format(self) -> str:
        lines = [f"{self.name}: mesh={self.mesh} "
                 f"edges={len(self.edges)} "
                 f"comm={self.comm_bytes_total:,}B "
                 f"per_device_peak={self.per_device_peak_bytes:,}B"]
        for ax in sorted(set(self.implicit_axis_bytes)
                         | set(self.explicit_axis_bytes)):
            lines.append(
                f"  axis {ax}: implicit "
                f"{self.implicit_axis_bytes.get(ax, 0):,}B + explicit "
                f"{self.explicit_axis_bytes.get(ax, 0):,}B")
        for f in self.findings:
            lines.append(f.format())
        for n in self.notes[:6]:
            lines.append(f"  note: {n}")
        return "\n".join(lines)


# ------------------------------------------------------------ interpreter
class _Acc:
    """One recording sink: edges + per-axis byte tallies. Probe passes
    (cond branches, scan fixpoint) run against a scratch sink so only
    the chosen/final pass charges the real one."""

    def __init__(self):
        self.edges: List[ReshardEdge] = []
        self.implicit: Dict[str, int] = {}
        self.explicit: Dict[str, int] = {}
        self.repl_sites: List[Tuple[str, str, int]] = []
        self.notes: List[str] = []

    def total(self) -> int:
        return sum(self.implicit.values()) + sum(self.explicit.values())


class _Interp:
    """Forward abstract interpretation of shardings over one program."""

    def __init__(self, name: str, sizes: Dict[str, int]):
        self.name = name
        self.sizes = sizes
        self.specs: Dict[object, tuple] = {}
        self.acc = _Acc()
        self.manual_depth = 0

    # -------------------------------------------------------- plumbing
    def read(self, atom) -> tuple:
        if _lit(atom):
            return _replicated(len(getattr(atom.aval, "shape", ())))
        got = self.specs.get(atom)
        if got is None:
            got = _replicated(len(atom.aval.shape))
        return got

    def write(self, var, spec) -> None:
        self.specs[var] = spec

    def note(self, msg: str) -> None:
        if msg not in self.acc.notes:
            self.acc.notes.append(msg)

    # ------------------------------------------------------- charging
    def _charge(self, kind: str, axes: Sequence[str], nbytes: int,
                mult: int, path: str, prim: str,
                src: tuple, dst: tuple) -> None:
        """One implicit resharding edge; psum charges 2x per axis."""
        per = 2 * nbytes if kind == "psum" else nbytes
        axis_bytes = {}
        for a in sorted(set(axes)):
            b = per * mult
            axis_bytes[a] = b
            self.acc.implicit[a] = self.acc.implicit.get(a, 0) + b
        if not axis_bytes:
            return
        self.acc.edges.append(ReshardEdge(
            path=path, primitive=prim, kind=kind, axis_bytes=axis_bytes,
            tensor_bytes=nbytes, src=_spec_str(src), dst=_spec_str(dst)))

    def _charge_explicit(self, eqn, path: str, mult: int) -> None:
        """Explicit collective: jaxcost's exact per-equation charge,
        attributed to the equation's named mesh axes."""
        name = eqn.primitive.name
        if name in _COMM_TWICE_IN:
            total = 2 * sum(var_bytes(v) for v in eqn.invars)
            axes = eqn.params.get("axes", ())
        elif name in _COMM_OUT:
            total = sum(var_bytes(v) for v in eqn.outvars)
            axes = (eqn.params.get("axis_name"),)
        else:
            total = sum(var_bytes(v) for v in eqn.invars)
            axes = (eqn.params.get("axis_name"),)
        flat = []
        for a in (axes or ()):
            if isinstance(a, (tuple, list)):
                flat.extend(a)
            elif a is not None:
                flat.append(a)
        named = sorted({str(a) for a in flat
                        if self.sizes.get(str(a), 1) > 1}) or ["?"]
        share = (total * mult) // len(named)
        for a in named:
            self.acc.explicit[a] = self.acc.explicit.get(a, 0) + share

    def transition(self, src: tuple, dst: tuple, aval, path: str,
                   prim: str, mult: int) -> None:
        """Charge whatever data movement turning `src` into `dst` costs
        (None = free slice). Records replication sites for the
        accidental-replication detector."""
        if src == dst:
            return
        nbytes = aval_bytes(aval)
        gathered, moved = [], []
        for s_e, d_e in zip(src, dst):
            s_set, d_set = set(s_e or ()), set(d_e or ())
            gathered.extend(sorted(s_set - d_set))
            if (d_set - s_set) and (s_set - d_set):
                moved.extend(sorted(s_set ^ d_set))
        if not gathered and not moved:
            return  # pure replicated->sharded: each device slices, free
        kind = "reshard" if moved else "all_gather"
        axes = sorted(set(gathered) | set(moved))
        self._charge(kind, axes, nbytes, mult, path, prim, src, dst)
        if (not any(dst) and any(src)
                and nbytes >= REPLICATION_MIN_BYTES):
            self.acc.repl_sites.append(
                (f"{prim}:{'+'.join(axes)}", path, nbytes))

    # ------------------------------------------------------------ run
    def run(self, jaxpr_like, in_specs: Sequence[tuple], path: str,
            mult: int = 1) -> List[tuple]:
        raw = getattr(jaxpr_like, "jaxpr", jaxpr_like)
        consts = getattr(raw, "constvars", ())
        for v in consts:
            self.write(v, _replicated(len(getattr(v.aval, "shape", ()))))
        for v, s in zip(raw.invars, in_specs):
            self.write(v, s)
        for i, eqn in enumerate(raw.eqns):
            self.eqn(eqn, f"{path}:{i}", mult)
        return [self.read(v) for v in raw.outvars]

    def _probe(self, fn) -> Tuple[int, object]:
        """Run `fn` against a scratch sink; return (bytes, result)."""
        saved, self.acc = self.acc, _Acc()
        try:
            out = fn()
            return self.acc.total(), out
        finally:
            self.acc = saved

    # ------------------------------------------------------- dispatch
    def eqn(self, eqn, path: str, mult: int) -> None:
        name = eqn.primitive.name
        handler = getattr(self, f"_h_{name}", None)
        if handler is not None:
            handler(eqn, path, mult)
            return
        if name in _COMM_TWICE_IN or name in _COMM_OUT \
                or name in _COMM_IN:
            self._charge_explicit(eqn, path, mult)
            # per-shard view: collectives return replicated-in-manual
            for v in eqn.outvars:
                self.write(v, _replicated(len(v.aval.shape)))
            return
        if name in _TRANSPARENT_CALLS:
            self._h_transparent(eqn, path, mult)
            return
        if name.startswith(("reduce_", "arg")) and "axes" in eqn.params:
            self._h_reduce(eqn, path, mult)
            return
        if name.startswith("cum"):
            self._h_cum(eqn, path, mult)
            return
        self._h_default(eqn, path, mult)

    # default: elementwise join over same-shaped operands
    def _h_default(self, eqn, path: str, mult: int) -> None:
        out0 = eqn.outvars[0]
        oshape = tuple(getattr(out0.aval, "shape", ()))
        mates = [(v, self.read(v)) for v in eqn.invars
                 if tuple(getattr(v.aval, "shape", ())) == oshape]
        if not mates:
            if any(any(self.read(v)) for v in eqn.invars):
                self.note(f"unmodeled primitive {eqn.primitive.name}: "
                          f"sharded operand treated as replicated")
            for v in eqn.outvars:
                self.write(v, _replicated(len(v.aval.shape)))
            return
        joined = list(_replicated(len(oshape)))
        for _, s in mates:
            for d, e in enumerate(s):
                if joined[d] is None and e is not None:
                    joined[d] = e
        joined = tuple(joined)
        for v, s in mates:
            if s != joined and any(s):
                # operand laid out differently from the join: GSPMD
                # reshards it (replicated operands slice for free)
                self.transition(s, joined, v.aval, path,
                                eqn.primitive.name, mult)
        for v in eqn.outvars:
            if tuple(getattr(v.aval, "shape", ())) == oshape:
                self.write(v, joined)
            else:
                self.write(v, _replicated(len(v.aval.shape)))

    # ------------------------------------------------- sharding markers
    def _h_sharding_constraint(self, eqn, path: str, mult: int) -> None:
        v = eqn.invars[0]
        ndim = len(v.aval.shape)
        src = self.read(v)
        sharding = eqn.params["sharding"]
        unc = frozenset(eqn.params.get("unconstrained_dims", ()) or ())
        tgt = _spec_of_pspec(getattr(sharding, "spec", ()), ndim,
                             self.sizes, unconstrained=unc)
        dst = tuple(src[d] if d in unc else tgt[d] for d in range(ndim))
        self.transition(src, dst, v.aval, path, "sharding_constraint",
                        mult)
        self.write(eqn.outvars[0], dst)

    def _h_pjit(self, eqn, path: str, mult: int) -> None:
        inner = eqn.params["jaxpr"]
        in_sh = eqn.params.get("in_shardings",
                               (None,) * len(eqn.invars))
        out_sh = eqn.params.get("out_shardings",
                                (None,) * len(eqn.outvars))
        entry = []
        for i, v in enumerate(eqn.invars):
            spec = self.read(v)
            sh = in_sh[i] if i < len(in_sh) else None
            pspec = getattr(sh, "spec", None)
            if pspec is not None:
                tgt = _spec_of_pspec(pspec, len(v.aval.shape),
                                     self.sizes)
                self.transition(spec, tgt, v.aval, f"{path}/in{i}",
                                "pjit", mult)
                spec = tgt
            entry.append(spec)
        body = self.run(inner, entry, f"{path}/pjit", mult)
        for i, v in enumerate(eqn.outvars):
            spec = body[i] if i < len(body) else \
                _replicated(len(v.aval.shape))
            sh = out_sh[i] if i < len(out_sh) else None
            pspec = getattr(sh, "spec", None)
            if pspec is not None:
                tgt = _spec_of_pspec(pspec, len(v.aval.shape),
                                     self.sizes)
                self.transition(spec, tgt, v.aval, f"{path}/out{i}",
                                "pjit", mult)
                spec = tgt
            self.write(v, spec)

    def _h_shard_map(self, eqn, path: str, mult: int) -> None:
        body = eqn.params["jaxpr"]
        in_names = eqn.params.get("in_names", ())
        out_names = eqn.params.get("out_names", ())
        for v, names in zip(eqn.invars, in_names):
            expected = self._spec_of_names(names, len(v.aval.shape))
            self.transition(self.read(v), expected, v.aval,
                            f"{path}/shmap_in", "shard_map", mult)
        raw = getattr(body, "jaxpr", body)
        self.manual_depth += 1
        try:
            self.run(body,
                     [_replicated(len(iv.aval.shape))
                      for iv in raw.invars],
                     f"{path}/shard_map", mult)
        finally:
            self.manual_depth -= 1
        for v, names in zip(eqn.outvars, out_names):
            self.write(v, self._spec_of_names(names,
                                              len(v.aval.shape)))

    def _spec_of_names(self, names, ndim: int) -> tuple:
        out = [None] * ndim
        for d, axes in dict(names or {}).items():
            if int(d) < ndim:
                out[int(d)] = _norm_entry(tuple(axes), self.sizes)
        return tuple(out)

    # ------------------------------------------------------- contraction
    def _h_dot_general(self, eqn, path: str, mult: int) -> None:
        (lhs_c, rhs_c), (lhs_b, rhs_b) = eqn.params["dimension_numbers"]
        lhs, rhs = eqn.invars[0], eqn.invars[1]
        ls, rs = self.read(lhs), self.read(rhs)
        ln, rn = len(lhs.aval.shape), len(rhs.aval.shape)
        out = eqn.outvars[0]

        batch = []
        for i, j in zip(lhs_b, rhs_b):
            a, b = ls[i], rs[j]
            if a and b and a != b:
                # operands tile the shared batch dim differently:
                # reshard rhs onto lhs's layout
                fixed = tuple(a if d == j else rs[d] for d in range(rn))
                self.transition(rs, fixed, rhs.aval, path,
                                "dot_general", mult)
                b = a
            batch.append(a or b)
        lhs_free = [ls[d] for d in range(ln)
                    if d not in lhs_c and d not in lhs_b]
        rhs_free = [rs[d] for d in range(rn)
                    if d not in rhs_c and d not in rhs_b]
        spec = tuple(batch + lhs_free + rhs_free)

        partial_axes = set()
        for d in lhs_c:
            partial_axes.update(ls[d] or ())
        for d in rhs_c:
            partial_axes.update(rs[d] or ())
        partial_axes -= {a for e in spec for a in (e or ())}
        if partial_axes:
            # contracting a sharded dim leaves every device a partial
            # sum: resolve eagerly with the implicit all-reduce here
            self._charge("psum", sorted(partial_axes),
                         aval_bytes(out.aval), mult, path,
                         "dot_general", spec, spec)
        self.write(out, spec[:len(out.aval.shape)]
                   + _replicated(len(out.aval.shape) - len(spec)))

    def _h_reduce(self, eqn, path: str, mult: int) -> None:
        v = eqn.invars[0]
        src = self.read(v)
        axes = tuple(eqn.params.get("axes", ()))
        hit = set()
        for d in axes:
            hit.update(src[d] or ())
        out_spec = tuple(e for d, e in enumerate(src) if d not in axes)
        if hit:
            self._charge("psum", sorted(hit),
                         aval_bytes(eqn.outvars[0].aval), mult, path,
                         eqn.primitive.name, src, out_spec)
        for ov in eqn.outvars:
            self.write(ov, out_spec[:len(ov.aval.shape)]
                       + _replicated(len(ov.aval.shape)
                                     - len(out_spec)))

    def _h_cum(self, eqn, path: str, mult: int) -> None:
        v = eqn.invars[0]
        src = self.read(v)
        d = eqn.params.get("axis", 0)
        dst = tuple(None if i == d else e for i, e in enumerate(src))
        if src[d]:
            self.transition(src, dst, v.aval, path,
                            eqn.primitive.name, mult)
        self.write(eqn.outvars[0], dst)

    # ---------------------------------------------------- shape plumbing
    def _h_broadcast_in_dim(self, eqn, path: str, mult: int) -> None:
        v = eqn.invars[0]
        src = self.read(v)
        bdims = eqn.params["broadcast_dimensions"]
        oshape = eqn.params["shape"]
        out = [None] * len(oshape)
        for j, d in enumerate(bdims):
            if int(v.aval.shape[j]) == int(oshape[d]):
                out[d] = src[j]
        self.write(eqn.outvars[0], tuple(out))

    def _h_transpose(self, eqn, path: str, mult: int) -> None:
        src = self.read(eqn.invars[0])
        perm = eqn.params["permutation"]
        self.write(eqn.outvars[0], tuple(src[p] for p in perm))

    def _h_squeeze(self, eqn, path: str, mult: int) -> None:
        src = self.read(eqn.invars[0])
        drop = set(eqn.params["dimensions"])
        self.write(eqn.outvars[0],
                   tuple(e for d, e in enumerate(src) if d not in drop))

    def _h_expand_dims(self, eqn, path: str, mult: int) -> None:
        src = list(self.read(eqn.invars[0]))
        for d in sorted(eqn.params["dimensions"]):
            src.insert(d, None)
        self.write(eqn.outvars[0], tuple(src))

    def _h_reshape(self, eqn, path: str, mult: int) -> None:
        v = eqn.invars[0]
        src = self.read(v)
        in_shape = tuple(int(d) for d in v.aval.shape)
        out_shape = tuple(int(d) for d in eqn.params["new_sizes"])
        spec, lost = _map_reshape(in_shape, out_shape, src, self.sizes)
        if lost:
            dst = tuple(spec)
            self.transition(src, _strip_axes(src, lost), v.aval, path,
                            "reshape", mult)
        self.write(eqn.outvars[0], tuple(spec))

    def _h_rev(self, eqn, path: str, mult: int) -> None:
        self.write(eqn.outvars[0], self.read(eqn.invars[0]))

    def _h_convert_element_type(self, eqn, path, mult) -> None:
        self.write(eqn.outvars[0], self.read(eqn.invars[0]))

    def _h_slice(self, eqn, path: str, mult: int) -> None:
        v = eqn.invars[0]
        src = self.read(v)
        starts = eqn.params["start_indices"]
        limits = eqn.params["limit_indices"]
        self._sliced(eqn, src, [int(l) - int(s) for s, l
                                in zip(starts, limits)], path, mult)

    def _h_dynamic_slice(self, eqn, path: str, mult: int) -> None:
        src = self.read(eqn.invars[0])
        self._sliced(eqn, src, eqn.params["slice_sizes"], path, mult)

    def _sliced(self, eqn, src, out_sizes, path, mult) -> None:
        v = eqn.invars[0]
        dst = []
        for d, e in enumerate(src):
            full = int(out_sizes[d]) == int(v.aval.shape[d])
            dst.append(e if full else None)
        dst = tuple(dst)
        if any(s and not d for s, d in zip(src, dst)):
            self.transition(src, dst, v.aval, path,
                            eqn.primitive.name, mult)
        self.write(eqn.outvars[0], dst)

    def _h_dynamic_update_slice(self, eqn, path, mult) -> None:
        op = eqn.invars[0]
        spec = self.read(op)
        upd = eqn.invars[1]
        us = self.read(upd)
        if any(us) and us[:len(spec)] != spec:
            self.transition(us, _replicated(len(us)), upd.aval, path,
                            "dynamic_update_slice", mult)
        self.write(eqn.outvars[0], spec)

    def _h_concatenate(self, eqn, path: str, mult: int) -> None:
        dim = eqn.params["dimension"]
        out = eqn.outvars[0]
        joined = list(_replicated(len(out.aval.shape)))
        for v in eqn.invars:
            s = self.read(v)
            if s[dim]:
                # concatenating along a sharded dim: gather first
                dst = tuple(None if d == dim else e
                            for d, e in enumerate(s))
                self.transition(s, dst, v.aval, path, "concatenate",
                                mult)
                s = dst
            for d, e in enumerate(s):
                if d != dim and joined[d] is None and e is not None:
                    joined[d] = e
        self.write(out, tuple(joined))

    def _h_pad(self, eqn, path: str, mult: int) -> None:
        self.write(eqn.outvars[0], self.read(eqn.invars[0]))

    def _h_iota(self, eqn, path: str, mult: int) -> None:
        self.write(eqn.outvars[0],
                   _replicated(len(eqn.outvars[0].aval.shape)))

    def _h_gather(self, eqn, path: str, mult: int) -> None:
        op, idx = eqn.invars[0], eqn.invars[1]
        os, xs = self.read(op), self.read(idx)
        dn = eqn.params["dimension_numbers"]
        out = eqn.outvars[0]
        out_ndim = len(out.aval.shape)
        offset = set(dn.offset_dims)
        # sharded lookup dims: GSPMD lowers a gather from a sharded
        # table as masked local lookup + psum of the dense result (the
        # vocab-parallel embedding pattern)
        lookup_axes = set()
        for d in set(dn.start_index_map) | set(dn.collapsed_slice_dims):
            lookup_axes.update(os[d] or ())
        # surviving operand dims feed the offset dims in order
        surviving = [d for d in range(len(os))
                     if d not in dn.collapsed_slice_dims]
        slice_sizes = eqn.params.get("slice_sizes", ())
        off_entries = []
        for d in surviving:
            full = (d < len(slice_sizes)
                    and int(slice_sizes[d]) == int(op.aval.shape[d]))
            off_entries.append(os[d] if full else None)
        batch_entries = [e for e in xs[:-1]] if len(xs) else []
        spec, oi, bi = [], 0, 0
        for d in range(out_ndim):
            if d in offset:
                spec.append(off_entries[oi] if oi < len(off_entries)
                            else None)
                oi += 1
            else:
                spec.append(batch_entries[bi]
                            if bi < len(batch_entries) else None)
                bi += 1
        if lookup_axes:
            self._charge("psum", sorted(lookup_axes),
                         aval_bytes(out.aval), mult, path, "gather",
                         os, tuple(spec))
            nbytes = aval_bytes(out.aval)
            if not any(spec) and nbytes >= REPLICATION_MIN_BYTES:
                self.acc.repl_sites.append(
                    (f"gather:{'+'.join(sorted(lookup_axes))}",
                     path, nbytes))
        self.write(out, tuple(spec))

    def _h_scatter(self, eqn, path: str, mult: int) -> None:
        self.write(eqn.outvars[0], self.read(eqn.invars[0]))

    _h_scatter_add = _h_scatter

    # ------------------------------------------------------ control flow
    def _h_scan(self, eqn, path: str, mult: int) -> None:
        p = eqn.params
        body = p["jaxpr"]
        raw = getattr(body, "jaxpr", body)
        n_c, n_carry = p["num_consts"], p["num_carry"]
        length = int(p.get("length", 1))
        consts = [self.read(v) for v in eqn.invars[:n_c]]
        carry = [self.read(v) for v in eqn.invars[n_c:n_c + n_carry]]
        xs = []
        for v in eqn.invars[n_c + n_carry:]:
            s = self.read(v)
            if s and s[0]:
                # scanning over a sharded leading dim: gather it
                dst = (None,) + tuple(s[1:])
                self.transition(s, dst, v.aval, path, "scan", mult)
                s = dst
            xs.append(tuple(s[1:]))
        # one scratch pass to a fixpoint on the carry layout, then the
        # recorded pass at trip-count multiplicity
        _, probe_out = self._probe(
            lambda: self.run(body, consts + carry + xs,
                             f"{path}/scan", mult))
        joined = [_meet(a, b) for a, b in
                  zip(carry, probe_out[:n_carry])]
        outs = self.run(body, consts + joined + xs, f"{path}/scan",
                        mult * max(length, 1))
        final_carry = [_meet(a, b) for a, b in
                       zip(joined, outs[:n_carry])]
        ys = [(None,) + tuple(s) for s in outs[n_carry:]]
        for v, s in zip(eqn.outvars, final_carry + ys):
            self.write(v, tuple(s)[:len(v.aval.shape)]
                       + _replicated(len(v.aval.shape) - len(s)))

    def _h_while(self, eqn, path: str, mult: int) -> None:
        p = eqn.params
        body = p["body_jaxpr"]
        n_b = p.get("body_nconsts", 0)
        n_cond = p.get("cond_nconsts", 0)
        carry = [self.read(v) for v in eqn.invars[n_cond + n_b:]]
        consts = [self.read(v)
                  for v in eqn.invars[n_cond:n_cond + n_b]]
        self.note("while body resharding charged once (trip count "
                  "unknown)")
        outs = self.run(body, consts + carry, f"{path}/while", mult)
        for v, a, b in zip(eqn.outvars, carry, outs):
            self.write(v, _meet(a, b))

    def _h_cond(self, eqn, path: str, mult: int) -> None:
        branches = eqn.params["branches"]
        operands = [self.read(v) for v in eqn.invars[1:]]
        # probe every branch; charge only the heaviest (jaxcost's
        # per-metric max convention), meet the branch out layouts
        probes = []
        for bi, br in enumerate(branches):
            cost, outs = self._probe(
                lambda br=br: self.run(br, operands,
                                       f"{path}/branch", mult))
            probes.append((cost, bi, outs))
        cost, heavy, _ = max(probes, key=lambda t: (t[0], -t[1]))
        outs = self.run(branches[heavy], operands,
                        f"{path}/branches[{heavy}]", mult)
        for _, _, other in probes:
            outs = [_meet(a, b) for a, b in zip(outs, other)]
        for v, s in zip(eqn.outvars, outs):
            self.write(v, s)

    def _h_transparent(self, eqn, path: str, mult: int) -> None:
        body = eqn.params.get("call_jaxpr") or eqn.params.get("jaxpr")
        raw = getattr(body, "jaxpr", body) if body is not None else None
        if raw is None or len(raw.invars) != len(eqn.invars):
            for v in eqn.outvars:
                self.write(v, _replicated(len(v.aval.shape)))
            self.note(f"opaque call {eqn.primitive.name}: outputs "
                      f"treated as replicated")
            return
        outs = self.run(body, [self.read(v) for v in eqn.invars],
                        f"{path}/{eqn.primitive.name}", mult)
        for v, s in zip(eqn.outvars, outs):
            self.write(v, s)


def _lit(v) -> bool:
    return type(v).__name__ == "Literal" or hasattr(v, "val")


def _meet(a: tuple, b: tuple) -> tuple:
    """Join two layouts of the same value: keep agreeing entries, drop
    the rest to unsharded (conservative: disagreement means GSPMD will
    pick one and reshard the other; we model the value as needing the
    common denominator)."""
    if a == b:
        return a
    return tuple(x if x == y else None for x, y in zip(a, b))


def _strip_axes(spec: tuple, axes) -> tuple:
    kill = set(axes)
    out = []
    for e in spec:
        kept = tuple(a for a in (e or ()) if a not in kill)
        out.append(kept or None)
    return tuple(out)


def _map_reshape(in_shape, out_shape, spec, sizes):
    """Propagate a per-dim spec through reshape by factor grouping.
    Returns (out_spec, lost_axes): a sharded in-dim survives a split if
    it lands on the leading factor and the shard count divides it, and
    survives a merge if it is the group's leading in-dim; anything else
    is a resharding (GSPMD re-tiles) and its axes are `lost`."""
    out = [None] * len(out_shape)
    lost: List[str] = []
    i = j = 0
    while i < len(in_shape) or j < len(out_shape):
        gi, gj = [i], [j]
        pi = in_shape[i] if i < len(in_shape) else 1
        pj = out_shape[j] if j < len(out_shape) else 1
        while pi != pj:
            if pi < pj and len(gi) + gi[0] < len(in_shape):
                gi.append(gi[0] + len(gi))
                pi *= in_shape[gi[-1]]
            elif pj < pi and len(gj) + gj[0] < len(out_shape):
                gj.append(gj[0] + len(gj))
                pj *= out_shape[gj[-1]]
            else:
                break
        group_axes = [a for d in gi if d < len(spec)
                      for a in (spec[d] or ())]
        if len(gi) == 1 and len(gj) == 1:
            if gi[0] < len(spec):
                out[gj[0]] = spec[gi[0]]
        elif group_axes:
            lead = gi[0]
            lead_entry = spec[lead] if lead < len(spec) else None
            others = [a for d in gi[1:] if d < len(spec)
                      for a in (spec[d] or ())]
            factor = 1
            for a in (lead_entry or ()):
                factor *= sizes.get(a, 1)
            if others:
                lost.extend(group_axes)      # non-leading factor sharded
            elif lead_entry and out_shape[gj[0]] % max(factor, 1) == 0:
                out[gj[0]] = lead_entry      # rides the leading factor
            elif lead_entry:
                lost.extend(lead_entry)
        i = gi[-1] + 1
        j = gj[-1] + 1
    return out, sorted(set(lost))


# --------------------------------------------------------------- analysis
def analyze_jit(fn, *args, name: str, mesh,
                envelope: Optional[int] = None,
                suppress: Optional[Dict[str, str]] = None,
                ) -> ShardReport:
    """Analyze one jitted callable. The trace must stage a single pjit
    equation (any jax.jit-wrapped fn does); its in/out shardings and
    donated_invars seed the interpreter and the donation detector."""
    sizes = _mesh_sizes(mesh)
    closed = jax.make_jaxpr(fn)(*args)
    outer = closed.jaxpr
    pj = [e for e in outer.eqns if e.primitive.name == "pjit"]
    if len(outer.eqns) != 1 or not pj:
        raise ValueError(
            f"{name}: expected a single top-level pjit equation "
            f"(wrap the program in jax.jit), got "
            f"{[e.primitive.name for e in outer.eqns]}")
    eqn = pj[0]
    inner = eqn.params["jaxpr"]
    in_sh = eqn.params.get("in_shardings", ())
    out_sh = eqn.params.get("out_shardings", ())
    donated = eqn.params.get("donated_invars",
                             (False,) * len(eqn.invars))

    interp = _Interp(name, sizes)
    entry = []
    for i, v in enumerate(eqn.invars):
        sh = in_sh[i] if i < len(in_sh) else None
        pspec = getattr(sh, "spec", None)
        ndim = len(v.aval.shape)
        entry.append(_spec_of_pspec(pspec, ndim, sizes)
                     if pspec is not None else _replicated(ndim))
    body_out = interp.run(inner, entry, name)
    final_out = []
    for i, v in enumerate(inner.jaxpr.outvars):
        spec = body_out[i]
        sh = out_sh[i] if i < len(out_sh) else None
        pspec = getattr(sh, "spec", None)
        if pspec is not None:
            tgt = _spec_of_pspec(pspec, len(v.aval.shape), sizes)
            interp.transition(spec, tgt, v.aval, f"{name}/out{i}",
                              "pjit_out", 1)
            spec = tgt
        final_out.append(spec)

    if envelope is None:
        envelope = _default_envelope()
    report = ShardReport(name=name, mesh=dict(sizes),
                         edges=interp.acc.edges,
                         implicit_axis_bytes=interp.acc.implicit,
                         explicit_axis_bytes=interp.acc.explicit,
                         envelope_bytes=envelope,
                         notes=interp.acc.notes)

    # per-device peak: liveness with every buffer divided by its true
    # shard factor (vars the interpreter never saw count full-size)
    def _pd_bytes(v):
        b = var_bytes(v)
        spec = interp.specs.get(v)
        if b and spec is not None:
            b //= max(_shard_factor(spec, sizes), 1)
        return b

    rep = peak_live_bytes(inner, name=name, bytes_fn=_pd_bytes)
    report.per_device_peak_bytes = rep.peak_bytes
    report.peak_where = rep.where

    _collect_findings(report, interp, eqn, inner, entry, final_out,
                      body_out, donated, sizes)
    _apply_suppressions(report, suppress or {})
    return report


def _default_envelope() -> int:
    from . import jaxplan
    plan = jaxplan.load_plan()
    if plan and "envelope_bytes" in plan:
        return int(plan["envelope_bytes"])
    return jaxplan.DEFAULT_HBM_ENVELOPE


def _collect_findings(report, interp, eqn, inner, entry, final_out,
                      body_out, donated, sizes) -> None:
    # implicit-collective groups >= IMPLICIT_MIN_BYTES, keyed by
    # (kind, axes) so a backward pass's N gradient psums triage as one
    groups: Dict[str, ShardFinding] = {}
    for edge in report.edges:
        key = (f"implicit:{edge.kind}:"
               f"{'+'.join(sorted(edge.axis_bytes))}")
        b = sum(edge.axis_bytes.values())
        if key in groups:
            g = groups[key]
            g.count += 1
            g.nbytes += b
        else:
            groups[key] = ShardFinding(
                key=key, kind="implicit",
                message=f"implicit {edge.kind} over "
                        f"{'+'.join(sorted(edge.axis_bytes))}",
                nbytes=b, example=f"{edge.path} ({edge.primitive} "
                                  f"{edge.src}->{edge.dst})")
    for g in groups.values():
        if g.nbytes >= IMPLICIT_MIN_BYTES:
            g.message += (f": {g.count} site(s), {g.nbytes:,} wire "
                          f"bytes — first at {g.example}")
            report.findings.append(g)

    # accidental full replication of >= 1 MiB tensors
    repl: Dict[str, ShardFinding] = {}
    for what, path, nbytes in interp.acc.repl_sites:
        key = f"replication:{what}"
        if key in repl:
            repl[key].count += 1
            repl[key].nbytes = max(repl[key].nbytes, nbytes)
        else:
            repl[key] = ShardFinding(
                key=key, kind="replication",
                message=f"{nbytes:,}B tensor gathered to full "
                        f"replication at {path}",
                nbytes=nbytes, example=path)
    report.findings.extend(repl.values())

    # donation defeated by sharding: greedy aval-match of donated
    # invars to outputs (jaxcost's audit convention), then compare the
    # layouts across the aliasing
    taken = set()
    invars = list(eqn.invars)
    outvars = list(inner.jaxpr.outvars)
    inset = set(id(v) for v in invars)
    for i, (v, don) in enumerate(zip(invars, donated)):
        if not don or var_bytes(v) < 1024:
            continue
        match = None
        for j, ov in enumerate(outvars):
            if j in taken or _lit(ov) or id(ov) in inset:
                continue
            if (tuple(ov.aval.shape) == tuple(v.aval.shape)
                    and ov.aval.dtype == v.aval.dtype):
                match = j
                break
        if match is None:
            continue
        taken.add(match)
        in_spec = entry[i]
        out_spec = final_out[match]
        produced = body_out[match]
        if in_spec != out_spec:
            report.findings.append(ShardFinding(
                key=f"donation:defeated:{i}",
                kind="donation",
                message=f"donated invar {i} {_spec_str(in_spec)} "
                        f"aliases output {match} "
                        f"{_spec_str(out_spec)}: layouts differ, "
                        f"aliasing is defeated",
                nbytes=var_bytes(v), example=f"invar{i}->out{match}"))
        elif produced != out_spec and any(produced):
            report.findings.append(ShardFinding(
                key=f"donation:reshard:{i}",
                kind="donation",
                message=f"donated invar {i}'s aliased output {match} "
                        f"is produced {_spec_str(produced)} but held "
                        f"{_spec_str(out_spec)}: XLA gathers into the "
                        f"donated buffer",
                nbytes=var_bytes(v), example=f"invar{i}->out{match}"))

    if report.per_device_peak_bytes > report.envelope_bytes:
        report.findings.append(ShardFinding(
            key="envelope", kind="envelope",
            message=f"per-device peak "
                    f"{report.per_device_peak_bytes:,}B exceeds the "
                    f"jaxplan HBM envelope {report.envelope_bytes:,}B",
            nbytes=report.per_device_peak_bytes))
    report.findings.sort(key=lambda f: f.key)


def _apply_suppressions(report: ShardReport,
                        suppress: Dict[str, str]) -> None:
    unused = dict(suppress)
    for f in report.findings:
        if f.key in unused:
            f.suppressed = unused.pop(f.key)
    for key, reason in sorted(unused.items()):
        report.notes.append(
            f"unused suppression {key!r} ({reason}) — the finding it "
            f"triaged no longer fires")


# --------------------------------------------------------------- registry
@dataclass(frozen=True)
class _ShardProgram:
    name: str
    #: () -> (jitted_fn, args, mesh); lazy so building one program
    #: never traces the others
    build: Callable
    #: finding key -> triage reason (the committed suppressions)
    suppress: Dict[str, str] = field(default_factory=dict)


def _need_devices(n: int):
    devs = jax.devices()
    if len(devs) < n:
        raise RuntimeError(
            f"sharding registry programs need >= {n} devices; run "
            f"under XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            f"(the jaxshard CLI and tests/conftest.py both set this)")
    return devs


@functools.lru_cache(maxsize=1)
def _tp_train_setup():
    """The fsdp x tp flagship: ZeRO-1 ShardedTrainStep of a TP-marked
    GPT on a sharding=2 x tp=2 mesh (SNIPPETS.md [2] layouts). Params
    stay replicated while optimizer moments shard over 'sharding' —
    the weight-update-sharding layout of arxiv 2004.13336, whose
    implicit allgather-into-donated-params is exactly what the
    donation detector must see."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.optimizer as popt
    from ..models.gpt import GPT, GPTConfig, gpt_loss_fn
    from ..parallel.api import ShardedTrainStep, ShardingStage
    from ..parallel.mesh import build_mesh, set_global_mesh

    devs = _need_devices(4)
    mesh = build_mesh(sharding=2, tp=2, devices=devs[:4])
    set_global_mesh(mesh)
    paddle.seed(0)
    # vocab x hidden sized so wte / lm_head cross the 1 MiB
    # replication threshold (f32 2048 x 128 = 1 MiB)
    cfg = GPTConfig(vocab_size=2048, hidden_size=128, num_layers=2,
                    num_heads=4, max_seq_len=32)
    model = GPT(cfg)
    optim = popt.AdamW(1e-3, parameters=model.parameters())
    step = ShardedTrainStep(model, gpt_loss_fn, optim, mesh=mesh,
                            sharding_stage=ShardingStage.OPTIMIZER)
    x = paddle.to_tensor(np.zeros((4, 32), np.int64))
    y = paddle.to_tensor(np.zeros((4, 32), np.int64))
    return step, x, y, mesh


def _traced_sharded_step(step, x, y):
    """The jitted step fn + example args, mirroring
    ShardedTrainStep._lowered's assembly without compiling."""
    import jax.numpy as jnp

    params, frozen = step._split_params()
    buffers = {k: b._value for k, b in step.model.named_buffers()
               if b is not None}
    opt_state = step._opt_state or step.optimizer.init_opt_state(params)
    acc = jax.tree_util.tree_map(jnp.zeros_like, params)
    arr = [a._value for a in (x, y)]
    if step._jitted is None:
        step._build(params, frozen, buffers, opt_state, arr)
    args = (params, frozen, buffers, opt_state, acc,
            jnp.asarray(True), jnp.asarray(1e-3, jnp.float32),
            jax.random.PRNGKey(0), *arr)
    return step._jitted, args


def _prog_train_fsdp_tp():
    step, x, y, mesh = _tp_train_setup()
    fn, args = _traced_sharded_step(step, x, y)
    return fn, args, mesh


def _prog_train_dp():
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.optimizer as popt
    from ..models.gpt import GPT, GPTConfig, gpt_loss_fn
    from ..parallel.api import ShardedTrainStep
    from ..parallel.mesh import build_mesh, set_global_mesh

    devs = _need_devices(4)
    mesh = build_mesh(dp=4, devices=devs[:4])
    set_global_mesh(mesh)
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=24)
    model = GPT(cfg)
    optim = popt.SGD(learning_rate=0.1, parameters=model.parameters())
    step = ShardedTrainStep(model, gpt_loss_fn, optim, mesh=mesh)
    x = paddle.to_tensor(np.zeros((4, 24), np.int64))
    y = paddle.to_tensor(np.zeros((4, 24), np.int64))
    fn, args = _traced_sharded_step(step, x, y)
    return fn, args, mesh


def _collective_mesh_programs():
    """The three explicit-collective programs, IDENTICAL shapes to
    jaxcost's `collective.*` registry entries: their per-axis explicit
    bytes must sum to jaxcost's committed comm_bytes (enforced by
    tools/jaxcost.py's cross-artifact check)."""
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from ..parallel.compat import shard_map
    from ..parallel.ring_attention import (ring_attention,
                                           ulysses_attention)

    devs = _need_devices(4)
    mesh = Mesh(np.asarray(devs[:4]), ("sp",))
    B, H, T, D = 1, 4, 32, 8
    q = jnp.zeros((B, H, T, D), jnp.float32)
    # ptlint: disable=PT-S001  this IS the committed layout (mirrors
    # jaxcost's collective.* literals so both artifacts budget the
    # same program)
    spec = P(None, None, "sp", None)

    ring = shard_map(lambda a, b, c: ring_attention(a, b, c, "sp"),
                     mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
                     axis_names={"sp"})
    uly = shard_map(lambda a, b, c: ulysses_attention(a, b, c, "sp"),
                    mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
                    axis_names={"sp"})

    def psum_tree(grads):
        return jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, "dp"), grads)

    dmesh = Mesh(np.asarray(devs[:4]), ("dp",))
    tree = {"w": jnp.zeros((8, 8), jnp.float32),
            "b": jnp.zeros((4,), jnp.float32)}
    pt = shard_map(psum_tree, mesh=dmesh,
                   # ptlint: disable=PT-S001  committed registry layout
                   in_specs=({"w": P("dp", None), "b": P("dp")},),
                   # ptlint: disable=PT-S001  committed registry layout
                   out_specs={"w": P(None, None), "b": P(None)},
                   check_vma=False)
    return [
        ("collective.ring_attention", jax.jit(ring), (q, q, q), mesh),
        ("collective.ulysses_attention", jax.jit(uly), (q, q, q),
         mesh),
        ("collective.psum_tree", jax.jit(pt), (tree,), dmesh),
    ]


def _tp_param_specs(params, tp_axis="tp"):
    """Megatron layout for the flat serving param dict: column-parallel
    qkv/up/lm_head, row-parallel out/down, vocab-parallel wte."""
    from jax.sharding import PartitionSpec as P

    def spec(k):
        if k.endswith(("attn.qkv.weight", "mlp.up.weight",
                       "lm_head.weight")):
            return P(None, tp_axis)
        if k.endswith(("attn.qkv.bias", "mlp.up.bias")):
            return P(tp_axis)
        if k.endswith(("attn.out.weight", "mlp.down.weight")):
            return P(tp_axis, None)
        if k == "wte.weight":
            return P(tp_axis, None)
        return P()

    return {k: spec(k) for k in params}


@functools.lru_cache(maxsize=1)
def _serving_tp_setup():
    import paddle_tpu as paddle
    from ..models import generation
    from ..models.gpt import GPT, GPTConfig
    from ..parallel.mesh import build_mesh, set_global_mesh

    devs = _need_devices(4)
    mesh = build_mesh(tp=4, devices=devs[:4])
    set_global_mesh(None)  # serving programs carry explicit shardings
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=32768, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=32)
    model = GPT(cfg)
    geom = (cfg.num_layers, cfg.num_heads,
            cfg.hidden_size // cfg.num_heads, cfg.max_seq_len)
    params = generation.extract_params(model)
    return params, geom, mesh


def _named(mesh, pspec):
    from jax.sharding import NamedSharding
    return NamedSharding(mesh, pspec)


def _serving_decode_programs():
    from jax.sharding import PartitionSpec as P

    from ..models import generation as g

    params, geom, mesh = _serving_tp_setup()
    L, H, D, S = geom
    C = H * D
    dtype = jnp.asarray(params["wte.weight"]).dtype
    B = 8
    psh = {k: _named(mesh, v)
           for k, v in _tp_param_specs(params).items()}
    repl = _named(mesh, P())
    # ptlint: disable=PT-S001  this IS the committed serving layout:
    # the registry defines the head-sharded KV contract the plan pins
    head_sh = _named(mesh, P(None, "tp", None, None))

    tokens = jnp.zeros((B,), jnp.int32)
    positions = jnp.zeros((B,), jnp.int32)
    x = jnp.zeros((B, 1, C), dtype)
    q = jnp.zeros((B, H, 1, D), dtype)
    kc = jnp.zeros((B, H, S, D), dtype)

    embed = jax.jit(lambda p, t, pos: g._token_embed(p, t, pos),
                    in_shardings=(psh, repl, repl),
                    out_shardings=repl)
    qkv = jax.jit(lambda p, xx: g._decode_qkv(p, 0, xx, geom),
                  in_shardings=(psh, repl))
    attn = jax.jit(
        lambda p, xx, qq, k, v, pos: g._decode_attn(
            p, 0, xx, qq, k, v, pos, geom),
        in_shardings=(psh, repl, head_sh, head_sh, head_sh, repl),
        out_shardings=repl)
    head = jax.jit(lambda p, xx: g._decode_head(p, xx),
                   in_shardings=(psh, repl), out_shardings=repl)
    return [
        ("serving.token_embed.tp", embed, (params, tokens, positions),
         mesh),
        ("serving.decode_qkv.tp", qkv, (params, x), mesh),
        ("serving.decode_attn.tp", attn,
         (params, x, q, kc, kc, positions), mesh),
        ("serving.decode_head.tp", head, (params, x), mesh),
    ]


def _prog_cache_write_tp():
    """The donated paged-cache write under head sharding: kc/vc are
    donated AND hold the same head-sharded layout in and out — the
    donation true-negative the plan pins (contrast with the training
    step's donation:reshard hit)."""
    from jax.sharding import PartitionSpec as P

    from ..models import generation as g

    params, geom, mesh = _serving_tp_setup()
    L, H, D, S = geom
    dtype = jnp.asarray(params["wte.weight"]).dtype
    B = 8
    # ptlint: disable=PT-S001  committed registry layout (head-sharded
    # KV donation true-negative the plan pins)
    head_sh = _named(mesh, P(None, "tp", None, None))
    repl = _named(mesh, P())
    kc = jnp.zeros((B, H, S, D), dtype)
    k_new = jnp.zeros((B, H, 1, D), dtype)
    pos = jnp.zeros((), jnp.int32)
    fn = jax.jit(
        lambda kc, vc, kn, vn, p: g._cache_write.__wrapped__(
            kc, vc, kn, vn, p),
        in_shardings=(head_sh, head_sh, head_sh, head_sh, repl),
        out_shardings=(head_sh, head_sh),
        # ptlint: disable=PT-T009  deliberately mirrors generation.
        # _cache_write's planned donation so the analyzer can prove the
        # head-sharded in==out layout keeps the aliasing intact (the
        # donation true-negative this registry program exists to pin)
        donate_argnums=(0, 1))
    return fn, (kc, kc, k_new, k_new, pos), mesh


# The committed registry. Suppression reasons ARE the triage record —
# the plan cannot be written while any finding lacks one.
_SHARD_REGISTRY: Tuple[_ShardProgram, ...] = (
    _ShardProgram(
        "train_step.fsdp_tp", _prog_train_fsdp_tp,
        suppress={
            "implicit:psum:tp":
                "Megatron tp reductions by design: the vocab-parallel "
                "wte lookup (masked local gather + psum) and the "
                "RowParallelLinear contractions (attn.out / mlp.down "
                "contract the tp-sharded inner dim), one all-reduce "
                "per block pair (distributed/tp_layers.py)",
            "implicit:psum:sharding":
                "data-parallel gradient synchronization over the "
                "'sharding' axis; with ZeRO-1 moments XLA lowers this "
                "psum + sharded update to reduce-scatter + allgather "
                "(weight-update sharding, arxiv 2004.13336)",
            "implicit:all_gather:sharding":
                "ZeRO-1 weight-update allgather: params stay "
                "replicated while updates are computed over sharded "
                "moments, so the new params gather over 'sharding' "
                "once per step — intentional (stage-1 trades this "
                "gather for sharded optimizer state)",
            "implicit:all_gather:tp":
                "lm_head gather_output=True: the vocab-sharded logits "
                "gather at the loss flatten so cross-entropy sees the "
                "full vocab (tp_layers.ColumnParallelLinear)",
            "donation:reshard:27":
                "REAL HIT (triaged, intentional): the donated params "
                "pytree (flat invar 27) aliases a new param produced "
                "through the ZeRO-1 'sharding' weight-update path — "
                "XLA materializes the gathered copy before writing "
                "the donated buffer. Keeping stage-1 semantics; "
                "stage-3 (PARAMETER) removes the gather by keeping "
                "params sharded",
        }),
    _ShardProgram(
        "train_step.dp", _prog_train_dp,
        suppress={
            "implicit:psum:dp":
                "the data-parallel gradient all-reduce: every grad "
                "dot contracts the dp-sharded batch dim (this IS the "
                "allreduce jaxcost charges explicitly in "
                "collective.psum_tree)",
        }),
    _ShardProgram("collective.ring_attention", None),
    _ShardProgram("collective.ulysses_attention", None),
    _ShardProgram("collective.psum_tree", None),
    _ShardProgram(
        "serving.token_embed.tp", None,
        suppress={
            "implicit:psum:tp":
                "vocab-parallel embedding lookup: gathering rows from "
                "the tp-sharded wte is lowered as masked local lookup "
                "+ psum (tp_layers.VocabParallelEmbedding semantics)",
        }),
    _ShardProgram(
        "serving.decode_qkv.tp", None,
        suppress={
            "implicit:all_gather:tp":
                "fused qkv [B,1,3C]->[B,1,3,H,D] reshape crosses the "
                "tp-tiled column dim (the split's leading factor 3 is "
                "not divisible by tp=4), so the column shards gather "
                "before re-tiling onto heads — a per-token 3C row, "
                "accepted; the committed serving layout keeps q/k/v "
                "head-sharded after this point",
        }),
    _ShardProgram(
        "serving.decode_attn.tp", None,
        suppress={
            "implicit:psum:tp":
                "REAL HIT (triaged, intentional): the Megatron "
                "row-parallel attention-output reduction — att "
                "[B,1,C] is tp-sharded on C after the head merge and "
                "contracts with the replicated out-projection, one "
                "psum per decode step per layer. This is the quantized-"
                "collective target of ROADMAP item 2",
        }),
    _ShardProgram(
        "serving.decode_head.tp", None,
        suppress={
            "implicit:all_gather:tp":
                "REAL HIT (triaged, intentional): serving logits "
                "[B,V] leave the column-parallel lm_head gathered to "
                "full replication (>=1MiB at vocab 32768) because the "
                "sampler consumes the full vocab row; a sharded "
                "top-k would remove this gather (ROADMAP item 2)",
            "replication:pjit_out:tp":
                "same gather as implicit:all_gather:tp — the "
                "replicated-logits contract of the dense sampler",
        }),
    _ShardProgram("serving.cache_write.tp", _prog_cache_write_tp),
)


def registry_names() -> List[str]:
    return [p.name for p in _SHARD_REGISTRY]


def _build_shard_programs(names: Optional[Sequence[str]] = None):
    known = {p.name: p for p in _SHARD_REGISTRY}
    if names is not None:
        unknown = sorted(set(names) - set(known))
        if unknown:
            raise KeyError(
                f"unknown program(s): {', '.join(unknown)}; known: "
                f"{', '.join(known)}")
    wanted = list(names) if names is not None else list(known)
    out = []
    coll = None
    serv = None
    for name in wanted:
        prog = known[name]
        if prog.build is not None:
            out.append((prog, prog.build))
            continue
        if name.startswith("collective."):
            if coll is None:
                coll = {n: (f, a, m)
                        for n, f, a, m in _collective_mesh_programs()}
            fam = coll
        else:
            if serv is None:
                serv = {n: (f, a, m)
                        for n, f, a, m in _serving_decode_programs()}
            fam = serv
        f, a, m = fam[name]
        out.append((prog, lambda f=f, a=a, m=m: (f, a, m)))
    return out


def compute_reports(names: Optional[Sequence[str]] = None,
                    envelope: Optional[int] = None,
                    ) -> Dict[str, ShardReport]:
    """Analyze every (selected) registry program."""
    reports = {}
    for prog, build in _build_shard_programs(names):
        fn, args, mesh = build()
        reports[prog.name] = analyze_jit(
            fn, *args, name=prog.name, mesh=mesh, envelope=envelope,
            suppress=prog.suppress)
    return reports


# ------------------------------------------------------------ plan I/O
def _plan_payload(reports: Dict[str, ShardReport]) -> dict:
    return {
        "version": PLAN_VERSION,
        "tolerance": DEFAULT_TOLERANCE,
        "envelope_bytes": next(iter(reports.values())).envelope_bytes
        if reports else _default_envelope(),
        "programs": {name: rep.to_dict()
                     for name, rep in sorted(reports.items())},
    }


def write_plan(path: str, reports: Dict[str, ShardReport]) -> dict:
    payload = _plan_payload(reports)
    with open(path, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    return payload


@functools.lru_cache(maxsize=16)
def _load_plan_cached(path: str, mtime_ns: int) -> dict:
    with open(path) as f:
        return json.load(f)


def load_plan(path: str = DEFAULT_PLAN_PATH) -> Optional[dict]:
    """Committed shard plan, or None when missing. stdlib-only."""
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        return None
    return _load_plan_cached(path, mtime)


def committed_shard_factors(path: str = DEFAULT_PLAN_PATH
                            ) -> Dict[str, Dict[str, int]]:
    """program name -> mesh axis sizes from the committed plan (the
    shard factors jaxcost's cross-artifact check consumes)."""
    plan = load_plan(path)
    if not plan:
        return {}
    return {name: dict(entry.get("mesh", {}))
            for name, entry in plan.get("programs", {}).items()}


def _num_drift(cur, ref, tol: float) -> bool:
    lo, hi = sorted((float(cur), float(ref)))
    return hi - lo > tol * max(hi, 1.0)


def diff_plans(committed: dict, current: dict,
               tolerance: Optional[float] = None) -> List[str]:
    """Violations between a committed plan and a freshly computed one:
    coverage both directions, structural drift exact, bytes within
    tolerance."""
    tol = tolerance if tolerance is not None else float(
        committed.get("tolerance", DEFAULT_TOLERANCE))
    out: List[str] = []
    cp = committed.get("programs", {})
    np_ = current.get("programs", {})
    for name in sorted(set(cp) - set(np_)):
        out.append(f"{name}: committed but no longer in the registry")
    for name in sorted(set(np_) - set(cp)):
        out.append(f"{name}: registry program missing from the "
                   f"committed plan")
    for name in sorted(set(cp) & set(np_)):
        a, b = cp[name], np_[name]
        if a.get("mesh") != b.get("mesh"):
            out.append(f"{name}: mesh drift {a.get('mesh')} -> "
                       f"{b.get('mesh')}")
        if int(a.get("edge_count", 0)) != int(b.get("edge_count", 0)):
            out.append(f"{name}: resharding edge count "
                       f"{a.get('edge_count')} -> "
                       f"{b.get('edge_count')}")
        if bool(a.get("envelope_ok", True)) \
                != bool(b.get("envelope_ok", True)):
            out.append(f"{name}: envelope_ok flipped "
                       f"{a.get('envelope_ok')} -> "
                       f"{b.get('envelope_ok')}")
        for fieldname in ("implicit_axis_bytes", "explicit_axis_bytes"):
            fa, fb = a.get(fieldname, {}), b.get(fieldname, {})
            if sorted(fa) != sorted(fb):
                out.append(f"{name}: {fieldname} axes "
                           f"{sorted(fa)} -> {sorted(fb)}")
                continue
            for ax in fa:
                if _num_drift(fb[ax], fa[ax], tol):
                    out.append(
                        f"{name}: {fieldname}[{ax}] drifted "
                        f"{fa[ax]:,} -> {fb[ax]:,} (> {tol:.0%})")
        for fieldname in ("comm_bytes_total", "per_device_peak_bytes"):
            if _num_drift(b.get(fieldname, 0), a.get(fieldname, 0),
                          tol):
                out.append(f"{name}: {fieldname} drifted "
                           f"{a.get(fieldname, 0):,} -> "
                           f"{b.get(fieldname, 0):,} (> {tol:.0%})")
        af, bf = a.get("findings", {}), b.get("findings", {})
        if sorted(af) != sorted(bf):
            out.append(f"{name}: finding keys drifted "
                       f"{sorted(af)} -> {sorted(bf)}")
        else:
            for key in af:
                sa = af[key].get("suppressed")
                sb = bf[key].get("suppressed")
                if bool(sa) != bool(sb):
                    out.append(f"{name}: finding {key} suppression "
                               f"changed ({bool(sa)} -> {bool(sb)})")
    return out


def unsuppressed_findings(reports: Dict[str, ShardReport]
                          ) -> List[str]:
    out = []
    for name, rep in sorted(reports.items()):
        for f in rep.unsuppressed():
            out.append(f"{name}: {f.key}: {f.message}")
    return out


def check_plan(path: str = DEFAULT_PLAN_PATH,
               reports: Optional[Dict[str, ShardReport]] = None,
               ) -> List[str]:
    """Violations of the committed plan: missing/stale file, version
    drift, structural/numeric drift vs a fresh analysis, and any
    unsuppressed finding."""
    committed = load_plan(path)
    if committed is None:
        return [f"no committed shard plan at {path} — run "
                f"tools/jaxshard.py --plan write"]
    if committed.get("version") != PLAN_VERSION:
        return [f"plan version {committed.get('version')} != analyzer "
                f"version {PLAN_VERSION} — re-write the plan"]
    if reports is None:
        reports = compute_reports(
            envelope=int(committed.get("envelope_bytes", 0)) or None)
    out = unsuppressed_findings(reports)
    out += diff_plans(committed, _plan_payload(reports))
    return out


# --------------------------------------------------- cross-artifact check
def crosscheck_with_budget(budget: dict,
                           plan_path: str = DEFAULT_PLAN_PATH,
                           tolerance: Optional[float] = None,
                           ) -> List[str]:
    """jaxcost x jaxshard consistency: for every program committed in
    BOTH artifacts, jaxshard's explicit per-axis bytes must sum to
    jaxcost's comm_bytes (same byte table, so disagreement means one
    artifact is stale). stdlib-only; returns violation strings."""
    plan = load_plan(plan_path)
    if not plan:
        return []  # no shard plan committed yet: nothing to check
    tol = tolerance if tolerance is not None else float(
        plan.get("tolerance", DEFAULT_TOLERANCE))
    out: List[str] = []
    budget_programs = budget.get("programs", {})
    for name, entry in sorted(plan.get("programs", {}).items()):
        if name not in budget_programs:
            continue
        shard_comm = sum(entry.get("explicit_axis_bytes", {}).values())
        cost_comm = int(budget_programs[name].get("comm_bytes", 0))
        if _num_drift(shard_comm, cost_comm, tol):
            out.append(
                f"{name}: jaxshard explicit collective bytes "
                f"{shard_comm:,} disagree with jaxcost comm_bytes "
                f"{cost_comm:,} (> {tol:.0%}) — shardplan.json and "
                f"jaxcost_budget.json have drifted apart")
    return out
