"""paddle_tpu.analysis: framework-aware static analysis (ptlint).

The runtime invariants this package guards are the ones no unit test can
see until they break in production (docs/static_analysis.md):

- trace safety: jitted programs must stay trace-pure and recompile-free
  (rules/trace_safety.py — tracer branching, host materialization,
  Python side effects under trace, jit-in-loop recompile churn,
  non-hashable statics, host RNG under trace);
- jaxpr health: the compiled entry points (jit.TrainStep, the decode
  sub-programs) must not grow host callbacks, captured-constant bloat
  or silent dtype downcasts (jaxpr_audit.py — a trace-time check, the
  analogue of the reference's graph-pass validation in
  paddle/fluid/framework/ir);
- lock discipline: shared serving state annotated in a `_GUARDED_BY`
  map is only touched while holding its lock (rules/concurrency.py);
- static cost: jaxcost.py + liveness.py model FLOPs, bytes, collective
  volume and peak live-buffer bytes of every registered jitted program
  from its jaxpr, gate them against jaxcost_budget.json, and audit
  buffer donation (docs/static_cost.md); hlo_bytes.py is the shared
  HLO-text byte accounting used by tools/hlo_bytes.py and
  tools/scaling_analysis.py.

The lint core (ast_core + rules + hlo_bytes) is stdlib-only so
`tools/ptlint.py` and `tools/hlo_bytes.py` run without importing jax;
`jaxpr_audit`, `liveness` and `jaxcost` need jax and are imported on
demand (never from this __init__).
"""
from __future__ import annotations

from .ast_core import (Finding, LintEngine, LintReport, load_baseline,
                       write_baseline)
from .rules import RULE_CATALOG, default_rules

__all__ = ["Finding", "LintEngine", "LintReport", "RULE_CATALOG",
           "default_rules", "holds_lock", "load_baseline",
           "write_baseline"]


def holds_lock(*locks):
    """Annotate a method as requiring its CALLER to already hold the
    named lock attribute(s) (e.g. ``@holds_lock("_lock")``).

    Runtime no-op; the ptlint concurrency rule (PT-C001) treats every
    access to a `_GUARDED_BY` field inside a decorated method as guarded.
    The annotation is a promise the call graph must keep — public entry
    points take the lock with ``with self._lock:`` and only they may call
    a ``holds_lock`` helper."""
    def deco(fn):
        fn._ptlint_holds_locks = tuple(locks)
        return fn
    return deco
