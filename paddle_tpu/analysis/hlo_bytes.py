"""HLO-text byte accounting — the one source of truth.

Stdlib-only (no jax import) so CLI wrappers can parse dumps without
initializing a backend. Three entry points:

- `shape_bytes(text)`: bytes of every HLO shape literal in a string
  (`f32[8,128]` -> 4096); tuples and layout `{...}` blocks tolerated;
- `audit_text(text, top_n)`: rank an optimized-HLO ENTRY computation's
  instructions by first-order HBM traffic (output + operand bytes;
  fusion internals intentionally uncounted — they live in VMEM);
- `allreduce_payload(hlo)`: total payload bytes and op count over
  `all-reduce` / `all-reduce-start` defining lines of a partitioned
  module (the per-device wire-volume invariant scaling_analysis gates).

tools/hlo_bytes.py is a thin CLI wrapper over this module, and
analysis/jaxcost.py re-exports `shape_bytes` so jaxpr-level and
HLO-level byte accounting share one dtype table.
"""
from __future__ import annotations

import re
from collections import defaultdict

__all__ = ["DTYPE_BYTES", "shape_bytes", "audit_text",
           "allreduce_payload"]

DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2,
               "f16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
               "f64": 8, "c64": 8, "c128": 16}

_SHAPE_RE = re.compile(r"\b([a-z]+\d*)\[([\d,]*)\]")
# "  %name = <type> <opkind>(operands...), attrs"  — type may contain
# tuple parens and {layout} blocks; opkind is a bare lowercase word with
# optional dashes directly before the operand paren.
_INSTR_RE = re.compile(r"^\s+(%[\w.-]+)\s*=\s*(.*?)\s([a-z][a-z0-9-]*)\(")
_OPERAND_RE = re.compile(r"%[\w.-]+")


def shape_bytes(text: str) -> int:
    """Sum bytes over every HLO shape literal found in `text`."""
    total = 0
    for dt, dims in _SHAPE_RE.findall(text):
        b = DTYPE_BYTES.get(dt)
        if b is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * b
    return total


def allreduce_payload(hlo: str):
    """(payload_bytes, op_count) over all-reduce ops in partitioned HLO.

    Shapes appear as `f32[1576960]{0} all-reduce(` or, for multi-operand
    ops, `(f32[8], f32[16384]) all-reduce(`. Counts each op once (the
    defining line, not operand uses).
    """
    total, count = 0, 0
    for line in hlo.splitlines():
        m = re.search(r"=\s+(\([^)]*\)|\S+)\s+all-reduce(?:-start)?\(",
                      line)
        if not m:
            continue
        count += 1
        total += shape_bytes(m.group(1))
    return total, count


def audit_text(text: str, top_n: int = 30):
    """Rank ENTRY instructions of an optimized-HLO dump by bytes touched
    (output + named operands). Prints a report; returns the rows."""
    i = text.index("\nENTRY ")
    entry = text[i + 1:]
    entry = entry[:entry.index("\n}")]
    lines = entry.splitlines()
    # entry params: name: type pairs in the header (may span the one line)
    out_bytes = {}
    header = lines[0]
    for m in re.finditer(r"(%?[\w.-]+):\s*((?:\([^)]*\)|[a-z]+\d*\[[\d,]*\])"
                         r"(?:\{[^}]*\})?)", header):
        out_bytes["%" + m.group(1).lstrip("%")] = shape_bytes(m.group(2))
    rows = []
    for line in lines[1:]:
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, out_type, kind = m.groups()
        ob = shape_bytes(out_type)
        out_bytes[name] = ob
        # operand list: inside the first top-level paren after kind
        args_start = line.index(kind + "(") + len(kind)
        depth = 0
        j = args_start
        for j in range(args_start, len(line)):
            if line[j] == "(":
                depth += 1
            elif line[j] == ")":
                depth -= 1
                if depth == 0:
                    break
        args = line[args_start:j + 1]
        ab = sum(out_bytes.get(op, 0) for op in _OPERAND_RE.findall(args))
        rows.append((ob + ab, ob, ab, kind, name, line.strip()[:180]))
    rows.sort(reverse=True)
    total = sum(r[0] for r in rows)
    print(f"total touched (first-order): {total/1e9:.2f} GB over "
          f"{len(rows)} instructions")
    by_kind = defaultdict(float)
    for tb, ob, ab, kind, name, _ in rows:
        by_kind[kind] += tb
    print("\n== bytes by op kind ==")
    for kind, b in sorted(by_kind.items(), key=lambda kv: -kv[1])[:15]:
        print(f"{b/1e9:8.2f} GB  {kind}")
    print(f"\n== top {top_n} instructions ==")
    print(f"{'MB':>9} {'outMB':>8} {'kind':<14} name")
    for tb, ob, ab, kind, name, line in rows[:top_n]:
        print(f"{tb/1e6:9.1f} {ob/1e6:8.1f} {kind:<14} {name[:60]}")
    # f32 big-tensor check: any instruction producing a large fp32 output
    big_f32 = [(ob, name, line) for tb, ob, ab, kind, name, line in rows
               if ob > 40e6 and re.search(r"\bf32\[", line.split(" = ")[1]
                                          if " = " in line else line)]
    print(f"\n== >40MB fp32 outputs: {len(big_f32)} ==")
    for ob, name, line in big_f32[:15]:
        print(f"{ob/1e6:9.1f} {name[:60]}")
    return rows
