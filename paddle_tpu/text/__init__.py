"""paddle.text (reference: python/paddle/text/datasets/)."""
from .datasets import Imdb, UCIHousing  # noqa: F401
