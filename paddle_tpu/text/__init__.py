"""paddle.text (reference: python/paddle/text/datasets/)."""
from .datasets import (  # noqa: F401
    Imdb, UCIHousing, Imikolov, Movielens, Conll05st, WMT14, WMT16,
)
