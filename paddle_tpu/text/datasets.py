"""Text datasets (reference: python/paddle/text/datasets/imdb.py,
uci_housing.py). Local-file loading with synthetic fallback (zero egress)."""
from __future__ import annotations

import os
import re
import tarfile

import numpy as np

from ..io import Dataset


class Imdb(Dataset):
    def __init__(self, data_file=None, mode="train", cutoff=150):
        self.mode = mode
        if data_file and os.path.exists(data_file):
            self._load_real(data_file, mode, cutoff)
        else:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            n, vocab = 512, 5000
            self.docs = [rng.randint(2, vocab, rng.randint(20, 100))
                         for _ in range(n)]
            self.labels = rng.randint(0, 2, n).astype(np.int64)
            self.word_idx = {f"w{i}": i for i in range(vocab)}

    def _load_real(self, data_file, mode, cutoff):
        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        freq = {}
        docs, labels = [], []
        with tarfile.open(data_file) as tf:
            for member in tf.getmembers():
                m = pat.match(member.name)
                if not m:
                    continue
                text = tf.extractfile(member).read().decode(
                    "latin-1").lower().split()
                docs.append(text)
                labels.append(1 if m.group(1) == "pos" else 0)
                for w in text:
                    freq[w] = freq.get(w, 0) + 1
        words = sorted(freq, key=lambda w: -freq[w])[:cutoff]
        self.word_idx = {w: i + 2 for i, w in enumerate(words)}
        self.docs = [np.asarray([self.word_idx.get(w, 1) for w in d],
                                np.int64) for d in docs]
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class UCIHousing(Dataset):
    FEATURES = 13

    def __init__(self, data_file=None, mode="train"):
        if data_file and os.path.exists(data_file):
            raw = np.loadtxt(data_file).astype(np.float32)
        else:
            rng = np.random.RandomState(0)
            X = rng.randn(506, self.FEATURES).astype(np.float32)
            w = rng.randn(self.FEATURES).astype(np.float32)
            y = X @ w + rng.randn(506).astype(np.float32) * 0.1
            raw = np.concatenate([X, y[:, None]], axis=1)
        split = int(len(raw) * 0.8)
        self.data = raw[:split] if mode == "train" else raw[split:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)




def _no_real_loader(cls_name, data_file):
    if data_file:
        raise NotImplementedError(
            f"{cls_name}: loading a real corpus from {data_file!r} is not "
            "implemented in this build (zero-egress environment ships "
            "synthetic fallbacks); pass data_file=None for synthetic data "
            "or preprocess the corpus into the slot-file format for "
            "paddle_tpu.io.InMemoryDataset.")


class Imikolov(Dataset):
    """reference: text/datasets/imikolov.py — PTB-style n-gram/seq pairs.
    Local-file loading with synthetic fallback (zero egress)."""

    BOS, EOS = 0, 1

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50):
        if data_type not in ("NGRAM", "SEQ"):
            raise ValueError("data_type must be 'NGRAM' or 'SEQ'")
        self.data_type = data_type
        self.window_size = window_size
        if data_file:
            if not os.path.exists(data_file):
                raise FileNotFoundError(
                    f"Imikolov: data_file {data_file!r} does not exist "
                    "(pass None for the synthetic fallback)")
            self._load_real(data_file, mode, min_word_freq)
        else:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            vocab = 2000
            self.word_idx = {f"w{i}": i for i in range(vocab)}
            stream = rng.randint(2, vocab, 20000)
            if data_type == "SEQ":
                # variable-length [BOS, ..., EOS] sequences
                self.data = []
                i = 0
                while i < len(stream) - 2:
                    ln = int(rng.randint(3, 12))
                    seq = stream[i:i + ln]
                    self.data.append(tuple([self.BOS, *seq, self.EOS]))
                    i += ln
            else:
                # mirror the real reader: pseudo-lines wrapped in <s>/<e>
                # before the n-gram window (reference builds n-grams over
                # ['<s>'] + line + ['<e>'])
                self.data = []
                i = 0
                while i < len(stream):
                    ln = int(rng.randint(3, 12))
                    ids = [self.BOS, *stream[i:i + ln], self.EOS]
                    for j in range(0, max(len(ids) - window_size + 1, 0)):
                        self.data.append(tuple(ids[j:j + window_size]))
                    i += ln

    def _load_real(self, data_file, mode, min_word_freq):
        sub = "train" if mode == "train" else "valid"
        freq = {}
        lines = []
        with tarfile.open(data_file) as tf:
            for m in tf.getmembers():
                if f"ptb.{sub}.txt" in m.name:
                    lines = tf.extractfile(m).read().decode().splitlines()
        for ln in lines:
            for w in ln.split():
                freq[w] = freq.get(w, 0) + 1
        # specials live IN word_idx (reference includes '<unk>' too), so
        # Embedding(len(ds.word_idx)) covers every emitted id; PTB corpora
        # contain a literal '<unk>' token — exclude specials from the
        # frequency ranking so ids stay dense and in-range
        specials = ("<s>", "<e>", "<unk>")
        words = [w for w, c in sorted(freq.items(), key=lambda kv: -kv[1])
                 if c >= min_word_freq and w not in specials]
        self.word_idx = {"<s>": 0, "<e>": 1}
        for i, w in enumerate(words):
            self.word_idx[w] = i + 2
        unk = len(self.word_idx)
        self.word_idx["<unk>"] = unk
        self.data = []
        for ln in lines:
            ids = [self.word_idx.get(w, unk) for w in ln.split()]
            if self.data_type == "SEQ":
                if ids:
                    self.data.append(tuple([self.BOS, *ids, self.EOS]))
                continue
            # reference reader builds n-grams over ['<s>'] + line + ['<e>'],
            # so boundary tokens participate and short lines still emit
            ids = [self.BOS, *ids, self.EOS]
            # +1: a line of exactly window_size tokens yields one n-gram
            for i in range(0, max(len(ids) - self.window_size + 1, 0)):
                self.data.append(tuple(ids[i:i + self.window_size]))

    def __getitem__(self, idx):
        if self.data_type == "SEQ":
            return np.asarray(self.data[idx], np.int64)
        return tuple(np.asarray(v, np.int64) for v in self.data[idx])

    def __len__(self):
        return len(self.data)


class Movielens(Dataset):
    """reference: text/datasets/movielens.py — (user, movie, rating)
    records with categorical features (synthetic fallback)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0):
        _no_real_loader("Movielens", data_file)
        rng = np.random.RandomState(rand_seed)
        n_users, n_movies = 500, 1000
        n = 8000
        users = rng.randint(0, n_users, n)
        movies = rng.randint(0, n_movies, n)
        # learnable structure: rating correlates with (user+movie) parity
        ratings = (1 + (users + movies) % 5).astype(np.float32)
        split = int(n * (1 - test_ratio))
        sl = slice(0, split) if mode == "train" else slice(split, n)
        self.data = list(zip(users[sl], movies[sl], ratings[sl]))

    def __getitem__(self, idx):
        u, m, r = self.data[idx]
        return (np.asarray([u], np.int64), np.asarray([m], np.int64),
                np.asarray([r], np.float32))

    def __len__(self):
        return len(self.data)


class Conll05st(Dataset):
    """reference: text/datasets/conll05.py — SRL: (tokens, predicate,
    labels) triples (synthetic fallback with consistent tag structure)."""

    LABELS = 59  # reference label dict size

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, mode="train"):
        _no_real_loader("Conll05st", data_file)
        rng = np.random.RandomState(0 if mode == "train" else 1)
        vocab, n = 3000, 512
        self.word_dict = {f"w{i}": i for i in range(vocab)}
        self.label_dict = {f"tag{i}": i for i in range(self.LABELS)}
        self.data = []
        for _ in range(n):
            ln = rng.randint(5, 30)
            words = rng.randint(0, vocab, ln)
            pred = rng.randint(0, ln)
            labels = rng.randint(0, self.LABELS, ln)
            self.data.append((words, pred, labels))

    def get_dict(self):
        return self.word_dict, {0: 0}, self.label_dict

    def __getitem__(self, idx):
        words, pred, labels = self.data[idx]
        return (np.asarray(words, np.int64), np.asarray([pred], np.int64),
                np.asarray(labels, np.int64))

    def __len__(self):
        return len(self.data)


class WMT14(Dataset):
    """reference: text/datasets/wmt14.py — (src_ids, trg_ids, trg_next)
    translation triples (synthetic fallback)."""

    BOS, EOS, UNK = 0, 1, 2

    def __init__(self, data_file=None, mode="train", dict_size=3000):
        _no_real_loader(type(self).__name__, data_file)
        rng = np.random.RandomState(0 if mode == "train" else 1)
        self.dict_size = max(int(dict_size), 10)
        n = 512
        self.data = []
        for _ in range(n):
            ln = rng.randint(4, 20)
            src = rng.randint(3, self.dict_size, ln)
            trg = (src[::-1] % (self.dict_size - 3)) + 3  # learnable rule
            self.data.append((src,
                              np.concatenate([[self.BOS], trg]),
                              np.concatenate([trg, [self.EOS]])))

    def get_dict(self, lang="en", reverse=False):
        d = {f"tok{i}": i for i in range(self.dict_size)}
        return {v: k for k, v in d.items()} if reverse else d

    def __getitem__(self, idx):
        s, t, tn = self.data[idx]
        return (np.asarray(s, np.int64), np.asarray(t, np.int64),
                np.asarray(tn, np.int64))

    def __len__(self):
        return len(self.data)


class WMT16(WMT14):
    """reference: text/datasets/wmt16.py — same triple shape, subword
    vocab (synthetic fallback shares the WMT14 generator)."""

    def __init__(self, data_file=None, mode="train", src_dict_size=3000,
                 trg_dict_size=3000, lang="en"):
        super().__init__(data_file, mode, max(src_dict_size, trg_dict_size))
