"""Text datasets (reference: python/paddle/text/datasets/imdb.py,
uci_housing.py). Local-file loading with synthetic fallback (zero egress)."""
from __future__ import annotations

import os
import re
import tarfile

import numpy as np

from ..io import Dataset


class Imdb(Dataset):
    def __init__(self, data_file=None, mode="train", cutoff=150):
        self.mode = mode
        if data_file and os.path.exists(data_file):
            self._load_real(data_file, mode, cutoff)
        else:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            n, vocab = 512, 5000
            self.docs = [rng.randint(2, vocab, rng.randint(20, 100))
                         for _ in range(n)]
            self.labels = rng.randint(0, 2, n).astype(np.int64)
            self.word_idx = {f"w{i}": i for i in range(vocab)}

    def _load_real(self, data_file, mode, cutoff):
        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        freq = {}
        docs, labels = [], []
        with tarfile.open(data_file) as tf:
            for member in tf.getmembers():
                m = pat.match(member.name)
                if not m:
                    continue
                text = tf.extractfile(member).read().decode(
                    "latin-1").lower().split()
                docs.append(text)
                labels.append(1 if m.group(1) == "pos" else 0)
                for w in text:
                    freq[w] = freq.get(w, 0) + 1
        words = sorted(freq, key=lambda w: -freq[w])[:cutoff]
        self.word_idx = {w: i + 2 for i, w in enumerate(words)}
        self.docs = [np.asarray([self.word_idx.get(w, 1) for w in d],
                                np.int64) for d in docs]
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class UCIHousing(Dataset):
    FEATURES = 13

    def __init__(self, data_file=None, mode="train"):
        if data_file and os.path.exists(data_file):
            raw = np.loadtxt(data_file).astype(np.float32)
        else:
            rng = np.random.RandomState(0)
            X = rng.randn(506, self.FEATURES).astype(np.float32)
            w = rng.randn(self.FEATURES).astype(np.float32)
            y = X @ w + rng.randn(506).astype(np.float32) * 0.1
            raw = np.concatenate([X, y[:, None]], axis=1)
        split = int(len(raw) * 0.8)
        self.data = raw[:split] if mode == "train" else raw[split:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)
