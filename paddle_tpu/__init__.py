"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle-parity
capabilities (reference: /root/reference, PaddlePaddle ~v2.0/2.1-dev).

Architecture (see SURVEY.md §7): the user-visible surface mirrors paddle 2.0
(dygraph Tensor/Layer/optimizer, static Program/Executor, Fleet distributed
strategies), while the execution substrate is JAX/XLA — ops are pure JAX
functions that run eagerly with a vjp autograd tape, and compile into single
fused XLA programs under paddle_tpu.jit / pjit / shard_map. Distribution is
SPMD over jax.sharding.Mesh with XLA collectives on ICI/DCN instead of
NCCL rings.
"""
from __future__ import annotations

import jax as _jax

# Paddle's default index/integer dtype is int64 (reference:
# framework.proto VarType INT64 used by lookup_table, arg_max, …). jax
# truncates to 32-bit unless x64 is enabled; float defaults stay f32 via this
# package's own dtype plumbing (core.dtypes.get_default_dtype).
_jax.config.update("jax_enable_x64", True)

# -- core dtypes (paddle.float32 etc.) --------------------------------------
from .core.dtypes import (  # noqa: F401
    bool_ as bool8, uint8, int8, int16, int32, int64, float16, bfloat16,
    float32, float64, complex64, complex128,
    set_default_dtype, get_default_dtype, convert_dtype,
)
from .core.dtypes import bool_  # noqa: F401

# -- places / devices -------------------------------------------------------
from .core.place import (  # noqa: F401
    CPUPlace, CUDAPlace, CUDAPinnedPlace, TPUPlace, XLAPlace, Place,
    set_device, get_device, is_compiled_with_cuda, is_compiled_with_tpu,
    device_count,
)

# -- flags / errors ---------------------------------------------------------
from .core.flags import set_flags, get_flags  # noqa: F401
from .core import errors  # noqa: F401
from .core import monitor  # noqa: F401
from .core import anomaly  # noqa: F401

# -- tensor + autograd ------------------------------------------------------
from .core.tensor import Tensor, to_tensor  # noqa: F401
from .core.autograd import (  # noqa: F401
    no_grad, enable_grad, set_grad_enabled, is_grad_enabled, grad,
)
from .core.random import seed, get_rng_state  # noqa: F401

# -- ops --------------------------------------------------------------------
from .ops import *  # noqa: F401,F403
from . import ops  # noqa: F401
from .ops import sum, max, min, abs, all, any, round, pow, slice  # noqa: F401,A004
from .ops import fft  # noqa: E402  (paddle.fft module parity)

# -- subsystem namespaces ---------------------------------------------------
from . import nn  # noqa: F401,E402
from . import optimizer  # noqa: F401,E402
from . import amp  # noqa: F401,E402
from . import io  # noqa: F401,E402
from . import metric  # noqa: F401,E402
from . import jit  # noqa: F401,E402
from . import static  # noqa: F401,E402
from . import vision  # noqa: F401,E402
from . import text  # noqa: F401,E402
from . import distributed  # noqa: F401,E402
from . import models  # noqa: F401,E402
from . import parallel  # noqa: F401,E402
from . import utils  # noqa: F401,E402
from . import hapi  # noqa: F401,E402
from . import incubate  # noqa: F401,E402
from . import obs  # noqa: F401,E402
from . import profiler  # noqa: F401,E402
from . import inference  # noqa: F401,E402
from . import slim  # noqa: F401,E402
from .hapi import Model, summary, flops  # noqa: F401,E402
from .hapi import callbacks  # noqa: F401,E402
from .framework_io import save, load  # noqa: F401,E402

from .nn.layer.base import ParamAttr  # noqa: E402

# legacy op-name aliases resolve against ops registered by nn.functional
from .ops.extra_ops import register_legacy_aliases as _rla  # noqa: E402
_rla()

# dygraph-mode API parity helpers (reference: fluid/framework.py
# in_dygraph_mode; this framework is dygraph-by-default like paddle 2.0)
from .static.mode import (  # noqa: F401,E402
    in_dynamic_mode, enable_static, disable_static,
)


def disable_signal_handler():
    """Parity no-op (reference: pybind disable_signal_handler)."""


def in_dygraph_mode():
    return in_dynamic_mode()


class DataParallel:  # real impl re-exported below once distributed loads
    pass


from .distributed.parallel import DataParallel  # noqa: F401,E402,F811

# ---------------------------------------------------------------------------
# top-level namespace parity with the reference python/paddle/__init__.py
# (audited mechanically by tests/test_api_parity.py)

from . import distribution  # noqa: F401,E402
from . import regularizer  # noqa: F401,E402
from . import compat  # noqa: F401,E402
from . import sysconfig  # noqa: F401,E402
from . import onnx  # noqa: F401,E402
from . import device  # noqa: F401,E402
from . import framework  # noqa: F401,E402
from .batch import batch  # noqa: F401,E402
from .legacy_api import *  # noqa: F401,F403,E402
from .core.place import XPUPlace  # noqa: F401,E402
from .core.selected_rows import get_tensor_from_selected_rows  # noqa: F401,E402
from .ops.extra_ops import multiplex  # noqa: F401,E402
from .ops.array_ops import TensorArray as LoDTensorArray  # noqa: E402
from .static.program import data  # noqa: F401,E402
from .static.nn import create_global_var  # noqa: F401,E402
from .static.program import create_parameter  # noqa: F401,E402
from . import ops as tensor  # noqa: F401,E402  (paddle.tensor module alias)

# pybind-era aliases: the eager tensor IS VarBase/LoDTensor here
VarBase = Tensor
LoDTensor = Tensor


def enable_dygraph(place=None):
    """reference fluid/dygraph/base.py enable_dygraph — dygraph is the
    default mode; this leaves static mode if it was entered."""
    disable_static()


def disable_dygraph():
    enable_static()


from .device import get_cudnn_version, is_compiled_with_xpu  # noqa: F401,E402

# the legacy namespace reference-era code imports (paddle.fluid.*);
# pure delegation onto the modules above
from . import fluid  # noqa: F401,E402
from . import reader  # noqa: F401,E402
from . import dataset  # noqa: F401,E402

__version__ = "0.1.0"
version = __version__
