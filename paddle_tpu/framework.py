"""paddle.framework — the reference's framework re-export module
(/root/reference/python/paddle/framework/__init__.py: random/seed,
get/set_default_dtype, ParamAttr, places, VarBase, no_grad, grad,
save/load, DataParallel)."""
from __future__ import annotations

from .core.random import seed  # noqa: F401
from .core.dtypes import get_default_dtype, set_default_dtype  # noqa: F401
from .nn.layer.base import ParamAttr  # noqa: F401
from .core.place import (  # noqa: F401
    CPUPlace, CUDAPlace, CUDAPinnedPlace, TPUPlace, XPUPlace,
)
from .core.tensor import Tensor  # noqa: F401
from .core.autograd import no_grad, grad  # noqa: F401
from .framework_io import save, load  # noqa: F401

VarBase = Tensor  # reference fluid/core VarBase == the eager tensor

__all__ = ["seed", "get_default_dtype", "set_default_dtype", "ParamAttr",
           "CPUPlace", "CUDAPlace", "CUDAPinnedPlace", "TPUPlace",
           "XPUPlace", "VarBase", "no_grad", "grad", "save", "load"]
