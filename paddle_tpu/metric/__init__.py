"""paddle.metric (reference: python/paddle/metric/metrics.py — Metric base,
Accuracy, Precision, Recall, Auc; paddle.metric.accuracy op wrapper)."""
from .metrics import Metric, Accuracy, Precision, Recall, Auc, accuracy  # noqa: F401
