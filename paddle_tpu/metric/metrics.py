"""Metrics (reference: python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """reference: operators/metrics/accuracy_op.cc."""
    pred = _np(input)
    lab = _np(label).reshape(-1)
    topk = np.argsort(-pred, axis=-1)[..., :k].reshape(len(lab), k)
    hit = (topk == lab[:, None]).any(axis=1)
    return Tensor(np.asarray(hit.mean(), dtype=np.float32))


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        p = _np(pred)
        l = _np(label)
        if l.ndim == p.ndim and l.shape[-1] != 1:
            l = l.argmax(-1)  # one-hot to index
        l = l.reshape(-1)
        topk = np.argsort(-p.reshape(len(l), -1), axis=-1)[:, :self.maxk]
        correct = (topk == l[:, None])
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = _np(correct)
        num = c.shape[0]
        accs = []
        for k in self.topk:
            hit = c[:, :k].sum()
            self.total[self.topk.index(k)] += hit
            self.count[self.topk.index(k)] += num
            accs.append(hit / max(num, 1))
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).reshape(-1).astype(int)
        l = _np(labels).reshape(-1).astype(int)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).reshape(-1).astype(int)
        l = _np(labels).reshape(-1).astype(int)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC AUC via threshold buckets (reference: metrics.py Auc /
    operators/metrics/auc_op.cc)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc", *args,
                 **kwargs):
        super().__init__()
        self._num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2:
            p = p[:, -1]
        l = _np(labels).reshape(-1)
        buckets = np.minimum(
            (p * self._num_thresholds).astype(int), self._num_thresholds)
        for b, lab in zip(buckets, l):
            if lab:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self._num_thresholds + 1)
        self._stat_neg = np.zeros(self._num_thresholds + 1)

    def accumulate(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(self._num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * (new_neg - tot_neg) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        return auc / (tot_pos * tot_neg)

    def name(self):
        return self._name
