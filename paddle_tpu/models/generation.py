"""Autoregressive generation with a KV cache for models.gpt.GPT.

Reference-era Paddle served decoding through fluid inference programs
(beam_search/while ops); the TPU-native design is a PURE-JAX decode pair
— `prefill` (one full forward that also returns per-layer K/V) and
`decode_step` (single-token forward against the cache, updated with
`lax.dynamic_update_slice`) — scanned under jit with STATIC shapes:
the cache is an L-tuple of (k, v) [B, H, max_seq, D] buffers from the
start (per-layer leaves so updates alias in place — see `prefill`),
positions past `cur_len` masked, so one compilation serves every
prompt/output length.

The decode math mirrors GPT.forward exactly (pre-LN blocks, tanh-gelu
MLP, 1/sqrt(D) attention scale, tied layout conventions); parity with
the Layer forward is asserted in tests/test_generation.py, so the two
implementations cannot drift silently.
"""
from __future__ import annotations

import functools
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["extract_params", "prefill", "decode_step", "generate",
           "beam_search_generate"]


def extract_params(model) -> dict:
    """GPT Layer → flat {name: jnp array} pytree for the decode fns."""
    return {k: p._value for k, p in model.named_parameters()}


def _ln(x, w, b, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * w + b


def _gelu(x):
    # constants pinned to x.dtype: a bare numpy float64 scalar would
    # promote everything under this package's x64 default
    c0 = jnp.asarray(np.sqrt(2.0 / np.pi), x.dtype)
    c1 = jnp.asarray(0.044715, x.dtype)
    half = jnp.asarray(0.5, x.dtype)
    one = jnp.asarray(1.0, x.dtype)
    return half * x * (one + jnp.tanh(c0 * (x + c1 * x ** 3)))


def _qkv_proj(p, i, x, geom):
    """ln1 + fused qkv projection → [3, B, H, t, D] (computed ONCE per
    layer per step; both the cache write and the attention consume it)."""
    _, H, D, _ = geom
    pre = f"blocks.{i}."
    h = _ln(x, p[pre + "ln1.weight"], p[pre + "ln1.bias"])
    qkv = h @ p[pre + "attn.qkv.weight"] + p[pre + "attn.qkv.bias"]
    B, t = x.shape[0], x.shape[1]
    return qkv.reshape(B, t, 3, H, D).transpose(2, 0, 3, 1, 4)


def _block(p, i, x, q, k_cache, v_cache, pos_mask, geom):
    """One pre-LN block over x [B, t, H*D]: attention of the precomputed
    q [B, H, t, D] against the cache, then the MLP.
    k_cache/v_cache: [B, H, S, D]; pos_mask True=attend — [t, S] shared
    across the batch (dense decode) or [B, 1, t, S] per-sequence (the
    ragged paged-attention path, inference/serving/attention.py)."""
    _, H, D, _ = geom
    pre = f"blocks.{i}."
    B, t = x.shape[0], x.shape[1]
    scores = jnp.einsum("bhtd,bhsd->bhts", q, k_cache) \
        * jnp.asarray(1.0 / np.sqrt(D), q.dtype)
    mask = pos_mask if pos_mask.ndim == 4 else pos_mask[None, None]
    scores = jnp.where(mask, scores,
                       jnp.asarray(-1e30, scores.dtype))
    probs = jax.nn.softmax(scores, axis=-1)
    att = jnp.einsum("bhts,bhsd->bhtd", probs, v_cache)
    return _attn_merge(p, i, x, att, geom)


def _attn_merge(p, i, x, att, geom):
    """Post-attention half of _block: heads-major att [B, H, t, D] →
    out-projection residual, then the MLP. Split out so attention-kernel
    substitutes (the TPU ragged paged-attention kernel,
    ops/pallas/ragged_paged_attention.py) can replace only the
    score/softmax math and reuse this half verbatim; _block calling
    through it traces to the identical jaxpr as the inline form."""
    _, H, D, _ = geom
    pre = f"blocks.{i}."
    B, t = x.shape[0], x.shape[1]
    att = att.transpose(0, 2, 1, 3).reshape(B, t, H * D)
    x = x + att @ p[pre + "attn.out.weight"] + p[pre + "attn.out.bias"]
    h = _ln(x, p[pre + "ln2.weight"], p[pre + "ln2.bias"])
    h = _gelu(h @ p[pre + "mlp.up.weight"] + p[pre + "mlp.up.bias"])
    x = x + h @ p[pre + "mlp.down.weight"] + p[pre + "mlp.down.bias"]
    return x


def _embed(p, ids, pos0):
    tok = p["wte.weight"][ids]                        # [B, t, H]
    t = ids.shape[1]
    pos = p["wpe.weight"][pos0 + jnp.arange(t)]       # [t, H]
    return tok + pos[None]


@functools.partial(jax.jit, static_argnums=(2,))
def prefill(params, input_ids, geom):
    """Full forward over the prompt; returns (last-position logits,
    cache: L-tuple of (k [B, H, max_seq, D], v)). geom: hashable static
    geometry (num_layers, num_heads, head_dim, max_seq_len).

    The cache is a PER-LAYER pytree, not one [L, 2, B, H, S, D] array:
    with a monolithic buffer every layer's `.at[i].set` in decode_step
    rewrote the whole cache — L full-cache copies per token, measured as
    flat ~1.7k tok/s decode from bs=32 to bs=64 (batch-independent =
    bandwidth burned on copies). Leaf-wise, each layer touches only its
    own k/v buffers and the scan carry aliases in place."""
    L, H, D, S = geom
    B, T = input_ids.shape
    x = _embed(params, input_ids, 0)
    causal = (jnp.arange(T)[:, None] >= jnp.arange(S)[None, :]) & \
        (jnp.arange(S)[None, :] < T)
    cache = []
    for i in range(L):
        # one ln1+qkv projection per layer: the cache write AND the
        # attention both consume it
        qkv = _qkv_proj(params, i, x, geom)
        kc = jnp.zeros((B, H, S, D), x.dtype).at[:, :, :T].set(qkv[1])
        vc = jnp.zeros((B, H, S, D), x.dtype).at[:, :, :T].set(qkv[2])
        cache.append((kc, vc))
        x = _block(params, i, x, qkv[0], kc, vc, causal, geom)
    x = _ln(x, params["ln_f.weight"], params["ln_f.bias"])
    logits = x[:, -1] @ params["lm_head.weight"]
    return logits, tuple(cache)


# --------------------------------------------------------------------------
# The decode step is DECOMPOSED into top-level jitted sub-programs shared
# with the paged serving path (inference/serving/attention.py): embed,
# per-layer qkv, per-layer attention+MLP, final head. Two monolithic jits
# (dense decode_step vs paged decode) fuse differently and drift by ~1e-7
# per step (measured on the CPU backend); routing BOTH paths through the
# SAME compiled executables makes paged decode bitwise-identical to the
# dense path by construction — positions beyond a sequence's length are
# masked to -1e30 before softmax, so cache garbage is erased exactly.
# Under an enclosing jit (the generate()/beam rollout scans, jax.export)
# these sub-jits inline and fuse into one program, exactly as before.

@jax.jit
def _token_embed(params, tokens, positions):
    """Per-row embedding: tokens [B] at per-sequence positions [B] ->
    [B, 1, C]. Same gather+add as _embed at a shared scalar position."""
    return params["wte.weight"][tokens[:, None]] \
        + params["wpe.weight"][positions][:, None]


@functools.partial(jax.jit, static_argnums=(1, 3))
def _decode_qkv(params, i, x, geom):
    return _qkv_proj(params, i, x, geom)


# ptlint: disable=PT-T009  agrees with the committed plan entry
# decode.cache_write (donate=[0, 1]); the jaxplan donation gate pins it
@functools.partial(jax.jit, donate_argnums=(0, 1))
def _cache_write(kc, vc, k_new, v_new, pos):
    """Write the new token's K/V [B, H, 1, D] at position pos (scalar)
    of the dense [B, H, S, D] cache.

    kc/vc are DONATED: every caller rebinds its cache to the returned
    pair (decode_step's per-layer loop, DecoderPredictor), so XLA can
    update the [B, H, S, D] buffers in place instead of double-residing
    old+new cache per layer per token. Under an enclosing jit (the
    generate()/beam rollout scans) donation of this inner program is
    ignored and the scan carry aliasing takes over — same effect."""
    z = jnp.asarray(0, pos.dtype)
    return (jax.lax.dynamic_update_slice(kc, k_new, (z, z, pos, z)),
            jax.lax.dynamic_update_slice(vc, v_new, (z, z, pos, z)))


@functools.partial(jax.jit, static_argnums=(1, 7))
def _decode_attn(params, i, x, q, kc, vc, positions, geom):
    """One block over the (dense-layout) context [B, H, S, D], attending
    row b to positions <= positions[b]."""
    S = kc.shape[2]
    attend = (jnp.arange(S)[None, :]
              <= positions[:, None])[:, None, None, :]  # [B, 1, 1, S]
    return _block(params, i, x, q, kc, vc, attend, geom)


@jax.jit
def _decode_head(params, x):
    x = _ln(x, params["ln_f.weight"], params["ln_f.bias"])
    return x[:, 0] @ params["lm_head.weight"]


def decode_step(params, cache, token, pos, geom):
    """One cached decode step. cache: the per-layer pytree from
    `prefill`; token [B], pos scalar (int32). Returns (logits [B, V],
    updated cache). Composed of the shared jitted sub-programs above;
    call it under jax.jit (as the generate()/beam scans do) to fuse the
    whole step into one program."""
    token = jnp.asarray(token, jnp.int32)
    pos = jnp.asarray(pos, jnp.int32)
    positions = jnp.broadcast_to(pos, token.shape)
    x = _token_embed(params, token, positions)        # [B, 1, H]
    new_cache = []
    for i, (kc, vc) in enumerate(cache):
        qkv = _decode_qkv(params, i, x, geom)         # once per layer
        kc, vc = _cache_write(kc, vc, qkv[1], qkv[2], pos)
        new_cache.append((kc, vc))
        x = _decode_attn(params, i, x, qkv[0], kc, vc, positions, geom)
    return _decode_head(params, x), tuple(new_cache)


@functools.lru_cache(maxsize=32)
def _sampling_rollout(geom, max_new: int, temperature: float, top_k: int,
                      top_p: float = 1.0, eos: int = -1):
    """One jitted (prefill + decode scan) program per static config.

    generate() used to run its lax.scan eagerly with per-call closures;
    each call re-traced, re-lowered and re-compiled the whole 12-layer
    rollout (~8.5 s host time per WARM call on the bench box, vs 0.15 ms
    for a cached decode_step — measured before this factory existed).
    Caching the jitted program by its static knobs makes warm generate
    calls pure device time.

    top_p >= 1.0 compiles the EXACT plain-temperature program (the
    nucleus mask is dropped at trace time), so top_p=1.0 is bitwise
    identical to not passing it. eos >= 0 adds a per-row finished flag
    to the scan carry: finished rows emit eos forever; shapes stay
    static, the scan still runs all max_new steps."""

    def run(params, ids, key):
        T = ids.shape[1]
        B = ids.shape[0]
        logits, cache = prefill(params, ids, geom)

        def sample(logits, key):
            if temperature <= 0.0:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            lg = logits.astype(jnp.float32) / temperature
            if top_k:
                kth = jnp.sort(lg, axis=-1)[:, -top_k][:, None]
                lg = jnp.where(lg < kth, -1e30, lg)
            if 0.0 < top_p < 1.0:
                # nucleus: keep the smallest rank-prefix whose mass
                # reaches top_p (rank 0 always kept — exclusive cumsum)
                srt = jnp.sort(lg, axis=-1)[:, ::-1]
                probs = jax.nn.softmax(srt, axis=-1)
                excl = jnp.cumsum(probs, axis=-1) - probs
                n_keep = jnp.sum(excl < top_p, axis=-1)
                kth = jnp.take_along_axis(srt, (n_keep - 1)[:, None],
                                          axis=-1)
                lg = jnp.where(lg < kth, -1e30, lg)
            return jax.random.categorical(key, lg, axis=-1).astype(
                jnp.int32)

        def body(carry, _):
            logits, cache, pos, key, finished = carry
            key, sub = jax.random.split(key)
            tok = sample(logits, sub)
            if eos >= 0:
                tok = jnp.where(finished, jnp.asarray(eos, tok.dtype),
                                tok)
                finished = finished | (tok == eos)
            logits, cache = decode_step(params, cache, tok, pos, geom)
            return (logits, cache, pos + 1, key, finished), tok

        carry0 = (logits, cache, jnp.asarray(T, jnp.int32), key,
                  jnp.zeros((B,), bool))
        _, toks = jax.lax.scan(body, carry0, None, length=max_new)
        return toks

    return jax.jit(run)


def generate(model, input_ids, max_new_tokens: int,
             temperature: float = 0.0, top_k: Optional[int] = None,
             top_p: Optional[float] = None,
             eos_token_id: Optional[int] = None, seed: int = 0):
    """Autoregressive sampling: greedy at temperature 0, else
    temperature(+top-k/top-p) sampling. eos_token_id stops finished rows
    early: once a row samples eos, every later position is frozen to eos
    (masked inside the jitted scan — shapes stay static). input_ids:
    [B, T] array-like; returns np.ndarray [B, T + max_new_tokens]."""
    from ..core.tensor import Tensor
    cfg = model.cfg
    geom = (cfg.num_layers, cfg.num_heads,
            cfg.hidden_size // cfg.num_heads, cfg.max_seq_len)
    params = extract_params(model)
    ids = np.asarray(input_ids.numpy() if isinstance(input_ids, Tensor)
                     else input_ids)
    B, T = ids.shape
    if T + max_new_tokens > cfg.max_seq_len:
        raise ValueError(
            f"prompt {T} + new {max_new_tokens} exceeds max_seq_len "
            f"{cfg.max_seq_len}")
    fn = _sampling_rollout(geom, int(max_new_tokens), float(temperature),
                           int(top_k) if top_k else 0,
                           float(top_p) if top_p is not None else 1.0,
                           -1 if eos_token_id is None else int(eos_token_id))
    toks = fn(params, jnp.asarray(ids, jnp.int32),
              jax.random.PRNGKey(seed))
    return np.concatenate([ids, np.asarray(toks).T], axis=1)


@functools.lru_cache(maxsize=32)
def _beam_rollout(geom, max_new: int, K: int, V: int, eos: int):
    """Jitted beam-search rollout per static (geometry, beam, vocab,
    eos) config — same per-call retrace fix as _sampling_rollout."""

    def run(params, expanded_ids):
        BK, T = expanded_ids.shape
        B = BK // K
        logits, cache = prefill(params, expanded_ids, geom)
        # only beam 0 is live at step 0 (all beams hold the same prompt)
        scores0 = jnp.tile(jnp.asarray([0.0] + [-1e30] * (K - 1),
                                       jnp.float32)[None], (B, 1))

        def body(carry, _):
            logits, cache, scores, finished, lengths, pos = carry
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            logp = logp.reshape(B, K, V)
            if eos >= 0:
                # finished beams may only emit eos, at zero marginal cost
                only_eos = jnp.full((V,), -jnp.inf).at[eos].set(0.0)
                logp = jnp.where(finished[..., None],
                                 only_eos[None, None], logp)
            total = scores[..., None] + logp          # [B, K, V]
            flat = total.reshape(B, K * V)
            top_scores, top_idx = jax.lax.top_k(flat, K)   # [B, K]
            parent = top_idx // V
            token = (top_idx % V).astype(jnp.int32)
            brow = jnp.arange(B)[:, None]
            was_finished = finished[brow, parent]
            new_lengths = lengths[brow, parent] + (~was_finished).astype(
                lengths.dtype)  # frozen beams stop accruing length
            new_finished = was_finished
            if eos >= 0:
                new_finished = new_finished | (token == eos)
            # re-gather beams: cache batch dim is B*K, parents per-batch
            gidx = (brow * K + parent).reshape(-1)
            cache = jax.tree_util.tree_map(lambda a: a[gidx], cache)
            logits, cache = decode_step(params, cache, token.reshape(-1),
                                        pos, geom)
            return ((logits, cache, top_scores, new_finished,
                     new_lengths, pos + 1), (parent, token))

        finished0 = jnp.zeros((B, K), bool)
        lengths0 = jnp.full((B, K), T, jnp.float32)
        carry0 = (logits, cache, scores0, finished0, lengths0,
                  jnp.asarray(T, jnp.int32))
        (_, _, scores, _, lengths, _), (parents, tokens) = jax.lax.scan(
            body, carry0, None, length=max_new)
        return scores, lengths, parents, tokens

    return jax.jit(run)


def beam_search_generate(model, input_ids, beam_size: int,
                         max_new_tokens: int, length_penalty: float = 0.0,
                         eos_token_id: Optional[int] = None):
    """Beam search over the KV cache (reference: beam_search_op.cc +
    beam_search_decode_op.cc — the fluid decoding workhorse; here the
    beams live as an expanded batch dim, the cache is re-gathered to the
    surviving parents each step, and the token history is backtracked
    through the recorded (parent, token) lattice like the reference's
    sentence-ids/sentence-scores reconstruction).

    Returns (sequences [B, T + max_new_tokens], scores [B]) for the best
    beam per batch row; finished beams (eos emitted) freeze their score.
    """
    from ..core.tensor import Tensor
    cfg = model.cfg
    geom = (cfg.num_layers, cfg.num_heads,
            cfg.hidden_size // cfg.num_heads, cfg.max_seq_len)
    params = extract_params(model)
    ids = np.asarray(input_ids.numpy() if isinstance(input_ids, Tensor)
                     else input_ids)
    B, T = ids.shape
    K, V = int(beam_size), cfg.vocab_size
    if T + max_new_tokens > cfg.max_seq_len:
        raise ValueError("beam search exceeds max_seq_len")

    expanded = np.repeat(ids, K, axis=0)              # [B*K, T]
    eos = -1 if eos_token_id is None else int(eos_token_id)
    fn = _beam_rollout(geom, int(max_new_tokens), K, V, eos)
    scores, lengths, parents, tokens = (
        np.asarray(a) for a in fn(params,
                                  jnp.asarray(expanded, jnp.int32)))
    # parents/tokens: [steps, B, K]; scores/lengths: [B, K]

    if length_penalty:
        # per-HYPOTHESIS length normalization (reference beam_search_op):
        # beams that emitted eos early divide by their own shorter length
        scores = scores / (lengths ** length_penalty)
    best = scores.argmax(axis=1)                      # [B]
    # backtrack the (parent, token) lattice from the best leaf
    out = np.zeros((B, max_new_tokens), np.int64)
    for b in range(B):
        k = best[b]
        for s in range(max_new_tokens - 1, -1, -1):
            out[b, s] = tokens[s, b, k]
            k = parents[s, b, k]
    return np.concatenate([ids, out], axis=1), scores[np.arange(B), best]


def export_decoder(model, path_prefix: str):
    """Serialize the decode pair as StableHLO (jax.export) so a server
    can run autoregressive generation WITHOUT the model class or Python
    graph rebuild — the LLM-serving analogue of save_inference_model.
    Writes <prefix>.prefill.pdmodel, <prefix>.decode.pdmodel and
    <prefix>.pdmeta (geometry + param tree layout; parameters are baked
    into the artifacts as constants)."""
    import json
    import os
    from jax import export as jexport
    cfg = model.cfg
    geom = (cfg.num_layers, cfg.num_heads,
            cfg.hidden_size // cfg.num_heads, cfg.max_seq_len)
    L, H, D, S = geom
    params = extract_params(model)

    def prefill_fn(ids):
        return prefill(params, ids, geom)

    def decode_fn(cache, token, pos):
        return decode_step(params, cache, token, pos, geom)

    # symbolic batch, static seq buckets: export one prompt length (S//2
    # by convention) for prefill; decode is length-independent
    Tp = S // 2
    b = jexport.symbolic_shape("b")[0]
    ids_spec = jax.ShapeDtypeStruct((b, Tp), jnp.int32)
    # ptlint: disable=PT-T004  (export path: jit built once per
    # export_decoder() call, traced on specs, never dispatched)
    ex_prefill = jexport.export(jax.jit(prefill_fn))(ids_spec)
    leaf = jax.ShapeDtypeStruct((b, H, S, D), jnp.float32)
    cache_spec = tuple((leaf, leaf) for _ in range(L))
    tok_spec = jax.ShapeDtypeStruct((b,), jnp.int32)
    pos_spec = jax.ShapeDtypeStruct((), jnp.int32)
    # ptlint: disable=PT-T004  (same export-only jit as above)
    ex_decode = jexport.export(jax.jit(decode_fn))(cache_spec, tok_spec,
                                                   pos_spec)
    d = os.path.dirname(path_prefix)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path_prefix + ".prefill.pdmodel", "wb") as f:
        f.write(ex_prefill.serialize())
    with open(path_prefix + ".decode.pdmodel", "wb") as f:
        f.write(ex_decode.serialize())
    with open(path_prefix + ".pdmeta", "w") as f:
        # JSON, not pickle: serving artifacts may come from third parties
        # and must not be able to execute code at load (same rule as the
        # p2p raw-buffer framing)
        json.dump({"geom": list(geom), "prefill_len": Tp,
                   "vocab_size": cfg.vocab_size}, f)


class DecoderPredictor:
    """Serves an export_decoder artifact: greedy generation from
    serialized StableHLO only (no model class). The rollout is
    device-resident: a jitted lax.scan feeds each argmax token straight
    back into the exported decode program, so the whole generation is
    ONE dispatch + ONE host fetch regardless of max_new_tokens (the
    exported artifact composes under tracing — exported.call is itself
    traceable)."""

    def __init__(self, path_prefix: str):
        import json
        from jax import export as jexport
        with open(path_prefix + ".prefill.pdmodel", "rb") as f:
            self._prefill = jexport.deserialize(f.read())
        with open(path_prefix + ".decode.pdmodel", "rb") as f:
            self._decode = jexport.deserialize(f.read())
        with open(path_prefix + ".pdmeta") as f:
            meta = json.load(f)  # JSON: no code execution at load
        self.geom = tuple(meta["geom"])
        self.prefill_len = int(meta["prefill_len"])
        self.vocab_size = int(meta["vocab_size"])
        self._rollouts = {}                  # max_new -> jitted scan

    def _rollout(self, max_new: int):
        """One jitted greedy rollout per max_new (memoized — same
        build-once discipline as _sampling_rollout's lru_cache, keyed
        per instance because the scan closes over this artifact's
        decode program)."""
        fn = self._rollouts.get(max_new)
        if fn is None:
            decode = self._decode

            def run(logits, cache, pos0):
                def body(carry, _):
                    logits, cache, pos = carry
                    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    logits, cache = decode.call(cache, tok, pos)
                    return (logits, cache, pos + 1), tok

                _, toks = jax.lax.scan(body, (logits, cache, pos0),
                                       None, length=max_new)
                return toks                  # [max_new, B]

            # ptlint: disable=PT-T004  (memoized above: built once per
            # (artifact, max_new), never per generate() call)
            fn = jax.jit(run)
            self._rollouts[max_new] = fn
        return fn

    def generate(self, input_ids, max_new_tokens: int):
        """Greedy decode. Prompts must be EXACTLY the exported prefill
        length: the fixed-shape prefill has no pad masking, so a shorter
        prompt would silently attend pad tokens at shifted positions and
        diverge from generate() — a loud error beats silent divergence.
        (Serve multiple buckets by exporting one artifact per length.)"""
        ids = np.asarray(input_ids)
        B, T = ids.shape
        Tp = self.prefill_len
        if T != Tp:
            raise ValueError(
                f"prompt length {T} != exported prefill length {Tp}; the "
                "fixed-shape prefill has no pad masking — export an "
                "artifact per prompt-length bucket")
        S = self.geom[3]
        if Tp + max_new_tokens > S:
            raise ValueError("generation exceeds max_seq_len")
        logits, cache = self._prefill.call(jnp.asarray(ids, jnp.int32))
        toks = self._rollout(max_new_tokens)(
            logits, cache, jnp.asarray(Tp, jnp.int32))
        # one fetch for the whole generation (the pre-device-resident
        # loop synced once per token — ptlint PT-T007's defect class)
        return np.concatenate([ids, np.asarray(toks).T], axis=1)
