"""Flagship model families (reference marketing targets, BASELINE.md):
GPT (decoder, config 5), BERT/ERNIE (encoders, configs 3-4). Vision
CNNs live in paddle_tpu.vision.models."""
from .gpt import GPT, GPTConfig, gpt_loss_fn  # noqa: F401
from .bert import (Bert, BertConfig, BertForPretraining,  # noqa: F401
                   bert_base, bert_pretrain_loss_fn, ernie_large)
from .generation import generate  # noqa: F401
