"""BERT/ERNIE-style bidirectional encoders with pretraining heads.

Reference capability target: BASELINE.md configs 3-4 (BERT-base
pretraining over Fleet DP, ERNIE-large with ZeRO-2 + AMP). The reference
builds these from python/paddle/nn/layer/transformer.py encoder layers;
ERNIE shares the BERT architecture (the differences are pretraining data
and masking strategy), so `ernie_large()` is a preset of the same model.

Written sharded-by-default like models/gpt.py: QKV/MLP-up as
ColumnParallel, attn-out/MLP-down as RowParallel over 'tp', vocab-
parallel embeddings, flash attention (non-causal) on TPU via
nn.functional.scaled_dot_product_attention.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .. import nn
from ..nn import functional as F
from ..core.tensor import Tensor
from ..ops import manipulation as M
from ..ops.linalg import matmul
from ..distributed.tp_layers import (ColumnParallelLinear, RowParallelLinear,
                                     VocabParallelEmbedding)

__all__ = ["BertConfig", "Bert", "BertForPretraining",
           "bert_pretrain_loss_fn", "bert_base", "ernie_large",
           "make_bert_pretrain_batch"]


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_position: int = 512
    type_vocab_size: int = 2
    ffn_mult: int = 4
    dropout: float = 0.0
    layer_norm_eps: float = 1e-12


def bert_base():
    return BertConfig()


def ernie_large():
    """ERNIE-large (BASELINE config 4): same architecture, 24L/1024H/16H,
    the config the reference trains with Fleet sharding + AMP."""
    return BertConfig(vocab_size=18000, hidden_size=1024, num_layers=24,
                      num_heads=16, max_position=512, type_vocab_size=4)


class BertSelfAttention(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.num_heads = cfg.num_heads
        self.head_dim = cfg.hidden_size // cfg.num_heads
        self.qkv = ColumnParallelLinear(cfg.hidden_size,
                                        3 * cfg.hidden_size,
                                        gather_output=False)
        self.out = RowParallelLinear(cfg.hidden_size, cfg.hidden_size,
                                     input_is_parallel=True)

    def _pack_gate(self, T: int, attn_mask) -> bool:
        """Packed-pair flash routing (ops/pallas/packed_flash.route_gate).
        At ERNIE-large geometry (T=512, d=64, 16 heads) the upstream
        flash kernel pads head_dim 64->128 AND stages an f32 output —
        128 MB/layer of HLO temps (the bs=32 OOM receipt in BENCH_DETAIL
        notes); the packed kernel keeps pairs on the 128 lanes with bf16
        in/out."""
        from ..ops.pallas import packed_flash
        return packed_flash.route_gate(
            self.head_dim, self.num_heads, T, T,
            dropout_active=self.cfg.dropout > 0.0 and self.training,
            masked=attn_mask is not None)

    def forward(self, x, attn_mask=None):
        from .gpt import sliced_qkv
        B, T = x.shape[0], x.shape[1]
        pack = self._pack_gate(T, attn_mask)
        q, k, v = sliced_qkv(x, self.qkv, self.num_heads, self.head_dim,
                             pack_pairs=pack)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=False,
            dropout_p=self.cfg.dropout, training=self.training,
            _heads_major=True, _packed_pairs=pack)
        out = M.reshape(M.transpose(out, [0, 2, 1, 3]), [B, T, -1])
        return self.out(out)


class BertLayer(nn.Layer):
    """Post-LN encoder block (the BERT/reference transformer layout:
    residual then LayerNorm, unlike GPT's pre-LN)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.attn = BertSelfAttention(cfg)
        self.ln1 = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        inner = cfg.ffn_mult * cfg.hidden_size
        self.up = ColumnParallelLinear(cfg.hidden_size, inner,
                                       gather_output=False)
        self.down = RowParallelLinear(inner, cfg.hidden_size,
                                      input_is_parallel=True)
        self.ln2 = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.drop = nn.Dropout(cfg.dropout)

    def forward(self, x, attn_mask=None):
        from ..parallel.api import shard_batch_activation
        x = self.ln1(x + self.drop(self.attn(x, attn_mask)))
        h = self.down(F.gelu(self.up(x), approximate=True))
        return shard_batch_activation(self.ln2(x + self.drop(h)))


class Bert(nn.Layer):
    """Encoder trunk: embeddings + N bidirectional blocks + pooler."""

    def __init__(self, cfg: BertConfig = None, **kwargs):
        super().__init__()
        cfg = cfg or BertConfig(**kwargs)
        self.cfg = cfg
        self.word_emb = VocabParallelEmbedding(cfg.vocab_size,
                                               cfg.hidden_size)
        self.pos_emb = nn.Embedding(cfg.max_position, cfg.hidden_size)
        self.type_emb = nn.Embedding(cfg.type_vocab_size, cfg.hidden_size)
        self.emb_ln = nn.LayerNorm(cfg.hidden_size,
                                   epsilon=cfg.layer_norm_eps)
        self.drop = nn.Dropout(cfg.dropout)
        self.layers = nn.LayerList([BertLayer(cfg)
                                    for _ in range(cfg.num_layers)])
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attn_mask=None):
        import jax.numpy as jnp
        B, T = input_ids.shape[0], input_ids.shape[1]
        pos = Tensor(jnp.arange(T, dtype=jnp.int32)[None, :])
        x = self.word_emb(input_ids) + self.pos_emb(pos)
        if token_type_ids is not None:
            x = x + self.type_emb(token_type_ids)
        x = self.drop(self.emb_ln(x))
        from ..parallel.api import shard_batch_activation
        x = shard_batch_activation(x)
        for layer in self.layers:
            x = layer(x, attn_mask)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class BertForPretraining(nn.Layer):
    """MLM + NSP heads (the reference pretraining objective). The MLM
    decoder IS weight-tied to the word embedding — logits come from
    h @ word_emb.weight^T plus a per-vocab bias, the standard BERT
    parameterization (no separate V x H decoder matrix)."""

    def __init__(self, cfg: BertConfig = None, **kwargs):
        super().__init__()
        cfg = cfg or BertConfig(**kwargs)
        self.cfg = cfg
        self.bert = Bert(cfg)
        self.mlm_transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.mlm_ln = nn.LayerNorm(cfg.hidden_size,
                                   epsilon=cfg.layer_norm_eps)
        self.mlm_bias = self.create_parameter(
            [cfg.vocab_size], is_bias=True)
        self.nsp = nn.Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attn_mask=None,
                masked_positions=None):
        """masked_positions: optional [B, P] int positions of the masked
        tokens. When given, the MLM transform + tied unembed run only on
        those P rows ([B, P, V] logits instead of [B, T, V]) — the
        reference design (bert_dygraph_model.py:335 gathers mask_pos
        before PretrainingHeads; ernie/static BERT do the same). At the
        standard 15% masking this cuts the dominant V x H matmul and its
        logits traffic ~6x. Omit it for dense whole-sequence logits."""
        seq, pooled = self.bert(input_ids, token_type_ids, attn_mask)
        if masked_positions is not None:
            idx = M.unsqueeze(masked_positions, -1)
            seq = M.take_along_axis(seq, idx, axis=1)  # [B, P, H]
        h = self.mlm_ln(F.gelu(self.mlm_transform(seq), approximate=True))
        logits = matmul(h, self.bert.word_emb.weight,
                        transpose_y=True) + self.mlm_bias
        return logits, self.nsp(pooled)

    def loss(self, input_ids, token_type_ids, mlm_labels,
             nsp_labels=None, masked_positions=None):
        """mlm_labels: [B, T] with -100 at unmasked positions (the
        standard ignore_index contract the fused CE honours) — or [B, P]
        labels aligned with masked_positions when those are passed
        (ragged batches pad with -100);
        nsp_labels: [B] int64 or None."""
        logits, nsp_logits = self(input_ids, token_type_ids,
                                  masked_positions=masked_positions)
        mlm = F.cross_entropy(
            M.reshape(logits, [-1, self.cfg.vocab_size]),
            M.reshape(mlm_labels, [-1]), ignore_index=-100)
        if nsp_labels is None:
            return mlm
        return mlm + F.cross_entropy(nsp_logits, nsp_labels)


def bert_pretrain_loss_fn(model, input_ids, token_type_ids, mlm_labels,
                          nsp_labels, masked_positions=None):
    """loss_fn signature for jit.TrainStep / parallel.ShardedTrainStep."""
    return model.loss(input_ids, token_type_ids, mlm_labels, nsp_labels,
                      masked_positions=masked_positions)


def make_bert_pretrain_batch(rng, vocab_size, bs, seq, mask_rate=0.15):
    """Synthetic MLM+NSP pretraining batch in the masked-position layout
    the head expects (bench.py, examples/bert_pretrain.py, tools/bert_cost
    all share this recipe — keep the contract in one place).

    Returns numpy arrays (input_ids, token_type_ids, mlm_labels,
    nsp_labels, masked_positions); P = round(mask_rate*seq) positions per
    row, chosen without replacement and SORTED (the gather head's
    contract)."""
    x = rng.randint(0, vocab_size, (bs, seq), dtype=np.int32)
    tt = rng.randint(0, 2, (bs, seq), dtype=np.int32)
    P = max(1, int(round(seq * mask_rate)))
    pos = np.stack([rng.choice(seq, P, replace=False) for _ in range(bs)])
    pos.sort(axis=1)
    mlm = rng.randint(0, vocab_size, (bs, P)).astype(np.int64)
    nsp = rng.randint(0, 2, (bs,)).astype(np.int64)
    return x, tt, mlm, nsp, pos.astype(np.int32)
