"""GPT: the flagship decoder-only transformer.

Reference capability target: GPT-3-style static-graph training with Fleet
pipeline+recompute (BASELINE.json config 5) and ERNIE/BERT-style encoders
(configs 3-4). The reference builds these from python/paddle/nn/layer/
transformer.py primitives + fleet meta-optimizers; here the model is written
sharded-by-default (SPMD annotations are no-ops without a mesh):

- tensor parallel: QKV/MLP-up as ColumnParallel, attn-out/MLP-down as
  RowParallel over the 'tp' axis (Megatron layout: one psum per block pair)
- sequence parallel: activations between blocks sharded over 'sp' on the
  sequence dim (ring-free: XLA chooses all-gather/reduce-scatter points)
- attention: nn.functional.scaled_dot_product_attention (pallas flash on
  TPU for long sequences)
- recompute: per-block jax.checkpoint via fleet.utils.recompute
- pipeline: the stacked-parameter variant lives in
  paddle_tpu.parallel.pipeline (shard_map + ppermute microbatch schedule)
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

from .. import nn
from ..nn import functional as F
from ..core.tensor import Tensor
from ..ops import manipulation as M
from ..parallel.api import (shard_activation, shard_batch_activation,
                            mark_sharding)
from ..distributed.tp_layers import (ColumnParallelLinear, RowParallelLinear,
                                     VocabParallelEmbedding)


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    ffn_mult: int = 4
    dropout: float = 0.0
    # False/"none": no remat. True: legacy per-block recompute inside
    # GPTBlock.forward. "full": per-block remat applied by the GPT-level
    # loop. "group:<k>": contiguous groups of k blocks, each wrapped in
    # ONE jax.checkpoint (k trades recompute FLOPs against live bytes).
    # "auto": the policy committed by the static planner
    # (analysis/jaxplan.py, jaxplan.json) — pick the cheapest policy
    # whose predicted peak fits the HBM envelope instead of hand-tuning.
    use_recompute: object = False
    # NOTE: block outputs are unconditionally constrained to the canonical
    # [batch=(dp,sharding), seq=sp] layout regardless of this flag; on
    # build_mesh meshes sp defaults to size 1 so this is a no-op, but a
    # custom mesh with sp>1 gets sequence-sharded activations even with
    # sequence_parallel=False. This flag still controls the ln/dropout
    # scatter-gather placement choices.
    sequence_parallel: bool = False
    # context parallelism: attention itself runs ring-sharded over the
    # 'sp' mesh axis (parallel/ring_attention.py) — the long-context path
    # where even one layer's [T, T] scores don't fit a chip
    context_parallel: bool = False
    # mixture-of-experts: >0 replaces the dense MLP with an
    # expert-parallel MoEMLP (distributed/moe.py, 'ep' mesh axis) in
    # every moe_every-th block; load-balance aux added to loss()
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_every: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01

    # presets (reference marketing targets: BASELINE.json configs)
    @staticmethod
    def gpt3_1p3b():
        return GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                         num_heads=16, max_seq_len=2048)

    @staticmethod
    def tiny():
        return GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                         num_heads=4, max_seq_len=64)


def sliced_qkv(x, qkv_layer, num_heads: int, head_dim: int,
               pack_pairs: bool = False):
    """q/k/v heads-major [B, H, T, D] from a fused qkv projection.

    tp == 1 (the single-chip/dp fast path): THREE F.linear calls against
    trace-time slices of the fused weight (same parameters, identical
    math) — each output goes straight to [B, H, T, D] with a small
    transpose XLA fuses into the matmul epilogue. The packed alternative
    (one [B,T,3HD] matmul -> reshape -> 5-D transpose -> unstack) left
    ~20 ms/step of materialised layout copies around the pallas
    custom-call at the GPT bench geometry; this form measured +8.7% step
    throughput (r4). F.linear keeps the bias add inside the AMP
    white-listed op, so O1 autocast emits bf16 q/k/v exactly like the
    fused layer would.

    tp > 1: the fused ColumnParallelLinear path — its shard boundaries
    split the 3*HD columns evenly across 'tp', so thirds-slicing would
    force per-layer resharding.
    """
    from ..parallel.mesh import get_global_mesh
    B, T = x.shape[0], x.shape[1]
    mesh = get_global_mesh()
    if mesh is not None and mesh.shape.get("tp", 1) > 1:
        qkv = M.reshape(qkv_layer(x), [B, T, 3, num_heads, head_dim])
        qkv = M.transpose(qkv, [2, 0, 3, 1, 4])
        return M.unstack(qkv, axis=0)
    HD = num_heads * head_dim
    w, bias = qkv_layer.weight, qkv_layer.bias
    out = []
    for i in range(3):
        o = F.linear(x, w[:, i * HD:(i + 1) * HD],
                     bias[i * HD:(i + 1) * HD])
        if pack_pairs:
            # adjacent head pairs stay merged on the 128-lane minor dim:
            # [B,T,H,64] -> [B,T,H/2,128] is a pure view, and THIS
            # transpose fuses (128-minor), unlike the d=64 one —
            # ops/pallas/packed_flash.py consumes the packed layout
            o = M.reshape(o, [B, T, num_heads // 2, 2 * head_dim])
        else:
            o = M.reshape(o, [B, T, num_heads, head_dim])
        out.append(M.transpose(o, [0, 2, 1, 3]))  # [B, H(, /2), T, D(*2)]
    return out


class GPTAttention(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.num_heads = cfg.num_heads
        self.head_dim = cfg.hidden_size // cfg.num_heads
        self.qkv = ColumnParallelLinear(cfg.hidden_size,
                                        3 * cfg.hidden_size,
                                        gather_output=False)
        self.out = RowParallelLinear(cfg.hidden_size, cfg.hidden_size,
                                     input_is_parallel=True)

    def _pack_gate(self, T: int) -> bool:
        """Packed-pair flash (head pairs on 128 lanes, ops/pallas/
        packed_flash.py): at head_dim 64 it removes the layout copies the
        custom-call boundary forces on 64-minor tensors. Shared routing
        gate: packed_flash.route_gate (flash conditions + kernel scope +
        unpacked-tp exclusion)."""
        from ..ops.pallas import packed_flash
        return packed_flash.route_gate(
            self.head_dim, self.num_heads, T, T,
            dropout_active=self.cfg.dropout > 0.0 and self.training)

    def forward(self, x):
        B, T = x.shape[0], x.shape[1]
        use_ring = False
        if self.cfg.context_parallel:
            from ..parallel.mesh import ensure_global_mesh
            use_ring = ensure_global_mesh().shape.get("sp", 1) > 1
        pack = not use_ring and self._pack_gate(T)
        q, k, v = sliced_qkv(x, self.qkv, self.num_heads, self.head_dim,
                             pack_pairs=pack)
        if use_ring:
            out = self._ring_attention(q, k, v)  # [B, H, T, D]
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=True, dropout_p=self.cfg.dropout,
                training=self.training, _heads_major=True,
                _packed_pairs=pack)  # [B, H, T, D] (packed: [B,H/2,T,2D])
        # the [0,2,1,3] transpose + reshape maps BOTH layouts to [B, T, C]
        # with heads in natural order (packed pairs are lane-adjacent)
        out = M.reshape(M.transpose(out, [0, 2, 1, 3]), [B, T, -1])
        return self.out(out)

    def _ring_attention(self, q, k, v):
        """Attention sequence-sharded over the 'sp' mesh axis: Q resident,
        K/V rotating over ICI (parallel/ring_attention.py). Manual over
        'sp' only — dp/tp/sharding stay in GSPMD auto mode so context
        parallelism composes with the other degrees."""
        from jax.sharding import PartitionSpec as P
        from ..core.dispatch import dispatch
        from ..parallel.compat import shard_map
        from ..parallel.mesh import ensure_global_mesh
        from ..parallel.ring_attention import ring_attention
        if self.cfg.dropout > 0.0 and self.training:
            raise NotImplementedError(
                "attention dropout under context_parallel is not "
                "implemented (per-chunk RNG across the rotating ring); "
                "set dropout=0.0 or context_parallel=False")
        mesh = ensure_global_mesh()
        # ptlint: disable=PT-S001  the sequence-parallel contract of
        # ring attention: heads stay local, sequence shards over 'sp' —
        # jaxshard budgets this exact layout in collective.ring_attention
        spec = P(None, None, "sp", None)
        fn = shard_map(
            lambda q_, k_, v_: ring_attention(q_, k_, v_, "sp",
                                              causal=True),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            axis_names={"sp"}, check_vma=False)
        return dispatch("ring_attention", fn, (q, k, v), {}, True)


class GPTMLP(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        inner = cfg.ffn_mult * cfg.hidden_size
        self.up = ColumnParallelLinear(cfg.hidden_size, inner,
                                       gather_output=False)
        self.down = RowParallelLinear(inner, cfg.hidden_size,
                                      input_is_parallel=True)

    def forward(self, x):
        return self.down(F.gelu(self.up(x), approximate=True))


class GPTBlock(nn.Layer):
    def __init__(self, cfg: GPTConfig, layer_idx: int = 0):
        super().__init__()
        self.cfg = cfg
        self.ln1 = nn.LayerNorm(cfg.hidden_size)
        self.attn = GPTAttention(cfg)
        self.ln2 = nn.LayerNorm(cfg.hidden_size)
        use_moe = (cfg.moe_experts > 0
                   and layer_idx % max(cfg.moe_every, 1)
                   == max(cfg.moe_every, 1) - 1)
        if use_moe:
            from ..distributed.moe import MoEMLP
            self.mlp = MoEMLP(cfg.hidden_size, cfg.moe_experts,
                              ffn_hidden_size=cfg.ffn_mult * cfg.hidden_size,
                              top_k=cfg.moe_top_k,
                              capacity_factor=cfg.moe_capacity_factor)
        else:
            self.mlp = GPTMLP(cfg)

    def _body(self, x):
        x = x + self.attn(self.ln1(x))
        x = x + self.mlp(self.ln2(x))
        x = shard_batch_activation(x)
        return x

    def forward(self, x):
        # `is True` on purpose: planned policies ("auto"/"full"/
        # "group:k" — truthy strings) are applied by the GPT-level
        # block loop, which may wrap SEVERAL blocks in one checkpoint;
        # only the legacy boolean keeps the per-block path here.
        if self.cfg.use_recompute is True:
            from ..distributed.fleet.utils import recompute
            from ..distributed.moe import MoEMLP
            if isinstance(self.mlp, MoEMLP):
                # aux loss must ride the checkpointed return — a Tensor
                # stashed on the layer inside jax.checkpoint would leak
                # its tracer into the outer trace
                def body_with_aux(x_):
                    out = self._body(x_)
                    return out, self.mlp.aux_loss
                out, aux = recompute(body_with_aux, x)
                self.mlp.aux_loss = aux
                return out
            return recompute(self._body, x)
        return self._body(x)


def _resolve_remat_group(cfg: GPTConfig) -> int:
    """Map cfg.use_recompute to the GPT-level checkpoint group size
    (0 = no GPT-level remat). Booleans resolve to 0 — False is off and
    True keeps the legacy per-block path inside GPTBlock.forward.
    "auto" resolves through the committed plan (jaxplan.json); explicit
    "none"/"full"/"group:<k>" policies are what the planner itself uses
    to build scoring candidates."""
    pol = cfg.use_recompute
    if pol is True or pol is False or pol is None:
        return 0
    if isinstance(pol, str):
        from ..analysis import jaxplan
        if pol == "auto":
            pol = jaxplan.committed_remat_policy()
        return jaxplan.remat_group_size(pol, cfg.num_layers)
    raise ValueError(
        f"use_recompute must be a bool, 'auto', 'none', 'full' or "
        f"'group:<k>', got {pol!r}")


class GPT(nn.Layer):
    def __init__(self, cfg: GPTConfig = None, **kwargs):
        super().__init__()
        cfg = cfg or GPTConfig(**kwargs)
        self.cfg = cfg
        self.wte = VocabParallelEmbedding(cfg.vocab_size, cfg.hidden_size)
        self.wpe = nn.Embedding(cfg.max_seq_len, cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)
        self.blocks = nn.LayerList([GPTBlock(cfg, layer_idx=i)
                                    for i in range(cfg.num_layers)])
        # planned remat: group size applied by forward()'s block loop
        # (0 = off; legacy use_recompute=True stays inside GPTBlock)
        self._remat_group = _resolve_remat_group(cfg)
        self.ln_f = nn.LayerNorm(cfg.hidden_size)
        # column-parallel LM head over vocab (untied: its own V x H
        # matrix; the bench FLOPs formula counts the unembed matmul once
        # either way)
        self.lm_head = ColumnParallelLinear(cfg.hidden_size, cfg.vocab_size,
                                            has_bias=False,
                                            gather_output=True)

    def forward(self, input_ids):
        B, T = input_ids.shape[0], input_ids.shape[1]
        import jax.numpy as jnp
        pos = Tensor(jnp.arange(T, dtype=jnp.int32)[None, :])
        x = self.wte(input_ids) + self.wpe(pos)
        x = self.drop(x)
        x = shard_batch_activation(x)
        g = self._remat_group
        if g:
            blocks = list(self.blocks)
            for s in range(0, len(blocks), g):
                x = self._run_group_rematted(blocks[s:s + g], x)
        else:
            for blk in self.blocks:
                x = blk(x)
        x = self.ln_f(x)
        return self.lm_head(x)

    def _run_group_rematted(self, group, x):
        """One checkpointed segment of `group` consecutive blocks. MoE
        aux losses must ride the checkpointed return — a Tensor stashed
        on a layer inside jax.checkpoint would leak its tracer into the
        outer trace — so they come back as extra outputs and are
        restored onto their layers afterwards."""
        from ..distributed.fleet.utils import recompute
        from ..distributed.moe import MoEMLP
        moe_blocks = [b for b in group if isinstance(b.mlp, MoEMLP)]

        def segment(x_):
            for b in group:
                x_ = b(x_)
            return (x_, *[b.mlp.aux_loss for b in moe_blocks])

        if not moe_blocks:
            return recompute(lambda x_: segment(x_)[0], x)
        out, *auxes = recompute(segment, x)
        for b, aux in zip(moe_blocks, auxes):
            b.mlp.aux_loss = aux
        return out

    def loss(self, input_ids, labels):
        logits = self(input_ids)
        loss = F.cross_entropy(
            M.reshape(logits, [-1, self.cfg.vocab_size]),
            M.reshape(labels, [-1]))
        if self.cfg.moe_experts > 0 and self.cfg.moe_aux_weight > 0:
            from ..distributed.moe import MoEMLP
            for blk in self.blocks:
                if isinstance(blk.mlp, MoEMLP) and blk.mlp.aux_loss is not None:
                    loss = loss + self.cfg.moe_aux_weight * blk.mlp.aux_loss
        return loss


def gpt_loss_fn(model, input_ids, labels):
    """loss_fn signature for jit.TrainStep / parallel.ShardedTrainStep."""
    return model.loss(input_ids, labels)
