"""incubate.fleet — the fleet v1 API namespace.

Reference: python/paddle/fluid/incubate/fleet/ (base/fleet_base.py Fleet/
DistributedOptimizer, collective/, parameter_server/) — the pre-2.0 fleet
surface. v1 was itself a wrapper layer; here it is a compat shim delegating
to the fleet 2.0 implementation (distributed/fleet) so v1-era scripts run:
`fleet.init(role)` / `fleet.distributed_optimizer(opt).minimize(loss)` /
`is_worker`/`is_server`/`worker_num` keep their meanings.
"""
from __future__ import annotations

from ...distributed.fleet import (  # noqa: F401
    DistributedStrategy, PaddleCloudRoleMaker, UserDefinedRoleMaker)
from ...distributed.fleet import fleet_base as _fb


class _FleetV1:
    """reference: incubate/fleet/base/fleet_base.py Fleet (v1 singleton)."""

    def __init__(self):
        self._fleet2 = _fb.Fleet()
        self._inited = False

    # -- lifecycle -------------------------------------------------------
    def init(self, role_maker=None, is_collective=False):
        self._fleet2.init(role_maker=role_maker,
                          is_collective=is_collective)
        self._inited = True
        return self

    def init_worker(self):
        return self._fleet2.init_worker()

    def init_server(self, model_dir=None, **kwargs):
        return self._fleet2.init_server(model_dir, **kwargs)

    def run_server(self):
        return self._fleet2.run_server()

    def stop_worker(self):
        return self._fleet2.stop_worker()

    # -- topology --------------------------------------------------------
    def is_worker(self):
        return self._fleet2.is_worker()

    def is_server(self):
        return self._fleet2.is_server()

    def is_first_worker(self):
        return self._fleet2.is_first_worker()

    def worker_num(self):
        return self._fleet2.worker_num()

    def server_num(self):
        return self._fleet2.server_num()

    def worker_index(self):
        return self._fleet2.worker_index()

    def server_index(self):
        rm = getattr(self._fleet2, "_role_maker", None)
        if rm is not None and hasattr(rm, "server_index"):
            return rm.server_index()
        return 0  # single-server / collective roles

    def worker_endpoints(self, to_string=False):
        eps = self._fleet2.worker_endpoints()
        return ",".join(eps) if to_string else eps

    def server_endpoints(self, to_string=False):
        eps = self._fleet2.server_endpoints()
        return ",".join(eps) if to_string else eps

    # -- optimizer -------------------------------------------------------
    def distributed_optimizer(self, optimizer, strategy=None):
        """reference: fleet_base.py:255 — returns a DistributedOptimizer
        whose minimize() applies the strategy's meta-optimizers (the v2
        path underneath)."""
        return self._fleet2.distributed_optimizer(optimizer, strategy)

    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None,
                             export_for_deployment=True):
        from ...static.io import save_inference_model as sim
        from ...static.program import default_main_program
        import os
        if main_program is None:  # v1 callers usually omit it (fleet_base)
            main_program = default_main_program()
        feed_vars = [main_program.global_block.var(n)
                     for n in feeded_var_names]
        return sim(os.path.join(dirname, "model"), feed_vars, target_vars,
                   executor, main_program)

    def save_persistables(self, executor, dirname, main_program=None):
        from ...static.io import save
        from ...static.program import default_main_program
        import os
        if main_program is None:
            main_program = default_main_program()
        return save(main_program, os.path.join(dirname, "persistables"))


fleet = _FleetV1()
DistributedOptimizer = _fb._FleetOptimizer  # v1 name for the wrapper
