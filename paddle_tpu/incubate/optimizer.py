"""paddle.incubate.optimizer (reference: incubate LookAhead/ModelAverage)."""
from ..optimizer.wrappers import (  # noqa: F401
    LookaheadOptimizer as LookAhead, ModelAverage, ExponentialMovingAverage,
)
