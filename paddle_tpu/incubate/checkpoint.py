"""Auto-checkpoint: periodic training snapshots with automatic resume.

Reference: incubate/checkpoint/auto_checkpoint.py (hooked into Executor.run
at executor.py:1209 — env-driven periodic save of program+scope to HDFS with
epoch metadata, so a preempted job restarts where it left off) and
checkpoint_saver.py.

TPU-native: the training state is an explicit pytree (params + optimizer
accumulators + LR scheduler + RNG + progress counters), saved atomically per
epoch via framework_io; `train_epoch_range` resumes by fast-forwarding the
epoch counter after restoring. Sharded (mesh) state saves per-shard .npz
files so multi-host jobs write only addressable shards.
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil
import tempfile
import time
from typing import Iterator, List, Optional

import numpy as np
import jax

__all__ = ["AutoCheckpointManager", "train_epoch_range", "register",
           "save_sharded_state", "load_sharded_state"]


def _to_host(obj):
    """Recursively fetch every Tensor / device array to host numpy,
    preserving container structure (no Tensor reconstruction)."""
    from ..core.tensor import Tensor
    if isinstance(obj, Tensor):
        return obj.numpy()
    if isinstance(obj, jax.Array):
        return np.asarray(obj)
    if isinstance(obj, dict):
        return {k: _to_host(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_host(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


CHECKSUM_FILE = "checksums.json"


def _array_manifest(state, prefix="$"):
    """sha256 per array leaf of a (host-side) state tree, keyed by JSON
    path — the integrity manifest written next to every snapshot. Hashes
    contiguous raw bytes so the digest is layout-independent."""
    out = {}
    if isinstance(state, np.ndarray):
        out[prefix] = hashlib.sha256(
            np.ascontiguousarray(state).tobytes()).hexdigest()
    elif isinstance(state, dict):
        for k in sorted(state):
            out.update(_array_manifest(state[k], f"{prefix}.{k}"))
    elif isinstance(state, (list, tuple)):
        for i, v in enumerate(state):
            out.update(_array_manifest(v, f"{prefix}[{i}]"))
    return out


class AutoCheckpointManager:
    """Periodic save + resume of the full training state.

    Usage:
        acp = AutoCheckpointManager("ckpt_dir", models=[m], optimizers=[o])
        for epoch in acp.train_epoch_range(10):
            train_one_epoch(...)
    A killed-and-restarted run resumes from the last finished epoch with
    identical subsequent state (tests/test_checkpoint.py).
    """

    def __init__(self, save_dir: str, models=(), optimizers=(),
                 lr_schedulers=(), max_keep: int = 3,
                 save_interval_epochs: int = 1, async_save: bool = False,
                 save_every_n_steps: Optional[int] = None,
                 require_manifest: bool = False):
        self.save_dir = save_dir
        # strict-manifest mode (serving/deploy.py publishes revisions
        # through this): a snapshot with no checksums.json is treated as
        # corrupt instead of tolerated — a deploy must never load
        # weights it cannot verify. Default False keeps pre-manifest
        # snapshots restorable for ordinary training resume.
        self.require_manifest = bool(require_manifest)
        self.models = list(models)
        self.optimizers = list(optimizers)
        self.lr_schedulers = list(lr_schedulers)
        self.max_keep = max_keep
        self.save_interval = max(int(save_interval_epochs), 1)
        # step-granular mode (elastic restart window bound): train_step_range
        # snapshots every N steps into step_N dirs, so a supervised worker
        # killed mid-epoch resumes at most N-1 steps back, not epoch-0
        self.save_every_n_steps = (None if save_every_n_steps is None
                                   else max(int(save_every_n_steps), 1))
        self.async_save = async_save
        self._pending = None  # in-flight async save (threading.Thread)
        self._async_error = None
        # (kind, index) of the snapshot restore_latest() actually loaded
        self.restored_kind: Optional[str] = None
        self.restored_index: Optional[int] = None
        os.makedirs(save_dir, exist_ok=True)

    # ---------------------------------------------------------------- state
    def _collect(self, epoch: int, step: Optional[int] = None) -> dict:
        from .. import framework_io  # noqa: F401  (format owner)
        from ..core import random as _random
        state = {"epoch": epoch, "step": step, "time": time.time(),
                 "models": [m.state_dict() for m in self.models],
                 "optimizers": [o.state_dict() for o in self.optimizers],
                 "lr_schedulers": [s.state_dict()
                                   for s in self.lr_schedulers],
                 "rng": np.asarray(_random.get_rng_state())}
        return state

    def _restore(self, state: dict):
        from ..core import random as _random
        for m, sd in zip(self.models, state["models"]):
            m.set_state_dict(sd)
        for o, sd in zip(self.optimizers, state["optimizers"]):
            o.set_state_dict(sd)
        for s, sd in zip(self.lr_schedulers, state["lr_schedulers"]):
            s.set_state_dict(sd)
        if "rng" in state:
            _random.set_rng_state(np.asarray(state["rng"]))

    # ----------------------------------------------------------------- save
    def _snap_dir(self, kind: str, idx: int) -> str:
        return os.path.join(self.save_dir, f"{kind}_{idx}")

    def _epoch_dir(self, epoch: int) -> str:
        return self._snap_dir("epoch", epoch)

    def save(self, epoch: int):
        """Atomic snapshot: write to a temp dir, rename into place, then
        prune old epochs (the reference's HDFS tmp+mv pattern). Joins any
        in-flight async save first — two concurrent _write threads would
        race _prune's '.tmp_*' sweep against the other's live temp dir."""
        self.wait()
        self._write(self._collect(epoch), epoch)

    def save_step(self, step: int, epoch: int = 0):
        """Step-granular atomic snapshot (step_N dir). Same durability
        contract as save(); used by train_step_range so an elastic restart
        replays at most save_every_n_steps-1 steps."""
        self.wait()
        self._write(self._collect(epoch, step=step), epoch,
                    kind="step", idx=step)

    def save_async(self, epoch: int):
        """Snapshot the state synchronously (cheap: the training state is
        functional, so collecting is reference-capture + host fetch), then
        serialize + write + rename in a background thread so disk/remote-fs
        latency overlaps the next epoch's compute. At most one save is in
        flight: a new save (or restore/exit) first joins the previous one.
        A failed background save re-raises at the next save/wait call —
        never silently dropped."""
        self._save_async_snapshot(self._collect(epoch), epoch)

    def save_step_async(self, step: int, epoch: int = 0):
        """Async twin of save_step (same contract as save_async)."""
        self._save_async_snapshot(self._collect(epoch, step=step), epoch,
                                  kind="step", idx=step)

    def _save_async_snapshot(self, state, epoch, kind="epoch", idx=None):
        import threading
        self.wait()
        # host-materialise now: after this the background thread touches
        # no device state, so training may freely continue. (NOT tree_map:
        # rebuilding Tensor nodes from numpy leaves would round-trip the
        # data back to the device.)
        state = _to_host(state)

        def work():
            try:
                if kind == "epoch":  # two-arg form: the stable test seam
                    self._write(state, epoch)
                else:
                    self._write(state, epoch, kind=kind, idx=idx)
            except BaseException as e:  # surfaced on next wait()
                self._async_error = e

        self._pending = threading.Thread(target=work, daemon=True)
        self._pending.start()

    def wait(self):
        """Join the in-flight async save (if any); re-raise its failure."""
        if self._pending is not None:
            self._pending.join()
            self._pending = None
        if self._async_error is not None:
            err, self._async_error = self._async_error, None
            raise err

    def _write(self, state: dict, epoch: int, kind: str = "epoch",
               idx: Optional[int] = None):
        from .. import framework_io, obs
        idx = epoch if idx is None else idx
        t0 = time.perf_counter()
        # span + histogram cover serialize/hash/rename (may run on the
        # async save thread — the obs sinks are thread-safe)
        span = obs.span("checkpoint.save", cat="checkpoint",
                        annotate=False,
                        args={"kind": kind, "index": idx})
        span.begin()
        tmp = tempfile.mkdtemp(dir=self.save_dir, prefix=".tmp_")
        try:
            framework_io.save(state, os.path.join(tmp, "state.pdparams"))
            # integrity manifest: hash what a verifier will actually load
            # back (round-trip through the serialized file), so dtype
            # normalisation inside save/load can't drift the digests
            digests = _array_manifest(framework_io.load(
                os.path.join(tmp, "state.pdparams"), return_numpy=True))
            with open(os.path.join(tmp, CHECKSUM_FILE), "w") as f:
                json.dump(digests, f)
            with open(os.path.join(tmp, "meta.json"), "w") as f:
                json.dump({"epoch": epoch, "kind": kind, "index": idx,
                           "time": time.time()}, f)
            final = self._snap_dir(kind, idx)
            if os.path.exists(final):
                shutil.rmtree(final)
            os.rename(tmp, final)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        finally:
            span.end()
        obs.histogram("checkpoint_save_seconds",
                      "snapshot write duration (serialize+hash+rename)",
                      labels=("kind",),
                      unit="seconds").labels(kind=kind).observe(
                          time.perf_counter() - t0)
        self._prune()

    def _prune(self):
        for kind in ("epoch", "step"):
            done = sorted(self._saved(kind))
            for e in done[:-self.max_keep]:
                shutil.rmtree(self._snap_dir(kind, e), ignore_errors=True)
        # stale temp dirs from crashed saves (the writer died before its
        # rename): harmless to restores (no meta outside a renamed dir)
        # but they accumulate on slow/remote filesystems — sweep them
        for name in os.listdir(self.save_dir):
            if name.startswith(".tmp_"):
                shutil.rmtree(os.path.join(self.save_dir, name),
                              ignore_errors=True)

    def _saved(self, kind: str) -> List[int]:
        out = []
        pre = kind + "_"
        if not os.path.isdir(self.save_dir):
            return out
        for name in os.listdir(self.save_dir):
            if name.startswith(pre) and name[len(pre):].isdigit():
                # (quarantined *.corrupt dirs don't count)
                meta = os.path.join(self.save_dir, name, "meta.json")
                if os.path.exists(meta):
                    out.append(int(name[len(pre):]))
        return out

    def _saved_epochs(self) -> List[int]:
        return self._saved("epoch")

    def _snapshots_newest_first(self):
        """All complete snapshots as (kind, idx), newest save first (by
        meta save time; epoch and step snapshots share one ordering so a
        mixed-mode run resumes from whichever landed last)."""
        snaps = []
        for kind in ("epoch", "step"):
            for idx in self._saved(kind):
                t = idx
                try:
                    with open(os.path.join(self._snap_dir(kind, idx),
                                           "meta.json")) as f:
                        t = json.load(f).get("time", idx)
                except (OSError, ValueError):
                    pass
                snaps.append((t, kind, idx))
        snaps.sort(reverse=True)
        return [(kind, idx) for _, kind, idx in snaps]

    def restore_latest(self) -> Optional[int]:
        """Load the newest complete snapshot; returns its epoch (or step,
        for step-granular snapshots) or None. Which kind was restored is
        left in .restored_kind/.restored_index.
        A snapshot that fails to parse (disk-level truncation/corruption
        AFTER the atomic rename — the failure mode remote filesystems add
        beyond the tmp+mv contract) OR whose per-array sha256 digests no
        longer match its checksums.json manifest (silent bit rot: the
        pickle still parses, the data is wrong) is quarantined with a
        warning and the next-newest snapshot is tried, so one bad file
        never bricks the resume path."""
        from .. import framework_io, obs
        self.wait()  # a restore racing an in-flight save would read torn
        t0 = time.perf_counter()
        for kind, idx in self._snapshots_newest_first():
            path = os.path.join(self._snap_dir(kind, idx), "state.pdparams")
            try:
                with obs.span("checkpoint.restore", cat="checkpoint",
                              annotate=False,
                              args={"kind": kind, "index": idx}):
                    state = framework_io.load(path)
                    self._verify_checksums(kind, idx, path)
            except Exception as e:
                import warnings
                obs.counter(
                    "checkpoint_quarantined_total",
                    "snapshots quarantined by restore (corrupt/bit-rot)"
                ).inc()
                bad = self._snap_dir(kind, idx)
                warnings.warn(
                    f"auto-checkpoint: snapshot {kind}_{idx} is corrupt "
                    f"({e!r}); quarantining {bad} and falling back",
                    RuntimeWarning)
                try:
                    os.rename(bad, bad + ".corrupt")
                except OSError:
                    shutil.rmtree(bad, ignore_errors=True)
                continue
            self._restore(state)
            self.restored_kind, self.restored_index = kind, idx
            obs.histogram(
                "checkpoint_restore_seconds",
                "restore_latest duration incl. quarantine fallbacks",
                unit="seconds").observe(time.perf_counter() - t0)
            return idx
        self.restored_kind = self.restored_index = None
        return None

    def _verify_checksums(self, kind: str, idx: int, path: str):
        """Recompute every array digest of a snapshot and compare against
        its checksums.json. Raises on any mismatch. A missing manifest
        is tolerated by default (pre-manifest snapshots stay restorable)
        but is a hard error under require_manifest=True — the
        strict-manifest mode published revisions (serving/deploy.py)
        restore with, so unverifiable weights never deploy. The data is
        re-loaded with return_numpy=True so digests see exactly the bytes
        the manifest hashed at save time."""
        manifest_path = os.path.join(os.path.dirname(path), CHECKSUM_FILE)
        if not os.path.exists(manifest_path):
            if self.require_manifest:
                raise IOError(
                    f"snapshot {kind}_{idx} has no {CHECKSUM_FILE} "
                    f"manifest (require_manifest=True refuses "
                    f"unverifiable weights)")
            return
        with open(manifest_path) as f:
            want = json.load(f)
        from .. import framework_io
        got = _array_manifest(framework_io.load(path, return_numpy=True))
        bad = sorted(k for k in set(want) | set(got)
                     if want.get(k) != got.get(k))
        if bad:
            raise IOError(
                f"checksum mismatch in snapshot {kind}_{idx} at "
                f"{bad[:3]}{'...' if len(bad) > 3 else ''} "
                f"({len(bad)}/{len(want)} arrays)")

    # ---------------------------------------------------------------- range
    def train_epoch_range(self, max_epoch_num: int) -> Iterator[int]:
        """reference: auto_checkpoint.py train_epoch_range — yields epoch
        indices, skipping epochs already completed by a previous run."""
        from ..distributed import elastic
        last = self.restore_latest()
        # restore_latest returns the newest snapshot of EITHER kind; a step
        # snapshot's index is not an epoch, so only an epoch snapshot may
        # advance the start (mirrors train_step_range's symmetric guard)
        start = 0 if self.restored_kind != "epoch" else last + 1
        try:
            for epoch in range(start, max_epoch_num):
                elastic.heartbeat()  # no-op outside a supervised run
                yield epoch
                if (epoch + 1) % self.save_interval == 0 \
                        or epoch == max_epoch_num - 1:
                    if self.async_save:
                        self.save_async(epoch)
                    else:
                        self.save(epoch)
        finally:
            # also runs on generator close (caller `break`): the last
            # dispatched snapshot must be durable — the writer thread is a
            # daemon and would be killed mid-rename at interpreter exit
            self.wait()

    def train_step_range(self, max_steps: int) -> Iterator[int]:
        """Step-granular twin of train_epoch_range for supervised elastic
        workers: yields step indices, snapshotting every
        `save_every_n_steps` (and at the final step), and resumes from the
        newest step snapshot after a kill — the restart window is bounded
        by the save interval instead of an epoch. Each step also beats the
        elastic heartbeat, so a hung step is detectable by the
        supervisor."""
        from ..distributed import elastic
        every = self.save_every_n_steps or 1
        last = self.restore_latest()
        start = 0 if self.restored_kind != "step" else last + 1
        try:
            for step in range(start, max_steps):
                elastic.heartbeat()
                yield step
                if (step + 1) % every == 0 or step == max_steps - 1:
                    if self.async_save:
                        self.save_step_async(step)
                    else:
                        self.save_step(step)
        finally:
            self.wait()


# module-level convenience mirroring the reference's implicit API ----------
_default_mgr: Optional[AutoCheckpointManager] = None


def register(save_dir: str = None, models=(), optimizers=(),
             lr_schedulers=(), **kw):
    """Bind training objects for the module-level train_epoch_range
    (the reference discovers state via the global Scope; eager mode needs
    explicit registration)."""
    global _default_mgr
    save_dir = save_dir or os.environ.get("PADDLE_CHECKPOINT_DIR",
                                          "./auto_checkpoint")
    _default_mgr = AutoCheckpointManager(save_dir, models, optimizers,
                                         lr_schedulers, **kw)
    return _default_mgr


def train_epoch_range(max_epoch_num: int, save_checkpoint_inter=None):
    if _default_mgr is None:
        raise RuntimeError(
            "call paddle.incubate.checkpoint.register(save_dir, models=..., "
            "optimizers=...) before train_epoch_range")
    return _default_mgr.train_epoch_range(max_epoch_num)


# ------------------------------------------------------------ sharded save
def save_sharded_state(state: dict, path: str, process_index: int = None):
    """Save a name→jax.Array state dict under a mesh: each process writes
    ONLY its addressable shards (multi-host safe), plus a JSON manifest of
    global shapes/shardings. Analogue of the reference's distributed
    save_persistables (fleet_base.py) where each PS table saves its range.
    """
    pi = jax.process_index() if process_index is None else process_index
    os.makedirs(path, exist_ok=True)
    manifest = {}
    shards = {}
    from ..core.tensor import Tensor
    for name, arr in state.items():
        # unwrap framework Tensors only — jax.Array has its own `._value`
        # (internal numpy cache) that must not be taken
        if isinstance(arr, Tensor):
            arr = arr._value
        # the manifest records ONLY global metadata: per-shard entries
        # written by process 0 alone would under-describe a multi-host
        # save (each process sees only its addressable shards). Shard
        # placement is self-describing in the shard_*.npz keys.
        manifest[name] = {"shape": list(arr.shape), "dtype": str(arr.dtype)}
        for s in arr.addressable_shards:
            shards[_flat_key(name, s.index)] = np.asarray(s.data)
    np.savez(os.path.join(path, f"shard_{pi}.npz"), **shards)
    if pi == 0:
        with open(os.path.join(path, "manifest.json"), "w") as f:
            json.dump(manifest, f)


def _flat_key(name, index):
    parts = [f"{sl.start or 0}:{'' if sl.stop is None else sl.stop}"
             for sl in index]
    return name + "||" + ",".join(parts)


def load_sharded_state(path: str) -> dict:
    """Reassemble the global arrays from all shard files (single-host
    restore; multi-host jobs restore per-process shards the same way)."""
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    out = {name: np.zeros(m["shape"], dtype=m["dtype"])
           for name, m in manifest.items()}
    for fn in os.listdir(path):
        if not fn.startswith("shard_") or not fn.endswith(".npz"):
            continue
        data = np.load(os.path.join(path, fn))
        for key in data.files:
            name, idx = key.split("||")
            target = out[name]
            if idx:
                slices = []
                for part in idx.split(","):
                    a, b = part.split(":")
                    slices.append(slice(int(a), None if b == "" else int(b)))
                target[tuple(slices)] = data[key]
            else:
                out[name] = data[key]
    return out
