"""paddle.incubate (reference: python/paddle/fluid/incubate/ +
paddle.incubate 2.x): experimental features that graduated into the core
packages here — re-exported for API parity."""
from . import checkpoint  # noqa: F401
from . import optimizer  # noqa: F401


class LayerHelper:
    """reference fluid/layer_helper.py LayerHelper — the static-graph
    op-authoring helper (create_parameter / append_op / activation).
    Thin form over static.program; kept for incubate parity (custom
    layer recipes written against it)."""

    def __init__(self, layer_type, **kwargs):
        self.layer_type = layer_type
        self.kwargs = kwargs

    def create_parameter(self, attr=None, shape=None, dtype="float32",
                         is_bias=False, default_initializer=None):
        from ..static.program import create_parameter
        return create_parameter(shape, dtype,
                                initializer=default_initializer)

    def append_op(self, type=None, inputs=None, outputs=None, attrs=None):
        from ..core.dispatch import get_op
        fn = get_op(type)
        if fn is None:
            raise ValueError(f"LayerHelper.append_op: unknown op {type!r}")
        ins = [v for v in (inputs or {}).values()]
        flat = []
        for v in ins:
            flat.extend(v if isinstance(v, (list, tuple)) else [v])
        return fn(*flat, **(attrs or {}))

    def append_activation(self, out, act=None):
        if act is None:
            act = self.kwargs.get("act")
        if act is None:
            return out
        from ..nn import functional as F
        return getattr(F, act)(out)


def load_op_library(lib_filename):
    from ..utils import load_op_library as _l
    return _l(lib_filename)


from ..io import DataLoader as _DL  # noqa: E402


class reader:  # noqa: N801 - module-style shim (reference contrib.reader)
    """reference fluid/contrib/reader (distributed_reader decorator)."""

    @staticmethod
    def distributed_batch_reader(batch_reader):
        """Shard a batch reader across trainers by round-robin (reference
        contrib/reader/distributed_reader.py)."""
        import os

        def rd():
            rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
            nranks = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
            for i, b in enumerate(batch_reader()):
                if i % nranks == rank:
                    yield b
        return rd
