"""paddle.incubate (reference: python/paddle/fluid/incubate/ +
paddle.incubate 2.x): experimental features that graduated into the core
packages here — re-exported for API parity."""
from . import checkpoint  # noqa: F401
from . import optimizer  # noqa: F401
