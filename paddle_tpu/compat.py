"""paddle.compat — python 2/3 string-compat helpers kept for API parity.

Reference: /root/reference/python/paddle/compat.py (to_text:36,
to_bytes:132, round:217, floor_division:243, get_exception_message:260).
On python-3-only this collapses to thin conversions with the same
recursive list/set/dict semantics (inplace honoured for containers).
"""
from __future__ import annotations

import math


def _convert(obj, encoding, inplace, conv):
    if obj is None:
        return obj
    if isinstance(obj, list):
        if inplace:
            obj[:] = [_convert(i, encoding, inplace, conv) for i in obj]
            return obj
        return [_convert(i, encoding, inplace, conv) for i in obj]
    if isinstance(obj, set):
        out = {_convert(i, encoding, False, conv) for i in obj}
        if inplace:
            obj.clear()
            obj.update(out)
            return obj
        return out
    if isinstance(obj, dict):
        out = {_convert(k, encoding, False, conv):
               _convert(v, encoding, False, conv) for k, v in obj.items()}
        if inplace:
            obj.clear()
            obj.update(out)
            return obj
        return out
    return conv(obj, encoding)


def to_text(obj, encoding="utf-8", inplace=False):
    """bytes → str (recursively through list/set/dict)."""
    def conv(o, enc):
        return o.decode(enc) if isinstance(o, bytes) else o
    return _convert(obj, encoding, inplace, conv)


def to_bytes(obj, encoding="utf-8", inplace=False):
    """str → bytes (recursively through list/set/dict)."""
    def conv(o, enc):
        return o.encode(enc) if isinstance(o, str) else o
    return _convert(obj, encoding, inplace, conv)


def round(x, d=0):  # noqa: A001 - reference name
    """Half-away-from-zero rounding (python2 semantics the reference
    preserves; python3 builtin round is banker's)."""
    if x in (float("inf"), float("-inf")) or x != x:  # inf / nan
        return x
    p = 10 ** d
    if x >= 0.0:
        return float(math.floor((x * p) + math.copysign(0.5, x))) / p
    return float(math.ceil((x * p) + math.copysign(0.5, x))) / p


def floor_division(x, y):
    return x // y


def get_exception_message(exc):
    assert exc is not None
    return str(exc)
