"""SelectedRows: sparse row-wise gradients.

Reference: framework/selected_rows.h (rows vector + value tensor + height)
— the representation lookup_table's grad kernel emits so a huge embedding
table's gradient costs O(batch·dim), not O(vocab·dim); consumed by sgd/adam
kernels with row-wise updates (operators/optimizers/sgd_op.h SelectedRows
branch, adam_op.h lazy_mode) and by merge_selected_rows /
get_tensor_from_selected_rows ops.

TPU-native placement: inside a COMPILED step XLA's scatter-add on the dense
buffer is already optimal, so SelectedRows is an EAGER-path structure —
exactly where the reference uses it (the eager dygraph tape + PS push).
F.embedding(..., sparse=True) makes the tape deliver one of these to the
weight's .grad; optimizers apply row-sliced updates.
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp


class SelectedRows:
    """rows: int array [n]; values: [n, ...dim]; height: vocab size."""

    __slots__ = ("rows", "values", "height")

    def __init__(self, rows, values, height: int):
        self.rows = jnp.asarray(rows)
        self.values = jnp.asarray(values)
        self.height = int(height)

    @property
    def shape(self):
        return (self.height,) + tuple(self.values.shape[1:])

    @property
    def dtype(self):
        return self.values.dtype

    def merge(self) -> "SelectedRows":
        """Deduplicate rows, summing values (reference:
        operators/math/selected_rows_functor.h MergeAdd)."""
        rows = np.asarray(self.rows)
        uniq, inv = np.unique(rows, return_inverse=True)
        merged = jnp.zeros((len(uniq),) + self.values.shape[1:],
                           self.values.dtype)
        merged = merged.at[jnp.asarray(inv)].add(self.values)
        return SelectedRows(jnp.asarray(uniq), merged, self.height)

    def to_dense(self):
        """get_tensor_from_selected_rows (reference:
        get_tensor_from_selected_rows_op.cc)."""
        out = jnp.zeros(self.shape, self.values.dtype)
        return out.at[self.rows].add(self.values)

    def __add__(self, other):
        if isinstance(other, SelectedRows):
            assert other.height == self.height
            return SelectedRows(
                jnp.concatenate([self.rows, other.rows]),
                jnp.concatenate([self.values, other.values]), self.height)
        # dense + sparse → dense
        return jnp.asarray(other).at[self.rows].add(self.values)

    __radd__ = __add__

    def __repr__(self):
        return (f"SelectedRows(height={self.height}, "
                f"rows={self.rows.shape[0]}, dim={self.values.shape[1:]})")


def merge_selected_rows(x: SelectedRows) -> SelectedRows:
    """reference: merge_selected_rows_op.cc."""
    return x.merge()


def get_tensor_from_selected_rows(x: SelectedRows):
    from .tensor import Tensor
    return Tensor(x.to_dense())


def rowwise_update(optimizer, param_arr, sr: SelectedRows, state, lr):
    """Apply an optimizer update only on touched rows (reference: the
    SelectedRows branches of sgd_op.h / adam_op.h lazy_mode / momentum).
    Falls back to a dense update for optimizers whose math is not
    row-separable (those with global-norm terms, e.g. Lamb/Lars)."""
    from ..optimizer.optimizers import SGD, Adam, AdamW, Momentum
    m = sr.merge()
    rows = m.rows

    if "master" in state:
        # amp O2: the fp32 master is authoritative — a row-sliced update of
        # only the low-precision param would be erased by the next dense
        # step reading the stale master. Densify (correct, loses sparsity
        # only under multi_precision).
        return None, m.to_dense()

    if isinstance(optimizer, SGD):
        return param_arr.at[rows].add(-lr * m.values), state
    if isinstance(optimizer, Momentum):
        if getattr(optimizer, "_use_nesterov", False) or \
                getattr(optimizer, "_rescale_grad", 1.0) != 1.0:
            # dense path applies the Nesterov/rescale formula
            # (optimizers.py Momentum._update); keep the math identical
            return None, m.to_dense()
        vel = state.get("velocity")
        v_rows = optimizer._momentum * vel[rows] + m.values
        new_p = param_arr.at[rows].add(-lr * v_rows)
        state = dict(state)
        state["velocity"] = vel.at[rows].set(v_rows)
        return new_p, state
    if isinstance(optimizer, (Adam, AdamW)) and \
            getattr(optimizer, "_lazy_mode", False):
        # lazy adam: moments/bias-correction advance only on touched rows
        st = dict(state)
        b1, b2, eps = optimizer._beta1, optimizer._beta2, optimizer._epsilon
        m1 = st["moment1"]
        m2 = st["moment2"]
        b1p = st["beta1_pow"] * b1
        b2p = st["beta2_pow"] * b2
        g = m.values
        if isinstance(optimizer, AdamW):
            fn = optimizer._apply_decay_param_fun
            pname = getattr(optimizer, "_current_param_name", None)
            if fn is None or (pname is not None and fn(pname)):
                param_arr = param_arr.at[rows].multiply(
                    1.0 - lr * optimizer._coeff)
        nm1 = b1 * m1[rows] + (1 - b1) * g
        nm2 = b2 * m2[rows] + (1 - b2) * g * g
        lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
        new_p = param_arr.at[rows].add(-lr_t * nm1 / (jnp.sqrt(nm2) + eps))
        st["moment1"] = m1.at[rows].set(nm1)
        st["moment2"] = m2.at[rows].set(nm2)
        st["beta1_pow"] = b1p
        st["beta2_pow"] = b2p
        return new_p, st
    # not row-separable (or non-lazy adam, which must update ALL moments):
    # densify — correct, costs the dense memory the caller opted out of
    dense = m.to_dense()
    return None, dense  # caller falls back to the dense path


def split_selected_rows(x: "SelectedRows", height_sections):
    """reference: operators/split_selected_rows_op.cc — partition rows into
    contiguous height ranges (the PS parameter-partition step); rows are
    re-based to each section's origin."""
    import numpy as np
    outs = []
    start = 0
    rows = np.asarray(x.rows)
    for h in height_sections:
        sel = np.where((rows >= start) & (rows < start + h))[0]
        outs.append(SelectedRows(rows[sel] - start, x.values[sel], int(h)))
        start += h
    return outs
