"""Global stat registry (monitoring counters).

Reference: platform/monitor.h — StatRegistry:77 (named int64 stats,
STAT_ADD:130 / STAT_SUB / STAT_RESET macros, e.g. STAT_gpu0_mem_size used
by the allocator), exported to Python via pybind.

TPU-native: host-side counters over the same API; device-memory stats read
live from the PJRT client (memory_stats) instead of allocator hooks —
PJRT owns memory here (SURVEY C11 collapse).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Tuple

__all__ = ["stat_add", "stat_sub", "stat_reset", "stat_get", "stat_names",
           "print_stats", "device_memory_stats"]

_lock = threading.Lock()
_stats: Dict[str, int] = {}


def stat_add(name: str, value: int = 1) -> int:
    """reference: STAT_ADD (monitor.h:130)."""
    with _lock:
        _stats[name] = _stats.get(name, 0) + int(value)
        return _stats[name]


def stat_sub(name: str, value: int = 1) -> int:
    return stat_add(name, -int(value))


def stat_reset(name: str = None):
    with _lock:
        if name is None:
            _stats.clear()
        else:
            _stats[name] = 0


def stat_get(name: str) -> int:
    with _lock:
        return _stats.get(name, 0)


def stat_names() -> List[str]:
    with _lock:
        return sorted(_stats)


def print_stats() -> str:
    """reference: StatRegistry::publish-style dump."""
    with _lock:
        rows = sorted(_stats.items())
    lines = ["-" * 44, f"{'Stat':<32}{'Value':>12}", "-" * 44]
    lines += [f"{k[:31]:<32}{v:>12}" for k, v in rows]
    lines.append("-" * 44)
    return "\n".join(lines)


def device_memory_stats(device=None) -> Dict[str, int]:
    """Live device memory counters from PJRT (the analogue of the
    reference's STAT_gpuN_mem_size fed by the allocator)."""
    import jax
    dev = device or jax.devices()[0]
    try:
        ms = dev.memory_stats() or {}
    except (AttributeError, RuntimeError, jax.errors.JaxRuntimeError):
        return {}
    return {k: int(v) for k, v in ms.items() if isinstance(v, (int, float))}
