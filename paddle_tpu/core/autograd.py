"""Eager autograd engine.

TPU-native analogue of the reference's imperative runtime + BasicEngine
(/root/reference/paddle/fluid/imperative/tracer.cc:132 TraceOp records
GradOpNodes; basic_engine.cc:39/221/265 Init/PrepareDeps/Execute runs a
dep-counted BFS over grad ops; gradient_accumulator.cc sums grads).

Design differences, deliberately TPU-first:
- Instead of per-op C++ grad kernels selected from a registry, each eager op
  call captures a jax.vjp closure (XLA-differentiated); backward replays the
  closures in reverse topological order. The same op functions are pure JAX,
  so under `paddle_tpu.jit.to_static`/`jax.jit` NO tape is recorded — the
  whole step traces into one XLA computation and jax.grad handles AD (this is
  the performance path; the tape is the eager-semantics path).
- Grad accumulation is functional (cotangent dict keyed by producer slot)
  rather than mutation of a GradientAccumulator.
"""
from __future__ import annotations

import itertools
import weakref
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_seq_counter = itertools.count()


class TapeNode:
    """One recorded differentiable op (reference: GradOpNode, layer.h)."""

    __slots__ = ("seq", "op_type", "vjp_fn", "inputs", "out_specs",
                 "out_refs", "__weakref__")

    def __init__(self, op_type: str, vjp_fn: Callable, inputs: List[Any],
                 out_specs: List[Tuple[tuple, Any]]):
        self.seq = next(_seq_counter)
        self.op_type = op_type
        self.vjp_fn: Optional[Callable] = vjp_fn
        self.inputs = inputs            # Tensors (strong refs keep graph alive)
        self.out_specs = out_specs      # [(shape, dtype)] per flat output
        self.out_refs: List[Optional[weakref.ref]] = [None] * len(out_specs)

    @property
    def n_out(self) -> int:
        return len(self.out_specs)

    def release(self):
        """Free vjp residuals after backward (retain_graph=False)."""
        self.vjp_fn = None
        self.inputs = []


class _GradState:
    enabled = True


@contextmanager
def no_grad():
    """paddle.no_grad — disables tape recording."""
    prev = _GradState.enabled
    _GradState.enabled = False
    try:
        yield
    finally:
        _GradState.enabled = prev


@contextmanager
def enable_grad():
    prev = _GradState.enabled
    _GradState.enabled = True
    try:
        yield
    finally:
        _GradState.enabled = prev


def set_grad_enabled(mode: bool):
    @contextmanager
    def _ctx():
        prev = _GradState.enabled
        _GradState.enabled = mode
        try:
            yield
        finally:
            _GradState.enabled = prev
    return _ctx()


def is_grad_enabled() -> bool:
    return _GradState.enabled


def _zero_cotangent(spec):
    shape, dtype = spec
    if jnp.issubdtype(dtype, jnp.inexact):
        return jnp.zeros(shape, dtype)
    return np.zeros(shape, jax.dtypes.float0)


def _is_float0(x) -> bool:
    return getattr(x, "dtype", None) == jax.dtypes.float0


def _collect_nodes(root: TapeNode):
    """Reachable subgraph, not crossing stop_gradient tensors."""
    seen = set()
    stack = [root]
    nodes = []
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        nodes.append(n)
        for inp in n.inputs:
            pn = getattr(inp, "_node", None)
            if pn is not None and not inp.stop_gradient and id(pn) not in seen:
                stack.append(pn)
    nodes.sort(key=lambda n: -n.seq)
    return nodes


def _run_engine(root_tensor, root_grad, retain_graph: bool,
                sink: Optional[Dict[int, Any]] = None,
                sink_ids: Optional[set] = None):
    """Reverse-topological sweep (reference: BasicEngine::Execute
    basic_engine.cc:265). `sink`/`sink_ids`: when set (paddle.grad path),
    leaf cotangents are written there instead of .grad.
    """
    from .tensor import Tensor  # local import to break cycle

    def apply_hooks(t: Tensor, cot):
        """Hooks see/return Tensors (paddle parity: VarBase hooks)."""
        for h in t._hooks:
            out = h(Tensor(cot, stop_gradient=True))
            if out is not None:
                cot = out._value if isinstance(out, Tensor) else out
        return cot

    def deliver_leaf(t: Tensor, cot):
        if _is_float0(cot) or t.stop_gradient:
            return
        cot = apply_hooks(t, cot)
        if sink is not None:
            if sink_ids is None or id(t) in sink_ids:
                sink[id(t)] = cot if id(t) not in sink else sink[id(t)] + cot
            return
        t._accumulate_grad(cot)

    node = root_tensor._node
    if node is None:
        deliver_leaf(root_tensor, root_grad)
        return

    cot: Dict[Tuple[int, int], Any] = {(id(node), root_tensor._out_idx): root_grad}
    for n in _collect_nodes(node):
        outs = [cot.pop((id(n), i), None) for i in range(n.n_out)]
        if all(o is None for o in outs):
            if not retain_graph:
                n.release()
            continue
        if n.vjp_fn is None:
            raise RuntimeError(
                "Trying to backward through the graph a second time, but the "
                "saved intermediate results have already been freed. Specify "
                "retain_graph=True the first time you call backward().")
        # apply registered tensor hooks of the produced tensors
        for i, o in enumerate(outs):
            if o is None:
                continue
            ref = n.out_refs[i]
            t = ref() if ref is not None else None
            if t is not None:
                o = apply_hooks(t, o)
                outs[i] = o
                if sink is not None and sink_ids is not None and id(t) in sink_ids:
                    sink[id(t)] = o if id(t) not in sink else sink[id(t)] + o
        outs = [o if o is not None else _zero_cotangent(s)
                for o, s in zip(outs, n.out_specs)]
        in_cots = n.vjp_fn(tuple(outs) if n.n_out > 1 else outs[0])
        inputs = n.inputs
        if not retain_graph:
            n.release()
        for inp, ic in zip(inputs, in_cots):
            if _is_float0(ic) or inp.stop_gradient:
                continue
            pn = inp._node
            if pn is None:
                deliver_leaf(inp, ic)
            else:
                key = (id(pn), inp._out_idx)
                cot[key] = ic if key not in cot else cot[key] + ic
                if sink is None and inp._retain_grads:
                    inp._accumulate_grad(ic)


def backward(tensor, grad_tensor=None, retain_graph: bool = False):
    """Tensor.backward entry (reference: pybind/imperative.cc:871
    VarBase._run_backward → BasicEngine)."""
    from .tensor import Tensor
    if grad_tensor is None:
        root_grad = jnp.ones(tensor.shape, tensor._value.dtype)
    else:
        root_grad = grad_tensor._value if isinstance(grad_tensor, Tensor) \
            else jnp.asarray(grad_tensor)
    _run_engine(tensor, root_grad, retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph: bool = False, only_inputs: bool = True,
         allow_unused: bool = False, no_grad_vars=None):
    """paddle.grad (reference: PartialGradEngine, partial_grad_engine.cc).

    Returns grads of `outputs` w.r.t. `inputs` without touching .grad.
    """
    from .tensor import Tensor
    if create_graph:
        raise NotImplementedError(
            "create_graph=True (double grad) is not supported yet")
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]
    if retain_graph is None:
        retain_graph = False
    sink: Dict[int, Any] = {}
    sink_ids = {id(t) for t in inputs}
    # no_grad_vars: temporarily mark as stop_gradient so traversal and
    # cotangent routing treat their subgraphs as constant
    blocked = []
    if no_grad_vars:
        for v in (no_grad_vars if isinstance(no_grad_vars, (list, tuple))
                  else [no_grad_vars]):
            if not v.stop_gradient:
                v.stop_gradient = True
                blocked.append(v)
    try:
        for k, (out, g) in enumerate(zip(outputs, grad_outputs)):
            if g is None:
                g = jnp.ones(out.shape, out._value.dtype)
            else:
                g = g._value if isinstance(g, Tensor) else jnp.asarray(g)
            last = (k == len(outputs) - 1)
            _run_engine(out, g, retain_graph or not last,
                        sink=sink, sink_ids=sink_ids)
    finally:
        for v in blocked:
            v.stop_gradient = False
    results = []
    for t in inputs:
        if id(t) in sink:
            results.append(Tensor(sink[id(t)], stop_gradient=True))
        elif allow_unused:
            results.append(None)
        else:
            raise RuntimeError(
                "One of the differentiated tensors appears to not have been "
                "used in the graph. Set allow_unused=True if this is desired.")
    return results
