"""Eager autograd engine.

TPU-native analogue of the reference's imperative runtime + BasicEngine
(/root/reference/paddle/fluid/imperative/tracer.cc:132 TraceOp records
GradOpNodes; basic_engine.cc:39/221/265 Init/PrepareDeps/Execute runs a
dep-counted BFS over grad ops; gradient_accumulator.cc sums grads).

Design differences, deliberately TPU-first:
- Instead of per-op C++ grad kernels selected from a registry, each eager op
  call captures a jax.vjp closure (XLA-differentiated); backward replays the
  closures in reverse topological order. The same op functions are pure JAX,
  so under `paddle_tpu.jit.to_static`/`jax.jit` NO tape is recorded — the
  whole step traces into one XLA computation and jax.grad handles AD (this is
  the performance path; the tape is the eager-semantics path).
- Grad accumulation is functional (cotangent dict keyed by producer slot)
  rather than mutation of a GradientAccumulator.
"""
from __future__ import annotations

import itertools
import weakref
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_seq_counter = itertools.count()


class TapeNode:
    """One recorded differentiable op (reference: GradOpNode, layer.h)."""

    __slots__ = ("seq", "op_type", "vjp_fn", "fwd_fn", "inputs", "in_arrays",
                 "out_specs", "out_refs", "__weakref__")

    def __init__(self, op_type: str, vjp_fn: Callable, inputs: List[Any],
                 out_specs: List[Tuple[tuple, Any]],
                 fwd_fn: Optional[Callable] = None, in_arrays=None):
        self.seq = next(_seq_counter)
        self.op_type = op_type
        self.vjp_fn: Optional[Callable] = vjp_fn
        self.fwd_fn = fwd_fn            # pure fn of input arrays (replay/AD²)
        self.inputs = inputs            # Tensors (strong refs keep graph alive)
        self.in_arrays = in_arrays      # forward-time input values (replay
        # must not see later in-place mutations of leaf tensors; these are
        # the same arrays the vjp residuals retain, so no extra memory)
        self.out_specs = out_specs      # [(shape, dtype)] per flat output
        self.out_refs: List[Optional[weakref.ref]] = [None] * len(out_specs)

    @property
    def n_out(self) -> int:
        return len(self.out_specs)

    def release(self):
        """Free vjp residuals after backward (retain_graph=False)."""
        self.vjp_fn = None
        self.fwd_fn = None
        self.inputs = []
        self.in_arrays = None


class _GradState:
    enabled = True
    # When True, the tape records even under a jax trace (normally bypassed
    # for the one-fused-XLA-module perf path). Set by enable_grad(): inside
    # jit this is the explicit opt-in for paddle.grad/double-grad regions.
    force_tape = False


@contextmanager
def no_grad():
    """paddle.no_grad — disables tape recording."""
    prev = _GradState.enabled
    _GradState.enabled = False
    try:
        yield
    finally:
        _GradState.enabled = prev


@contextmanager
def enable_grad():
    prev = _GradState.enabled
    prev_force = _GradState.force_tape
    _GradState.enabled = True
    _GradState.force_tape = True
    try:
        yield
    finally:
        _GradState.enabled = prev
        _GradState.force_tape = prev_force


def set_grad_enabled(mode: bool):
    @contextmanager
    def _ctx():
        prev = _GradState.enabled
        _GradState.enabled = mode
        try:
            yield
        finally:
            _GradState.enabled = prev
    return _ctx()


def is_grad_enabled() -> bool:
    return _GradState.enabled


def _zero_cotangent(spec):
    shape, dtype = spec
    if jnp.issubdtype(dtype, jnp.inexact):
        return jnp.zeros(shape, dtype)
    return np.zeros(shape, jax.dtypes.float0)


def _is_float0(x) -> bool:
    return getattr(x, "dtype", None) == jax.dtypes.float0


def _collect_nodes(root: TapeNode):
    """Reachable subgraph, not crossing stop_gradient tensors."""
    seen = set()
    stack = [root]
    nodes = []
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        nodes.append(n)
        for inp in n.inputs:
            pn = getattr(inp, "_node", None)
            if pn is not None and not inp.stop_gradient and id(pn) not in seen:
                stack.append(pn)
    nodes.sort(key=lambda n: -n.seq)
    return nodes


def _run_engine(root_tensor, root_grad, retain_graph: bool,
                sink: Optional[Dict[int, Any]] = None,
                sink_ids: Optional[set] = None):
    """Reverse-topological sweep (reference: BasicEngine::Execute
    basic_engine.cc:265). `sink`/`sink_ids`: when set (paddle.grad path),
    leaf cotangents are written there instead of .grad.
    """
    from .tensor import Tensor  # local import to break cycle

    def apply_hooks(t: Tensor, cot):
        """Hooks see/return Tensors (paddle parity: VarBase hooks)."""
        for h in t._hooks:
            out = h(Tensor(cot, stop_gradient=True))
            if out is not None:
                cot = out._value if isinstance(out, Tensor) else out
        return cot

    def deliver_leaf(t: Tensor, cot):
        if _is_float0(cot) or t.stop_gradient:
            return
        cot = apply_hooks(t, cot)
        if sink is not None:
            if sink_ids is None or id(t) in sink_ids:
                sink[id(t)] = cot if id(t) not in sink else sink[id(t)] + cot
            return
        t._accumulate_grad(cot)

    node = root_tensor._node
    if node is None:
        deliver_leaf(root_tensor, root_grad)
        return

    cot: Dict[Tuple[int, int], Any] = {(id(node), root_tensor._out_idx): root_grad}
    for n in _collect_nodes(node):
        outs = [cot.pop((id(n), i), None) for i in range(n.n_out)]
        if all(o is None for o in outs):
            if not retain_graph:
                n.release()
            continue
        if n.vjp_fn is None:
            raise RuntimeError(
                "Trying to backward through the graph a second time, but the "
                "saved intermediate results have already been freed. Specify "
                "retain_graph=True the first time you call backward().")
        # apply registered tensor hooks of the produced tensors
        for i, o in enumerate(outs):
            if o is None:
                continue
            ref = n.out_refs[i]
            t = ref() if ref is not None else None
            if t is not None:
                o = apply_hooks(t, o)
                outs[i] = o
                if sink is not None and sink_ids is not None and id(t) in sink_ids:
                    sink[id(t)] = o if id(t) not in sink else sink[id(t)] + o
        outs = [o if o is not None else _zero_cotangent(s)
                for o, s in zip(outs, n.out_specs)]
        in_cots = n.vjp_fn(tuple(outs) if n.n_out > 1 else outs[0])
        inputs = n.inputs
        if not retain_graph:
            n.release()
        for inp, ic in zip(inputs, in_cots):
            if _is_float0(ic) or inp.stop_gradient:
                continue
            pn = inp._node
            if pn is None:
                deliver_leaf(inp, ic)
            else:
                key = (id(pn), inp._out_idx)
                cot[key] = ic if key not in cot else cot[key] + ic
                if sink is None and inp._retain_grads:
                    inp._accumulate_grad(ic)


def backward(tensor, grad_tensor=None, retain_graph: bool = False):
    """Tensor.backward entry (reference: pybind/imperative.cc:871
    VarBase._run_backward → BasicEngine)."""
    from .tensor import Tensor
    if grad_tensor is None:
        root_grad = jnp.ones(tensor.shape, tensor._value.dtype)
    else:
        root_grad = grad_tensor._value if isinstance(grad_tensor, Tensor) \
            else jnp.asarray(grad_tensor)
    _run_engine(tensor, root_grad, retain_graph)


def _tensor_key(t):
    """Identity of a value in the replay env: producer slot for op outputs,
    object id for leaves."""
    if t._node is not None:
        return (id(t._node), t._out_idx)
    return ("leaf", id(t))


def _collect_forward(outputs, blocked_ids):
    """Forward subgraph reaching `outputs` in execution (seq) order."""
    seen, nodes, stack = set(), [], []
    for t in outputs:
        if t._node is not None and id(t) not in blocked_ids:
            stack.append(t._node)
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        nodes.append(n)
        for inp in n.inputs:
            pn = inp._node
            if pn is not None and not inp.stop_gradient \
                    and id(inp) not in blocked_ids and id(pn) not in seen:
                stack.append(pn)
    nodes.sort(key=lambda n: n.seq)
    return nodes


def _grad_create_graph(outputs, inputs, grad_outputs, allow_unused,
                       no_grad_vars):
    """Higher-order paddle.grad: replay the recorded forward as one pure JAX
    function of the leaf inputs, then dispatch its vjp as a single
    'partial_grad' op — which itself lands on the tape, so the returned
    grads are differentiable to any order (reference:
    imperative/partial_grad_engine.cc create_graph path; here AD composes
    for free because every replayed op is pure JAX)."""
    from .tensor import Tensor
    from . import dispatch as _dispatch

    blocked_ids = {id(v) for v in (no_grad_vars or [])}
    nodes = _collect_forward(outputs, blocked_ids)
    for n in nodes:
        if n.fwd_fn is None:
            raise RuntimeError(
                "create_graph=True requires the forward graph to be alive; "
                "it was already freed by a previous backward() without "
                "retain_graph=True.")

    # forward-time snapshot of every node input (in-place updates of leaves
    # between forward and grad must not leak into the replay — eager parity:
    # the vjp residuals were captured at forward time too)
    recorded: Dict[int, Any] = {}
    used_keys = set()
    for n in nodes:
        for t, a in zip(n.inputs, n.in_arrays or []):
            recorded.setdefault(id(t), a)
            used_keys.add(_tensor_key(t))
    used_keys.update(_tensor_key(t) for t in outputs)

    def _rec_value(t):
        return recorded.get(id(t), t._value)

    # eager parity: a stop_gradient input is "not used in the graph"
    unused = [t.stop_gradient or _tensor_key(t) not in used_keys
              for t in inputs]
    if any(unused) and not allow_unused:
        raise RuntimeError(
            "One of the differentiated tensors appears to not have been "
            "used in the graph. Set allow_unused=True if this is desired.")

    seeds = []
    for out, g in zip(outputs, grad_outputs):
        if g is None:
            seeds.append(jnp.ones(out.shape, out._value.dtype))
        else:
            seeds.append(g._value if isinstance(g, Tensor) else jnp.asarray(g))
    seeds = tuple(seeds)

    # The dispatched op must stay connected to EVERY differentiable leaf in
    # the subgraph (not just the requested inputs), so that backward through
    # the returned grads reaches e.g. model weights (gradient penalties).
    # Deduplicate by value identity: a tensor requested twice gets the same
    # gradient at both positions.
    all_args, arg_keys, pos_of = [], [], {}
    for t in inputs:
        k = _tensor_key(t)
        if k not in pos_of:
            pos_of[k] = len(all_args)
            all_args.append(t)
            arg_keys.append(k)
    for n in nodes:
        for t in n.inputs:
            k = _tensor_key(t)
            if t._node is None and not t.stop_gradient \
                    and id(t) not in blocked_ids and k not in pos_of:
                pos_of[k] = len(all_args)
                all_args.append(t)
                arg_keys.append(k)

    def replay(*in_arrs):
        override = dict(zip(arg_keys, in_arrs))
        env = dict(override)
        for n in nodes:
            vals = []
            for t in n.inputs:
                # blocked (no_grad_vars) tensors are constants; stop_gradient
                # frontiers are constants automatically (their producers were
                # never collected, so env has no entry)
                if id(t) in blocked_ids:
                    vals.append(_rec_value(t))
                else:
                    vals.append(env.get(_tensor_key(t), _rec_value(t)))
            outs = n.fwd_fn(*vals)
            flat, _ = jax.tree_util.tree_flatten(outs)
            for i, o in enumerate(flat):
                k = (id(n), i)
                if k not in override:  # requested intermediates stay pinned
                    env[k] = o
        return tuple(env.get(_tensor_key(t), _rec_value(t)) for t in outputs)

    def grad_fn(*in_arrs):
        _, vjp = jax.vjp(replay, *in_arrs)
        return vjp(seeds)

    # Evaluate at the forward-time point: temporarily pin each arg tensor's
    # value to its recorded array so the dispatched vjp (and any further
    # differentiation of it) is taken where the graph was actually built.
    saved = [(t, t._value) for t in all_args if id(t) in recorded]
    try:
        for t, _ in saved:
            t._value = recorded[id(t)]
        grads = _dispatch.dispatch("partial_grad", grad_fn,
                                   tuple(all_args), {})
    finally:
        for t, v in saved:
            t._value = v
    results = []
    for t, is_unused in zip(inputs, unused):
        results.append(None if is_unused
                       else grads[pos_of[_tensor_key(t)]])
    return results


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph: bool = False, only_inputs: bool = True,
         allow_unused: bool = False, no_grad_vars=None):
    """paddle.grad (reference: PartialGradEngine, partial_grad_engine.cc).

    Returns grads of `outputs` w.r.t. `inputs` without touching .grad.
    """
    from .tensor import Tensor
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if create_graph:
        if grad_outputs is None:
            grad_outputs = [None] * len(outputs)
        elif not isinstance(grad_outputs, (list, tuple)):
            grad_outputs = [grad_outputs]
        if no_grad_vars is not None and not isinstance(no_grad_vars,
                                                       (list, tuple)):
            no_grad_vars = [no_grad_vars]
        return _grad_create_graph(outputs, inputs, grad_outputs,
                                  allow_unused, no_grad_vars)
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]
    if retain_graph is None:
        retain_graph = False
    sink: Dict[int, Any] = {}
    sink_ids = {id(t) for t in inputs}
    # no_grad_vars: temporarily mark as stop_gradient so traversal and
    # cotangent routing treat their subgraphs as constant
    blocked = []
    if no_grad_vars:
        for v in (no_grad_vars if isinstance(no_grad_vars, (list, tuple))
                  else [no_grad_vars]):
            if not v.stop_gradient:
                v.stop_gradient = True
                blocked.append(v)
    try:
        for k, (out, g) in enumerate(zip(outputs, grad_outputs)):
            if g is None:
                g = jnp.ones(out.shape, out._value.dtype)
            else:
                g = g._value if isinstance(g, Tensor) else jnp.asarray(g)
            last = (k == len(outputs) - 1)
            _run_engine(out, g, retain_graph or not last,
                        sink=sink, sink_ids=sink_ids)
    finally:
        for v in blocked:
            v.stop_gradient = False
    results = []
    for t in inputs:
        if id(t) in sink:
            results.append(Tensor(sink[id(t)], stop_gradient=True))
        elif allow_unused:
            results.append(None)
        else:
            raise RuntimeError(
                "One of the differentiated tensors appears to not have been "
                "used in the graph. Set allow_unused=True if this is desired.")
    return results
