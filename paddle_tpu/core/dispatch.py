"""Op dispatch: the eager trace step.

TPU-native analogue of the reference's Tracer::TraceOp
(/root/reference/paddle/fluid/imperative/tracer.cc:132: create op → AMP cast →
kernel dispatch → record GradOpNode) and of the generated `core.ops.*`
fast-path functions (pybind/op_function_generator.cc).

Every framework op is a *pure JAX function* wrapped by @op. Dispatch:
1. unwraps Tensor leaves (pytree-general, so list-of-Tensor inputs work),
2. applies dygraph AMP autocast if active (reference: amp_auto_cast.cc:27),
3. if gradients are required, records a TapeNode carrying a jax.vjp closure,
4. wraps outputs back into Tensors.

Under jax tracing (to_static / jax.jit / shard_map) values are jax Tracers:
the tape is bypassed and the op contributes straight to the traced jaxpr, so
whole training steps compile into one fused XLA module — the analogue of the
reference's ParallelExecutor graph mode, but via XLA instead of SSA graphs.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from .autograd import TapeNode, _GradState
from .tensor import Tensor
from . import flags as _flags

_OP_REGISTRY = {}

# hooks installed by other subsystems (set lazily to avoid import cycles)
_amp_cast_hook = None          # ops.amp installs: fn(op_type, tensors)->tensors
_static_capture_hook = None    # static.program installs


def register_amp_hook(fn):
    global _amp_cast_hook
    _amp_cast_hook = fn


def _is_tracer(x):
    return isinstance(x, jax.core.Tracer)


def _leaf_is_tensor(x):
    return isinstance(x, Tensor)


def dispatch(op_type: str, fn: Callable, args, kwargs, differentiable=True):
    leaves, treedef = jax.tree_util.tree_flatten(
        (args, kwargs), is_leaf=_leaf_is_tensor)
    tensor_pos = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]

    if _amp_cast_hook is not None and tensor_pos:
        casted = _amp_cast_hook(op_type, [leaves[i] for i in tensor_pos])
        if casted is not None:
            for i, t in zip(tensor_pos, casted):
                leaves[i] = t

    in_tensors = [leaves[i] for i in tensor_pos]
    arrs = [t._value for t in in_tensors]

    def pure(*arrs_):
        ll = list(leaves)
        for i, a in zip(tensor_pos, arrs_):
            ll[i] = a
        a2, k2 = jax.tree_util.tree_unflatten(treedef, ll)
        return fn(*a2, **k2)

    if _static_capture_hook is not None:
        captured = _static_capture_hook(op_type, pure, in_tensors,
                                        differentiable)
        if captured is not None:
            return captured

    tracing = any(_is_tracer(a) for a in arrs)
    need_grad = (differentiable and _GradState.enabled
                 and (not tracing or _GradState.force_tape)
                 and any(not t.stop_gradient for t in in_tensors))

    if not need_grad:
        out = pure(*arrs)
        return _wrap_outputs(op_type, out, None, stop_gradient=True)

    out, vjp_fn = jax.vjp(pure, *arrs)
    flat_out, out_tree = jax.tree_util.tree_flatten(out)
    node = TapeNode(
        op_type,
        _vjp_adapter(vjp_fn, out_tree, len(flat_out)),
        in_tensors,
        [(tuple(a.shape), a.dtype) for a in flat_out],
        fwd_fn=pure,
        in_arrays=arrs,
    )
    return _wrap_outputs(op_type, out, node, stop_gradient=False)


def _vjp_adapter(vjp_fn, out_tree, n_out):
    """Engine delivers flat cotangents; vjp expects the output pytree."""
    def run(cots):
        flat = [cots] if n_out == 1 else list(cots)
        return vjp_fn(jax.tree_util.tree_unflatten(out_tree, flat))
    return run


def _check_finite(op_type, arrs):
    for a in arrs:
        if jnp.issubdtype(a.dtype, jnp.inexact) and not bool(jnp.isfinite(a).all()):
            raise FloatingPointError(
                f"Operator {op_type} output contains NaN/Inf "
                "(FLAGS_check_nan_inf is set; reference hook operator.cc:1172)")


def _wrap_outputs(op_type, out, node, stop_gradient):
    flat, out_tree = jax.tree_util.tree_flatten(out)
    if _flags.flag("check_nan_inf") and not any(_is_tracer(a) for a in flat):
        _check_finite(op_type, flat)
    wrapped = []
    for i, a in enumerate(flat):
        t = Tensor(a, stop_gradient=stop_gradient)
        if node is not None:
            t._node = node
            t._out_idx = i
            import weakref
            node.out_refs[i] = weakref.ref(t)
        wrapped.append(t)
    return jax.tree_util.tree_unflatten(out_tree, wrapped)


def op(op_type: str, differentiable: bool = True):
    """Declare a framework op (reference: REGISTER_OPERATOR
    op_registry.h:256 — here registration is a decorator and the 'kernel' is
    a pure JAX function lowered by XLA for whatever backend is active)."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return dispatch(op_type, fn, args, kwargs, differentiable)
        wrapper.op_type = op_type
        wrapper.raw_fn = fn
        _OP_REGISTRY[op_type] = wrapper
        return wrapper
    return deco


def get_op(op_type: str):
    return _OP_REGISTRY.get(op_type)


def registered_ops():
    return sorted(_OP_REGISTRY)
