"""Anomaly guard: NaN/Inf detection on losses and gradients with policies.

Reference: Paddle's FLAGS_check_nan_inf hook (operator.cc:1172, surfaced
here as core.flags 'check_nan_inf' + core.dispatch._check_finite) aborts on
the FIRST non-finite op output — right for debugging, wrong for a week-long
pod run where one flaky step should not cost the job. This module adds the
production policy layer:

  raise      — fail fast with the offending parameter names (debug parity
               with FLAGS_check_nan_inf, but at step granularity)
  skip_step  — drop the poisoned update entirely (params, accumulators and
               scheduler state unchanged), count it, continue — the same
               recovery the AMP GradScaler applies to overflow steps
  zero_grads — zero the non-finite gradient entries and apply the rest of
               the update (useful when a single layer overflows but the
               global step is still informative)

All detection primitives are jit-compatible (pure jnp reductions, no host
sync), so the same guard drives the eager `optimizer.step` path, the AMP
scaler, and the fused TrainStep used by hapi's fit loop — the compiled step
gates the whole parameter/optimizer update through `jnp.where` exactly like
the static-graph found_inf path. Skipped/zeroed steps are counted on the
guard so silent recovery is still observable.
"""
from __future__ import annotations

from contextlib import contextmanager
from typing import Optional

import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np

__all__ = ["AnomalyGuard", "anomaly_guard", "set_anomaly_guard",
           "current_guard", "tree_not_finite", "rows_not_finite",
           "any_not_finite_host", "rows_not_finite_host",
           "sanitize_tree", "POLICIES"]

POLICIES = ("raise", "skip_step", "zero_grads")


# ---------------------------------------------------------------- primitives
def _leaf_not_finite(a):
    a = jnp.asarray(a)
    if not jnp.issubdtype(a.dtype, jnp.inexact):
        return jnp.asarray(False)
    return ~jnp.isfinite(a).all()


def tree_not_finite(tree):
    """True iff ANY inexact leaf of `tree` contains NaN/Inf. Pure jnp —
    safe inside jit (returns a traced bool scalar) and reused by the AMP
    scaler's found-inf sweep."""
    flags = [_leaf_not_finite(a) for a in jtu.tree_leaves(tree)]
    if not flags:
        return jnp.asarray(False)
    out = flags[0]
    for f in flags[1:]:
        out = out | f
    return out


def rows_not_finite(a):
    """Per-row anomaly flags for a [N, ...] batch of values: True where
    row i contains any NaN/Inf. The attribution primitive of the serving
    engine's step guard — one poisoned request's logits must cost that
    request, not the batch. Returns a [N] bool array (jnp; jit-safe);
    1-D input is treated as a single row → [1]."""
    a = jnp.asarray(a)
    if a.ndim == 0:
        a = a[None]
    if a.ndim == 1:
        a = a[None]
    return ~jnp.isfinite(a).reshape(a.shape[0], -1).all(axis=1)


def any_not_finite_host(a) -> bool:
    """Host-side twin of tree_not_finite for a value that is ALREADY
    host numpy (e.g. the serving engine's fetched logits). Pushing an
    already-materialized array back through jnp costs a device upload +
    download per step (ptlint PT-T002's defect class, caught on the
    serving decode loop); plain np.isfinite keeps the check on host."""
    a = np.asarray(a)
    if not np.issubdtype(a.dtype, np.inexact):
        return False
    return not bool(np.isfinite(a).all())


def rows_not_finite_host(a) -> "np.ndarray":
    """Host-side twin of rows_not_finite ([N, ...] numpy → [N] bool),
    for attribution over logits the engine has already materialized."""
    a = np.asarray(a)
    if a.ndim == 0:
        a = a[None]
    if a.ndim == 1:
        a = a[None]
    if not np.issubdtype(a.dtype, np.inexact):
        return np.zeros(a.shape[0], bool)
    return ~np.isfinite(a).reshape(a.shape[0], -1).all(axis=1)


def sanitize_tree(tree):
    """Replace non-finite entries with 0 in every inexact leaf (the
    zero_grads policy's repair step). jit-compatible."""
    def fix(a):
        a = jnp.asarray(a)
        if not jnp.issubdtype(a.dtype, jnp.inexact):
            return a
        return jnp.where(jnp.isfinite(a), a, jnp.zeros((), a.dtype))
    return jtu.tree_map(fix, tree)


# --------------------------------------------------------------------- guard
class AnomalyGuard:
    """Policy + counters for non-finite losses/gradients.

    Counters (host-side ints, surfaced so silent recovery stays
    observable):
      skipped_steps   updates dropped under skip_step (incl. AMP-overflow
                      skips reported by GradScaler when a guard is active)
      zeroed_steps    updates applied with sanitized grads under zero_grads
      raised          anomalies that escalated to FloatingPointError
      checked_steps   total guarded step checks
    """

    def __init__(self, policy: str = "raise"):
        if policy not in POLICIES:
            raise ValueError(
                f"anomaly policy must be one of {POLICIES}, got {policy!r}")
        self.policy = policy
        self.skipped_steps = 0
        self.zeroed_steps = 0
        self.raised = 0
        self.checked_steps = 0

    # ------------------------------------------------------------- counters
    def record(self, bad: bool, where: str = "step",
               counter: Optional[str] = None) -> bool:
        """Count one guarded check whose anomaly flag is `bad` (a host
        bool); applies the policy's counter and raises under 'raise'.
        `counter` ('skipped'|'zeroed') overrides the policy-derived choice
        for callers that know what ACTUALLY happened — e.g. the AMP scaler
        drops an overflow step entirely even when the guard's policy is
        zero_grads, so it must land in skipped_steps. Returns bad for
        chaining."""
        self.checked_steps += 1
        if not bad:
            return False
        if self.policy == "raise":
            self.raised += 1
            raise FloatingPointError(
                f"anomaly guard: non-finite values detected in {where} "
                f"(policy='raise'; use 'skip_step'/'zero_grads' to ride "
                f"through)")
        if counter is None:
            counter = "zeroed" if self.policy == "zero_grads" else "skipped"
        if counter == "zeroed":
            self.zeroed_steps += 1
        else:
            self.skipped_steps += 1
        return True

    # --------------------------------------------------------- eager checks
    def check_loss(self, loss) -> bool:
        """Eager loss check (host sync). True → caller should skip."""
        arr = loss._value if hasattr(loss, "_value") else loss
        return self.record(bool(tree_not_finite(arr)), where="loss")

    def state_dict(self):
        return {"policy": self.policy, "skipped_steps": self.skipped_steps,
                "zeroed_steps": self.zeroed_steps, "raised": self.raised,
                "checked_steps": self.checked_steps}

    def __repr__(self):
        return (f"AnomalyGuard(policy={self.policy!r}, "
                f"checked={self.checked_steps}, "
                f"skipped={self.skipped_steps}, zeroed={self.zeroed_steps}, "
                f"raised={self.raised})")


# ------------------------------------------------------------- global guard
_current: Optional[AnomalyGuard] = None


def set_anomaly_guard(guard) -> Optional[AnomalyGuard]:
    """Install a process-wide guard consulted by optimizer.step and the AMP
    scaler. Accepts an AnomalyGuard, a policy string, or None (disable).
    Returns the installed guard."""
    global _current
    if isinstance(guard, str):
        guard = AnomalyGuard(guard)
    _current = guard
    return guard


def current_guard() -> Optional[AnomalyGuard]:
    return _current


@contextmanager
def anomaly_guard(policy_or_guard="raise"):
    """Scoped guard: `with anomaly_guard('skip_step') as g: train()`."""
    prev = _current
    g = set_anomaly_guard(policy_or_guard)
    try:
        yield g
    finally:
        set_anomaly_guard(prev)
