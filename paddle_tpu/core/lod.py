"""LoD (level-of-detail) offsets facade.

Reference: framework/lod_tensor.h:56 — the reference's LoDTensor carries a
list of offset levels describing ragged sequence boundaries over a flat
rows-concatenated tensor, e.g. lod=[[0, 2, 5]] means two sequences of
lengths 2 and 3.

TPU-native substrate: ragged data lives as (dense [B, Tmax, ...], lengths
[B]) pairs — the static-shape encoding XLA requires (ops/sequence_ops.py).
This module is the offsets-facing facade over that substrate: a LoDTensor
holding the flat concatenation + offset levels, with lossless conversion to
and from the padded form, mirroring the reference API (lod()/set_lod()/
recursive_sequence_lengths()) so reference-style code ports unchanged.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .tensor import Tensor, to_tensor


def _wrap(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _offsets_from_lengths(lengths):
    off = [0]
    for n in lengths:
        off.append(off[-1] + int(n))
    return off


def _lengths_from_offsets(offsets):
    return [int(b) - int(a) for a, b in zip(offsets[:-1], offsets[1:])]


class LoDTensor:
    """Flat rows-concatenated tensor + offset levels (reference
    framework/lod_tensor.h). `data` is [total_rows, ...]."""

    def __init__(self, data, lod=None):
        self.data = _wrap(data)
        self._lod = [list(map(int, level)) for level in (lod or [])]

    # -- reference API ------------------------------------------------------
    def lod(self):
        return [list(level) for level in self._lod]

    def set_lod(self, lod):
        for level in lod:
            if list(level) != sorted(map(int, level)) or (level and
                                                          level[0] != 0):
                raise ValueError(f"invalid LoD level {level}: offsets must "
                                 "be ascending and start at 0")
        self._lod = [list(map(int, level)) for level in lod]

    def recursive_sequence_lengths(self):
        return [_lengths_from_offsets(level) for level in self._lod]

    def set_recursive_sequence_lengths(self, seq_lens):
        self._lod = [_offsets_from_lengths(level) for level in seq_lens]

    def has_valid_recursive_sequence_lengths(self):
        if not self._lod:
            return True
        for upper, lower in zip(self._lod[:-1], self._lod[1:]):
            if upper[-1] != len(lower) - 1:
                return False
        return self._lod[-1][-1] == int(self.data.shape[0])

    @property
    def shape(self):
        return self.data.shape

    def numpy(self):
        return self.data.numpy()

    def __repr__(self):
        return f"LoDTensor(shape={self.data.shape}, lod={self._lod})"

    # -- bridge to the TPU-native (dense, lengths) rep ----------------------
    def to_padded(self, pad_value=0.0):
        """Returns (dense [B, Tmax, ...], lengths [B]) from level-(-1)."""
        if not self._lod:
            raise ValueError("LoDTensor has no LoD; it is already dense")
        lengths = _lengths_from_offsets(self._lod[-1])
        from ..ops.sequence_ops import sequence_pad
        padded, lens = sequence_pad(self.data,
                                    to_tensor(np.asarray(lengths, np.int64)),
                                    pad_value=pad_value)
        return padded, lens

    @staticmethod
    def from_padded(dense, lengths):
        """Build from (dense [B, Tmax, ...], lengths [B]): flat rows +
        single offset level."""
        from ..ops.sequence_ops import sequence_unpad
        lens = [int(v) for v in np.asarray(_wrap(lengths).numpy())]
        flat = sequence_unpad(dense, _wrap(lengths))
        return LoDTensor(flat, [_offsets_from_lengths(lens)])


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """reference: python/paddle/fluid/lod_tensor.py create_lod_tensor."""
    t = LoDTensor(data)
    t.set_recursive_sequence_lengths(recursive_seq_lens)
    if not t.has_valid_recursive_sequence_lengths():
        raise ValueError(
            f"recursive_seq_lens {recursive_seq_lens} inconsistent with "
            f"data shape {t.shape}")
    return t


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place=None,
                                low=0, high=1):
    """reference: fluid/lod_tensor.py create_random_int_lodtensor."""
    total = sum(recursive_seq_lens[-1])
    data = np.random.randint(low, high + 1,
                             [total] + list(base_shape)).astype(np.int64)
    return create_lod_tensor(data, recursive_seq_lens, place)
