"""Global flags registry.

TPU-native analogue of the reference's gflags surface
(/root/reference/paddle/fluid/platform/flags.cc:33-565, exposed to Python via
pybind/global_value_getter_setter.cc and paddle.set_flags/get_flags). Flags are
plain Python values seeded from FLAGS_* environment variables; a handful map
straight onto XLA/JAX configuration.
"""
from __future__ import annotations

import os
from typing import Any, Dict

_REGISTRY: Dict[str, Any] = {}
_PUBLIC: set = set()


def define_flag(name: str, default, help_str: str = "", public: bool = True):
    env = os.environ.get("FLAGS_" + name)
    value = default
    if env is not None:
        if isinstance(default, bool):
            value = env.lower() in ("1", "true", "yes")
        elif isinstance(default, int):
            value = int(env)
        elif isinstance(default, float):
            value = float(env)
        else:
            value = env
    _REGISTRY[name] = value
    if public:
        _PUBLIC.add(name)
    return value


def set_flags(flags: Dict[str, Any]):
    for k, v in flags.items():
        key = k[6:] if k.startswith("FLAGS_") else k
        if key not in _REGISTRY:
            raise ValueError(f"Unknown flag {k!r}")
        _REGISTRY[key] = v


def get_flags(flags):
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for k in flags:
        key = k[6:] if k.startswith("FLAGS_") else k
        if key not in _REGISTRY:
            raise ValueError(f"Unknown flag {k!r}")
        out[k] = _REGISTRY[key]
    return out


def flag(name: str):
    return _REGISTRY[name]


# -- core flags (subset of reference's 32+, mapped to TPU-relevant knobs) ----
define_flag("check_nan_inf", False,
            "Scan op outputs for NaN/Inf after every eager op "
            "(reference: operator.cc:1172 hook).")
define_flag("eager_delete_tensor_gb", 0.0,
            "GC knob; a no-op under XLA's buffer management, kept for parity.")
define_flag("allocator_strategy", "auto_growth",
            "Parity flag; allocation is delegated to PJRT.")
define_flag("use_system_allocator", False, "Parity flag.")
define_flag("fraction_of_gpu_memory_to_use", 0.92,
            "Maps onto XLA_PYTHON_CLIENT_MEM_FRACTION semantics.")
define_flag("cudnn_deterministic", False,
            "Maps onto XLA deterministic-ops preference.")
define_flag("paddle_num_threads", 1, "Host-side intra-op threads.")
define_flag("tpu_matmul_precision", "default",
            "jax matmul precision: default|high|highest.")
define_flag("benchmark", False, "Sync after each op for timing.")
define_flag("check_finite", False, "Alias surface for AMP debugging.")
define_flag("max_inplace_grad_add", 0, "Parity flag for grad accumulation.")
define_flag("retain_grad_for_all_tensor", False,
            "Keep .grad on non-leaf tensors during backward.")
define_flag("call_stack_level", 1, "Error stack verbosity (enforce.h parity).")
define_flag("sort_sum_gradient", False,
            "Deterministic gradient accumulation order "
            "(reference: imperative/flags gradient add order).")
define_flag("use_mkldnn", False, "Parity flag; XLA:CPU is the CPU backend.")
define_flag("conv_workspace_size_limit", 512, "Parity flag.")
define_flag("cudnn_exhaustive_search", False, "Parity flag (autotune).")
define_flag("sync_nccl_allreduce", True, "Parity flag; XLA orders collectives.")
define_flag("fuse_parameter_memory_size", -1, "Parity flag; XLA fuses.")
define_flag("init_allocated_mem", False, "Parity flag.")
define_flag("enable_parallel_graph", False, "Parity flag.")
