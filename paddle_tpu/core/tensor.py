"""Tensor: the user-facing eager tensor.

TPU-native analogue of the reference's VarBase/VariableWrapper + Tensor
(/root/reference/paddle/fluid/imperative/layer.h VarBase,
framework/tensor.h:89 Tensor with Allocation+DDim+dtype and inplace version
counter at tensor.h:77). Here a Tensor wraps a jax.Array (device memory is
owned by PJRT — the whole memory/allocation layer C11 of the reference
collapses into the XLA runtime) plus autograd metadata (producing TapeNode,
.grad, stop_gradient) mirroring VarBase.

Registered as a jax pytree so Tensors flow transparently through jax.jit /
pjit / shard_map — that is what makes the dygraph API compile into single
fused XLA programs instead of per-op dispatch (reference hot loop §3.2).
"""
from __future__ import annotations

import weakref
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import dtypes as _dt
from . import place as _place
from .autograd import backward as _backward
from .selected_rows import SelectedRows as _SelectedRows

_tensor_name_counter = [0]


def _auto_name(prefix="generated_tensor"):
    _tensor_name_counter[0] += 1
    return f"{prefix}_{_tensor_name_counter[0]}"


class Tensor:
    __slots__ = ("_value", "stop_gradient", "_grad", "_node", "_out_idx",
                 "name", "persistable", "_hooks", "_retain_grads",
                 "_inplace_version", "is_parameter", "__weakref__",
                 "trainable", "optimize_attr", "regularizer", "do_model_average",
                 "need_clip", "_partition_spec")

    def __init__(self, value, stop_gradient: bool = True, name: str = None,
                 persistable: bool = False):
        if isinstance(value, Tensor):
            value = value._value
        elif not isinstance(value, (jax.Array, jax.core.Tracer,
                                    _SelectedRows)):
            value = jnp.asarray(value)
        self._value = value
        self.stop_gradient = stop_gradient
        self._grad: Optional[Tensor] = None
        self._node = None          # producing TapeNode (None => leaf)
        self._out_idx = 0
        self.name = name or _auto_name()
        self.persistable = persistable
        self._hooks = []
        self._retain_grads = False
        self._inplace_version = 0
        self.is_parameter = False
        self._partition_spec = None

    # ------------------------------------------------------------------ meta
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def dtype(self):
        return jnp.dtype(self._value.dtype)

    @property
    def ndim(self):
        return self._value.ndim

    def dim(self):
        return self._value.ndim

    def rank(self):
        return self._value.ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self):
        if isinstance(self._value, jax.core.Tracer):
            return _place._default_place()
        try:
            dev = list(self._value.devices())[0]
            if dev.platform == "cpu":
                return _place.CPUPlace()
            return _place.TPUPlace(dev.id)
        except Exception:
            return _place._default_place()

    @property
    def is_leaf(self):
        return self._node is None

    @property
    def grad(self):
        return self._grad

    @grad.setter
    def grad(self, g):
        self._grad = g if (g is None or isinstance(g, Tensor)) else Tensor(g)

    # -------------------------------------------------------------- autograd
    def backward(self, grad_tensor=None, retain_graph: bool = False):
        _backward(self, grad_tensor, retain_graph)

    def _accumulate_grad(self, cot):
        if self._grad is None:
            self._grad = Tensor(cot, stop_gradient=True,
                                name=self.name + "@GRAD")
        else:
            self._grad._value = self._grad._value + cot

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def zero_(self):
        self._value = jnp.zeros_like(self._value)
        self._inplace_version += 1
        return self

    def register_hook(self, hook):
        self._hooks.append(hook)

        class _Handle:
            def remove(inner):
                try:
                    self._hooks.remove(hook)
                except ValueError:
                    pass
        return _Handle()

    def retain_grads(self):
        self._retain_grads = True

    def detach(self):
        t = Tensor(self._value, stop_gradient=True, name=self.name)
        return t

    def clone(self):
        from ..ops import assign
        return assign(self)

    # --------------------------------------------------------------- convert
    def numpy(self):
        return np.asarray(self._value)

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    # numpy must defer binary ops to Tensor's reflected dunders instead of
    # converting via __array__ (np.float64(2) * t would otherwise produce
    # an f64 ndarray, bypassing the framework's promotion rules)
    __array_priority__ = 100

    def __array__(self, dtype=None):
        a = np.asarray(self._value)
        return a.astype(dtype) if dtype is not None else a

    def astype(self, dtype):
        from ..ops import cast
        return cast(self, dtype)

    def cast(self, dtype):
        return self.astype(dtype)

    def cpu(self):
        return Tensor(jax.device_put(self._value, jax.devices("cpu")[0]),
                      stop_gradient=self.stop_gradient, name=self.name)

    def cuda(self, device_id=0, blocking=True):
        return Tensor(jax.device_put(self._value,
                                     _place.TPUPlace(device_id).get_device()),
                      stop_gradient=self.stop_gradient, name=self.name)

    def tpu(self, device_id=0):
        return self.cuda(device_id)

    def pin_memory(self):
        return self.cpu()

    def to(self, *args, **kwargs):
        out = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, str) and not a.startswith(("cpu", "tpu", "gpu")):
                a = _dt.convert_dtype(a)
            if isinstance(a, str):
                out = out.cpu() if a.startswith("cpu") else out.cuda()
            elif isinstance(a, _place.Place):
                out = out.cpu() if isinstance(a, _place.CPUPlace) else out.cuda(a.device_id)
            else:
                out = out.astype(a)
        return out

    def value(self):
        return self

    def get_tensor(self):
        return self

    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._value
        value = jnp.asarray(value, dtype=self._value.dtype)
        if tuple(value.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch: {value.shape} vs {self._value.shape}")
        self._value = value
        self._inplace_version += 1
        return self

    def _copy_to(self, place, blocking=True):
        return self.cpu() if isinstance(place, _place.CPUPlace) else self.cuda()

    # ----------------------------------------------------------------- repr
    def __repr__(self):
        if isinstance(self._value, jax.core.Tracer):
            return (f"Tensor(shape={self.shape}, dtype={_dt.dtype_name(self.dtype)}, "
                    f"traced=True)")
        return (f"Tensor(shape={self.shape}, dtype={_dt.dtype_name(self.dtype)}, "
                f"place={self.place}, stop_gradient={self.stop_gradient},\n"
                f"       {np.asarray(self._value)!r})")

    __str__ = __repr__

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self.shape[0]

    def __bool__(self):
        return bool(np.asarray(self._value))

    def __int__(self):
        return int(np.asarray(self._value))

    def __float__(self):
        return float(np.asarray(self._value))

    def __index__(self):
        return int(np.asarray(self._value))

    def __hash__(self):
        return id(self)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # Dunder arithmetic and the full method surface (matmul, sum, reshape,
    # …) are attached by paddle_tpu.ops._attach_tensor_methods at import
    # time — the analogue of the reference's generated core.ops fast-path +
    # monkey-patched VarBase methods
    # (python/paddle/fluid/dygraph/math_op_patch.py).


# --------------------------------------------------------------------- pytree
def _tensor_flatten(t: Tensor):
    return (t._value,), (t.stop_gradient, t.name)


def _tensor_unflatten(aux, children):
    t = Tensor(children[0], stop_gradient=aux[0], name=aux[1])
    return t


jax.tree_util.register_pytree_node(Tensor, _tensor_flatten, _tensor_unflatten)


def alias_for_inplace(t: Tensor) -> Tensor:
    """Snapshot a tensor's (value, producer) identity before an in-place
    rebind. In-place ops compute functionally and re-point the original
    Tensor at the new op's output; the op's recorded *input* must be this
    alias, not the rebound original, or the autograd graph would contain a
    self-cycle and drop gradients (the reference guards the analogous hazard
    with inplace version counters, tensor.h:57-77)."""
    a = Tensor(t._value, stop_gradient=t.stop_gradient, name=t.name)
    a._node, a._out_idx = t._node, t._out_idx
    return a


def check_inplace_allowed(t: Tensor):
    """Paddle parity: an in-place op on a *leaf* tensor that requires grad is
    an error (reference: imperative checks 'Leaf Var that doesn't stop
    gradient can't use inplace strategy') — otherwise the rebind would
    silently orphan its gradient."""
    from .autograd import _GradState
    if _GradState.enabled and t._node is None and not t.stop_gradient:
        raise RuntimeError(
            f"Leaf Tensor {t.name} that requires grad is being used in an "
            "in-place operation; this would silently detach it from "
            "autograd. Wrap the update in paddle.no_grad() or use the "
            "functional form.")


def rebind_inplace(t: Tensor, out: Tensor) -> Tensor:
    t._value, t._node, t._out_idx = out._value, out._node, out._out_idx
    t._inplace_version += 1
    return t


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor (reference: python/paddle/tensor/creation.py to_tensor)."""
    dtype = _dt.convert_dtype(dtype)
    if isinstance(data, Tensor):
        arr = data._value
        if dtype is not None and arr.dtype != dtype:
            arr = arr.astype(dtype)
        t = Tensor(arr, stop_gradient=stop_gradient)
        return t
    if dtype is None:
        a = np.asarray(data)
        # Paddle parity (python/paddle/tensor/creation.py to_tensor): an
        # explicit float64 ndarray keeps float64; Python floats/lists (which
        # numpy defaults to f64) take the framework default dtype.
        if a.dtype == np.float64 and not isinstance(data, np.ndarray):
            dtype = _dt.get_default_dtype()
        arr = jnp.asarray(a, dtype=dtype)
    else:
        arr = jnp.asarray(np.asarray(data), dtype=dtype)
    if place is not None and not isinstance(place, _place.CPUPlace):
        arr = jax.device_put(arr, place.get_device())
    elif place is not None:
        arr = jax.device_put(arr, jax.devices("cpu")[0])
    return Tensor(arr, stop_gradient=stop_gradient)
