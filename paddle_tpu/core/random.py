"""Global RNG state.

TPU-native analogue of the reference's Generator
(/root/reference/paddle/fluid/framework/generator.cc — per-place mt19937 with
global seed via paddle.seed). On TPU, randomness is counter-based: a root
jax.random key derived from the seed, with a monotonically increasing
fold_in counter per draw. This keeps the stateful paddle API (`paddle.seed`,
implicit global generator) while staying reproducible and trace-safe: inside a
jit trace the current counter value is burned into the compiled program, so a
captured step function draws fresh randomness per call only if it threads keys
explicitly (paddle_tpu.jit handles this for dropout via functional keys).
"""
from __future__ import annotations

import itertools

import jax


class _RNGState:
    seed = 0
    counter = 0
    # Lazily materialized: building a PRNGKey initializes the XLA backend,
    # which must not happen at import time (jax.distributed.initialize in
    # init_parallel_env must run before any backend use).
    _root_key = None

    @classmethod
    def get_root_key(cls):
        if cls._root_key is None:
            # The first use may be INSIDE a jit trace (e.g. a static
            # startup program's initializer ops); the cached key must be a
            # concrete array, not that trace's tracer.
            with jax.ensure_compile_time_eval():
                cls._root_key = jax.random.PRNGKey(cls.seed)
        return cls._root_key


def seed(s: int):
    _RNGState.seed = int(s)
    _RNGState._root_key = jax.random.PRNGKey(int(s))
    _RNGState.counter = 0
    return _RNGState


def get_rng_state():
    """Read-only snapshot (seed, draw counter) — does NOT advance the
    stream."""
    return (_RNGState.seed, _RNGState.counter)


def set_rng_state(state):
    seed(state[0])
    _RNGState.counter = int(state[1])


class _TraceKey:
    """Functional key threading for jitted steps: when a trace key is
    installed (paddle_tpu.jit), random draws fold into IT instead of the
    host counter's root key, so each compiled step invocation gets fresh
    randomness (dropout masks differ across steps) while each call *site*
    inside the trace stays distinct via the site counter."""
    key = None
    site_counter = 0


from contextlib import contextmanager  # noqa: E402


@contextmanager
def trace_key_scope(key):
    prev_key, prev_ctr = _TraceKey.key, _TraceKey.site_counter
    _TraceKey.key = key
    _TraceKey.site_counter = 0
    try:
        yield
    finally:
        _TraceKey.key, _TraceKey.site_counter = prev_key, prev_ctr


def next_key():
    """Fresh PRNG key for one random draw."""
    if _TraceKey.key is not None:
        _TraceKey.site_counter += 1
        return jax.random.fold_in(_TraceKey.key, _TraceKey.site_counter)
    _RNGState.counter += 1
    return jax.random.fold_in(_RNGState.get_root_key(), _RNGState.counter)


def default_seed() -> int:
    return _RNGState.seed
