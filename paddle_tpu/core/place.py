"""Device/place model.

TPU-native analogue of the reference's Place variant
(/root/reference/paddle/fluid/platform/place.h:26-130: CPUPlace, CUDAPlace,
XPUPlace, boost::variant Place) and DeviceContextPool
(platform/device_context.h:623). On TPU the whole L0 platform layer collapses
onto jax.Device / the PJRT client: a Place is a thin named handle resolving to
a jax.Device; streams/handles/contexts are owned by XLA.
"""
from __future__ import annotations

import functools

import jax


class Place:
    """Base place: identifies a device a Tensor lives on."""

    device_type = "unknown"

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    # -- jax bridge ---------------------------------------------------------
    def get_device(self):
        """Resolve to a jax.Device (falls back to default backend)."""
        devs = _devices_of(self.device_type)
        if not devs:
            devs = jax.devices()
        return devs[min(self.device_id, len(devs) - 1)]

    def __eq__(self, other):
        return (isinstance(other, Place)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"


class CPUPlace(Place):
    device_type = "cpu"

    def __init__(self):
        super().__init__(0)


class TPUPlace(Place):
    """A TPU chip (reference analogue: the XPUPlace+BKCL pairing,
    platform/place.h:62 — the in-repo model for a non-CUDA accelerator)."""
    device_type = "tpu"


# The reference exposes CUDAPlace ubiquitously; map it onto the accelerator
# backend so reference-style code (`paddle.CUDAPlace(0)`) runs unchanged.
class XLAPlace(TPUPlace):
    device_type = "tpu"


CUDAPlace = XLAPlace

# reference platform/place.h:62 XPUPlace (Kunlun accelerator): map onto
# THE accelerator backend here too — on this stack that is the TPU chip
XPUPlace = XLAPlace


class CUDAPinnedPlace(CPUPlace):
    """Pinned host memory is a PJRT implementation detail; alias of CPU."""


@functools.lru_cache(maxsize=None)
def _accelerator_platform():
    """Best accelerator platform name available in this process."""
    try:
        platform = jax.default_backend()
    except RuntimeError:
        return "cpu"
    return platform


@functools.lru_cache(maxsize=None)
def _devices_of(device_type: str):
    if device_type == "cpu":
        try:
            return tuple(jax.devices("cpu"))
        except RuntimeError:
            return tuple(jax.devices())
    # 'tpu' (or any accelerator request) → default backend devices
    return tuple(jax.devices())


_current_place = None


def set_device(device: str):
    """paddle.set_device — 'cpu', 'tpu', 'tpu:0', 'gpu:0' (gpu→accelerator)."""
    global _current_place
    name, _, idx = device.partition(":")
    idx = int(idx) if idx else 0
    if name == "cpu":
        _current_place = CPUPlace()
    elif name in ("tpu", "xla", "gpu", "cuda", "npu", "xpu"):
        _current_place = TPUPlace(idx)
    else:
        raise ValueError(f"Unknown device {device!r}")
    return _current_place


def get_device() -> str:
    p = _default_place()
    if isinstance(p, CPUPlace):
        return "cpu"
    return f"{p.device_type}:{p.device_id}"


def _default_place() -> Place:
    global _current_place
    if _current_place is None:
        _current_place = (
            TPUPlace(0) if _accelerator_platform() != "cpu" else CPUPlace())
    return _current_place


def is_compiled_with_cuda() -> bool:
    # For API parity; reports whether an accelerator backend is present.
    return False


def is_compiled_with_tpu() -> bool:
    return _accelerator_platform() not in ("cpu",)


def device_count() -> int:
    return len(jax.devices())
