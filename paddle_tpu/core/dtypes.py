"""Dtype system.

TPU-native analogue of the reference's VarType::Type dtype enum
(/root/reference/paddle/fluid/framework/framework.proto:106-141) and the
proto_type<->numpy mapping in python/paddle/fluid/data_feeder.py. Instead of a
protobuf enum dispatched through OpKernelType, dtypes here ARE jax/numpy
dtypes — XLA is the only "kernel library", so the enum collapses onto
jnp.dtype with paddle-style names preserved for API parity.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical dtype singletons (paddle exposes these as paddle.float32 etc.)
bool_ = jnp.dtype(jnp.bool_)
uint8 = jnp.dtype(jnp.uint8)
int8 = jnp.dtype(jnp.int8)
int16 = jnp.dtype(jnp.int16)
int32 = jnp.dtype(jnp.int32)
int64 = jnp.dtype(jnp.int64)
float16 = jnp.dtype(jnp.float16)
bfloat16 = jnp.dtype(jnp.bfloat16)
float32 = jnp.dtype(jnp.float32)
float64 = jnp.dtype(jnp.float64)
complex64 = jnp.dtype(jnp.complex64)
complex128 = jnp.dtype(jnp.complex128)

_NAME_TO_DTYPE = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "fp16": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float32": float32,
    "fp32": float32,
    "float64": float64,
    "fp64": float64,
    "complex64": complex64,
    "complex128": complex128,
}

_FLOATING = {float16, bfloat16, float32, float64}
_INTEGER = {uint8, int8, int16, int32, int64}
_COMPLEX = {complex64, complex128}


def convert_dtype(dtype):
    """Normalise any dtype spec (str / np dtype / jnp dtype / None) to jnp.dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        try:
            return _NAME_TO_DTYPE[dtype]
        except KeyError:
            raise ValueError(f"Unsupported dtype string: {dtype!r}")
    try:
        return jnp.dtype(dtype)
    except TypeError:
        raise ValueError(f"Cannot convert {dtype!r} to a dtype")


def dtype_name(dtype) -> str:
    d = convert_dtype(dtype)
    if d == bool_:
        return "bool"
    return d.name


def is_floating_point(dtype) -> bool:
    return convert_dtype(dtype) in _FLOATING


def is_integer(dtype) -> bool:
    return convert_dtype(dtype) in _INTEGER


def is_complex(dtype) -> bool:
    return convert_dtype(dtype) in _COMPLEX


# ---------------------------------------------------------------------------
# Default dtype state (reference: python/paddle/framework/framework.py
# set_default_dtype/get_default_dtype)
# ---------------------------------------------------------------------------
_default_dtype = float32


def set_default_dtype(d):
    global _default_dtype
    d = convert_dtype(d)
    if d not in _FLOATING:
        raise TypeError(
            "set_default_dtype only supports floating dtypes, got %s" % d)
    _default_dtype = d


def get_default_dtype():
    return _default_dtype


def promote_types(a, b):
    return jnp.promote_types(convert_dtype(a), convert_dtype(b))
