"""Typed errors + enforce helpers.

TPU-native analogue of the reference's enforce machinery
(/root/reference/paddle/fluid/platform/enforce.h:411-464 PADDLE_ENFORCE*/
PADDLE_THROW, errors.cc, error_codes.proto). The C++ macro + stack-capture
system collapses into Python exceptions with the same typed taxonomy.
"""
from __future__ import annotations


class EnforceNotMet(RuntimeError):
    """Base framework error (reference: platform::EnforceNotMet)."""


class InvalidArgumentError(EnforceNotMet, ValueError):
    pass


class NotFoundError(EnforceNotMet, KeyError):
    pass


class OutOfRangeError(EnforceNotMet, IndexError):
    pass


class AlreadyExistsError(EnforceNotMet):
    pass


class ResourceExhaustedError(EnforceNotMet, MemoryError):
    pass


class PreconditionNotMetError(EnforceNotMet):
    pass


class PermissionDeniedError(EnforceNotMet):
    pass


class ExecutionTimeoutError(EnforceNotMet, TimeoutError):
    pass


class UnimplementedError(EnforceNotMet, NotImplementedError):
    pass


class UnavailableError(EnforceNotMet):
    pass


class FatalError(EnforceNotMet):
    pass


class ExternalError(EnforceNotMet):
    pass


def enforce(cond, message: str, exc=InvalidArgumentError):
    """PADDLE_ENFORCE analogue."""
    if not cond:
        raise exc(message)


def enforce_eq(a, b, message: str = "", exc=InvalidArgumentError):
    if a != b:
        raise exc(f"Expected {a!r} == {b!r}. {message}")


def enforce_gt(a, b, message: str = "", exc=InvalidArgumentError):
    if not a > b:
        raise exc(f"Expected {a!r} > {b!r}. {message}")


def enforce_not_none(v, message: str = "", exc=NotFoundError):
    if v is None:
        raise exc(message or "Expected a non-None value")
    return v
