"""paddle.dataset.conll05 (reference dataset/conll05.py) over
paddle.text.datasets.Conll05st."""
from __future__ import annotations

__all__ = ["test", "get_dict"]


def get_dict():
    from ..text.datasets import Conll05st
    ds = Conll05st()
    return ds.word_dict, ds.predicate_dict, ds.label_dict


def test():
    def rd():
        from ..text.datasets import Conll05st
        ds = Conll05st()
        for i in range(len(ds)):
            yield tuple(ds[i])
    return rd
