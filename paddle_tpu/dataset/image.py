"""paddle.dataset.image (reference dataset/image.py: numpy image
transforms used by the fluid-era pipelines)."""
from __future__ import annotations

import numpy as np

__all__ = ["resize_short", "center_crop", "random_crop", "left_right_flip",
           "to_chw", "simple_transform"]


def _resize(im, h, w):
    # nearest-neighbour resize in pure numpy (no cv2/PIL dependency)
    src_h, src_w = im.shape[:2]
    ri = (np.arange(h) * src_h / h).astype(np.int64)
    ci = (np.arange(w) * src_w / w).astype(np.int64)
    return im[ri][:, ci]


def resize_short(im, size):
    h, w = im.shape[:2]
    if h < w:
        return _resize(im, size, int(w * size / h))
    return _resize(im, int(h * size / w), size)


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    hs, ws = (h - size) // 2, (w - size) // 2
    return im[hs:hs + size, ws:ws + size]


def random_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    hs = np.random.randint(0, max(h - size, 0) + 1)
    ws = np.random.randint(0, max(w - size, 0) + 1)
    return im[hs:hs + size, ws:ws + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1]


def to_chw(im, order=(2, 0, 1)):
    return im.transpose(order)


def simple_transform(im, resize_size, crop_size, is_train,
                     is_color=True, mean=None):
    im = resize_short(im, resize_size)
    im = random_crop(im, crop_size) if is_train else \
        center_crop(im, crop_size)
    if is_train and np.random.randint(2):
        im = left_right_flip(im)
    if im.ndim == 3:
        im = to_chw(im)
    im = im.astype(np.float32)
    if mean is not None:
        im -= np.asarray(mean, np.float32).reshape(-1, 1, 1)
    return im
