"""paddle.dataset.movielens (reference dataset/movielens.py) over
paddle.text.datasets.Movielens."""
from __future__ import annotations

__all__ = ["train", "test"]


def _reader(mode):
    def rd():
        from ..text.datasets import Movielens
        ds = Movielens(mode=mode)
        for i in range(len(ds)):
            yield tuple(ds[i])
    return rd


def train():
    return _reader("train")


def test():
    return _reader("test")
