"""paddle.dataset.mnist (reference dataset/mnist.py: train()/test()
yield (image[784] float32, label int) samples) over
paddle.vision.datasets.MNIST."""
from __future__ import annotations

import numpy as np

__all__ = ["train", "test"]


def _reader(mode):
    def rd():
        from ..vision.datasets import MNIST
        ds = MNIST(mode=mode)
        for i in range(len(ds)):
            img, lab = ds[i]
            yield np.asarray(img, np.float32).reshape(-1), int(lab)
    return rd


def train():
    return _reader("train")


def test():
    return _reader("test")
