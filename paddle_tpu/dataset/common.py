"""paddle.dataset.common (reference dataset/common.py: DATA_HOME,
md5file, download, cluster_files split helpers)."""
from __future__ import annotations

import hashlib
import os

DATA_HOME = os.path.expanduser("~/.cache/paddle/dataset")

__all__ = ["DATA_HOME", "md5file", "download", "split",
           "cluster_files_reader"]


def md5file(fname):
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    """reference common.py download — fetch into DATA_HOME. This
    environment has no network egress; a pre-placed file at the target
    path is used as-is, otherwise the error says what to place where."""
    dirname = os.path.join(DATA_HOME, module_name)
    os.makedirs(dirname, exist_ok=True)
    filename = os.path.join(
        dirname, save_name or url.split("/")[-1])
    if os.path.exists(filename) and (
            not md5sum or md5file(filename) == md5sum):
        return filename
    raise RuntimeError(
        f"no network egress in this environment: place the file from "
        f"{url} at {filename} (md5 {md5sum}) to use this dataset")


def split(reader, line_count, suffix="%05d.pickle", dumper=None):
    import pickle
    dumper = dumper or pickle.dump
    lines, index = [], 0
    out = []
    for e in reader():
        lines.append(e)
        if len(lines) >= line_count:
            fn = suffix % index
            with open(fn, "wb") as f:
                dumper(lines, f)
            out.append(fn)
            lines, index = [], index + 1
    if lines:
        fn = suffix % index
        with open(fn, "wb") as f:
            dumper(lines, f)
        out.append(fn)
    return out


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=None):
    import glob
    import pickle
    loader = loader or pickle.load

    def reader():
        flist = sorted(glob.glob(files_pattern))
        for i, fn in enumerate(flist):
            if i % trainer_count == trainer_id:
                with open(fn, "rb") as f:
                    yield from loader(f)
    return reader
