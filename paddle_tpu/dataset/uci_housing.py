"""paddle.dataset.uci_housing (reference dataset/uci_housing.py:
train()/test() yielding (features[13], price))."""
from __future__ import annotations

import numpy as np

__all__ = ["train", "test", "feature_names"]

feature_names = ["CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE",
                 "DIS", "RAD", "TAX", "PTRATIO", "B", "LSTAT"]


def _reader(mode):
    def rd():
        from ..text.datasets import UCIHousing
        ds = UCIHousing(mode=mode)
        for i in range(len(ds)):
            x, y = ds[i]
            yield np.asarray(x, np.float32), np.asarray(y, np.float32)
    return rd


def train():
    return _reader("train")


def test():
    return _reader("test")
