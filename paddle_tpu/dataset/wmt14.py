"""paddle.dataset.wmt14 (reference dataset/wmt14.py) over
paddle.text.datasets.WMT14."""
from __future__ import annotations

__all__ = ["train", "test"]


def _reader(mode, dict_size):
    def rd():
        from ..text.datasets import WMT14
        ds = WMT14(mode=mode, dict_size=dict_size)
        for i in range(len(ds)):
            yield tuple(ds[i])
    return rd


def train(dict_size):
    return _reader("train", dict_size)


def test(dict_size):
    return _reader("test", dict_size)
