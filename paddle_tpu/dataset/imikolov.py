"""paddle.dataset.imikolov (reference dataset/imikolov.py:
build_dict(), train(word_idx, n)/test(word_idx, n) yielding n-gram
tuples)."""
from __future__ import annotations

__all__ = ["train", "test", "build_dict"]


def build_dict(min_word_freq=50):
    from ..text.datasets import Imikolov
    return Imikolov(mode="train", data_type="NGRAM", window_size=2) \
        .word_idx


def _reader(mode, word_idx, n):
    def rd():
        from ..text.datasets import Imikolov
        ds = Imikolov(mode=mode, data_type="NGRAM", window_size=n)
        for i in range(len(ds)):
            yield tuple(int(v) for v in ds[i])
    return rd


def train(word_idx, n, data_type="NGRAM"):
    return _reader("train", word_idx, n)


def test(word_idx, n, data_type="NGRAM"):
    return _reader("test", word_idx, n)
