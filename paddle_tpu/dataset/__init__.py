"""paddle.dataset — the fluid-era reader-style dataset zoo.

Reference: /root/reference/python/paddle/dataset/ (mnist.py, cifar.py,
imdb.py, imikolov.py, uci_housing.py, movielens.py, conll05.py,
flowers.py, voc2012.py, wmt14.py, wmt16.py, common.py, image.py) — each
module exposes `train()`/`test()` sample GENERATORS. Here every module
adapts the 2.0 Dataset classes (paddle.vision.datasets /
paddle.text.datasets) back into that generator protocol, so fluid-era
`paddle.batch(paddle.dataset.mnist.train(), 32)` pipelines run
unchanged.
"""
from . import common  # noqa: F401
from . import mnist  # noqa: F401
from . import cifar  # noqa: F401
from . import imdb  # noqa: F401
from . import imikolov  # noqa: F401
from . import uci_housing  # noqa: F401
from . import movielens  # noqa: F401
from . import conll05  # noqa: F401
from . import flowers  # noqa: F401
from . import voc2012  # noqa: F401
from . import wmt14  # noqa: F401
from . import wmt16  # noqa: F401
from . import image  # noqa: F401

__all__ = ["common", "mnist", "cifar", "imdb", "imikolov",
           "uci_housing", "movielens", "conll05", "flowers", "voc2012",
           "wmt14", "wmt16", "image"]
