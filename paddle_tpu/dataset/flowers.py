"""paddle.dataset.flowers (reference dataset/flowers.py) over
paddle.vision.datasets.Flowers."""
from __future__ import annotations

import numpy as np

__all__ = ["train", "test", "valid"]


def _reader(mode):
    def rd():
        from ..vision.datasets import Flowers
        ds = Flowers(mode=mode)
        for i in range(len(ds)):
            img, lab = ds[i]
            yield np.asarray(img, np.float32), int(lab)
    return rd


def train():
    return _reader("train")


def test():
    return _reader("test")


def valid():
    return _reader("valid")
