"""paddle.dataset.wmt16 (reference dataset/wmt16.py) over
paddle.text.datasets.WMT16."""
from __future__ import annotations

__all__ = ["train", "test"]


def _reader(mode, src_dict_size, trg_dict_size):
    def rd():
        from ..text.datasets import WMT16
        ds = WMT16(mode=mode, src_dict_size=src_dict_size,
                   trg_dict_size=trg_dict_size)
        for i in range(len(ds)):
            yield tuple(ds[i])
    return rd


def train(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader("train", src_dict_size, trg_dict_size)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader("test", src_dict_size, trg_dict_size)
