"""paddle.dataset.cifar (reference dataset/cifar.py: train10/test10/
train100/test100 yielding (image[3072], label))."""
from __future__ import annotations

import numpy as np

__all__ = ["train10", "test10", "train100", "test100"]


def _reader(cls_name, mode):
    def rd():
        from ..vision import datasets as D
        ds = getattr(D, cls_name)(mode=mode)
        for i in range(len(ds)):
            img, lab = ds[i]
            yield np.asarray(img, np.float32).reshape(-1), int(lab)
    return rd


def train10():
    return _reader("Cifar10", "train")


def test10():
    return _reader("Cifar10", "test")


def train100():
    return _reader("Cifar100", "train")


def test100():
    return _reader("Cifar100", "test")
