"""paddle.dataset.voc2012 (reference dataset/voc2012.py) over
paddle.vision.datasets.VOC2012."""
from __future__ import annotations

__all__ = ["train", "test", "val"]


def _reader(mode):
    def rd():
        from ..vision.datasets import VOC2012
        ds = VOC2012(mode=mode)
        for i in range(len(ds)):
            yield tuple(ds[i])
    return rd


def train():
    return _reader("train")


def test():
    return _reader("test")


def val():
    return _reader("valid")
