"""paddle.dataset.imdb (reference dataset/imdb.py: word_dict(),
train(word_idx)/test(word_idx) yielding (token_ids, 0/1 label))."""
from __future__ import annotations

__all__ = ["train", "test", "word_dict"]


def word_dict(cutoff=150):
    from ..text.datasets import Imdb
    return Imdb(mode="train", cutoff=cutoff).word_idx


def _reader(mode, word_idx):
    def rd():
        from ..text.datasets import Imdb
        ds = Imdb(mode=mode)
        for i in range(len(ds)):
            doc, lab = ds[i]
            yield list(map(int, doc)), int(lab)
    return rd


def train(word_idx):
    return _reader("train", word_idx)


def test(word_idx):
    return _reader("test", word_idx)
