"""paddle.save / paddle.load.

TPU-native analogue of /root/reference/python/paddle/framework/io.py:201
(pickle-based state_dict save with Tensors converted to plain ndarrays —
_build_saved_state_dict / _unpack_saved_dict) and
fluid/dygraph/checkpoint.py. Tensors are pickled as bare numpy arrays in
the same nested-dict structure, so checkpoints are interchangeable with
reference-format state_dict pickles; load() rebuilds Tensors from ndarray
leaves unless return_numpy=True.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from .core.tensor import Tensor


def _to_serializable(obj):
    if isinstance(obj, Tensor):
        return obj.numpy()
    if isinstance(obj, dict):
        return {k: _to_serializable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_serializable(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def _from_serializable(obj, return_numpy=False):
    if isinstance(obj, np.ndarray):
        return obj if return_numpy else Tensor(obj, stop_gradient=True)
    if isinstance(obj, dict):
        if obj.get("__tensor__"):  # legacy pre-r2 checkpoint format
            if return_numpy:
                return obj["value"]
            return Tensor(obj["value"],
                          stop_gradient=obj.get("stop_gradient", True),
                          name=obj.get("name"))
        return {k: _from_serializable(v, return_numpy)
                for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_from_serializable(v, return_numpy) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_serializable(obj), f, protocol=protocol)


def load(path, **configs):
    return_numpy = configs.get("return_numpy", False)
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_serializable(obj, return_numpy)
