"""paddle.save / paddle.load.

TPU-native analogue of /root/reference/python/paddle/framework/io.py:201
(pickle-based state_dict save with Tensors converted to ndarray) and
fluid/dygraph/checkpoint.py. Uses numpy .npz-free pickle for exact parity
with the reference's nested-dict format.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from .core.tensor import Tensor


def _to_serializable(obj):
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "value": obj.numpy(), "name": obj.name,
                "stop_gradient": obj.stop_gradient}
    if isinstance(obj, dict):
        return {k: _to_serializable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_serializable(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def _from_serializable(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            if return_numpy:
                return obj["value"]
            t = Tensor(obj["value"], stop_gradient=obj.get(
                "stop_gradient", True), name=obj.get("name"))
            return t
        return {k: _from_serializable(v, return_numpy)
                for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_from_serializable(v, return_numpy) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_serializable(obj), f, protocol=protocol)


def load(path, **configs):
    return_numpy = configs.get("return_numpy", False)
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_serializable(obj, return_numpy)
