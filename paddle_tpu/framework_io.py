def save(obj, path, **k):
    raise NotImplementedError("paddle.save placeholder")
def load(path, **k):
    raise NotImplementedError("paddle.load placeholder")
