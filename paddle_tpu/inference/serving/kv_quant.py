"""int8 KV-block codec: per-block-per-head symmetric quantization.

Roadmap item 2 (TP serving with int8 KV-cache blocks, per PAPERS.md
"EQuARX: Efficient Quantized AllReduce in XLA") needs the KV pools and
the host spill tier to hold int8 codes instead of f32 — but only with
a *committed* error bound. This module is the codec the paged cache
consumes (`PagedKVCache(kv_cache_dtype="int8")` pool mode and the
quantized host-tier spill path), and the first real consumer of the
jaxnum numerics analyzer (analysis/jaxnum.py): `kv_block_roundtrip`
is registered as the `serving.kv_block_codec` program, jaxnum derives
its worst-case dequantization error from the quantize→dequantize
provenance in the jaxpr, and the derived bound is pinned in
numplan.json against the declared budget below.

Scheme (symmetric, zero-point-free — KV activations are zero-centered
and a zero-point would break the "fresh block is all-zero" parity
contract, since 0.0 must encode exactly):

    scale[b, h] = absmax over block b, head h / 127
    q           = clip(round(x / scale), -127, 127)  int8
    x_hat       = q * scale

Worst-case relative error (fullscale of the (block, head) tile):
|x - x_hat| <= 0.5 * scale = 0.5/127 * absmax — `KV_INT8_REL_ERR`,
the budget jaxnum checks the derived bound against.

Requantization stability: the pool-mode setter re-encodes the WHOLE
pool every decode chunk, so unchanged blocks must round-trip
bit-identically. `requantize_blocks` keeps scales MONOTONE
(s' = max(s_old, absmax/127)): an unchanged block's dequantized
values are q*s with |q| <= 127, so absmax/127 <= s_old, the scale
stays put, and round(q*s/s) recovers q exactly. Only blocks whose
content actually grew in magnitude re-encode at a larger scale.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["KV_INT8_LEVELS", "KV_INT8_REL_ERR", "quantize_blocks",
           "requantize_blocks", "dequantize_blocks",
           "kv_block_roundtrip"]

#: symmetric int8 code range: [-127, 127] (-128 unused so the range is
#: sign-symmetric and |q| * scale never exceeds absmax)
KV_INT8_LEVELS = 127

#: declared worst-case dequant error, relative to the (block, head)
#: tile's fullscale (its absmax at quantization time). jaxnum derives
#: the same 0.5/levels bound from the codec's jaxpr and numplan.json
#: pins the two against each other.
KV_INT8_REL_ERR = 0.5 / KV_INT8_LEVELS


def _safe(scale):
    # all-zero tiles have scale 0; dividing by 1 instead keeps q = 0
    # exactly (jaxnum cannot see this guard relationally — the codec's
    # finite:div suppression in numplan.json records why it is safe)
    return jnp.where(scale > 0, scale, 1.0)


def _encode(x, scale):
    s = _safe(scale)[:, None, :, None]
    q = jnp.clip(jnp.round(x / s), -KV_INT8_LEVELS, KV_INT8_LEVELS)
    return q.astype(jnp.int8)  # ptlint: disable=PT-N001  THE sanctioned KV codec: bound derived by jaxnum, pinned in numplan.json


def _quantize_blocks(x):
    """Fresh per-(block, head) symmetric encode of `x`
    [n, block_size, H, D] -> (q int8, scale f32 [n, H])."""
    absmax = jnp.max(jnp.abs(x), axis=(1, 3))
    scale = absmax / KV_INT8_LEVELS
    return _encode(x, scale), scale


def _requantize_blocks(x, prev_scale):
    """Monotone-scale encode for the pool-mode setter: scales never
    shrink, so a block whose dequantized content is unchanged
    round-trips bit-identically (see module docstring)."""
    absmax = jnp.max(jnp.abs(x), axis=(1, 3))
    scale = jnp.maximum(prev_scale, absmax / KV_INT8_LEVELS)
    return _encode(x, scale), scale


def _dequantize_blocks(q, scale):
    """Decode (q int8 [n, bs, H, D], scale f32 [n, H]) -> f32."""
    return q.astype(scale.dtype) * scale[:, None, :, None]


quantize_blocks = jax.jit(_quantize_blocks)
requantize_blocks = jax.jit(_requantize_blocks)
dequantize_blocks = jax.jit(_dequantize_blocks)


def kv_block_roundtrip(x):
    """quantize→dequantize composition — the `serving.kv_block_codec`
    jaxnum registry program. Un-jitted on purpose: jaxnum traces it
    directly and derives the dequant error bound from the round/clip/
    convert provenance, pinning it against KV_INT8_REL_ERR."""
    q, scale = _quantize_blocks(x)
    return _dequantize_blocks(q, scale)
