"""paddle_tpu.inference.serving — continuous-batching LLM serving.

The TPU-native serving subsystem (reference capability:
paddle/fluid/inference/, the ~38k-LoC deployment layer; design shape:
vLLM continuous batching + the TPU Ragged Paged Attention kernel,
PAPERS.md arxiv 2604.15464). Four cooperating modules:

- paged_cache:  PagedKVCache — block-pooled KV storage, block tables,
                alloc/free with CacheExhausted reporting, counters;
                refcounted block sharing when prefix caching is on.
- prefix_cache: PrefixCacheIndex — radix-trie prefix index (token ids
                -> cached blocks) behind copy-on-write block sharing
                (docs/serving.md "Prefix caching"); trie nodes carry a
                device|host tier tag for hierarchical tiering.
- host_tier:    HostTierStore — host-RAM KV tier behind the prefix
                trie: evicted-but-reusable prefix blocks spill here
                (sha256-verified) instead of being freed, and promote
                back on the next match (docs/serving.md "Hierarchical
                KV-cache tiering").
- attention:    ragged paged-attention decode step (pure-JAX reference,
                bitwise-pinned to models.generation.decode_step).
- scheduler:    FCFS continuous batching — admission, prefill/decode
                interleaving, preemption + requeue under pool pressure.
- engine:       LLMEngine (add_request/step/streamed outputs, profiler
                spans, throughput/latency stats) + ServingPredictor
                (the inference.create_predictor dispatch target).
- replica:      EngineReplica — one supervised engine slot (heartbeat,
                quarantine, capped-backoff restart + warmup probe),
                carrying its tier role (prefill | decode | mixed).
- migration:    BlockMigration — live KV-block migration between
                replicas (export/import of paged blocks, transactional
                commit, bitwise-invariant resume); the primitive behind
                disaggregated tiers, rebalance() and
                drain(recompute=False).
- router:       ReplicaSet — N replicas behind one front-end with
                free-block load balancing, replica-level failover
                (zero-lost-request requeue to survivors), draining,
                prefill/decode tiering with live handoff, and
                router-level backpressure.
- tenancy:      TenantRegistry / TenantConfig — tenants as first-class
                objects: priority class + weight (WFQ fair share),
                sliding-window token quotas (TenantQuotaExceeded),
                TTFT/deadline SLOs, weighted prefix-cache shares
                (docs/serving.md "Multi-tenant scheduling and
                autoscaling").
- autoscaler:   Autoscaler / AutoscalerPolicy — telemetry-driven
                role-aware fleet sizing: shrink via evacuating drain,
                grow via warmup-probe rejoin, prefill:decode balance
                from the measured phase split.
- deploy:       ModelRegistry / DeployController — multi-model replica
                pools over sha256-manifest checkpoint revisions, and
                chaos-gated zero-downtime rolling weight deploys
                (evacuating drain → swap → canary parity gate →
                probe rejoin, with instant warm rollback and
                revision-keyed KV so stale cache never serves new
                weights; docs/serving.md "Multi-model serving and
                rolling deploys").

See docs/serving.md for architecture and tuning.
"""
from .paged_cache import CacheExhausted, PagedKVCache  # noqa: F401
from .prefix_cache import PrefixCacheIndex, PrefixNode  # noqa: F401
from .host_tier import HostTierStore  # noqa: F401
from .attention import (gather_block_kv, paged_decode_step,  # noqa: F401
                        fused_decode_chunk)
from .scheduler import (EngineOverloaded, Request,  # noqa: F401
                        RequestState, SamplingParams, ScheduledBatch,
                        Scheduler, SchedulerConfig)
from .engine import (EngineConfig, EngineStats, LLMEngine,  # noqa: F401
                     RequestOutput, ServingPredictor)
from .replica import (EngineReplica, ReplicaCrashed,  # noqa: F401
                      ReplicaState)
from .migration import (BlockMigration,  # noqa: F401
                        MIGRATION_REASONS)
from .router import ReplicaSet, RouterConfig, RouterRequest  # noqa: F401
from .tenancy import (TenantConfig, TenantQuotaExceeded,  # noqa: F401
                      TenantRegistry)
from .autoscaler import (Autoscaler, AutoscalerConfig,  # noqa: F401
                         AutoscalerPolicy)
from .deploy import (DeployConfig, DeployController,  # noqa: F401
                     ModelRegistry, Revision)

__all__ = [
    "PagedKVCache", "CacheExhausted", "EngineOverloaded",
    "PrefixCacheIndex", "PrefixNode", "HostTierStore",
    "gather_block_kv",
    "paged_decode_step", "fused_decode_chunk",
    "SamplingParams", "Request", "RequestState",
    "Scheduler", "SchedulerConfig", "ScheduledBatch", "EngineConfig",
    "EngineStats", "LLMEngine", "RequestOutput", "ServingPredictor",
    "EngineReplica", "ReplicaCrashed", "ReplicaState",
    "BlockMigration", "MIGRATION_REASONS",
    "ReplicaSet", "RouterConfig", "RouterRequest",
    "TenantConfig", "TenantRegistry", "TenantQuotaExceeded",
    "Autoscaler", "AutoscalerConfig", "AutoscalerPolicy",
    "DeployConfig", "DeployController", "ModelRegistry", "Revision",
]
