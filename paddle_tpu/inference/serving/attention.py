"""Ragged paged-attention decode step (pure-JAX reference).

One decode step for N sequences at DIFFERENT positions against the
block-pooled KV cache (serving/paged_cache.py): per layer, the new
token's K/V is scattered into each sequence's reserved (block, offset)
slot, the sequence's context is gathered back through its block table,
and attention is masked per-sequence by length. This is the reference
semantics of the TPU Ragged Paged Attention kernel (PAPERS.md, arxiv
2604.15464) — block-table gather + ragged length masking — kept in
plain jnp so XLA owns the schedule; a pallas kernel can swap in under
the same signature later.

Parity contract: the math is NOT re-implemented — embedding, per-layer
qkv, the attention block and the LM head are the SAME top-level jitted
sub-programs generation.decode_step is composed of (_token_embed,
_decode_qkv, _decode_attn, _decode_head). When max_blocks_per_seq *
block_size == max_seq_len the gathered context has the exact dense
cache layout (position p = block p//bs, slot p%bs) and the same shape,
so XLA reuses the identical compiled executables for both paths; since
out-of-length positions are masked to -1e30 before softmax (erasing
pool garbage exactly: masked probs are exact zeros), the logits are
bitwise-identical to generation.decode_step (tests/test_serving.py
pins this). Padded bucket rows write out of bounds (dropped) and
attend only to block-table padding that their mask erases; their
logits are garbage and the engine ignores them.

Batch shape: everything here is shape-polymorphic only in
(N, max_blocks_per_seq, num_blocks). Under the default ragged kernel
the engine pads N to the FIXED max_num_seqs — dead rows cost zero
kernel work (per-row lengths gate every block), so ONE compilation
covers every batch mix and there is no bucket axis at all. The
`kernel="bucketed"` fallback keeps the old power-of-two bucketing
(one compile per bucket) as the parity oracle.

Chunked prefill: prompt tokens ride the same fused scan as decode —
each scan trip feeds a prefilling row one prompt token (KV write, no
sample), and the trip that consumes the last prompt token samples the
request's first output in-scan. Long prompts therefore never
monopolise a step: they are split into k-token chunks admitted
alongside decode slots (scheduler.prefill_chunk_threshold).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ...core.anomaly import rows_not_finite
from ...models.generation import (_attn_merge, _decode_attn, _decode_head,
                                  _decode_qkv, _token_embed)
from ...ops.pallas import ragged_paged_attention as _ragged

__all__ = ["gather_block_kv", "paged_decode_step", "fused_decode_chunk",
           "PACK_COLS", "pack_f32"]


def gather_block_kv(pool, block_tables):
    """[num_blocks, bs, H, D] pool + [N, MB] tables -> [N, H, MB*bs, D]
    contiguous per-sequence context, positions in block-table order."""
    n, mb = block_tables.shape
    bs, h, d = pool.shape[1], pool.shape[2], pool.shape[3]
    ctx = pool[block_tables]                     # [N, MB, bs, H, D]
    return ctx.reshape(n, mb * bs, h, d).transpose(0, 2, 1, 3)


@jax.jit
def _pool_write_gather(kp, vp, k_new, v_new, slot_blocks, slot_offsets,
                       block_tables):
    """Scatter the new token's K/V [N, H, 1, D] into each sequence's
    (block, offset) slot — out-of-range slot_blocks (padded rows) are
    dropped — then gather each sequence's context back through its
    block table."""
    kp = kp.at[slot_blocks, slot_offsets].set(k_new[:, :, 0], mode="drop")
    vp = vp.at[slot_blocks, slot_offsets].set(v_new[:, :, 0], mode="drop")
    return (kp, vp,
            gather_block_kv(kp, block_tables),
            gather_block_kv(vp, block_tables))


def paged_decode_step(params, pools, tokens, positions, block_tables,
                      slot_blocks, slot_offsets, geom):
    """One ragged decode step over the block pool.

    params: the models.generation.extract_params dict.
    pools: L-tuple of (k_pool, v_pool) [num_blocks, bs, H, D].
    tokens [N] int32 — last sampled token per sequence.
    positions [N] int32 — cached length per sequence (the new token's
        position); padded rows use 0.
    block_tables [N, MB] int32 — block ids padded with 0.
    slot_blocks/slot_offsets [N] int32 — write slot for the new token's
        K/V; padded rows point slot_blocks out of bounds (num_blocks) so
        the scatter drops them.
    geom: static (num_layers, num_heads, head_dim, max_seq_len), the
        models.generation geometry tuple.

    Returns (logits [N, V], updated pools). Composed of the shared
    jitted sub-programs of generation.decode_step plus the pool
    scatter/gather above — see the parity contract in the module
    docstring.
    """
    tokens = jnp.asarray(tokens, jnp.int32)
    positions = jnp.asarray(positions, jnp.int32)
    x = _token_embed(params, tokens, positions)   # [N, 1, C]
    new_pools = []
    for i, (kp, vp) in enumerate(pools):
        qkv = _decode_qkv(params, i, x, geom)     # [3, N, H, 1, D]
        kp, vp, kc, vc = _pool_write_gather(
            kp, vp, qkv[1], qkv[2], slot_blocks, slot_offsets,
            block_tables)
        new_pools.append((kp, vp))
        x = _decode_attn(params, i, x, qkv[0], kc, vc, positions, geom)
    return _decode_head(params, x), tuple(new_pools)


# ----------------------------------- fused k-token decode + prefill chunks
# Packed per-sequence control state, one int32 [N, PACK_COLS + k + MB]
# upload per chunk (column layout below; float fields travel as raw f32
# bits so the whole transfer stays a single dtype-homogeneous array):
#   0 tok        last sampled token (the next step's input)
#   1 pos        next KV write position (== cached length)
#   2 active     1 for live rows, 0 for padding
#   3 out_cnt    tokens generated so far (threads the PRNG fold_in)
#   4 max_out    SamplingParams.max_tokens
#   5 eos        eos_token_id, -1 when unset
#   6 temp       temperature as float32 bits
#   7 top_k      0 = disabled
#   8 top_p      top_p as float32 bits (>=1.0 = disabled)
#   9 seed       per-request PRNG seed (masked to 31 bits)
#   10 pf_feed   prompt tokens to consume this chunk (0 = pure decode row)
#   11 pf_more   1 if prompt remains after this chunk (pf_more=1 implies
#                pf_feed == k: the engine never leaves a mid-chunk gap
#                between the last fed prompt token and the first sample)
#   12..12+k-1   the pf_feed prompt tokens for this chunk (0-padded)
#   12+k..       the block table row [MB]
PACK_COLS = 12


def pack_f32(x) -> int:
    """Host-side helper: float -> raw float32 bits as a python int, for
    the packed control columns above."""
    import numpy as np
    return int(np.float32(x).view(np.int32))


def _sample_rows(logits, keys, temps, top_ks, top_ps):
    """Branchless per-row sampling over [N, V] logits — the device twin
    of LLMEngine._sample / generation._sampling_rollout: greedy when
    temp<=0, else temperature softmax restricted by top-k (kth-largest
    threshold, ties kept) and nucleus top-p (smallest prefix of the
    descending distribution with cumulative mass >= top_p; the kept set
    is computed with an EXCLUSIVE cumsum so the crossing token stays).
    All rows run every path; jnp.where selects, so the program is a
    fixed dataflow suitable as a lax.scan body."""
    vocab = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    lg = logits.astype(jnp.float32) / jnp.where(temps > 0, temps, 1.0)[:, None]
    # top-k: threshold at the k-th largest value (ties kept, like the
    # host sampler's kth = sort(lg)[-top_k]).
    srt = jnp.sort(lg, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(
        srt, jnp.clip(top_ks - 1, 0, vocab - 1)[:, None], axis=1)
    lg = jnp.where((top_ks[:, None] > 0) & (lg < kth), -1e30, lg)
    # top-p: exclusive cumulative mass < top_p keeps the crossing token.
    srt = jnp.sort(lg, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(srt, axis=-1)
    excl = jnp.cumsum(probs, axis=-1) - probs
    n_keep = jnp.sum(excl < top_ps[:, None], axis=-1)
    pth = jnp.take_along_axis(
        srt, jnp.clip(n_keep - 1, 0, vocab - 1)[:, None], axis=1)
    use_p = (top_ps > 0.0) & (top_ps < 1.0)
    lg = jnp.where(use_p[:, None] & (lg < pth), -1e30, lg)
    sampled = jax.vmap(jax.random.categorical)(keys, lg).astype(jnp.int32)
    return jnp.where(temps <= 0.0, greedy, sampled)


@jax.jit
def _pool_write(kp, vp, k_new, v_new, slot_blocks, slot_offsets):
    """Scatter-only variant of _pool_write_gather for the ragged kernel
    path: the kernel reads the pools through the block table itself, so
    no gathered context is materialised."""
    kp = kp.at[slot_blocks, slot_offsets].set(k_new[:, :, 0], mode="drop")
    vp = vp.at[slot_blocks, slot_offsets].set(v_new[:, :, 0], mode="drop")
    return kp, vp


# ptlint: disable=PT-T009  agrees with the committed plan entry
# serving.decode_chunk (donate=[1]); the jaxplan donation gate pins it
@functools.partial(jax.jit, static_argnums=(3, 4, 5), donate_argnums=(1,))
def fused_decode_chunk(params, pools, packed, geom, k, kernel="ragged"):
    """k decode steps for N sequences entirely on device: one lax.scan
    whose body is the paged decode step above plus on-device sampling
    and termination tracking. The host uploads ONE packed int32 array
    (layout at PACK_COLS) and fetches ONE int32 [k+2, N] result:

        rows 0..k-1   sampled token per scan step, -1 where the row was
                      frozen (inactive / already finished / flagged bad)
                      or silently consuming a prompt token (prefill trip)
        row  k        finished mask after the chunk (EOS or max_tokens)
        row  k+1      per-row not-finite flag, latched at the FIRST bad
                      step — the engine's anomaly attribution, computed
                      in-scan so quarantine needs no extra fetch

    Chunked prefill: rows with pf_feed > 0 spend their first pf_feed
    trips consuming prompt tokens from the feed columns — KV is written
    at the row's position exactly like a decode trip, but no token is
    sampled or emitted. The trip that consumes the LAST prompt token
    (pf_left==1 and pf_more==0) samples the request's first output from
    its logits with fold_in(seed, 0), then the row decodes normally for
    the rest of the chunk. Prefill and decode rows therefore share one
    program and one dispatch — a long prompt never stalls the batch.

    Frozen rows still flow through the fixed-shape body but scatter to
    slot_block=num_blocks (dropped) and keep their carry unchanged, so
    a chunk is bitwise-equivalent to running its live prefix as smaller
    chunks: sampling keys derive from fold_in(seed_key, out_cnt) — a
    function of per-request progress, NOT of chunk geometry — which
    makes token streams invariant under chunk size and under
    preemption/recovery replay (tests pin k-step vs k x 1-step).

    kernel (static): "ragged" (default) routes per-layer attention to
    the pallas ragged paged-attention kernel when the backend supports
    it (ops/pallas/ragged_paged_attention.route_gate) — the pools are
    read through the block table inside the kernel, dead rows cost zero
    work, and the batch is padded to ONE fixed width so a single
    compilation covers every mix. Off-TPU (CPU tier-1) both modes lower
    to the same gather + composed attention built from the shared
    jitted sub-programs, preserving the bitwise-parity contract;
    "bucketed" keeps the power-of-two padded path as the oracle.

    pools (arg 1) is DONATED: the KV carry is updated in place across
    the scan and the input buffers alias the output on TPU, so the k
    cache writes cost no extra copies of the pool.

    Returns (out [k+2, N] int32, updated pools).
    """
    num_layers, num_heads, head_dim, max_seq = geom
    tables = packed[:, PACK_COLS + k:]
    feed = packed[:, PACK_COLS:PACK_COLS + k].T      # [k, N] prompt feed
    num_blocks = pools[0][0].shape[0]
    block_size = pools[0][0].shape[1]
    n = packed.shape[0]
    active = packed[:, 2] > 0
    max_out = packed[:, 4]
    eos = packed[:, 5]
    temps = lax.bitcast_convert_type(packed[:, 6], jnp.float32)
    top_ks = packed[:, 7]
    top_ps = lax.bitcast_convert_type(packed[:, 8], jnp.float32)
    base_keys = jax.vmap(jax.random.PRNGKey)(packed[:, 9])
    pf_more = packed[:, 11] > 0
    use_ragged = (kernel == "ragged"
                  and _ragged.route_gate(head_dim, num_heads, block_size))

    def body(carry, feed_j):
        pools, tok, pos, out_cnt, finished, bad, pf_left = carry
        run = active & ~finished & ~bad
        prefilling = run & (pf_left > 0)
        last_pf = prefilling & (pf_left == 1) & ~pf_more
        sampling = (run & ~prefilling) | last_pf
        tok_in = jnp.where(prefilling, feed_j, tok)
        blk_idx = jnp.where(run, pos // block_size, 0)
        slot_blocks = jnp.where(
            run,
            jnp.take_along_axis(tables, blk_idx[:, None], axis=1)[:, 0],
            num_blocks)                      # frozen rows: scatter drops
        slot_offsets = pos % block_size
        x = _token_embed(params, tok_in, pos)
        att_lens = jnp.where(run, pos + 1, 0).astype(jnp.int32)
        new_pools = []
        for i, (kp, vp) in enumerate(pools):
            qkv = _decode_qkv(params, i, x, geom)
            if use_ragged:
                kp, vp = _pool_write(
                    kp, vp, qkv[1], qkv[2], slot_blocks, slot_offsets)
                att = _ragged.ragged_decode_attention(
                    qkv[0][:, :, 0, :], kp, vp, tables, att_lens)
                x = _attn_merge(params, i, x, att[:, :, None, :], geom)
            else:
                kp, vp, kc, vc = _pool_write_gather(
                    kp, vp, qkv[1], qkv[2], slot_blocks, slot_offsets,
                    tables)
                x = _decode_attn(params, i, x, qkv[0], kc, vc, pos, geom)
            new_pools.append((kp, vp))
        logits = _decode_head(params, x)
        row_bad = rows_not_finite(logits) & run
        bad = bad | row_bad
        keys = jax.vmap(jax.random.fold_in)(base_keys, out_cnt)
        tok_new = _sample_rows(logits, keys, temps, top_ks, top_ps)
        ok = run & ~row_bad
        step_ok = ok & sampling
        emit = jnp.where(step_ok, tok_new, -1)
        finished = finished | (step_ok & ((tok_new == eos)
                                          | (out_cnt + 1 >= max_out)))
        tok = jnp.where(step_ok, tok_new, tok)
        pos = jnp.where(ok, pos + 1, pos)
        out_cnt = jnp.where(step_ok, out_cnt + 1, out_cnt)
        pf_left = jnp.where(ok & prefilling, pf_left - 1, pf_left)
        return (tuple(new_pools), tok, pos, out_cnt, finished, bad,
                pf_left), emit

    carry0 = (pools, packed[:, 0], packed[:, 1], packed[:, 3],
              jnp.zeros((n,), bool), jnp.zeros((n,), bool),
              packed[:, 10])
    (pools, _, _, _, finished, bad, _), toks = lax.scan(
        body, carry0, feed, length=k)
    out = jnp.concatenate(
        [toks.astype(jnp.int32),
         finished[None].astype(jnp.int32),
         bad[None].astype(jnp.int32)], axis=0)
    return out, pools
