"""Ragged paged-attention decode step (pure-JAX reference).

One decode step for N sequences at DIFFERENT positions against the
block-pooled KV cache (serving/paged_cache.py): per layer, the new
token's K/V is scattered into each sequence's reserved (block, offset)
slot, the sequence's context is gathered back through its block table,
and attention is masked per-sequence by length. This is the reference
semantics of the TPU Ragged Paged Attention kernel (PAPERS.md, arxiv
2604.15464) — block-table gather + ragged length masking — kept in
plain jnp so XLA owns the schedule; a pallas kernel can swap in under
the same signature later.

Parity contract: the math is NOT re-implemented — embedding, per-layer
qkv, the attention block and the LM head are the SAME top-level jitted
sub-programs generation.decode_step is composed of (_token_embed,
_decode_qkv, _decode_attn, _decode_head). When max_blocks_per_seq *
block_size == max_seq_len the gathered context has the exact dense
cache layout (position p = block p//bs, slot p%bs) and the same shape,
so XLA reuses the identical compiled executables for both paths; since
out-of-length positions are masked to -1e30 before softmax (erasing
pool garbage exactly: masked probs are exact zeros), the logits are
bitwise-identical to generation.decode_step (tests/test_serving.py
pins this). Padded bucket rows write out of bounds (dropped) and
attend only to block-table padding that their mask erases; their
logits are garbage and the engine ignores them.

Shape bucketing: everything here is shape-polymorphic only in
(N, max_blocks_per_seq, num_blocks); the engine pads N to a power-of-two
bucket capped at max_num_seqs and keeps the other two fixed, so XLA
compiles once per bucket and NEVER recompiles per request mix.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...models.generation import (_decode_attn, _decode_head, _decode_qkv,
                                  _token_embed)

__all__ = ["gather_block_kv", "paged_decode_step"]


def gather_block_kv(pool, block_tables):
    """[num_blocks, bs, H, D] pool + [N, MB] tables -> [N, H, MB*bs, D]
    contiguous per-sequence context, positions in block-table order."""
    n, mb = block_tables.shape
    bs, h, d = pool.shape[1], pool.shape[2], pool.shape[3]
    ctx = pool[block_tables]                     # [N, MB, bs, H, D]
    return ctx.reshape(n, mb * bs, h, d).transpose(0, 2, 1, 3)


@jax.jit
def _pool_write_gather(kp, vp, k_new, v_new, slot_blocks, slot_offsets,
                       block_tables):
    """Scatter the new token's K/V [N, H, 1, D] into each sequence's
    (block, offset) slot — out-of-range slot_blocks (padded rows) are
    dropped — then gather each sequence's context back through its
    block table."""
    kp = kp.at[slot_blocks, slot_offsets].set(k_new[:, :, 0], mode="drop")
    vp = vp.at[slot_blocks, slot_offsets].set(v_new[:, :, 0], mode="drop")
    return (kp, vp,
            gather_block_kv(kp, block_tables),
            gather_block_kv(vp, block_tables))


def paged_decode_step(params, pools, tokens, positions, block_tables,
                      slot_blocks, slot_offsets, geom):
    """One ragged decode step over the block pool.

    params: the models.generation.extract_params dict.
    pools: L-tuple of (k_pool, v_pool) [num_blocks, bs, H, D].
    tokens [N] int32 — last sampled token per sequence.
    positions [N] int32 — cached length per sequence (the new token's
        position); padded rows use 0.
    block_tables [N, MB] int32 — block ids padded with 0.
    slot_blocks/slot_offsets [N] int32 — write slot for the new token's
        K/V; padded rows point slot_blocks out of bounds (num_blocks) so
        the scatter drops them.
    geom: static (num_layers, num_heads, head_dim, max_seq_len), the
        models.generation geometry tuple.

    Returns (logits [N, V], updated pools). Composed of the shared
    jitted sub-programs of generation.decode_step plus the pool
    scatter/gather above — see the parity contract in the module
    docstring.
    """
    tokens = jnp.asarray(tokens, jnp.int32)
    positions = jnp.asarray(positions, jnp.int32)
    x = _token_embed(params, tokens, positions)   # [N, 1, C]
    new_pools = []
    for i, (kp, vp) in enumerate(pools):
        qkv = _decode_qkv(params, i, x, geom)     # [3, N, H, 1, D]
        kp, vp, kc, vc = _pool_write_gather(
            kp, vp, qkv[1], qkv[2], slot_blocks, slot_offsets,
            block_tables)
        new_pools.append((kp, vp))
        x = _decode_attn(params, i, x, qkv[0], kc, vc, positions, geom)
    return _decode_head(params, x), tuple(new_pools)
