"""Paged KV cache: a fixed block pool + per-sequence block tables.

The dense decode cache in models/generation.py is [B, H, max_seq, D] per
layer — every sequence pays for max_seq_len positions and a batch slot,
so a serving mix of short and long requests wastes most of HBM. Here KV
lives in a per-layer block pool [num_blocks, block_size, H, D]; a
sequence owns an ordered list of block ids (its *block table*) and only
ever holds ceil(len/block_size) blocks. This is the TPU-native shape of
the Ragged Paged Attention kernel (PAPERS.md, arxiv 2604.15464) and of
vLLM's PagedAttention, with the pool as one jnp array per layer so the
ragged decode step (serving/attention.py) gathers it with one
block-table index per layer.

Prefix caching (docs/serving.md "Prefix caching"): with
`enable_prefix_cache=True` blocks become REFCOUNTED and content-
addressed through a radix-trie index (serving/prefix_cache.py) at
full-block granularity. Admission attaches the longest cached prefix
of a prompt to the new sequence's table (the same physical blocks,
refcount += 1), forks a private copy-on-write block when the prompt
diverges mid-block, and only the uncached suffix is ever prefilled.
A freed block returns to the free list only at refcount 0; blocks the
trie still indexes are RETAINED at refcount 0 (evictable LRU-leaf-
first under pool pressure) instead of freed. Scrub is refcount-aware:
a quarantined sequence scrubs only blocks it was the LAST holder of,
and distrusts (trie-evicts + taints) anything it shared — a tainted
block is scrubbed the moment its final reference drops.

Hierarchical tiering (docs/serving.md "Hierarchical KV-cache
tiering"): with `host_tier_blocks > 0` LRU eviction becomes
demote-instead-of-free — the victim block's payload is spilled to a
host-RAM HostTierStore (per-block numpy copy + sha256 digest) and the
trie node is retagged host-resident instead of destroyed. A later
match promotes the payload back into a fresh device block
(`ensure_promoted`), re-verifying the digest on fill; a promotion
that is killed, times out, races a store-side eviction or fails the
integrity check degrades to ordinary re-prefill of the missing
suffix. The zero-leak, refcount and scrub-taint invariants span both
tiers (`check_integrity` cross-tier keys; a distrusted subtree's
host copies are poisoned, never promoted).

int8 pool mode (docs/serving.md "int8 KV blocks"): with
`kv_cache_dtype="int8"` the pools are STORED as int8 codes plus
per-(block, head) f32 scales (serving/kv_quant.py), cutting resident
KV bytes ~4x. `pools` stays the logical f32 interface — the property
getter dequantizes, the setter re-encodes with MONOTONE scales so a
block whose content didn't change round-trips bit-identically — and
every consumer (attention gather, write_prefill scatter, migration,
scrub, promotion) is oblivious. The worst-case dequantization error
is not folklore: analysis/jaxnum.py derives it from the codec's
jaxpr (`serving.kv_block_codec`) and numplan.json pins it against
the declared `KV_INT8_REL_ERR` budget. Host-tier spill in this mode
stores the QUANTIZED payload (codes + scale rows under one sha256),
so the spill tier gets the same ~4x and the integrity contract is
unchanged.

Host/device split: block accounting (free list, tables, lengths,
refcounts, trie, counters) is plain Python — it feeds the scheduler
and never traces. The pools themselves are jax arrays; `write_prefill`
scatters a dense prefill cache into a sequence's blocks, and the
decode step returns updated pools that the engine assigns back.
"""
from __future__ import annotations

import hashlib
import time
from collections import Counter
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from . import kv_quant
from .host_tier import HostTierStore
from .prefix_cache import PrefixCacheIndex, PrefixNode

__all__ = ["PagedKVCache", "CacheExhausted"]


class CacheExhausted(RuntimeError):
    """Block pool exhaustion report: who needed how much vs. what's free.

    The scheduler catches this to preempt; anyone else sees a precise
    message instead of a silent mis-allocation."""

    def __init__(self, seq_id, needed: int, free: int, total: int,
                 what: str = "block"):
        self.seq_id = seq_id
        self.needed = needed
        self.free = free
        self.total = total
        super().__init__(
            f"KV {what} pool exhausted: seq {seq_id!r} needs {needed} "
            f"{what}(s), {free}/{total} free")


class PagedKVCache:
    """Fixed-size per-layer KV block pools with alloc/free accounting.

    Pools: L-tuple of (k_pool, v_pool), each [num_blocks, block_size, H,
    D]. Token position p of a sequence lives in its block table entry
    p // block_size at slot offset p % block_size — the identity layout
    that makes the gathered context bitwise-match the dense cache.

    Block lifecycle: free list -> owned (refcount = number of tables
    holding the block) -> either back to the free list at refcount 0,
    or — when the prefix trie indexes it — retained at refcount 0 as
    an evictable cached block. `blocks_allocated`/`blocks_freed` count
    free-list crossings only, so attaching a shared block is not an
    allocation and retaining a cached block is not (yet) a free; with
    the prefix cache disabled this reduces exactly to the historical
    allocated == freed zero-leak reconciliation.
    """

    def __init__(self, num_layers: int, num_heads: int, head_dim: int,
                 num_blocks: int, block_size: int, dtype=jnp.float32,
                 enable_prefix_cache: bool = False,
                 host_tier_blocks: int = 0,
                 promote_timeout_s: Optional[float] = None,
                 kv_cache_dtype: str = "float32"):
        if num_blocks <= 0 or block_size <= 0:
            raise ValueError("num_blocks and block_size must be positive")
        if kv_cache_dtype not in ("float32", "int8"):
            raise ValueError(
                f"kv_cache_dtype must be 'float32' or 'int8', got "
                f"{kv_cache_dtype!r}")
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.kv_cache_dtype = kv_cache_dtype
        shape = (num_blocks, block_size, num_heads, head_dim)
        if kv_cache_dtype == "int8":
            # quantized pool mode (module docstring): int8 codes +
            # per-(block, head) scales; the `pools` property is the
            # dequantized f32 view every consumer reads and writes
            self._qpools = tuple(
                (jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8))
                for _ in range(num_layers))
            self._scales = tuple(
                (jnp.zeros((num_blocks, num_heads), jnp.float32),
                 jnp.zeros((num_blocks, num_heads), jnp.float32))
                for _ in range(num_layers))
        else:
            self._qpools = None
            self._pools: Tuple[Tuple[jnp.ndarray, jnp.ndarray], ...] = \
                tuple((jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
                      for _ in range(num_layers))
        # ----------------------------------------------- host accounting
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._tables: Dict[object, List[int]] = {}
        self._lens: Dict[object, int] = {}
        # refcount[b] = number of block tables containing b; an entry
        # exists exactly while b is OFF the free list (0 only for
        # trie-cached, currently-unreferenced blocks)
        self._refcount: Dict[int, int] = {}
        # blocks whose content is distrusted (shared at scrub time):
        # never re-indexed, scrubbed when their last reference drops
        self._tainted: set = set()
        self.prefix_index: Optional[PrefixCacheIndex] = \
            PrefixCacheIndex(block_size) if enable_prefix_cache else None
        # host-RAM spill tier behind the trie: eviction demotes into it
        # instead of destroying (meaningless without the trie, so gated
        # on enable_prefix_cache)
        self.host_tier: Optional[HostTierStore] = \
            HostTierStore(host_tier_blocks) \
            if (enable_prefix_cache and host_tier_blocks > 0) else None
        self.promote_timeout_s = promote_timeout_s
        # tiering counters + promote-latency samples (the engine drains
        # the samples into its serving_tier_promote_seconds histogram)
        self.tier_demotions = 0
        self.tier_promotions = {"hit": 0, "timeout": 0,
                                "integrity": 0, "raced": 0}
        self._promote_seconds: List[float] = []
        # fault-injection hooks, armed per step by the owning engine
        # (inert when never armed); the promote guard excludes the
        # in-progress promotion path from demotion victim selection
        self._tier_faults = None
        self._tier_step = 0
        self._promote_guard: set = set()
        # lifetime counters (the zero-leak invariant is
        # blocks_allocated == blocks_freed once every sequence is freed
        # and, with prefix caching, the trie is cleared)
        self.blocks_allocated = 0
        self.blocks_freed = 0
        self.blocks_attached = 0             # shared-prefix attaches
        self.alloc_failures = 0
        self.high_water = 0
        # multi-tenant accounting (serving/tenancy.py; inert until the
        # scheduler feeds it): seq_id -> tenant so register_prefix can
        # stamp trie nodes, and the prefix-share weights arbitrating
        # weighted eviction (None = historical global LRU)
        self._seq_tenant: Dict[object, str] = {}
        self._tenant_weights: Optional[Dict[str, float]] = None

    # ------------------------------------------------ pool storage view
    @property
    def pools(self) -> Tuple[Tuple[jnp.ndarray, jnp.ndarray], ...]:
        """L-tuple of (k, v) [num_blocks, block_size, H, D] in the
        LOGICAL f32 layout — what the attention gather, write_prefill
        scatter, migration and scrub paths all read and assign. In f32
        mode this is the storage itself (bit-for-bit the historical
        attribute). In int8 mode the getter dequantizes the code/scale
        storage and the setter re-encodes through
        kv_quant.requantize_blocks, whose monotone scales make an
        unchanged block's round-trip bit-stable (kv_quant docstring),
        so repeated decode-chunk rebinds never walk stored values."""
        if self._qpools is None:
            return self._pools
        return tuple(
            (kv_quant.dequantize_blocks(qk, sk),
             kv_quant.dequantize_blocks(qv, sv))
            for (qk, qv), (sk, sv) in zip(self._qpools, self._scales))

    @pools.setter
    def pools(self, new_pools) -> None:
        if self._qpools is None:
            self._pools = tuple(new_pools)
            return
        qpools, scales = [], []
        for (k, v), (sk, sv) in zip(new_pools, self._scales):
            qk, nsk = kv_quant.requantize_blocks(k, sk)
            qv, nsv = kv_quant.requantize_blocks(v, sv)
            qpools.append((qk, qv))
            scales.append((nsk, nsv))
        self._qpools = tuple(qpools)
        self._scales = tuple(scales)

    def _reset_block_scales(self, ids) -> None:
        """Zero freshly-claimed blocks' scale rows (int8 mode): stale
        codes dequantize against scale 0 to exact zeros — the
        fresh-block invariant — and the next write derives its scale
        from the new content alone. A surviving (larger) scale from the
        block's previous tenant would inflate the quantization step
        past the committed relative-error bound (numplan.json)."""
        at = jnp.asarray(list(ids), jnp.int32)
        self._scales = tuple(
            (sk.at[at].set(0.0), sv.at[at].set(0.0))
            for sk, sv in self._scales)

    def arm_tier_faults(self, faults: "ServingFaultInjector",
                        step: int) -> None:
        """Point the demote/promote fault hooks (kill_demotion /
        kill_promotion) at the engine's injector for this step."""
        self._tier_faults = faults
        self._tier_step = step

    # -------------------------------------------------- tenant plumbing
    def note_seq_tenant(self, seq_id, tenant: str) -> None:
        """Tag the tenant whose fair share seq_id spends; the tag rides
        into the trie when the sequence's prefix registers and is
        dropped with the sequence's table."""
        self._seq_tenant[seq_id] = tenant

    def set_tenant_weights(self, weights: Optional[Dict[str, float]]
                           ) -> None:
        """Install the prefix-share weights (TenantRegistry snapshot;
        the scheduler refreshes on registry-version change). None
        restores the historical unweighted global-LRU eviction."""
        self._tenant_weights = dict(weights) if weights else None

    def _over_share_tenants(self) -> Optional[set]:
        """Tenants holding MORE device-resident cached blocks than
        their prefix_share-weighted proportion of the current cached
        pool — the victims weighted eviction charges first. None when
        weighting cannot discriminate (no weights installed, or zero/
        one tenant holding blocks): the caller falls back to the
        historical global LRU sweep, which keeps single-tenant stacks
        on the exact pre-tenancy path."""
        w = self._tenant_weights
        idx = self.prefix_index
        if not w or idx is None:
            return None
        census = idx.tenant_device_blocks()
        if len(census) <= 1:
            return None
        total = sum(census.values())
        total_w = sum(w.get(t, 1.0) for t in census)
        over = {t for t, n in census.items()
                if n > total * w.get(t, 1.0) / total_w}
        return over or None

    # ------------------------------------------------------------ queries
    def num_free(self) -> int:
        return len(self._free)

    def num_used(self) -> int:
        return self.num_blocks - len(self._free)

    def num_evictable(self) -> int:
        """Trie-cached blocks no table references — reclaimable on
        demand, so admission watermarks treat them as headroom."""
        if self.prefix_index is None:
            return 0
        return sum(1 for b in self.prefix_index.blocks()
                   if self._refcount.get(b, 0) == 0)

    def utilization(self) -> float:
        return self.num_used() / self.num_blocks

    def blocks_needed(self, num_tokens: int) -> int:
        return -(-num_tokens // self.block_size)

    def has_seq(self, seq_id) -> bool:
        return seq_id in self._tables

    def seq_len(self, seq_id) -> int:
        return self._lens[seq_id]

    def block_table(self, seq_id) -> List[int]:
        return list(self._tables[seq_id])

    # ------------------------------------------------------- alloc / free
    def _take_blocks(self, seq_id, n: int) -> List[int]:
        if n > len(self._free) and self.prefix_index is not None:
            self._evict_cached(n - len(self._free))
        if n > len(self._free):
            self.alloc_failures += 1
            raise CacheExhausted(seq_id, n, len(self._free),
                                 self.num_blocks)
        got = [self._free.pop() for _ in range(n)]
        for b in got:
            self._refcount[b] = 1
        if self._qpools is not None and got:
            self._reset_block_scales(got)
        self.blocks_allocated += n
        self.high_water = max(self.high_water, self.num_used())
        return got

    def _evict_cached(self, n: int) -> int:
        """Reclaim up to n unreferenced cached blocks, LRU leaf first
        (leaf-only removal keeps the trie rooted; clocks are monotone
        root-ward so the coldest extremity goes first). Evicted blocks
        are NOT scrubbed — finite stale KV is erased exactly by the
        attention length mask, the same contract as a non-scrub free.

        With a host tier, eviction is demote-instead-of-free: the LRU
        node on the demotion frontier spills its payload to host RAM
        and keeps its trie position (`_flush_demotions`); the device
        block is reclaimed either way, so each iteration makes
        progress."""
        idx = self.prefix_index
        evicted = 0
        if self.host_tier is not None:
            # batched demotion: select every victim first (pending
            # nodes count as demoted for frontier eligibility, so the
            # selection sequence matches the one-at-a-time loop), then
            # spill all payloads with ONE gather per pool tensor (on
            # TPU: one DMA per tensor instead of one per block; the
            # dispatch-bound CPU path gains the same way). A victim
            # the demote path refuses (tainted / injected
            # kill_demotion) flushes what is staged — its children
            # must be host-resident before _plain_evict drops them —
            # and plain-evicts
            pending: List[PrefixNode] = []
            pset: set = set()
            faults = self._tier_faults
            # share-weighted victim selection: tenants over their
            # prefix_share go first; once they are drained back under
            # share (among exhausts) the sweep widens to the global LRU
            among = self._over_share_tenants()
            while evicted < n:
                node = idx.lru_demotable(
                    lambda b: self._refcount.get(b, 0) == 0,
                    skip=self._promote_guard, pending=pset, among=among)
                if node is None and among is not None:
                    among = None
                    continue
                if node is None:
                    break
                evicted += 1
                if node.block in self._tainted or (
                        faults is not None
                        and faults.kill_demotion(self._tier_step)):  # ptlint: disable=PT-C004
                    self._flush_demotions(pending)
                    pending, pset = [], set()
                    self._plain_evict(node)
                    continue
                pending.append(node)
                pset.add(node)
            self._flush_demotions(pending)
            return evicted
        among = self._over_share_tenants()
        while evicted < n:
            node = idx.pop_lru_leaf(
                lambda b: self._refcount.get(b, 0) == 0, among=among)
            if node is None and among is not None:
                among = None                 # widen to the global LRU
                continue
            if node is None:
                break
            del self._refcount[node.block]
            self._free.append(node.block)
            self.blocks_freed += 1
            idx.evictions += 1
            evicted += 1
        return evicted

    # ---------------------------------------------------- host tiering
    def _payload_digest(self, payload) -> str:
        """sha256 over a per-block payload (L-tuple of (k, v) numpy
        arrays), taken at spill time and re-checked on every fill —
        the tier's end-to-end integrity contract."""
        h = hashlib.sha256()
        for k, v in payload:
            h.update(np.ascontiguousarray(k).tobytes())
            h.update(np.ascontiguousarray(v).tobytes())
        return h.hexdigest()

    def _dequant_payload(self, payload) -> tuple:
        """Decode a QUANTIZED spill payload (L int8 code pairs + the
        trailing (k_scales [L, H], v_scales [L, H]) pair) back to the
        L-pair f32 shape the scatter/wire paths expect. Only meaningful
        in int8 mode; called after the stored digest has verified."""
        ks, vs = payload[self.num_layers]
        return tuple(
            (payload[li][0].astype(np.float32) * ks[li][None, :, None],
             payload[li][1].astype(np.float32) * vs[li][None, :, None])
            for li in range(self.num_layers))

    def _flush_demotions(self, nodes: List[PrefixNode]) -> None:
        """Spill the staged victims' payloads to the host tier and free
        their device blocks (demote-instead-of-free). The payload read
        is ONE gather per pool tensor for the whole batch; blocks stay
        valid until here because nothing reclaims the free list inside
        `_evict_cached`. Victims that must not be spilled (tainted,
        kill_demotion) never reach this path — the selection loop
        routes them to `_plain_evict` before anything of theirs is
        read, so nothing hits the host tier half-written. `nodes` is
        leaf-ward (children before parents, the selection order), so
        each `demote` sees its device children already host-resident."""
        if not nodes:
            return
        ids = jnp.asarray([n.block for n in nodes], dtype=jnp.int32)
        if self._qpools is not None:
            # spill QUANTIZED (module docstring): the codes gather per
            # pool tensor, plus every layer's scale rows appended as ONE
            # extra (L+1)-th pair — the pair-iterating digest therefore
            # covers codes AND scales, and the store's byte accounting /
            # corrupt_oldest chaos hook work unchanged
            per_layer = [(np.asarray(qk[ids]), np.asarray(qv[ids]))
                         for qk, qv in self._qpools]
            sc = [(np.asarray(sk[ids]), np.asarray(sv[ids]))
                  for sk, sv in self._scales]
        else:
            per_layer = [(np.asarray(kp[ids]), np.asarray(vp[ids]))
                         for kp, vp in self.pools]
            sc = None
        for i, node in enumerate(nodes):
            b = node.block
            payload = tuple((np.array(pk[i]), np.array(pv[i]))
                            for pk, pv in per_layer)
            if sc is not None:
                payload += ((np.stack([k[i] for k, _ in sc]),
                             np.stack([v[i] for _, v in sc])),)
            hid, dropped = self.host_tier.put(
                payload, self._payload_digest(payload))
            self.prefix_index.demote(node, hid)
            for dh in dropped:
                # store-side LRU eviction: unlink the orphaned trie
                # subtrees (host nodes hang below the frontier, so the
                # subtree is all host-resident)
                dn = self.prefix_index.node_of_host(dh)
                if dn is not None:
                    self._drop_host_subtree(dn)
            del self._refcount[b]
            self._free.append(b)
            self.blocks_freed += 1
            self.tier_demotions += 1

    def _plain_evict(self, node: PrefixNode) -> None:
        """Destroy a frontier node the demote path refused: its host
        children (if any) are dropped with it — an unlinked host
        subtree is unreachable — and the device block returns to the
        free list, scrubbed if tainted."""
        for child in list(node.children.values()):
            self._drop_host_subtree(child)
        idx = self.prefix_index
        idx.remove(node)
        del self._refcount[node.block]
        self._free.append(node.block)
        self.blocks_freed += 1
        idx.evictions += 1
        if node.block in self._tainted:
            self._tainted.discard(node.block)
            self.scrub_blocks([node.block])

    def _drop_host_subtree(self, node: PrefixNode,
                           poison: bool = False) -> int:
        """Unlink a subtree rooted at a HOST node and drop its store
        entries (raced store eviction, failed integrity, distrust).
        `poison=True` marks the drops as taint-driven. Returns the
        number of host entries dropped."""
        dropped = 0
        for n in self.prefix_index.remove_subtree(node):
            if n.tier == "host":
                if self.host_tier is not None:
                    if poison:
                        self.host_tier.poison(n.host_id)
                    else:
                        self.host_tier.drop(n.host_id)
                dropped += 1
            elif self._refcount.get(n.block, 0) == 0:
                # defensive: device below host cannot exist (insert
                # stops at host nodes), but never strand a block
                del self._refcount[n.block]
                self._free.append(n.block)
                self.blocks_freed += 1
        return dropped

    def host_match_len(self, tokens) -> int:
        """Tier-aware pricing probe companion to `match_len`: how many
        ADDITIONAL leading tokens are host-resident behind the device
        match — promotable before prefill, so the scheduler prices the
        prompt at its true uncached cost at enqueue."""
        if self.host_tier is None or len(tokens) < 2:
            return 0
        toks = [int(t) for t in tokens[:len(tokens) - 1]]
        _dev, host_path = self.prefix_index.match_tiered(toks)
        return len(host_path) * self.block_size

    def ensure_promoted(self, tokens) -> Optional[dict]:
        """Fill the host-resident run extending `tokens`' device match
        back into fresh device blocks, root-ward, stopping at the
        first failure. Outcomes per node: "hit" (digest verified,
        scattered, trie retagged), "timeout" (injected kill_promotion,
        promote_timeout_s exceeded, or no device block free — entry
        stays host-resident and retryable), "raced" (store evicted the
        payload first) or "integrity" (sha256 mismatch) — the last two
        drop the subtree so the suffix re-prefills. Returns None when
        tiering is off or nothing host-resident matches, else
        {"promoted_blocks", "promoted_tokens", "outcomes", "seconds"}.
        Never raises: a misbehaving tier degrades to re-prefill."""
        if self.host_tier is None or len(tokens) < 2:
            return None
        toks = [int(t) for t in tokens[:len(tokens) - 1]]
        dev_path, host_path = self.prefix_index.match_tiered(toks)
        if not host_path:
            return None
        t0 = time.perf_counter()
        outcomes: List[str] = []
        staged: List[Tuple[PrefixNode, int, tuple]] = []
        # guard the active path: _take_blocks inside _promote_stage may
        # recurse into _evict_cached, which must not demote the parent
        # of the node being promoted
        self._promote_guard = set(dev_path)
        try:
            tail: List[str] = []
            for node in host_path:
                out, b, payload = self._promote_stage(node, t0)
                if out != "hit":
                    tail.append(out)
                    break
                staged.append((node, b, payload))
                self._promote_guard.add(node)
            # commit. Staging verified each node in hand, but a LATER
            # stage's _take_blocks may have demoted into a full host
            # store whose LRU eviction dropped an EARLIER staged entry
            # and unlinked its subtree — that node and everything
            # staged below it raced; give their blocks back
            live: List[Tuple[PrefixNode, int, tuple]] = []
            raced = False
            for node, b, payload in staged:
                if not raced and self.prefix_index.node_of_host(
                        node.host_id) is node:
                    live.append((node, b, payload))
                else:
                    raced = True
                    del self._refcount[b]
                    self._free.append(b)
                    self.blocks_freed += 1
            if raced:
                tail = ["raced"]
            outcomes = ["hit"] * len(live) + tail
            if live:
                # ONE batched scatter per pool tensor for the whole
                # chain (on TPU: one DMA per tensor instead of one per
                # block; the dispatch-bound CPU path gains the same
                # way — promote latency is the tail of revisit TTFT)
                ids = jnp.asarray([b for _n, b, _p in live],
                                  dtype=jnp.int32)
                self.pools = tuple(
                    (kp.at[ids].set(jnp.asarray(np.stack(
                        [p[li][0] for _n, _b, p in live]))),
                     vp.at[ids].set(jnp.asarray(np.stack(
                         [p[li][1] for _n, _b, p in live]))))
                    for li, (kp, vp) in enumerate(self.pools))
                for node, b, _p in live:
                    hid = node.host_id       # promote() clears it
                    self._refcount[b] = 0    # trie-cached, unreferenced
                    self.prefix_index.promote(node, b)
                    self.host_tier.drop(hid)
        finally:
            self._promote_guard = set()
        for out in outcomes:
            self.tier_promotions[out] += 1
        seconds = time.perf_counter() - t0
        if live:
            self._promote_seconds.append(seconds)
        return {"promoted_blocks": len(live),
                "promoted_tokens": len(live) * self.block_size,
                "outcomes": outcomes, "seconds": seconds}

    def _promote_stage(self, node: PrefixNode, t0: float
                       ) -> Tuple[str, Optional[int], Optional[tuple]]:
        """Verify + claim for one host->device fill; the caller
        batch-scatters every staged payload in one op. Returns
        (outcome, block, payload); block/payload are None unless the
        outcome is "hit". See ensure_promoted for outcome semantics."""
        faults = self._tier_faults
        if faults is not None \
                and faults.kill_promotion(self._tier_step):  # ptlint: disable=PT-C004
            return "timeout", None, None    # in-flight promotion cut
            # short: entry stays resident, the schedule-time retry
            # picks it up
        if self.promote_timeout_s is not None \
                and time.perf_counter() - t0 > self.promote_timeout_s:
            return "timeout", None, None
        entry = self.host_tier.get(node.host_id)
        if entry is None:
            # the store LRU-dropped the payload between match and fill
            self._drop_host_subtree(node)
            return "raced", None, None
        if self._payload_digest(entry["payload"]) != entry["digest"]:
            # torn host copy (corrupt_host_block chaos fault, bad DMA):
            # drop it — the request re-prefills this suffix
            self._drop_host_subtree(node)
            return "integrity", None, None
        try:
            b = self._take_blocks("_promote", 1)[0]
        except CacheExhausted:
            self.alloc_failures -= 1     # not an admission failure
            return "timeout", None, None    # pool too hot; stays
            # resident
        if self.prefix_index.node_of_host(node.host_id) is not node:
            # _take_blocks recursed into demotion, whose host-store put
            # LRU-evicted this very entry and unlinked the node — give
            # the block back and let the suffix re-prefill
            del self._refcount[b]
            self._free.append(b)
            self.blocks_freed += 1
            return "raced", None, None
        payload = entry["payload"]
        if self._qpools is not None:
            # the batched commit scatters through the f32 `pools` view;
            # decode the verified quantized payload here so the commit
            # path is mode-oblivious
            payload = self._dequant_payload(payload)
        return "hit", b, payload

    def drain_promote_seconds(self) -> List[float]:
        """Hand accumulated promote-latency samples to the engine's
        histogram (cleared on read)."""
        out, self._promote_seconds = self._promote_seconds, []
        return out

    def allocate(self, seq_id, num_tokens: int) -> List[int]:
        """Claim blocks for a new sequence of num_tokens cached tokens
        (prefill). Raises CacheExhausted without side effects."""
        if seq_id in self._tables:
            raise ValueError(f"seq {seq_id!r} already allocated")
        ids = self._take_blocks(seq_id, self.blocks_needed(num_tokens))
        self._tables[seq_id] = ids
        self._lens[seq_id] = num_tokens
        return ids

    def append_slot(self, seq_id) -> Tuple[int, int, int]:
        """Reserve the slot for the sequence's next token; grows the
        block table by one block on a block boundary. Returns
        (block_id, offset, position); raises CacheExhausted (leaving the
        sequence untouched) when a new block is needed but none is free.
        """
        pos = self._lens[seq_id]
        table = self._tables[seq_id]
        if pos % self.block_size == 0 and len(table) * self.block_size \
                <= pos:
            table.extend(self._take_blocks(seq_id, 1))
        self._lens[seq_id] = pos + 1
        block = table[pos // self.block_size]
        return block, pos % self.block_size, pos

    def reserve_slots(self, seq_id, n: int) -> Tuple[int, int, int]:
        """Reserve the slots for the sequence's next n tokens at once —
        the chunk-granular twin of append_slot for the fused k-token
        decode (serving/attention.py fused_decode_chunk). Grows the
        block table by however many blocks the n tokens need in ONE
        atomic _take_blocks claim (CacheExhausted leaves the sequence
        untouched), and advances the length by n. Returns the FIRST
        reserved slot (block_id, offset, position); the device scan
        derives slot j's location as position+j through the identity
        layout. A sequence that finishes mid-chunk simply leaves its
        tail reservation unwritten — the whole table is freed with the
        request, so over-reservation can never leak blocks."""
        if n <= 0:
            raise ValueError(f"reserve_slots needs n >= 1, got {n}")
        pos = self._lens[seq_id]
        table = self._tables[seq_id]
        need = self.blocks_needed(pos + n) - len(table)
        if need > 0:
            table.extend(self._take_blocks(seq_id, need))
        self._lens[seq_id] = pos + n
        return table[pos // self.block_size], pos % self.block_size, pos

    # -------------------------------------------------- prefix caching
    def match_len(self, tokens) -> int:
        """Pricing probe (no LRU side effects): how many leading tokens
        of `tokens` the cache could serve at admission. Capped at
        len(tokens) - 1 — at least one prompt token must run through
        the model so the first output has logits to sample from (and so
        the last prompt token's KV is written at its own position,
        never double-written)."""
        if self.prefix_index is None or len(tokens) < 2:
            return 0
        toks = [int(t) for t in tokens[:len(tokens) - 1]]
        path, partial = self.prefix_index.match(toks, touch=False)
        return len(path) * self.block_size + \
            (partial[1] if partial is not None else 0)

    def allocate_with_prefix(self, seq_id, tokens) -> int:
        """Admission with prefix reuse: start seq_id's table with the
        longest cached prefix of `tokens` — full-block trie hits attach
        the SHARED physical blocks (refcount += 1), a mid-block
        divergence forks a private copy-on-write duplicate of the
        partially-agreeing cached block (the sequence overwrites slots
        past the matched m as it prefills). Returns the number of
        prompt tokens served from cache (the sequence's initial length;
        prefill resumes there). With the prefix cache disabled this is
        exactly `allocate(seq_id, 0)` returning 0 — the chunked-prefill
        empty-table admission."""
        if seq_id in self._tables:
            raise ValueError(f"seq {seq_id!r} already allocated")
        idx = self.prefix_index
        if idx is None:
            self._tables[seq_id] = []
            self._lens[seq_id] = 0
            return 0
        toks = [int(t) for t in tokens]
        path, partial = idx.match(toks[:len(toks) - 1], touch=True)
        table = [node.block for node in path]
        for b in table:
            self._refcount[b] += 1
        self.blocks_attached += len(table)
        cached = len(table) * self.block_size
        if partial is not None:
            donor, m = partial
            try:
                fork = self._take_blocks(seq_id, 1)[0]
            except CacheExhausted:
                # the fork is an optimisation; under pressure fall back
                # to recomputing the partial block from tokens. The
                # attached full blocks stay attached — roll nothing back
                self.alloc_failures -= 1     # not an admission failure
            else:
                self._copy_block(donor.block, fork)
                table.append(fork)
                cached += m
                idx.cow_forks += 1
        self._tables[seq_id] = table
        self._lens[seq_id] = cached
        if cached > 0:
            idx.hits += 1
        else:
            idx.misses += 1
        idx.cached_tokens_total += cached
        idx.prompt_tokens_total += len(toks)
        return cached

    def note_prefix_miss(self, num_tokens: int) -> None:
        """Hit-rate accounting for admissions that bypass
        allocate_with_prefix (the dense prefill path — taken exactly
        when nothing matched): without this, dense misses would never
        enter the cached-token ratio's denominator."""
        if self.prefix_index is not None:
            self.prefix_index.misses += 1
            self.prefix_index.prompt_tokens_total += num_tokens

    def register_prefix(self, seq_id, tokens) -> int:
        """Index seq_id's full blocks under `tokens` — the tokens whose
        KV the sequence has actually WRITTEN (prefill progress, or the
        full log minus the never-fed-back last sampled token). Only
        whole blocks are indexed (partial blocks are still being
        written); first-wins dedupe keeps an existing node's physical
        block; tainted blocks are never indexed. Idempotent. Returns
        the number of newly indexed blocks."""
        idx = self.prefix_index
        if idx is None:
            return 0
        table = self._tables[seq_id]
        toks = [int(t) for t in tokens]
        full = min(len(toks) // self.block_size, len(table))
        if full <= 0:
            return 0
        return idx.insert(toks, table[:full],
                          skip=lambda b: b in self._tainted,
                          tenant=self._seq_tenant.get(seq_id, "default"))

    def clear_prefix_cache(self) -> int:
        """Drop the entire trie, returning unreferenced cached blocks
        to the free list (tainted ones scrubbed). Blocks still held by
        live tables just lose their index entry. The reconciliation
        hook: after clearing, a drained cache is back to the
        allocated == freed zero-leak identity. Returns the number of
        blocks released."""
        idx = self.prefix_index
        if idx is None:
            return 0
        if self.host_tier is not None:
            self.host_tier.clear()
        released: List[int] = []
        for b in idx.clear():
            if self._refcount.get(b, 0) == 0:
                del self._refcount[b]
                self._free.append(b)
                released.append(b)
        self.blocks_freed += len(released)
        dirty = [b for b in released if b in self._tainted]
        if dirty:
            self._tainted.difference_update(dirty)
            self.scrub_blocks(dirty)
        return len(released)

    def _copy_block(self, src: int, dst: int) -> None:
        """Device-side block duplication for copy-on-write forks: one
        gather + scatter per layer pool, no host sync."""
        self.pools = tuple(
            (kp.at[dst].set(kp[src]), vp.at[dst].set(vp[src]))
            for kp, vp in self.pools)

    # ---------------------------------------------------- block migration
    def export_blocks(self, seq_id) -> Tuple[tuple, int]:
        """Snapshot one sequence's KV payload for migration to another
        pool (serving/migration.py): an L-tuple of (k, v) arrays, each
        [len(table), block_size, H, D] — a device-side gather per layer
        pool, so the snapshot is a COPY and the source's table,
        refcounts and trie entries are untouched. Shared (refcount >= 2)
        and trie-cached blocks are therefore copied out, never stolen:
        the source keeps serving its other holders, and frees this
        sequence normally after the migration commits. Returns
        (payload, num_tokens); num_tokens is the sequence's current
        length — at a clean step boundary every one of those positions
        holds written KV."""
        table = self._tables[seq_id]
        if not table:
            return tuple((None, None) for _ in self.pools), \
                self._lens[seq_id]
        idx = jnp.asarray(table, jnp.int32)
        return tuple((kp[idx], vp[idx]) for kp, vp in self.pools), \
            self._lens[seq_id]

    def import_blocks(self, seq_id, payload, num_tokens: int) -> List[int]:
        """Admit a migrated sequence's KV payload (export_blocks from a
        SOURCE pool of identical geometry): allocate fresh private
        blocks, scatter the payload into them (one scatter per layer
        pool), and install the rewritten block table at `num_tokens`.
        Raises CacheExhausted with no side effects when the pool can't
        hold the table — migration aborts and the request keeps running
        at the source. The caller registers clean prefixes afterwards
        (register_prefix) so cached-prefix hit rates survive the hop."""
        if seq_id in self._tables:
            raise ValueError(f"seq {seq_id!r} already allocated")
        n = 0 if payload[0][0] is None else int(payload[0][0].shape[0])
        if n < self.blocks_needed(num_tokens):
            raise ValueError(
                f"migration payload holds {n} block(s) but {num_tokens} "
                f"tokens need {self.blocks_needed(num_tokens)}")
        ids = self._take_blocks(seq_id, n) if n else []
        if n:
            idx = jnp.asarray(ids, jnp.int32)
            self.pools = tuple(
                (kp.at[idx].set(pk), vp.at[idx].set(pv))
                for (kp, vp), (pk, pv) in zip(self.pools, payload))
        self._tables[seq_id] = ids
        self._lens[seq_id] = num_tokens
        return ids

    def payload_bytes(self, payload) -> int:
        """Wire size of an export_blocks payload (obs histogram food)."""
        return sum(int(a.size) * a.dtype.itemsize
                   for pair in payload for a in pair if a is not None)

    # ------------------------------------------------------- peer fetch
    def export_prefix(self, tokens) -> Optional[dict]:
        """Snapshot the longest cached full-block prefix of `tokens`
        for a peer replica (serving/migration.py fetch_prefix) — the
        fleet-level twin of export_blocks, walking BOTH tiers: device
        blocks are gathered out (digest taken now), host entries ship
        their stored payload after re-verifying the spill digest (a
        torn entry truncates the export and drops its subtree; the
        peer prefills the rest). Read-only on the device tier. Returns
        None when nothing matches, else {"blocks": [(payload, digest),
        ...] in root-ward order, "tokens": the tokens those blocks
        cover, "bytes": wire size}."""
        idx = self.prefix_index
        if idx is None or len(tokens) < 2:
            return None
        toks = [int(t) for t in tokens[:len(tokens) - 1]]
        dev_path, host_path = idx.match_tiered(toks)
        blocks: List[tuple] = []
        total = 0
        for node in dev_path:
            b = node.block
            payload = tuple((np.array(kp[b]), np.array(vp[b]))
                            for kp, vp in self.pools)
            blocks.append((payload, self._payload_digest(payload)))
        for node in host_path:
            entry = self.host_tier.get(node.host_id) \
                if self.host_tier is not None else None
            if entry is None:
                self._drop_host_subtree(node)
                break
            if self._payload_digest(entry["payload"]) != entry["digest"]:
                self._drop_host_subtree(node)
                break
            payload, digest = entry["payload"], entry["digest"]
            if self._qpools is not None:
                # peers admit uniform f32 payloads (admit_prefix stacks
                # per-layer pairs across blocks): decode the verified
                # quantized spill and digest the decoded wire form fresh
                payload = self._dequant_payload(payload)
                digest = self._payload_digest(payload)
            blocks.append((payload, digest))
        if not blocks:
            return None
        for payload, _ in blocks:
            total += sum(k.nbytes + v.nbytes for k, v in payload)
        return {"blocks": blocks,
                "tokens": toks[:len(blocks) * self.block_size],
                "bytes": total}

    def admit_prefix(self, tokens, blocks) -> int:
        """Install a peer's export_prefix snapshot into THIS pool's
        trie as device-resident cached blocks (refcount 0, evictable)
        so the next admission of `tokens` hits locally. Atomic-abort
        semantics mirror admit_migrated: every digest is verified
        BEFORE any block is claimed (ValueError on mismatch, nothing
        mutated), and CacheExhausted propagates with no side effects.
        First-wins insert dedupes against blocks cached meanwhile; a
        snapshot block the trie didn't take is returned to the free
        list immediately. Returns the number of newly indexed blocks."""
        idx = self.prefix_index
        if idx is None:
            raise ValueError("admit_prefix needs the prefix cache enabled")
        blocks = list(blocks)
        if not blocks:
            return 0
        for i, (payload, digest) in enumerate(blocks):
            if self._payload_digest(payload) != digest:
                raise ValueError(
                    f"peer prefix block {i} failed integrity check")
        ids = self._take_blocks("_peer_fetch", len(blocks))
        stacked = tuple(
            (jnp.asarray(np.stack([p[layer][0] for p, _ in blocks])),
             jnp.asarray(np.stack([p[layer][1] for p, _ in blocks])))
            for layer in range(self.num_layers))
        at = jnp.asarray(ids, jnp.int32)
        self.pools = tuple(
            (kp.at[at].set(pk), vp.at[at].set(pv))
            for (kp, vp), (pk, pv) in zip(self.pools, stacked))
        toks = [int(t) for t in tokens[:len(blocks) * self.block_size]]
        added = idx.insert(toks, ids,
                           skip=lambda b: b in self._tainted)
        for b in ids:
            if idx.node_of(b) is None:
                # first-wins dedupe kept an existing block instead
                del self._refcount[b]
                self._free.append(b)
                self.blocks_freed += 1
            else:
                self._refcount[b] = 0    # trie-cached, unreferenced
        return added

    def _distrust(self, b: int, to_scrub: List[int]) -> None:
        """Scrub-path hygiene for block b's trie entry: remove its
        whole subtree from the index (a removed parent orphans its
        children, and content downstream of a distrusted block must
        not be re-matched). Subtree blocks nobody references are
        released scrubbed; still-referenced ones are tainted — their
        final free scrubs them. HOST-resident descendants are POISONED:
        the spilled copy is dropped from the store immediately, never
        promoted (the satellite taint-across-tiers contract). b itself
        is left to the caller."""
        idx = self.prefix_index
        if idx is None:
            return
        node = idx.node_of(b)
        if node is None:
            return
        for n in idx.remove_subtree(node):
            if n.tier == "host":
                if self.host_tier is not None:
                    self.host_tier.poison(n.host_id)
                continue
            blk = n.block
            if blk == b:
                continue
            if self._refcount.get(blk, 0) == 0:
                del self._refcount[blk]
                self._free.append(blk)
                self.blocks_freed += 1
                self._tainted.discard(blk)
                to_scrub.append(blk)
            else:
                self._tainted.add(blk)

    def free(self, seq_id, scrub: bool = False, cache_tokens=None) -> int:
        """Drop seq_id's table (completion, preemption, cancellation),
        decrementing refcounts; blocks return to the pool only at
        refcount 0, and blocks the prefix trie indexes are RETAINED at
        refcount 0 (evictable) instead of freed. `cache_tokens` — the
        sequence's tokens with valid written KV — indexes its full
        blocks first, so finished/preempted work stays matchable.

        `scrub=True` (quarantine/recovery) zeroes the device contents
        of every block this call actually releases — finite stale
        garbage is erased exactly by the attention length mask (masked
        probs are exact zeros), but NaN survives it (0 * NaN = NaN), so
        a poisoned block must not re-enter the free list carrying NaN.
        Scrub is REFCOUNT-AWARE: a block other sequences still hold is
        never zeroed under them; it is evicted from the trie, tainted,
        and scrubbed when its final reference drops."""
        idx = self.prefix_index
        if idx is not None and cache_tokens is not None and not scrub \
                and len(cache_tokens):
            self.register_prefix(seq_id, cache_tokens)
        ids = self._tables.pop(seq_id)
        self._lens.pop(seq_id)
        self._seq_tenant.pop(seq_id, None)
        to_scrub: List[int] = []
        for b in reversed(ids):
            self._refcount[b] -= 1
            if scrub:
                self._distrust(b, to_scrub)
            if self._refcount[b] > 0:
                if scrub:
                    self._tainted.add(b)
                continue
            if not scrub and idx is not None \
                    and idx.node_of(b) is not None:
                continue                     # retained: cached, evictable
            del self._refcount[b]
            self._free.append(b)
            self.blocks_freed += 1
            if scrub or b in self._tainted:
                self._tainted.discard(b)
                to_scrub.append(b)
        if to_scrub:
            self.scrub_blocks(to_scrub)
        return len(ids)

    def scrub_blocks(self, block_ids) -> None:
        """Zero the given blocks in every layer's pools, restoring the
        fresh-block invariant the bitwise-parity contract relies on."""
        if not block_ids:
            return
        idx = jnp.asarray(list(block_ids), jnp.int32)
        self.pools = tuple(
            (kp.at[idx].set(0.0), vp.at[idx].set(0.0))
            for kp, vp in self.pools)

    def check_integrity(self) -> dict:
        """Invariant audit for the chaos harness: the free list and the
        LIVE blocks (table-owned plus trie-cached) must exactly
        partition the pool, refcounts must equal table multiplicity,
        unreferenced live blocks must be trie-cached, taints must point
        at owned blocks, the trie must be structurally sound, and the
        lifetime counters must account for every off-free-list block.
        Returns the audit dict; raises RuntimeError on any violation.
        With the prefix cache disabled this reduces to the historical
        free-list/table partition check."""
        in_tables = [b for ids in self._tables.values() for b in ids]
        owned = set(in_tables)
        free = set(self._free)
        idx = self.prefix_index
        cached = set(idx.blocks()) if idx is not None else set()
        live = owned | cached
        mult = Counter(in_tables)
        report = {
            "leaked": self.num_blocks - len(live | free),
            "double_owned": sum(
                1 for b in set(self._refcount) | owned
                if self._refcount.get(b, 0) != mult.get(b, 0)),
            "free_and_owned": len(live & free),
            "counter_drift": (self.blocks_allocated - self.blocks_freed)
            - (self.num_blocks - len(self._free)),
            "unreachable_zero_ref": sum(
                1 for b, rc in self._refcount.items()
                if rc == 0 and b not in cached),
            "stale_tainted": len(self._tainted - owned),
            "trie_defects": idx.audit() if idx is not None else 0,
        }
        # cross-tier keys: every trie host node must point at a live
        # store entry (orphan = promoted-from-under-us bug) and every
        # store entry must be reachable from the trie (leaked = host-
        # side block leak). Payload digests are deliberately NOT
        # re-verified here — a corrupted-but-never-promoted entry is
        # harmless until a fill checks it (that is the fill's job).
        if self.host_tier is not None and idx is not None:
            trie_hids = set(idx.host_ids())
            store_hids = set(self.host_tier.ids())
            report["host_orphans"] = len(trie_hids - store_hids)
            report["host_leaked"] = len(store_hids - trie_hids)
        else:
            report["host_orphans"] = 0
            report["host_leaked"] = 0
        # per-tenant reconciliation (multi-tenant accounting): each
        # tenant's lifetime inserted − removed counters must equal its
        # live trie census (both tiers) — a drift means a removal path
        # skipped attribution and the per-tenant gauges are lying
        if idx is not None:
            census = idx.tenant_census()
            names = set(idx.tenant_inserted) | set(idx.tenant_removed) \
                | set(census)
            report["tenant_drift"] = sum(
                abs(idx.tenant_inserted.get(t, 0)
                    - idx.tenant_removed.get(t, 0) - census.get(t, 0))
                for t in names)
        else:
            report["tenant_drift"] = 0
        if any(report.values()):
            # flight recorder (obs/reqtrace.py): an integrity violation
            # is a postmortem trigger — when armed, ship the full ring
            # + registry snapshot before raising. Lazy import keeps the
            # cache importable without the obs package loaded first.
            from ...obs import reqtrace
            reqtrace.maybe_flight("check_integrity",
                                  extra={"report": dict(report)})
            raise RuntimeError(f"paged cache integrity violated: {report} "
                               f"(tables={len(self._tables)}, "
                               f"cached={len(cached)}, "
                               f"free={len(free)}/{self.num_blocks})")
        return report

    # ------------------------------------------------------- device side
    def write_prefill(self, seq_id, dense_cache, num_tokens: int,
                      batch_index: int = 0):
        """Scatter one sequence's dense prefill cache (the L-tuple of
        (k [B, H, S, D], v) from models.generation.prefill) into its
        allocated blocks. Positions past num_tokens inside the last
        block stay zero (prefill zero-fills past the prompt), matching
        a fresh pool block bit-for-bit. Must only run on PRIVATE tables
        (dense admission never attaches shared blocks — any prefix hit
        is admitted through the chunked path, which writes only the
        uncached suffix positions)."""
        ids = self._tables[seq_id]
        n_blocks, bs = len(ids), self.block_size
        t_pad = n_blocks * bs
        idx = jnp.asarray(ids, jnp.int32)

        def scatter(pool, dense):
            # [H, S, D] -> [S, H, D] -> [n_blocks, bs, H, D]
            blk = dense[batch_index].transpose(1, 0, 2)[:t_pad]
            blk = blk.reshape(n_blocks, bs, self.num_heads, self.head_dim)
            return pool.at[idx].set(blk)

        self.pools = tuple(
            (scatter(kp, kc), scatter(vp, vc))
            for (kp, vp), (kc, vc) in zip(self.pools, dense_cache))

    def prefix_stats(self) -> dict:
        """Prefix-cache telemetry snapshot (engine gauges + load suite
        hit-rate reporting read this)."""
        idx = self.prefix_index
        if idx is None:
            return {"enabled": False, "cached_blocks": 0,
                    "shared_blocks": 0, "evictable_blocks": 0,
                    "hits": 0, "misses": 0, "evictions": 0,
                    "cow_forks": 0, "inserted_blocks": 0,
                    "cached_tokens_total": 0, "prompt_tokens_total": 0,
                    "cached_tokens_ratio": 0.0, "attached_blocks": 0,
                    "host_blocks": 0, "tier_demotions": 0,
                    "promote_hit": 0, "promote_timeout": 0,
                    "promote_integrity": 0, "promote_raced": 0,
                    "tenant_blocks": {}}
        out = {"enabled": True}
        out.update(idx.stats())
        out["tenant_blocks"] = idx.tenant_census()
        out["shared_blocks"] = sum(
            1 for rc in self._refcount.values() if rc >= 2)
        out["evictable_blocks"] = self.num_evictable()
        out["attached_blocks"] = self.blocks_attached
        out["tier_demotions"] = self.tier_demotions
        for k, v in self.tier_promotions.items():
            out[f"promote_{k}"] = v
        return out

    def stats(self) -> dict:
        return {
            "num_blocks": self.num_blocks,
            "block_size": self.block_size,
            "kv_cache_dtype": self.kv_cache_dtype,
            "free": self.num_free(),
            "used": self.num_used(),
            "utilization": self.utilization(),
            "blocks_allocated": self.blocks_allocated,
            "blocks_freed": self.blocks_freed,
            "blocks_attached": self.blocks_attached,
            "alloc_failures": self.alloc_failures,
            "high_water": self.high_water,
        }
