"""Paged KV cache: a fixed block pool + per-sequence block tables.

The dense decode cache in models/generation.py is [B, H, max_seq, D] per
layer — every sequence pays for max_seq_len positions and a batch slot,
so a serving mix of short and long requests wastes most of HBM. Here KV
lives in a per-layer block pool [num_blocks, block_size, H, D]; a
sequence owns an ordered list of block ids (its *block table*) and only
ever holds ceil(len/block_size) blocks. This is the TPU-native shape of
the Ragged Paged Attention kernel (PAPERS.md, arxiv 2604.15464) and of
vLLM's PagedAttention, with the pool as one jnp array per layer so the
ragged decode step (serving/attention.py) gathers it with one
block-table index per layer.

Host/device split: block accounting (free list, tables, lengths,
counters) is plain Python — it feeds the scheduler and never traces.
The pools themselves are jax arrays; `write_prefill` scatters a dense
prefill cache into a sequence's blocks, and the decode step returns
updated pools that the engine assigns back.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

import jax.numpy as jnp

__all__ = ["PagedKVCache", "CacheExhausted"]


class CacheExhausted(RuntimeError):
    """Block pool exhaustion report: who needed how much vs. what's free.

    The scheduler catches this to preempt; anyone else sees a precise
    message instead of a silent mis-allocation."""

    def __init__(self, seq_id, needed: int, free: int, total: int):
        self.seq_id = seq_id
        self.needed = needed
        self.free = free
        self.total = total
        super().__init__(
            f"KV block pool exhausted: seq {seq_id!r} needs {needed} "
            f"block(s), {free}/{total} free")


class PagedKVCache:
    """Fixed-size per-layer KV block pools with alloc/free accounting.

    Pools: L-tuple of (k_pool, v_pool), each [num_blocks, block_size, H,
    D]. Token position p of a sequence lives in its block table entry
    p // block_size at slot offset p % block_size — the identity layout
    that makes the gathered context bitwise-match the dense cache.
    """

    def __init__(self, num_layers: int, num_heads: int, head_dim: int,
                 num_blocks: int, block_size: int, dtype=jnp.float32):
        if num_blocks <= 0 or block_size <= 0:
            raise ValueError("num_blocks and block_size must be positive")
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.num_blocks = num_blocks
        self.block_size = block_size
        shape = (num_blocks, block_size, num_heads, head_dim)
        self.pools: Tuple[Tuple[jnp.ndarray, jnp.ndarray], ...] = tuple(
            (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
            for _ in range(num_layers))
        # ----------------------------------------------- host accounting
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._tables: Dict[object, List[int]] = {}
        self._lens: Dict[object, int] = {}
        # lifetime counters (the zero-leak invariant is
        # blocks_allocated == blocks_freed once every sequence is freed)
        self.blocks_allocated = 0
        self.blocks_freed = 0
        self.alloc_failures = 0
        self.high_water = 0

    # ------------------------------------------------------------ queries
    def num_free(self) -> int:
        return len(self._free)

    def num_used(self) -> int:
        return self.num_blocks - len(self._free)

    def utilization(self) -> float:
        return self.num_used() / self.num_blocks

    def blocks_needed(self, num_tokens: int) -> int:
        return -(-num_tokens // self.block_size)

    def has_seq(self, seq_id) -> bool:
        return seq_id in self._tables

    def seq_len(self, seq_id) -> int:
        return self._lens[seq_id]

    def block_table(self, seq_id) -> List[int]:
        return list(self._tables[seq_id])

    # ------------------------------------------------------- alloc / free
    def _take_blocks(self, seq_id, n: int) -> List[int]:
        if n > len(self._free):
            self.alloc_failures += 1
            raise CacheExhausted(seq_id, n, len(self._free),
                                 self.num_blocks)
        got = [self._free.pop() for _ in range(n)]
        self.blocks_allocated += n
        self.high_water = max(self.high_water, self.num_used())
        return got

    def allocate(self, seq_id, num_tokens: int) -> List[int]:
        """Claim blocks for a new sequence of num_tokens cached tokens
        (prefill). Raises CacheExhausted without side effects."""
        if seq_id in self._tables:
            raise ValueError(f"seq {seq_id!r} already allocated")
        ids = self._take_blocks(seq_id, self.blocks_needed(num_tokens))
        self._tables[seq_id] = ids
        self._lens[seq_id] = num_tokens
        return ids

    def append_slot(self, seq_id) -> Tuple[int, int, int]:
        """Reserve the slot for the sequence's next token; grows the
        block table by one block on a block boundary. Returns
        (block_id, offset, position); raises CacheExhausted (leaving the
        sequence untouched) when a new block is needed but none is free.
        """
        pos = self._lens[seq_id]
        table = self._tables[seq_id]
        if pos % self.block_size == 0 and len(table) * self.block_size \
                <= pos:
            table.extend(self._take_blocks(seq_id, 1))
        self._lens[seq_id] = pos + 1
        block = table[pos // self.block_size]
        return block, pos % self.block_size, pos

    def reserve_slots(self, seq_id, n: int) -> Tuple[int, int, int]:
        """Reserve the slots for the sequence's next n tokens at once —
        the chunk-granular twin of append_slot for the fused k-token
        decode (serving/attention.py fused_decode_chunk). Grows the
        block table by however many blocks the n tokens need in ONE
        atomic _take_blocks claim (CacheExhausted leaves the sequence
        untouched), and advances the length by n. Returns the FIRST
        reserved slot (block_id, offset, position); the device scan
        derives slot j's location as position+j through the identity
        layout. A sequence that finishes mid-chunk simply leaves its
        tail reservation unwritten — the whole table is freed with the
        request, so over-reservation can never leak blocks."""
        if n <= 0:
            raise ValueError(f"reserve_slots needs n >= 1, got {n}")
        pos = self._lens[seq_id]
        table = self._tables[seq_id]
        need = self.blocks_needed(pos + n) - len(table)
        if need > 0:
            table.extend(self._take_blocks(seq_id, need))
        self._lens[seq_id] = pos + n
        return table[pos // self.block_size], pos % self.block_size, pos

    def free(self, seq_id, scrub: bool = False) -> int:
        """Return every block of seq_id to the pool (completion,
        preemption or cancellation). `scrub=True` also zeroes the blocks'
        device contents — mandatory on the quarantine/recovery paths:
        finite stale garbage is erased exactly by the attention length
        mask (masked probs are exact zeros), but NaN survives it
        (0 * NaN = NaN), so a poisoned block must not re-enter the free
        list carrying NaN."""
        ids = self._tables.pop(seq_id)
        self._lens.pop(seq_id)
        self._free.extend(reversed(ids))
        self.blocks_freed += len(ids)
        if scrub:
            self.scrub_blocks(ids)
        return len(ids)

    def scrub_blocks(self, block_ids) -> None:
        """Zero the given blocks in every layer's pools, restoring the
        fresh-block invariant the bitwise-parity contract relies on."""
        if not block_ids:
            return
        idx = jnp.asarray(list(block_ids), jnp.int32)
        self.pools = tuple(
            (kp.at[idx].set(0.0), vp.at[idx].set(0.0))
            for kp, vp in self.pools)

    def check_integrity(self) -> dict:
        """Invariant audit for the chaos harness: the free list and the
        live block tables must exactly partition the pool, with lifetime
        counters consistent. Returns the audit dict; raises RuntimeError
        on any violation (a leaked or double-owned block)."""
        in_tables = [b for ids in self._tables.values() for b in ids]
        owned = set(in_tables)
        free = set(self._free)
        report = {
            "leaked": self.num_blocks - len(owned) - len(free),
            "double_owned": len(in_tables) - len(owned),
            "free_and_owned": len(owned & free),
            "counter_drift": (self.blocks_allocated - self.blocks_freed)
            - len(in_tables),
        }
        if any(report.values()):
            raise RuntimeError(f"paged cache integrity violated: {report} "
                               f"(tables={len(self._tables)}, "
                               f"free={len(free)}/{self.num_blocks})")
        return report

    # ------------------------------------------------------- device side
    def write_prefill(self, seq_id, dense_cache, num_tokens: int,
                      batch_index: int = 0):
        """Scatter one sequence's dense prefill cache (the L-tuple of
        (k [B, H, S, D], v) from models.generation.prefill) into its
        allocated blocks. Positions past num_tokens inside the last
        block stay zero (prefill zero-fills past the prompt), matching
        a fresh pool block bit-for-bit."""
        ids = self._tables[seq_id]
        n_blocks, bs = len(ids), self.block_size
        t_pad = n_blocks * bs
        idx = jnp.asarray(ids, jnp.int32)

        def scatter(pool, dense):
            # [H, S, D] -> [S, H, D] -> [n_blocks, bs, H, D]
            blk = dense[batch_index].transpose(1, 0, 2)[:t_pad]
            blk = blk.reshape(n_blocks, bs, self.num_heads, self.head_dim)
            return pool.at[idx].set(blk)

        self.pools = tuple(
            (scatter(kp, kc), scatter(vp, vc))
            for (kp, vp), (kc, vc) in zip(self.pools, dense_cache))

    def stats(self) -> dict:
        return {
            "num_blocks": self.num_blocks,
            "block_size": self.block_size,
            "free": self.num_free(),
            "used": self.num_used(),
            "utilization": self.utilization(),
            "blocks_allocated": self.blocks_allocated,
            "blocks_freed": self.blocks_freed,
            "alloc_failures": self.alloc_failures,
            "high_water": self.high_water,
        }
