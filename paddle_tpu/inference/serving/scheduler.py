"""Continuous-batching scheduler: FCFS admission, decode interleaving,
preemption under block-pool pressure.

Reference shape: vLLM's scheduler (and the fluid inference executor's
batch dispatch, reference paddle/fluid/inference/), specialised to the
paged cache in serving/paged_cache.py. Per engine step:

1. DECODE — every RUNNING sequence reserves the slot for its next token
   (cache.append_slot), earliest arrival first. If the pool is
   exhausted, the LATEST-arrived running sequence is preempted: its
   blocks are freed and it re-queues at the FRONT of the waiting line
   with prompt := prompt + generated-so-far (recompute-style preemption
   — cheap on TPU where prefill is one fused forward). FCFS priority is
   therefore strict: an earlier request can never be starved by a later
   one.
2. PREFILL/ADMIT — waiting requests are admitted in arrival order while
   the running set is under max_num_seqs, the per-step prefill token
   budget holds (at least one admission may overflow the budget so a
   long prompt is never starved), and the pool can hold their tokens.
   Admission never preempts: running sequences outrank new ones.

The scheduler only does host-side accounting; all device work (prefill
forward, paged decode) belongs to the engine.
"""
from __future__ import annotations

import itertools
from collections import deque
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from .paged_cache import CacheExhausted, PagedKVCache

__all__ = ["SamplingParams", "Request", "RequestState", "Scheduler",
           "SchedulerConfig", "ScheduledBatch"]


@dataclass(frozen=True)
class SamplingParams:
    """Per-request decode knobs (vLLM SamplingParams analogue)."""
    max_tokens: int = 16
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    seed: int = 0


class RequestState:
    WAITING = "waiting"
    RUNNING = "running"
    FINISHED_STOPPED = "finished_stopped"    # sampled eos
    FINISHED_LENGTH = "finished_length"      # hit max_tokens
    CANCELLED = "cancelled"

    FINISHED = (FINISHED_STOPPED, FINISHED_LENGTH, CANCELLED)


_arrival_counter = itertools.count()


@dataclass
class Request:
    request_id: str
    prompt_ids: np.ndarray                   # int32 [T], never mutated
    params: SamplingParams
    output_ids: List[int] = field(default_factory=list)
    state: str = RequestState.WAITING
    arrival: int = field(default_factory=lambda: next(_arrival_counter))
    num_preemptions: int = 0
    # engine bookkeeping
    slot: Optional[tuple] = None             # (block, offset, pos)
    arrival_time: float = 0.0
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None

    def all_token_ids(self) -> np.ndarray:
        """prompt + generated — the effective prompt after preemption."""
        if not self.output_ids:
            return self.prompt_ids
        return np.concatenate(
            [self.prompt_ids, np.asarray(self.output_ids, np.int32)])

    @property
    def last_token(self) -> int:
        return int(self.output_ids[-1]) if self.output_ids \
            else int(self.prompt_ids[-1])

    @property
    def finished(self) -> bool:
        return self.state in RequestState.FINISHED


@dataclass
class SchedulerConfig:
    max_num_seqs: int = 8                    # decode bucket ceiling
    max_prefill_tokens: int = 2048           # per-step admission budget


@dataclass
class ScheduledBatch:
    prefill: List[Request] = field(default_factory=list)
    decode: List[Request] = field(default_factory=list)
    preempted: List[Request] = field(default_factory=list)


class Scheduler:
    def __init__(self, config: SchedulerConfig, cache: PagedKVCache):
        self.config = config
        self.cache = cache
        self.waiting: deque = deque()
        self.running: List[Request] = []
        self.num_preemptions = 0

    # ------------------------------------------------------------- intake
    def add(self, req: Request):
        # a request that can never fit the pool would livelock the
        # preemption loop — refuse it up front, loudly
        worst = len(req.prompt_ids) + req.params.max_tokens
        if self.cache.blocks_needed(worst) > self.cache.num_blocks:
            raise ValueError(
                f"request {req.request_id!r} needs "
                f"{self.cache.blocks_needed(worst)} blocks at its longest"
                f" ({worst} tokens) but the pool only has "
                f"{self.cache.num_blocks}; grow num_blocks or shrink the"
                f" request")
        req.state = RequestState.WAITING
        self.waiting.append(req)

    def cancel(self, request_id: str) -> bool:
        for req in list(self.waiting):
            if req.request_id == request_id:
                self.waiting.remove(req)
                req.state = RequestState.CANCELLED
                return True
        for req in self.running:
            if req.request_id == request_id:
                self.running.remove(req)
                self.cache.free(request_id)
                req.state = RequestState.CANCELLED
                return True
        return False

    def has_unfinished(self) -> bool:
        return bool(self.waiting or self.running)

    # ---------------------------------------------------------- scheduling
    def _preempt(self, victim: Request, batch: ScheduledBatch):
        """Recompute-style preemption: drop the cache, requeue at the
        head of the line with the generated tokens folded into the
        prompt (all_token_ids)."""
        self.running.remove(victim)
        if victim in batch.decode:
            batch.decode.remove(victim)
        self.cache.free(victim.request_id)
        victim.slot = None
        victim.state = RequestState.WAITING
        victim.num_preemptions += 1
        self.num_preemptions += 1
        self.waiting.appendleft(victim)
        batch.preempted.append(victim)

    def schedule(self) -> ScheduledBatch:
        batch = ScheduledBatch()
        # 1. decode slots, earliest arrival first; preempt from the back
        for req in sorted(self.running, key=lambda r: r.arrival):
            if req not in self.running:      # preempted below, this step
                continue
            while True:
                try:
                    req.slot = self.cache.append_slot(req.request_id)
                    batch.decode.append(req)
                    break
                except CacheExhausted:
                    victim = max(self.running, key=lambda r: r.arrival)
                    self._preempt(victim, batch)
                    if victim is req:
                        break                # preempted itself; move on
        # 2. FCFS admission under seq count + prefill token budget
        budget = self.config.max_prefill_tokens
        while self.waiting and len(self.running) \
                < self.config.max_num_seqs:
            req = self.waiting[0]
            tokens = req.all_token_ids()
            if len(tokens) > budget and batch.prefill:
                break                        # budget spent; next step
            try:
                self.cache.allocate(req.request_id, len(tokens))
            except CacheExhausted:
                break                        # never preempt to admit
            self.waiting.popleft()
            req.state = RequestState.RUNNING
            self.running.append(req)
            batch.prefill.append(req)
            budget -= len(tokens)
        return batch

    # ------------------------------------------------------------ results
    def finish(self, req: Request, state: str):
        """Completion path: release blocks, detach from running."""
        self.running.remove(req)
        self.cache.free(req.request_id)
        req.slot = None
        req.state = state
