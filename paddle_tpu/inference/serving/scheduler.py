"""Continuous-batching scheduler: FCFS admission, decode interleaving,
preemption under block-pool pressure.

Reference shape: vLLM's scheduler (and the fluid inference executor's
batch dispatch, reference paddle/fluid/inference/), specialised to the
paged cache in serving/paged_cache.py. Per engine step:

1. DECODE — every RUNNING sequence reserves the slots for its next
   decode chunk (cache.reserve_slots, up to decode_chunk_size tokens),
   earliest arrival first. If the pool is
   exhausted, the LATEST-arrived running sequence is preempted: its
   blocks are freed and it re-queues at the FRONT of the waiting line
   with prompt := prompt + generated-so-far (recompute-style preemption
   — cheap on TPU where prefill is one fused forward). FCFS priority is
   therefore strict: an earlier request can never be starved by a later
   one.
2. PREFILL/ADMIT — waiting requests are admitted in arrival order while
   the running set is under max_num_seqs, the per-step prefill token
   budget holds (at least one admission may overflow the budget so a
   long prompt is never starved), the pool can hold their tokens, AND
   post-admission occupancy stays under `cache_high_watermark` — the
   backpressure valve that keeps decode headroom so admission can never
   strand running sequences into a preemption storm. Admission never
   preempts: running sequences outrank new ones.

Robustness surface (the hardened-serving layer):

- the waiting queue is bounded (`max_waiting`): a full queue either
  rejects new arrivals with `EngineOverloaded` (policy 'reject') or
  evicts the oldest waiting request (policy 'shed_oldest');
- queued requests expire (`expire_waiting`) once their `queue_ttl_s` /
  `deadline_s` elapses, and running requests past `deadline_s` are
  reported by `overdue_running` for the engine to abort at the step
  boundary;
- every requeue (preemption, engine crash recovery) goes through
  `_requeue`, an arrival-ordered insert, so a repeatedly-preempted
  request keeps its FCFS priority and can never be starved by later
  arrivals.

The scheduler only does host-side accounting; all device work (prefill
forward, paged decode) belongs to the engine.
"""
from __future__ import annotations

import itertools
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from ...analysis import holds_lock
from ...obs import reqtrace
from .paged_cache import CacheExhausted, PagedKVCache

__all__ = ["EngineOverloaded", "SamplingParams", "Request", "RequestState",
           "Scheduler", "SchedulerConfig", "ScheduledBatch",
           "record_promotion_events"]

ADMISSION_POLICIES = ("reject", "shed_oldest")


def record_promotion_events(tid: str, request_id: str,
                            promo: Optional[dict]) -> None:
    """Translate one `PagedKVCache.ensure_promoted` result into reqtrace
    events (shared by the engine's enqueue-time prefetch and the
    scheduler's admission-time retry). A partial promotion emits BOTH a
    `promote` (for the blocks that landed) and a `promote_abort` (for
    the failure that stopped the run); `promo is None` means the host
    run vanished between probe and promotion — raced, nothing landed.
    The causality checker requires every tiered prefix_match to be
    resolved by one of these before the request may emit."""
    if promo is None:
        reqtrace.record("promote_abort", tid, request_id,
                        outcome="raced", promoted=0)
        return
    if promo["promoted_blocks"]:
        reqtrace.record("promote", tid, request_id,
                        blocks=promo["promoted_blocks"],
                        tokens=promo["promoted_tokens"],
                        seconds=round(promo["seconds"], 6))
    if promo["outcomes"] and promo["outcomes"][-1] != "hit":
        reqtrace.record("promote_abort", tid, request_id,
                        outcome=promo["outcomes"][-1],
                        promoted=promo["promoted_blocks"])


class EngineOverloaded(RuntimeError):
    """Admission refused: the bounded waiting queue is full (policy
    'reject'). Carries the queue depth and, when the raiser can estimate
    one, a `retry_after_s` hint — the ReplicaSet router fills it from
    its observed drain rate so clients can back off instead of hammering
    a saturated fleet."""

    def __init__(self, request_id, depth: int, limit: int,
                 retry_after_s: Optional[float] = None):
        self.request_id = request_id
        self.depth = depth
        self.limit = limit
        self.retry_after_s = retry_after_s
        hint = "" if retry_after_s is None \
            else f"; retry after ~{retry_after_s:.2f}s"
        super().__init__(
            f"engine overloaded: request {request_id!r} rejected, waiting "
            f"queue at {depth}/{limit} (admission_policy='reject'; use "
            f"'shed_oldest' to evict instead){hint}")


@dataclass(frozen=True)
class SamplingParams:
    """Per-request decode knobs (vLLM SamplingParams analogue).

    deadline_s: wall-clock budget for the WHOLE request (queue + decode),
        measured from arrival; the engine aborts an overdue request at
        the next step boundary with finish_reason='timeout'.
    queue_ttl_s: how long the request may sit in the waiting queue before
        it expires unserved (finish_reason='timeout'); unlike deadline_s
        it only guards queueing, so an admitted request never re-arms it.
    tenant: which tenant's fair share this request spends (serving/
        tenancy.py). Resolved against the TenantRegistry when the stack
        is built with one; ignored (and left at "default") otherwise.
    model: which model this request wants (serving/deploy.ModelRegistry).
        Resolved by the ReplicaSet front-end against the registry when
        the fleet is built with one — the request is admitted to a
        replica pool serving that model's currently-routed revision.
        Ignored (and left at "default") on single-model stacks.
    """
    max_tokens: int = 16
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    seed: int = 0
    deadline_s: Optional[float] = None
    queue_ttl_s: Optional[float] = None
    tenant: str = "default"
    model: str = "default"


class RequestState:
    WAITING = "waiting"
    RUNNING = "running"
    FINISHED_STOPPED = "finished_stopped"    # sampled eos
    FINISHED_LENGTH = "finished_length"      # hit max_tokens
    FINISHED_TIMEOUT = "finished_timeout"    # deadline_s / queue_ttl_s hit
    FINISHED_SHED = "finished_shed"          # evicted by admission control
    FINISHED_ERROR = "finished_error"        # quarantined by the watchdog
    CANCELLED = "cancelled"
    MIGRATED = "migrated"                    # handed off to another engine

    # terminal FOR THIS ENGINE: a MIGRATED request lives on at its
    # destination (the router's record tracks it there), but this
    # engine will never step it again
    FINISHED = (FINISHED_STOPPED, FINISHED_LENGTH, FINISHED_TIMEOUT,
                FINISHED_SHED, FINISHED_ERROR, CANCELLED, MIGRATED)


_arrival_counter = itertools.count()


@dataclass
class Request:
    request_id: str
    prompt_ids: np.ndarray                   # int32 [T], never mutated
    params: SamplingParams
    output_ids: List[int] = field(default_factory=list)
    state: str = RequestState.WAITING
    arrival: int = field(default_factory=lambda: next(_arrival_counter))
    num_preemptions: int = 0
    # engine bookkeeping
    slot: Optional[tuple] = None             # (block, offset, pos)
    arrival_time: float = 0.0
    first_token_time: Optional[float] = None
    last_token_time: Optional[float] = None  # previous emit (token gap)
    finish_time: Optional[float] = None
    # chunked prefill (docs/serving.md "Ragged paged attention and
    # chunked prefill"): a long prompt admitted chunked feeds the fused
    # decode scan k prompt tokens per step instead of running a dense
    # prefill dispatch. pf_target is len(all_token_ids()) at admission;
    # prefill_pos advances per good chunk; the row is mid-prefill while
    # prefill_pos < pf_target. Both reset on every requeue (recompute
    # discipline: re-admission re-prefills from the token log).
    pf_target: int = 0
    prefill_pos: int = 0
    # per-request causal tracing (obs/reqtrace.py): stable id minted at
    # admission (router for fleet runs, engine for standalone) that
    # survives preemption, requeue, and cross-engine failover
    trace_id: Optional[str] = None

    @property
    def tid(self) -> str:
        """Trace id for reqtrace events (request_id for bare Requests
        built directly in tests)."""
        return self.trace_id or self.request_id

    def all_token_ids(self) -> np.ndarray:
        """prompt + generated — the effective prompt after preemption."""
        if not self.output_ids:
            return self.prompt_ids
        return np.concatenate(
            [self.prompt_ids, np.asarray(self.output_ids, np.int32)])

    @property
    def last_token(self) -> int:
        return int(self.output_ids[-1]) if self.output_ids \
            else int(self.prompt_ids[-1])

    @property
    def finished(self) -> bool:
        return self.state in RequestState.FINISHED


@dataclass
class SchedulerConfig:
    max_num_seqs: int = 8                    # decode bucket ceiling
    max_prefill_tokens: int = 2048           # per-step admission budget
    # static-cost admission: an object with .cost(num_tokens) and
    # .budget(max_prefill_tokens) (analysis/jaxplan.PrefillCostModel).
    # When set, each admission is charged its modelled prefill FLOPs
    # (quadratic in prompt length — attention) against
    # budget(max_prefill_tokens), so one long prompt pays super-linearly
    # instead of the same per-token rate as many short ones. None keeps
    # the flat token count.
    prefill_cost_model: Optional[object] = None
    # tokens decoded per fused device chunk: each scheduled decode
    # reserves min(decode_chunk_size, tokens-remaining) cache slots so
    # the fused scan (serving/attention.py) can write k tokens without
    # a host round-trip. 1 reproduces the classic one-token step.
    decode_chunk_size: int = 1
    # ------------------------------ admission control / backpressure
    max_waiting: Optional[int] = None        # waiting-queue bound (None=∞)
    admission_policy: str = "reject"         # 'reject' | 'shed_oldest'
    # pause prefill admission once post-admission pool occupancy would
    # exceed this fraction — reserves decode headroom so CacheExhausted
    # cannot strand running sequences. 1.0 disables the watermark.
    cache_high_watermark: float = 1.0
    # chunked prefill: prompts STRICTLY longer than this are admitted
    # chunked — they join the running set with an empty block table and
    # consume decode_chunk_size prompt tokens per step inside the fused
    # decode scan, so a long prompt never monopolises a step. Admission
    # charges only the first chunk against the prefill budget (later
    # chunks are inherently rate-limited at k tokens/step). None
    # disables chunking (every prompt takes the dense prefill path).
    prefill_chunk_threshold: Optional[int] = None
    # multi-tenant WFQ (serving/tenancy.TenantRegistry). When set, the
    # admission head is chosen by weighted fair queuing over per-tenant
    # virtual finish times priced in jaxplan FLOPs (full prompt cost, so
    # one 8k prompt charges its quadratic cost against its tenant's
    # share), with strict FCFS inside each tenant; deadline-aware early
    # reject also arms. None (the default) keeps the historical global
    # FCFS path untouched, and a single active tenant degenerates WFQ to
    # exactly that path (pinned by tests/test_tenancy.py).
    tenants: Optional[object] = None


@dataclass
class ScheduledBatch:
    prefill: List[Request] = field(default_factory=list)
    decode: List[Request] = field(default_factory=list)
    preempted: List[Request] = field(default_factory=list)


class Scheduler:
    """FCFS scheduler (module docstring). Thread contract (checked by
    ptlint PT-C001 via _GUARDED_BY): the queue/running structures are
    shared between the engine's step loop and intake threads and are
    only touched under self._lock. Public methods take the lock (RLock:
    safe to call from the engine's own locked frame — lock order is
    engine → scheduler, never the reverse); _requeue/_preempt are
    @holds_lock("_lock") helpers called from schedule()/
    requeue_for_recovery's locked frames."""

    _GUARDED_BY = {
        "waiting": "_lock",
        "running": "_lock",
        "num_preemptions": "_lock",
        "watermark_holds": "_lock",
        "_vtime": "_lock",
        "_vfinish": "_lock",
        "_wfq_weights": "_lock",
        "_weights_version": "_lock",
        "_step_ewma": "_lock",
        "deadline_rejects": "_lock",
    }

    def __init__(self, config: SchedulerConfig, cache: PagedKVCache):
        if config.admission_policy not in ADMISSION_POLICIES:
            raise ValueError(
                f"admission_policy must be one of {ADMISSION_POLICIES}, "
                f"got {config.admission_policy!r}")
        if not 0.0 < config.cache_high_watermark <= 1.0:
            raise ValueError(
                f"cache_high_watermark must be in (0, 1], got "
                f"{config.cache_high_watermark}")
        self.config = config
        self.cache = cache
        self._lock = threading.RLock()
        self.waiting: deque = deque()
        self.running: List[Request] = []
        self.num_preemptions = 0
        self.watermark_holds = 0             # admissions paused by watermark
        # multi-tenant WFQ state (inert when config.tenants is None):
        # start-time fair queuing over per-tenant virtual finish times.
        # _vtime is the system virtual clock (last admission's virtual
        # start); _vfinish[t] the tenant's last virtual finish. Prices
        # are jaxplan FLOPs of the FULL prompt (quadratic), weights the
        # registry's effective WFQ weights, snapshotted by version.
        self.tenants = config.tenants
        self._vtime = 0.0
        self._vfinish: dict = {}
        self._wfq_weights: dict = {}
        self._weights_version = -1
        # measured service rate for deadline-aware early reject: EWMA of
        # engine step wall seconds (note_step_seconds). 0.0 until the
        # first step — the estimator abstains rather than guess.
        self._step_ewma = 0.0
        self.deadline_rejects = 0            # statically-hopeless refusals

    # ------------------------------------------------------------- intake
    def add(self, req: Request) -> List[Request]:
        """Queue a request; returns the waiting requests shed to make
        room (empty normally). Raises EngineOverloaded when the bounded
        queue is full under the 'reject' policy."""
        # a request that can never fit the pool would livelock the
        # preemption loop — refuse it up front, loudly
        worst = len(req.prompt_ids) + req.params.max_tokens
        if self.cache.blocks_needed(worst) > self.cache.num_blocks:
            raise ValueError(
                f"request {req.request_id!r} needs "
                f"{self.cache.blocks_needed(worst)} blocks at its longest"
                f" ({worst} tokens) but the pool only has "
                f"{self.cache.num_blocks}; grow num_blocks or shrink the"
                f" request")
        with self._lock:
            # deadline-aware early reject (multi-tenant stacks only):
            # refuse a request that statically cannot meet its deadline
            # at the measured service rate BEFORE it burns prefill —
            # checked ahead of shed_oldest so a doomed arrival never
            # evicts viable queued work to make room for itself
            self._deadline_early_reject(req)
            shed: List[Request] = []
            limit = self.config.max_waiting
            if limit is not None:
                if self.config.admission_policy == "reject":
                    if len(self.waiting) >= limit:
                        raise EngineOverloaded(req.request_id,
                                               len(self.waiting), limit)
                else:                        # shed_oldest
                    while len(self.waiting) >= limit:
                        victim = self.waiting.popleft()
                        victim.state = RequestState.FINISHED_SHED
                        shed.append(victim)
            req.state = RequestState.WAITING
            self.waiting.append(req)
            self._note_tenant(req)
            return shed

    def readmit(self, req: Request):
        """Failover re-admission (docs/serving.md "Multi-replica
        serving"): insert a request recovered from a failed replica into
        THIS scheduler's waiting queue at its ORIGINAL arrival position —
        the same arrival-ordered requeue discipline `_requeue` applies to
        preemption and crash recovery, but crossing engines. Bypasses
        `max_waiting` deliberately: the bound is backpressure against NEW
        arrivals, and bouncing a recovered in-flight request would break
        the zero-lost-request guarantee (the transient overshoot drains
        at FCFS priority)."""
        worst = len(req.prompt_ids) + req.params.max_tokens
        if self.cache.blocks_needed(worst) > self.cache.num_blocks:
            raise ValueError(
                f"request {req.request_id!r} needs "
                f"{self.cache.blocks_needed(worst)} blocks at its longest"
                f" ({worst} tokens) but the pool only has "
                f"{self.cache.num_blocks}")
        with self._lock:
            self._requeue(req)
            self._note_tenant(req)

    # ----------------------------------------------------- block migration
    def adopt_running(self, req: Request):
        """Migration admission (serving/migration.py): install an
        in-flight request straight into the RUNNING set — its KV blocks
        were already imported into this scheduler's cache
        (PagedKVCache.import_blocks), so unlike `readmit` there is
        nothing to re-prefill: the next schedule() reserves its decode
        chunk and the fused scan continues exactly where the source
        stopped. Bypasses max_waiting for the same reason readmit does
        (the bound is backpressure against NEW arrivals)."""
        worst = len(req.prompt_ids) + req.params.max_tokens
        if self.cache.blocks_needed(worst) > self.cache.num_blocks:
            raise ValueError(
                f"request {req.request_id!r} needs "
                f"{self.cache.blocks_needed(worst)} blocks at its longest"
                f" ({worst} tokens) but the pool only has "
                f"{self.cache.num_blocks}")
        if not self.cache.has_seq(req.request_id):
            raise ValueError(
                f"adopt_running: seq {req.request_id!r} has no imported "
                f"cache state — import_blocks must run first")
        with self._lock:
            req.slot = None
            req.state = RequestState.RUNNING
            self.running.append(req)
            self._note_tenant(req)

    def release_running(self, req: Request):
        """Migration release (source side): detach a RUNNING request
        whose KV payload has been committed at the destination. Frees
        its blocks through the normal completion path — `cache_tokens`
        registers the clean prefix, so the SOURCE trie keeps (or gains)
        the entries this sequence wrote and shared blocks just drop one
        reference. No terminal output, no finish event: the request is
        still live, it just lives somewhere else now."""
        with self._lock:
            self.running.remove(req)
            self.cache.free(req.request_id,
                            cache_tokens=self._cache_tokens(req))
            req.slot = None
            req.state = RequestState.MIGRATED

    def remove_waiting(self, request_id: str) -> Optional[Request]:
        """Pull a WAITING request out of the queue without a terminal
        state (drain evacuation: queued work has no KV to migrate, so
        the router re-dispatches it to another replica from its token
        log). Returns the request, or None when it is not waiting."""
        with self._lock:
            for req in list(self.waiting):
                if req.request_id == request_id:
                    self.waiting.remove(req)
                    req.state = RequestState.MIGRATED
                    return req
            return None

    def abort_adopted(self, req: Request):
        """Roll back an adopt_running whose migration failed before the
        source released (kill-mid-migration): drop the request from the
        RUNNING set and free its imported blocks WITHOUT registering a
        prefix — the destination never decoded a token, and the victim
        re-prefills elsewhere from the router's token log."""
        with self._lock:
            if req in self.running:
                self.running.remove(req)
            if self.cache.has_seq(req.request_id):
                self.cache.free(req.request_id)
            req.slot = None
            req.state = RequestState.MIGRATED

    def running_requests(self) -> List[Request]:
        """Stable snapshot of the RUNNING set (migration coordinator
        scans it at step boundaries)."""
        with self._lock:
            return list(self.running)

    def shed_oldest(self) -> Optional[Request]:
        """Evict the oldest waiting request (router-level 'shed_oldest'
        spanning replicas: the ReplicaSet finds the globally-oldest
        waiting request and sheds it from whichever replica holds it).
        Returns it with state FINISHED_SHED, or None when nothing
        waits."""
        with self._lock:
            if not self.waiting:
                return None
            victim = self.waiting.popleft()
            victim.state = RequestState.FINISHED_SHED
            return victim

    def oldest_waiting_arrival(self) -> Optional[int]:
        """Arrival ticket of the head of the waiting line (None when
        empty) — the router's cross-replica shed_oldest scans these."""
        with self._lock:
            return self.waiting[0].arrival if self.waiting else None

    def backlog(self) -> dict:
        """Load snapshot for the router's free-block balancer:
        `waiting` (queue depth), `block_demand` (worst-case ADDITIONAL
        blocks needed to finish every admitted and queued request — the
        growth headroom this engine still owes), and `prefill_cost`
        (modelled cost of the re-prefills waiting in line, priced by the
        jaxplan cost model when configured, flat tokens otherwise)."""
        with self._lock:
            cost_model = self.config.prefill_cost_model
            demand = 0
            cost = 0.0
            for req in self.waiting:
                tokens = len(req.prompt_ids) + len(req.output_ids)
                remaining = max(0, req.params.max_tokens
                                - len(req.output_ids))
                demand += self.cache.blocks_needed(tokens + remaining)
                # ptlint: disable=PT-C004  admission cost model: pure
                # arithmetic over committed-plan coefficients (jaxplan),
                # contractually non-blocking and non-reentrant
                cost += cost_model.cost(tokens) if cost_model else tokens
            for req in self.running:
                tokens = len(req.prompt_ids) + len(req.output_ids)
                remaining = max(0, req.params.max_tokens
                                - len(req.output_ids))
                held = len(self.cache.block_table(req.request_id)) \
                    if self.cache.has_seq(req.request_id) else 0
                demand += max(
                    0, self.cache.blocks_needed(tokens + remaining) - held)
            return {"waiting": len(self.waiting),
                    "block_demand": demand,
                    "prefill_cost": cost}

    def cancel(self, request_id: str) -> bool:
        with self._lock:
            for req in list(self.waiting):
                if req.request_id == request_id:
                    self.waiting.remove(req)
                    req.state = RequestState.CANCELLED
                    return True
            for req in self.running:
                if req.request_id == request_id:
                    self.running.remove(req)
                    self.cache.free(request_id,
                                    cache_tokens=self._cache_tokens(req))
                    req.state = RequestState.CANCELLED
                    return True
            return False

    def has_unfinished(self) -> bool:
        with self._lock:
            return bool(self.waiting or self.running)

    def num_waiting(self) -> int:
        """Queue depth snapshot (the engine's step telemetry reads this
        instead of reaching into self.waiting unlocked)."""
        with self._lock:
            return len(self.waiting)

    def num_running(self) -> int:
        """Running-set size snapshot (same telemetry contract as
        num_waiting: the engine's step gauges read it locked)."""
        with self._lock:
            return len(self.running)

    # ----------------------------------------------------- expiry / abort
    def expire_waiting(self, now: float) -> List[Request]:
        """Remove waiting requests whose queue_ttl_s or deadline_s has
        elapsed (both measured from arrival_time). Returns them with
        state FINISHED_TIMEOUT; the engine emits the terminal outputs."""
        with self._lock:
            expired = []
            for req in list(self.waiting):
                p = req.params
                age = now - req.arrival_time
                if (p.queue_ttl_s is not None and age > p.queue_ttl_s) \
                        or (p.deadline_s is not None
                            and age > p.deadline_s):
                    self.waiting.remove(req)
                    req.state = RequestState.FINISHED_TIMEOUT
                    expired.append(req)
            return expired

    def overdue_running(self, now: float) -> List[Request]:
        """Running requests past their deadline_s; the engine aborts them
        (finish + terminal output) at the step boundary."""
        with self._lock:
            return [r for r in self.running
                    if r.params.deadline_s is not None
                    and (now - r.arrival_time) > r.params.deadline_s]

    # ---------------------------------------------------------- scheduling
    def _cache_tokens(self, req: Request):
        """Tokens whose KV the sequence has actually WRITTEN — what a
        release may index into the prefix trie (docs/serving.md "Prefix
        caching"). Mid-prefill that is the committed prefill_pos; after
        prefill it is everything except the last sampled token, which
        is emitted but never fed back (its KV slot is only written by
        the step that would have sampled its successor). None when the
        prefix cache is off."""
        if self.cache.prefix_index is None:
            return None
        toks = req.all_token_ids()
        if req.pf_target and req.prefill_pos < req.pf_target:
            valid = req.prefill_pos
        else:
            valid = len(req.prompt_ids) + max(0, len(req.output_ids) - 1)
        return toks[:valid]

    @holds_lock("_lock")
    def _requeue(self, req: Request):
        """Arrival-ordered insert into the waiting queue. Preemption and
        crash recovery both requeue through here so a bumped request
        keeps its ORIGINAL FCFS priority — appendleft would invert the
        relative order of a multi-request requeue and let later arrivals
        starve a repeatedly-preempted earlier one."""
        req.slot = None
        req.state = RequestState.WAITING
        # chunked-prefill progress is cache state; a requeue drops the
        # cache, so re-admission must re-prefill from the token log
        req.pf_target = 0
        req.prefill_pos = 0
        for i, w in enumerate(self.waiting):
            if w.arrival > req.arrival:
                self.waiting.insert(i, req)
                return
        self.waiting.append(req)

    @holds_lock("_lock")
    def _preempt(self, victim: Request, batch: ScheduledBatch):
        """Recompute-style preemption: drop the cache, requeue in arrival
        order with the generated tokens folded into the prompt
        (all_token_ids)."""
        self.running.remove(victim)
        if victim in batch.decode:
            batch.decode.remove(victim)
        # the victim's written KV stays matchable: its re-admission (or
        # any template sibling) re-attaches the cached blocks instead
        # of re-prefilling from token zero
        self.cache.free(victim.request_id,
                        cache_tokens=self._cache_tokens(victim))
        victim.num_preemptions += 1
        self.num_preemptions += 1
        self._requeue(victim)
        batch.preempted.append(victim)
        reqtrace.record("preempt", victim.tid, victim.request_id,
                        arrival=victim.arrival,
                        num_preemptions=victim.num_preemptions,
                        tokens_kept=len(victim.output_ids))

    def requeue_for_recovery(self, req: Request):
        """Crash-recovery rebuild: drop the (possibly tainted) cache
        state of a surviving RUNNING request and requeue it in arrival
        order; the next admission re-prefills it from its token log
        (all_token_ids), which the parity pins prove bitwise-equivalent
        to having never been disturbed. Freed blocks are scrubbed — a
        poisoned step may have scattered NaN into them, and NaN (unlike
        finite garbage) survives the attention length-mask via 0*NaN."""
        with self._lock:
            self.running.remove(req)
            self.cache.free(req.request_id, scrub=True)
            self._requeue(req)
            reqtrace.record("requeue", req.tid, req.request_id,
                            reason="recovery", arrival=req.arrival,
                            tokens_kept=len(req.output_ids))

    # ------------------------------------------------- multi-tenant WFQ
    def note_step_seconds(self, dt: float) -> None:
        """Engine step-time feed for the deadline early-reject service
        rate (EWMA; alpha favours recency so the estimate tracks load
        shifts within a few steps)."""
        with self._lock:
            self._step_ewma = dt if self._step_ewma == 0.0 \
                else 0.8 * self._step_ewma + 0.2 * dt

    def waiting_by_tenant(self) -> dict:
        """Queue depth per tenant (autoscaler pressure signal)."""
        with self._lock:
            out: dict = {}
            for req in self.waiting:
                t = req.params.tenant
                out[t] = out.get(t, 0) + 1
            return out

    @holds_lock("_lock")
    def _note_tenant(self, req: Request) -> None:
        """Tenant bookkeeping on intake (inert without a registry):
        refresh the weight snapshots and tag the sequence's tenant into
        the cache so prefix registration stamps trie nodes."""
        if self.tenants is None:
            return
        self._refresh_weights()
        self.cache.note_seq_tenant(req.request_id, req.params.tenant)

    @holds_lock("_lock")
    def _refresh_weights(self) -> None:
        """Re-snapshot registry weights when its version moved; also
        pushes prefix-share weights into the cache's weighted-eviction
        view so both stay coherent with one registry version."""
        reg = self.tenants
        if reg is None or reg.version == self._weights_version:
            return
        # ptlint: disable=PT-C004  TenantRegistry sits BELOW Scheduler
        # in lockgraph.json; wfq_weights() is a locked read, no re-entry
        self._wfq_weights = reg.wfq_weights()
        self._weights_version = reg.version
        # ptlint: disable=PT-C004  same registry read as above
        self.cache.set_tenant_weights(reg.prefix_shares())

    @holds_lock("_lock")
    def _full_price(self, req: Request) -> float:
        """WFQ price of a request: jaxplan FLOPs of its FULL effective
        prompt (quadratic — an 8k prompt charges its attention cost, not
        one ticket), flat tokens without a cost model. Deliberately NOT
        the per-step admission price (which sees only the first chunk /
        uncached suffix): fairness is about total work commanded."""
        n = len(req.prompt_ids) + len(req.output_ids)
        cost_model = self.config.prefill_cost_model
        # ptlint: disable=PT-C004  admission cost model (see backlog())
        return float(cost_model.cost(n)) if cost_model else float(n)

    @holds_lock("_lock")
    def _deadline_early_reject(self, req: Request) -> None:
        """Static admission check: at the measured service rate, can
        this request's prefill even START before its deadline? The bound
        is optimistic (queue-ahead cost at full budget throughput, zero
        decode time), so a rejection is a certainty, not a guess; raises
        EngineOverloaded with a retry_after_s hint sized to the excess.
        Abstains entirely when there is no registry (single-tenant
        stacks keep their historical semantics: overdue work is expired
        by TTL, not refused at the door) or no measured rate yet."""
        if self.tenants is None or self._step_ewma <= 0.0:
            return
        deadline = req.params.deadline_s
        if deadline is None:
            # ptlint: disable=PT-C004  TenantRegistry sits BELOW
            # Scheduler in lockgraph.json; resolve() is a locked read
            cfg = self.tenants.resolve(req.params.tenant)
            deadline = cfg.deadline_slo_s
        if deadline is None:
            return
        cost_model = self.config.prefill_cost_model
        # ptlint: disable=PT-C004  admission cost model (see backlog())
        budget = cost_model.budget(self.config.max_prefill_tokens) \
            if cost_model else float(self.config.max_prefill_tokens)
        ahead = sum(self._full_price(w) for w in self.waiting)
        own = self._full_price(req)
        steps = max(1.0, (ahead + own) / max(budget, 1.0))
        est = steps * self._step_ewma
        if est <= deadline:
            return
        self.deadline_rejects += 1
        retry = round(est - deadline + self._step_ewma, 3)
        reqtrace.record("rejected", req.tid, req.request_id,
                        reason="deadline", deadline_s=deadline,
                        estimate_s=round(est, 3),
                        tenant=req.params.tenant)
        raise EngineOverloaded(req.request_id, len(self.waiting),
                               self.config.max_waiting or 0,
                               retry_after_s=retry)

    @holds_lock("_lock")
    def _select_waiting(self) -> Request:
        """WFQ head selection: per-tenant FCFS heads (first waiting
        request of each tenant, in arrival order — intra-tenant order is
        inviolable), ranked by virtual finish time F = max(vtime,
        vfinish[tenant]) + price/weight, ties broken by arrival ticket.
        With zero or one active tenant this returns self.waiting[0]
        unconditionally — the exact object the historical FCFS path
        would take, so single-tenant scheduling stays bitwise-identical."""
        heads: dict = {}
        for req in self.waiting:
            t = req.params.tenant
            if t not in heads:
                heads[t] = req
        if len(heads) <= 1:
            return self.waiting[0]
        self._refresh_weights()
        best = None
        best_key = None
        for t, req in heads.items():
            w = max(self._wfq_weights.get(t, 1.0), 1e-9)
            start = max(self._vtime, self._vfinish.get(t, 0.0))
            key = (start + self._full_price(req) / w, req.arrival)
            if best is None or key < best_key:
                best, best_key = req, key
        return best

    @holds_lock("_lock")
    def _dequeue(self, req: Request) -> None:
        """Remove the admitted request from the waiting queue (the WFQ
        head need not be the deque head) and advance the virtual clock:
        the tenant's vfinish absorbs the full price over its weight, and
        vtime moves to the admission's virtual start so idle tenants
        re-enter at the current clock instead of a stale past."""
        if self.waiting and self.waiting[0] is req:
            self.waiting.popleft()
        else:
            self.waiting.remove(req)
        if self.tenants is None:
            return
        self._refresh_weights()
        t = req.params.tenant
        w = max(self._wfq_weights.get(t, 1.0), 1e-9)
        start = max(self._vtime, self._vfinish.get(t, 0.0))
        self._vfinish[t] = start + self._full_price(req) / w
        self._vtime = start

    def schedule(self) -> ScheduledBatch:
        with self._lock:
            return self._schedule_locked()

    @holds_lock("_lock")
    def _schedule_locked(self) -> ScheduledBatch:
        batch = ScheduledBatch()
        # 1. decode slots, earliest arrival first; preempt from the back.
        # Each sequence reserves its whole next CHUNK (up to
        # decode_chunk_size tokens, capped by its remaining budget) so
        # the fused device scan never needs a mid-chunk allocation; a
        # sequence that stops early (EOS) frees the unwritten tail with
        # the rest of its table.
        chunk = max(1, self.config.decode_chunk_size)
        for req in sorted(self.running, key=lambda r: r.arrival):
            if req not in self.running:      # preempted below, this step
                continue
            remaining = req.params.max_tokens - len(req.output_ids)
            if req.prefill_pos < req.pf_target:
                # mid-prefill row: the chunk consumes up to pf_rem fed
                # prompt tokens, then may sample/decode for the rest of
                # its k trips — every consumed trip writes one KV slot
                pf_rem = req.pf_target - req.prefill_pos
                n = min(chunk, pf_rem + max(0, remaining))
            else:
                n = min(chunk, remaining)
            n = max(1, n)
            while True:
                try:
                    req.slot = self.cache.reserve_slots(req.request_id, n)
                    batch.decode.append(req)
                    break
                except CacheExhausted:
                    victim = max(self.running, key=lambda r: r.arrival)
                    self._preempt(victim, batch)
                    if victim is req:
                        break                # preempted itself; move on
        # 2. FCFS admission under seq count + prefill cost budget +
        #    the cache occupancy high-watermark (decode headroom).
        #    With a cost model the budget is FLOPs (each request priced
        #    by the static model); without, the flat token count. Either
        #    way the head of line may overflow an untouched budget so a
        #    maximal request cannot starve.
        cost_model = self.config.prefill_cost_model
        # ptlint: disable=PT-C004  admission cost model (see backlog())
        budget = cost_model.budget(self.config.max_prefill_tokens) \
            if cost_model else self.config.max_prefill_tokens
        mark = self.config.cache_high_watermark
        thr = self.config.prefill_chunk_threshold
        admitted = 0
        while self.waiting and len(self.running) \
                < self.config.max_num_seqs:
            req = self.waiting[0] if self.tenants is None \
                else self._select_waiting()
            tokens = req.all_token_ids()
            # prefix caching: probe the longest cached prefix first —
            # a hit is admitted CHUNKED regardless of length (the
            # chunked path writes only uncached suffix positions, so
            # shared blocks are never touched; dense write_prefill
            # would scatter the WHOLE table), and admission is priced
            # on the uncached tokens only: a fully-templated prompt
            # admits at near-zero cost
            cached_probe = self.cache.match_len(tokens)
            # tier-aware pricing: a host-resident run behind the device
            # match is promotable before prefill — promote it NOW (the
            # admission-time retry of the engine's enqueue prefetch;
            # covers entries a timed-out promotion left behind) and
            # re-probe so the price reflects what actually landed
            host_probe = self.cache.host_match_len(tokens)
            if host_probe:
                reqtrace.record(
                    "prefix_match", req.tid, req.request_id,
                    cached_tokens=cached_probe, host_tokens=host_probe,
                    probe=cached_probe)
                promo = self.cache.ensure_promoted(tokens)
                record_promotion_events(req.tid, req.request_id, promo)
                cached_probe = self.cache.match_len(tokens)
            uncached = len(tokens) - cached_probe
            # chunked prefill: a long prompt is admitted with an empty
            # table and fed to the fused decode scan k tokens per step —
            # it is priced (and block-checked) per chunk, not per prompt
            chunked = (thr is not None and len(tokens) > thr) \
                or cached_probe > 0 or host_probe > 0
            eff = min(chunk, uncached) if chunked else len(tokens)
            # ptlint: disable=PT-C004  admission cost model (see backlog())
            price = cost_model.cost(eff) if cost_model else eff
            if price > budget and admitted:
                break                        # budget spent; next step
            needed = self.cache.blocks_needed(eff)
            used = self.cache.num_used() - self.cache.num_evictable()
            if (used + needed) > mark * self.cache.num_blocks \
                    and self.running:
                # above the watermark with live decodes: hold admission
                # so their growth can't hit CacheExhausted (evictable
                # cached blocks count as headroom — they reclaim on
                # demand). With nothing running there is nothing to
                # strand — admit (the head alone may legitimately
                # exceed the watermark).
                self.watermark_holds += 1
                break
            if chunked:
                remaining = max(0, req.params.max_tokens
                                - len(req.output_ids))
                d0 = self.cache.tier_demotions
                try:
                    got = self.cache.allocate_with_prefix(
                        req.request_id, tokens)
                    req.slot = self.cache.reserve_slots(
                        req.request_id,
                        min(chunk, (len(tokens) - got) + remaining))
                except CacheExhausted:
                    if self.cache.has_seq(req.request_id):
                        self.cache.free(req.request_id)
                    break                    # never preempt to admit
                dd = self.cache.tier_demotions - d0
                if dd:
                    reqtrace.record("demote", req.tid, req.request_id,
                                    blocks=dd)
                req.pf_target = len(tokens)
                req.prefill_pos = got
                self._dequeue(req)
                req.state = RequestState.RUNNING
                self.running.append(req)
                # rides THIS step's fused decode dispatch: first chunk
                # of prompt feed goes out alongside the decode slots
                batch.decode.append(req)
                if got:
                    bs = self.cache.block_size
                    reqtrace.record(
                        "prefix_match", req.tid, req.request_id,
                        cached_tokens=got, blocks=-(-got // bs),
                        cow_fork=bool(got % bs), probe=cached_probe)
                reqtrace.record(
                    "scheduled", req.tid, req.request_id, mode="chunked",
                    price=float(price), budget=float(budget),
                    arrival=req.arrival, cached=got,
                    target=req.pf_target)
            else:
                d0 = self.cache.tier_demotions
                try:
                    self.cache.allocate(req.request_id, len(tokens))
                except CacheExhausted:
                    break                    # never preempt to admit
                dd = self.cache.tier_demotions - d0
                if dd:
                    reqtrace.record("demote", req.tid, req.request_id,
                                    blocks=dd)
                self.cache.note_prefix_miss(len(tokens))
                self._dequeue(req)
                req.state = RequestState.RUNNING
                self.running.append(req)
                batch.prefill.append(req)
                reqtrace.record(
                    "scheduled", req.tid, req.request_id, mode="dense",
                    price=float(price), budget=float(budget),
                    arrival=req.arrival, tokens=len(tokens))
            admitted += 1
            budget -= price
        return batch

    # ------------------------------------------------------------ results
    def finish(self, req: Request, state: str, scrub: bool = False):
        """Completion path: release blocks, detach from running. `scrub`
        zeroes the freed blocks device-side — required when quarantining
        a poisoned request whose blocks may hold NaN (see
        requeue_for_recovery)."""
        with self._lock:
            self.running.remove(req)
            self.cache.free(
                req.request_id, scrub=scrub,
                cache_tokens=None if scrub else self._cache_tokens(req))
            req.slot = None
            req.state = state
