"""Multi-tenant serving: tenants as first-class scheduling objects.

ROADMAP direction 4 ("heavy traffic from millions of users") needs more
than one anonymous FCFS queue: different callers have different latency
contracts, different traffic shapes, and different willingness to pay
for prefill FLOPs. This module gives the serving stack the registry
half of that story; scheduler.py consumes it for weighted-fair-queuing
admission, paged_cache.py for share-weighted prefix-trie eviction, and
autoscaler.py for per-tenant pressure signals.

A `TenantConfig` carries everything admission needs to price one
tenant's work:

- `priority` class (PRIORITY_CLASSES) and `weight`: the tenant's fair
  share of admission FLOPs. The WFQ share is `weight` scaled by the
  class multiplier, and "FLOPs" means jaxplan-priced prefill cost
  (analysis/jaxplan.PrefillCostModel) — one 8k prompt charges its
  quadratic attention cost against the share, not "one request".
- `quota_tokens` per `quota_window_s`: a sliding-window token budget
  (prompt + max_tokens, charged at admission, refunded if admission
  ultimately refuses). Exhaustion rejects with a `retry_after_s` hint
  computed from the window — the same backpressure shape as
  EngineOverloaded, and in fact raised AS one (TenantQuotaExceeded)
  so router retry plumbing needs no new except arms.
- `ttft_slo_s` / `deadline_slo_s`: the tenant's latency contract. The
  scheduler uses the deadline for static early reject (a request that
  provably cannot meet it at the measured service rate is refused at
  admission, never after burning prefill); the autoscaler gates fleet
  growth on the TTFT SLO.
- `prefix_share`: the tenant's weight in prefix-cache eviction — one
  tenant's templates cannot evict everyone else's cached blocks beyond
  this share (paged_cache._evict_cached).

The registry is shared fleet-wide: one TenantRegistry instance rides
`EngineConfig.tenants` into every replica's engine (dataclasses.replace
copies the reference), so quota and fairness are fleet-level facts, not
per-replica ones.

Thread contract (ptlint PT-C001 via _GUARDED_BY): the registry is read
at every admission from intake threads and engine step loops; all
mutable state lives under self._lock. Lock order (lockgraph.json):
TenantRegistry._lock is acquired under Scheduler._lock (admission
consults shares) and LLMEngine._lock (quota charge) and takes nothing
itself, so it slots after Scheduler._lock in the declared order.

Single-tenant neutrality: a stack built WITHOUT a registry (the
default) never touches this module, and a registry holding only the
default tenant degenerates WFQ to FCFS — both pinned bitwise-identical
to the historical scheduler by tests/test_tenancy.py.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from .scheduler import EngineOverloaded

__all__ = ["DEFAULT_TENANT", "PRIORITY_CLASSES", "TenantConfig",
           "TenantQuotaExceeded", "TenantRegistry"]

DEFAULT_TENANT = "default"

# priority class -> WFQ weight multiplier. Classes are coarse knobs on
# top of the per-tenant weight: `batch` tenants cede admission FLOPs to
# `standard`, which cedes to `latency`.
PRIORITY_CLASSES = {"batch": 0.25, "standard": 1.0, "latency": 4.0}


class TenantQuotaExceeded(EngineOverloaded):
    """A tenant's sliding-window token quota is spent. Subclasses
    EngineOverloaded so every existing backpressure path (router
    retry loop, client retry_after_s plumbing, stats.rejected) handles
    it unchanged; `depth`/`limit` carry window spend / quota."""

    def __init__(self, request_id, tenant: str, spent: int, quota: int,
                 retry_after_s: Optional[float] = None):
        super().__init__(request_id, spent, quota,
                         retry_after_s=retry_after_s)
        self.tenant = tenant


@dataclass(frozen=True)
class TenantConfig:
    """One tenant's scheduling contract (module docstring)."""
    name: str
    priority: str = "standard"           # PRIORITY_CLASSES key
    weight: float = 1.0                  # WFQ share within the class
    quota_tokens: Optional[int] = None   # tokens per window (None = ∞)
    quota_window_s: float = 60.0
    ttft_slo_s: Optional[float] = None   # autoscaler growth gate
    deadline_slo_s: Optional[float] = None  # static early-reject bound
    prefix_share: float = 1.0            # trie-eviction share weight

    def __post_init__(self):
        if self.priority not in PRIORITY_CLASSES:
            raise ValueError(
                f"tenant {self.name!r}: priority {self.priority!r} not "
                f"in {tuple(PRIORITY_CLASSES)}")
        if self.weight <= 0 or self.prefix_share <= 0:
            raise ValueError(
                f"tenant {self.name!r}: weight and prefix_share must "
                f"be positive")
        if self.quota_tokens is not None and self.quota_tokens <= 0:
            raise ValueError(
                f"tenant {self.name!r}: quota_tokens must be positive "
                f"or None")
        if self.quota_window_s <= 0:
            raise ValueError(
                f"tenant {self.name!r}: quota_window_s must be positive")

    @property
    def wfq_weight(self) -> float:
        """Effective fair-share weight: class multiplier × weight."""
        return PRIORITY_CLASSES[self.priority] * self.weight


class TenantRegistry:
    """Fleet-wide tenant table + sliding-window quota accounting.

    `version` increments on every registration so consumers (the
    scheduler's weight snapshot, the cache's eviction shares) can cache
    derived views and refresh only on change.
    """

    _GUARDED_BY = {
        "_tenants": "_lock",
        "_spend": "_lock",
        "_model_spend": "_lock",
        "version": "_lock",
    }

    def __init__(self, tenants=()):
        self._lock = threading.RLock()
        self._tenants: Dict[str, TenantConfig] = {
            DEFAULT_TENANT: TenantConfig(DEFAULT_TENANT)}
        # tenant -> deque[(monotonic_ts, tokens)] inside the window
        self._spend: Dict[str, deque] = {}
        # (tenant, model) -> net tokens charged, cumulative — the
        # multi-model billing breakdown (serving/deploy.py). Quota
        # itself stays per-tenant across models: one budget, however
        # the tenant splits it.
        self._model_spend: Dict[Tuple[str, str], int] = {}
        self.version = 1
        for cfg in tenants:
            self.register(cfg)

    # ------------------------------------------------------------ table
    def register(self, cfg: TenantConfig) -> TenantConfig:
        """Add or replace one tenant's config."""
        if not isinstance(cfg, TenantConfig):
            raise TypeError(f"expected TenantConfig, got {type(cfg)}")
        with self._lock:
            self._tenants[cfg.name] = cfg
            self.version += 1
            return cfg

    def resolve(self, name: str) -> TenantConfig:
        """Admission-time lookup; unknown tenants are refused loudly —
        an unregistered id is a caller bug, not a new tenant."""
        with self._lock:
            cfg = self._tenants.get(name)
            if cfg is None:
                raise ValueError(
                    f"unknown tenant {name!r}; registered: "
                    f"{sorted(self._tenants)}")
            return cfg

    def names(self) -> Tuple[str, ...]:
        with self._lock:
            return tuple(sorted(self._tenants))

    def wfq_weights(self) -> Dict[str, float]:
        """Snapshot of effective WFQ weights (scheduler refresh)."""
        with self._lock:
            return {n: c.wfq_weight for n, c in self._tenants.items()}

    def prefix_shares(self) -> Dict[str, float]:
        """Snapshot of trie-eviction shares (cache refresh)."""
        with self._lock:
            return {n: c.prefix_share for n, c in self._tenants.items()}

    # ------------------------------------------------------------ quota
    def charge(self, name: str, tokens: int,
               now: Optional[float] = None,
               model: Optional[str] = None) -> None:
        """Charge `tokens` against the tenant's sliding window; raises
        TenantQuotaExceeded (with a retry_after_s hint — when the
        oldest window entry expires) once the window is spent. The
        caller refunds on a downstream admission refusal so a rejected
        request never burns quota. `model` tags the charge for the
        per-model billing breakdown (model_spend); quota enforcement
        is model-blind."""
        with self._lock:
            cfg = self._tenants.get(name)
            if cfg is None:
                raise ValueError(f"unknown tenant {name!r}")
            if model is not None:
                key = (name, model)
                self._model_spend[key] = \
                    self._model_spend.get(key, 0) + int(tokens)
            if cfg.quota_tokens is None:
                return
            now = time.monotonic() if now is None else now
            window = self._spend.setdefault(name, deque())
            horizon = now - cfg.quota_window_s
            while window and window[0][0] <= horizon:
                window.popleft()
            spent = sum(t for _, t in window)
            if spent + tokens > cfg.quota_tokens:
                retry = round(window[0][0] - horizon, 3) if window \
                    else round(cfg.quota_window_s, 3)
                if model is not None:
                    # refused before commit: the breakdown must not
                    # show tokens the tenant never got to spend
                    self._model_spend[(name, model)] -= int(tokens)
                raise TenantQuotaExceeded(
                    None, name, spent + tokens, cfg.quota_tokens,
                    retry_after_s=max(retry, 0.001))
            window.append((now, int(tokens)))

    def refund(self, name: str, tokens: int,
               model: Optional[str] = None) -> None:
        """Return a charge whose admission was refused downstream (the
        scheduler's queue bound or deadline early-reject fired after
        quota accepted). Removes the most recent matching charge."""
        with self._lock:
            if model is not None:
                key = (name, model)
                if key in self._model_spend:
                    self._model_spend[key] = max(
                        self._model_spend[key] - int(tokens), 0)
            window = self._spend.get(name)
            if not window:
                return
            for i in range(len(window) - 1, -1, -1):
                if window[i][1] == tokens:
                    del window[i]
                    return
            window.pop()

    def model_spend(self) -> Dict[str, Dict[str, int]]:
        """Cumulative net tokens charged, per tenant per model — the
        billing view a multi-model fleet reports (load_suite /
        router_stats consumers). Empty until a charge carries a model
        tag."""
        with self._lock:
            out: Dict[str, Dict[str, int]] = {}
            for (tenant, model), tok in sorted(self._model_spend.items()):
                out.setdefault(tenant, {})[model] = tok
            return out

    def window_spend(self, name: str,
                     now: Optional[float] = None) -> int:
        """Tokens charged inside the tenant's current window."""
        with self._lock:
            cfg = self._tenants.get(name)
            window = self._spend.get(name)
            if cfg is None or not window:
                return 0
            now = time.monotonic() if now is None else now
            horizon = now - cfg.quota_window_s
            return sum(t for ts, t in window if ts > horizon)

    def as_dict(self) -> dict:
        with self._lock:
            return {n: {"priority": c.priority, "weight": c.weight,
                        "wfq_weight": c.wfq_weight,
                        "quota_tokens": c.quota_tokens,
                        "quota_window_s": c.quota_window_s,
                        "ttft_slo_s": c.ttft_slo_s,
                        "deadline_slo_s": c.deadline_slo_s,
                        "prefix_share": c.prefix_share}
                    for n, c in sorted(self._tenants.items())}
