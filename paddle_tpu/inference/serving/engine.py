"""LLMEngine: continuous-batching serving loop over the paged KV cache.

The serving analogue of the reference inference layer's
AnalysisPredictor::Run — but instead of one synchronous batch per call,
requests stream in (add_request), the engine interleaves prefill and
decode per step() under the scheduler's FCFS/preemption policy, and
outputs stream back token by token.

Device work per step:
- prefill: models.generation.prefill (the SAME jitted program the dense
  generate() path uses — one compilation per prompt-length bucket),
  scattered into the sequence's blocks (PagedKVCache.write_prefill);
- decode: serving.attention.fused_decode_chunk — a jitted lax.scan that
  decodes, SAMPLES and tracks termination for up to decode_chunk_size
  tokens per running sequence entirely on device, padded to a
  power-of-two bucket capped at max_num_seqs, so XLA compiles once per
  (bucket, k) and never recompiles per request mix.

Host/device contract (docs/serving.md "Device-resident decode"): the
host uploads ONE packed control array per chunk and fetches ONE
(tokens[k], finished, not-finite flags) result — host syncs in
steady-state decode are 1 per k tokens, not 1 per token (the obs
host-sync counter pins this). The first token of a request is sampled
on host from the prefill logits (host numpy, per-request RNG); every
subsequent token is sampled in-scan with a fold_in(seed,
tokens-generated) PRNG key, a function of request progress only — so
token streams are invariant under chunk size, preemption and crash
replay, and greedy engine output token-matches
models.generation.generate (tests/test_serving.py pins this end to
end, preemptions included; tests/test_serving_chunked.py pins k-chunk
vs k x 1-chunk bitwise, temperature paths included).

Hardened step (docs/serving.md "Failure semantics"): every step first
expires overdue requests (deadline_s / queue_ttl_s → 'timeout'), then
runs prefill/decode under an anomaly guard (core/anomaly NaN/Inf
detection on the logits) and a step-progress watchdog
(step_timeout_s). A poisoned or wedged step quarantines the offending
request ('error'), scrubs+frees its blocks, and REBUILDS the remaining
running requests by requeueing them for re-prefill from their token
logs — bitwise-equivalent to an undisturbed run for the survivors, so
one bad request costs one request, not the fleet. Admission control
(max_waiting + admission_policy, cache_high_watermark) bounds the queue
('shed' / EngineOverloaded) before overload can strand decodes.

Telemetry (PR 6, docs/observability.md): every phase runs under an
obs.trace span — the step itself is cat="serving", the phases carry
their own categories (cat="schedule"/"prefill"/"decode") — so a chrome
trace exported with profiler.export_chrome_tracing (or obs.trace
.export_chrome) shows schedule/prefill/decode per engine step with
request counts in args. EngineStats is a thin view over the obs
metrics registry, and the step loop additionally records TTFT /
inter-token / request-latency / step-time histograms plus queue and
cache-occupancy gauges — all host-side on values the step already
fetched, so instrumentation adds ZERO device syncs (PT-T007 clean).
"""
from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np
import jax.numpy as jnp

from ... import obs
from ...analysis import holds_lock
from ...core import anomaly
from ...models import generation as gen
from ...profiler import RecordEvent
from .attention import PACK_COLS, fused_decode_chunk, pack_f32
from .paged_cache import CacheExhausted, PagedKVCache
from .scheduler import (EngineOverloaded, Request, RequestState,
                        SamplingParams, ScheduledBatch, Scheduler,
                        SchedulerConfig, record_promotion_events)

__all__ = ["EngineConfig", "EngineStats", "LLMEngine", "RequestOutput",
           "ServingPredictor"]


@dataclass
class EngineConfig:
    block_size: int = 16
    num_blocks: int = 256
    max_num_seqs: int = 8
    max_prefill_tokens: int = 2048
    # static-cost admission (docs/serving.md): a PrefillCostModel
    # (analysis/jaxplan) pricing each admission by its modelled prefill
    # FLOPs instead of a flat token count. "auto" loads the committed
    # plan's model (jaxplan.json; falls back to flat if no plan is
    # committed); None keeps the flat budget.
    prefill_cost_model: Optional[object] = None
    # tokens decoded per fused device chunk (the k of
    # attention.fused_decode_chunk): the host syncs with the device
    # once per k tokens instead of once per token. 1 reproduces the
    # classic single-token step (useful for A/B and debugging); larger
    # k amortizes dispatch further but coarsens the granularity at
    # which deadlines/watchdog/fault quarantine act (they all run at
    # chunk boundaries).
    decode_chunk_size: int = 8
    # serving attention kernel (docs/serving.md "Ragged paged attention
    # and chunked prefill"): "ragged" (default) pads every decode batch
    # to the ONE fixed max_num_seqs width — dead rows cost zero kernel
    # work under the pallas ragged paged-attention kernel, and a single
    # compilation covers every batch mix. "bucketed" keeps the legacy
    # power-of-two bucket padding (one compile per bucket) as the
    # fallback and parity oracle. Off-TPU both lower to the same
    # gather + composed attention, so they are bitwise-identical there.
    kernel: str = "ragged"
    # prompts STRICTLY longer than this are admitted CHUNKED: their
    # prefill rides the fused decode scan decode_chunk_size tokens per
    # step instead of a dedicated dense prefill dispatch, so long
    # prompts never stall a step. None disables chunking.
    prefill_chunk_threshold: Optional[int] = None
    # prefix caching (docs/serving.md "Prefix caching"): share KV
    # blocks across requests through a radix-trie index with
    # refcounts, copy-on-write forking and LRU eviction. Prompts with
    # a cached prefix are admitted chunked and prefill only their
    # uncached suffix; greedy output is bitwise-identical either way.
    enable_prefix_cache: bool = False
    # hierarchical tiering (docs/serving.md "Hierarchical KV-cache
    # tiering"): > 0 gives the prefix cache a host-RAM spill tier of
    # that many blocks — LRU eviction demotes payloads into it instead
    # of destroying them, and a later match promotes them back (sha256-
    # verified) instead of re-prefilling. Needs enable_prefix_cache.
    host_tier_blocks: int = 0
    # wall-clock budget for one promotion run; an overrun stops the run
    # (entries stay host-resident, retryable) and the request re-prefills
    # the unpromoted suffix. None = unbounded.
    promote_timeout_s: Optional[float] = None
    # KV pool storage dtype (docs/serving.md "int8 KV blocks"): "int8"
    # stores the block pools (and host-tier spills) as int8 codes +
    # per-(block, head) scales via serving/kv_quant.py, ~4x less
    # resident KV. Decoded output then tracks the f32 engine within the
    # dequantization bound jaxnum derives and numplan.json commits
    # (serving.kv_block_codec). "float32" (default) is the historical
    # bitwise-exact pool.
    kv_cache_dtype: str = "float32"
    # ----------------------------- robustness layer (docs/serving.md)
    max_waiting: Optional[int] = None    # bounded waiting queue (None=∞)
    admission_policy: str = "reject"     # 'reject' | 'shed_oldest'
    cache_high_watermark: float = 1.0    # pause prefill admission above
    step_timeout_s: Optional[float] = None  # watchdog budget per step
    # prefix for this engine's `engine` label in the obs registry; the
    # final label is ALWAYS uniquified per instance (prefix-N) so two
    # engines can never merge their metric series
    obs_label: Optional[str] = None
    # multi-tenant serving (serving/tenancy.TenantRegistry): shared
    # fleet-wide by REFERENCE (dataclasses.replace keeps it), so quota
    # windows and fair shares are fleet-level facts. Enables WFQ
    # admission, sliding-window quota enforcement, deadline-aware early
    # reject and share-weighted trie eviction. None (default) keeps the
    # historical single-tenant FCFS stack bit-for-bit.
    tenants: Optional[object] = None
    # multi-model fleets (serving/deploy.py): which model's weights this
    # engine serves and which published revision of them. The pair keys
    # every KV payload that leaves the engine (export_request,
    # export_prefix) and every admit path refuses a payload keyed for a
    # different (model, revision) — stale KV can never cross a weight
    # rollout. The defaults keep single-model stacks untagged and their
    # reqtrace dumps byte-identical to the pre-deploy schema.
    model: str = "default"
    revision: str = "r0"


@dataclass
class RequestOutput:
    """One streamed step result for one request. finish_reason taxonomy
    (docs/serving.md): 'stop' | 'length' | 'cancelled' | 'timeout'
    (deadline_s / queue_ttl_s) | 'shed' (admission eviction) | 'error'
    (quarantined by the anomaly guard / watchdog). Abnormal terminals
    carry new_token=None."""
    request_id: str
    new_token: Optional[int]
    token_ids: List[int]                 # all generated tokens so far
    finished: bool
    finish_reason: Optional[str] = None


# int event counters (serving_events_total{engine,event}); field name
# IS the event label. 'rejected' (EngineOverloaded raises) is new in
# the obs layer — the pre-obs stats never counted refused admissions.
_STAT_EVENTS = ("steps", "prefill_tokens", "generated_tokens",
                "preemptions", "completed", "cancelled", "expired",
                "timeouts", "shed", "errors", "recoveries", "rebuilt",
                "watchdog_trips", "rejected")
# float phase-time accumulators (serving_phase_seconds_total{engine,phase})
_STAT_PHASES = {"time_schedule": "schedule", "time_prefill": "prefill",
                "time_decode": "decode"}
# per-request wall-time sums over COMPLETED requests (the historical
# avg_ttft_s / avg_request_latency_s denominators)
_STAT_REQ_SUMS = {"ttft_sum": "ttft", "latency_sum": "latency"}

_ENGINE_IDS = itertools.count()


class EngineStats:
    """Engine statistics as a THIN VIEW over the obs registry (PR 6).

    Field surface and `as_dict()` are unchanged from the old dataclass
    (tests and tools/chaos_serve.py read `stats.errors`,
    `stats.as_dict()` exactly as before), but every field is now a
    generated property over a registry child — `stats.completed += 1`
    increments `serving_events_total{engine=...,event="completed"}` —
    so Prometheus/JSON exporters, the load suite and the engine itself
    all read ONE sink. Each instance gets a unique `engine` label
    (never shared: chaos_serve's reference and faulted engines must not
    merge), and the view also carries the engine's latency histograms
    (TTFT / inter-token gap / request latency / step time) and per-step
    gauges, recorded via the observe_*/set_* helpers below.

    Registry children are individually thread-safe and the engine
    mutates stats only under its own lock, so the view itself needs no
    `_GUARDED_BY` contract.
    """

    def __init__(self, label: str = None):
        if label is None:
            label = "engine"
        # ALWAYS uniquified — a caller-supplied label is a prefix
        self.label = f"{label}-{next(_ENGINE_IDS)}"
        lbl = dict(engine=self.label)
        ev = obs.counter("serving_events_total",
                         "engine lifecycle/robustness event counts",
                         labels=("engine", "event"))
        self._events = {f: ev.labels(event=f, **lbl) for f in _STAT_EVENTS}
        ph = obs.counter("serving_phase_seconds_total",
                         "host wall time accumulated per engine phase",
                         labels=("engine", "phase"), unit="seconds")
        self._phases = {f: ph.labels(phase=p, **lbl)
                        for f, p in _STAT_PHASES.items()}
        rs = obs.counter("serving_request_seconds_total",
                         "per-request wall-time sums over completed "
                         "requests (kind=ttft|latency)",
                         labels=("engine", "kind"), unit="seconds")
        self._req_sums = {f: rs.labels(kind=k, **lbl)
                          for f, k in _STAT_REQ_SUMS.items()}
        self._ttft = obs.histogram(
            "serving_ttft_seconds",
            "time to first token, observed once per request",
            labels=("engine",), unit="seconds").labels(**lbl)
        self._token_gap = obs.histogram(
            "serving_token_gap_seconds",
            "inter-token latency (gap between consecutive emitted "
            "tokens of one request)",
            labels=("engine",), unit="seconds").labels(**lbl)
        self._latency = obs.histogram(
            "serving_request_latency_seconds",
            "request wall time arrival→finish, observed at completion",
            labels=("engine",), unit="seconds").labels(**lbl)
        self._step = obs.histogram(
            "serving_step_seconds", "engine step() wall time",
            labels=("engine",), unit="seconds").labels(**lbl)
        self._decode_chunk = obs.histogram(
            "serving_decode_chunk_seconds",
            "fused k-token decode chunk wall time (the device scan plus "
            "its single host fetch)",
            labels=("engine",), unit="seconds").labels(**lbl)
        sy = obs.counter(
            "serving_host_syncs_total",
            "device->host synchronizations: one per prefill logits "
            "fetch, one per fused decode chunk fetch",
            labels=("engine", "phase"))
        self._syncs = {p: sy.labels(phase=p, **lbl)
                       for p in ("prefill", "decode")}
        self._g_syncs_per_token = obs.gauge(
            "serving_host_syncs_per_token",
            "decode host syncs / generated tokens — the steady-state "
            "per-token host round-trip cost the fused chunk amortizes "
            "to ~1/k",
            labels=("engine",)).labels(**lbl)
        g_run = obs.gauge("serving_running", "running sequences",
                          labels=("engine",))
        g_wait = obs.gauge("serving_waiting", "waiting-queue depth",
                           labels=("engine",))
        g_blk = obs.gauge("serving_cache_blocks",
                          "paged-cache block pool occupancy",
                          labels=("engine", "state"), unit="blocks")
        g_spend = obs.gauge("serving_prefill_spend_tokens",
                            "prompt tokens admitted to prefill this step "
                            "(per-step spend against max_prefill_tokens)",
                            labels=("engine",), unit="tokens")
        self._c_prefill_chunks = obs.counter(
            "serving_prefill_chunks_total",
            "prompt chunks consumed inside the fused decode scan — one "
            "per mid-prefill row per chunk dispatch (chunked prefill)",
            labels=("engine",)).labels(**lbl)
        self._g_padding_waste = obs.gauge(
            "serving_padding_waste_ratio",
            "dead (padded) rows / batch width of the last decode "
            "dispatch: (bucket - live)/bucket under the bucketed "
            "fallback; 0 under the ragged kernel, whose per-row length "
            "gating makes dead rows cost zero kernel work",
            labels=("engine",)).labels(**lbl)
        self._g_running = g_run.labels(**lbl)
        self._g_waiting = g_wait.labels(**lbl)
        self._g_blocks_used = g_blk.labels(state="used", **lbl)
        self._g_blocks_free = g_blk.labels(state="free", **lbl)
        self._g_prefill_spend = g_spend.labels(**lbl)
        # prefix cache (docs/observability.md): hit/miss/eviction
        # counters mirrored from the cache's lifetime counters via the
        # delta-inc pattern, plus cached/shared block gauges and the
        # cached-prompt-token ratio
        self._prefix_counters = {
            "hits": obs.counter(
                "serving_prefix_cache_hits_total",
                "admissions that attached at least one cached prefix "
                "token", labels=("engine",)).labels(**lbl),
            "misses": obs.counter(
                "serving_prefix_cache_misses_total",
                "admissions that matched nothing in the prefix trie",
                labels=("engine",)).labels(**lbl),
            "evictions": obs.counter(
                "serving_prefix_cache_evictions_total",
                "unreferenced cached blocks reclaimed under pool "
                "pressure (LRU leaf first)",
                labels=("engine",)).labels(**lbl),
        }
        self._g_prefix_ratio = obs.gauge(
            "serving_prefix_cached_tokens_ratio",
            "prompt tokens served from cache / prompt tokens admitted "
            "(lifetime, per engine)",
            labels=("engine",)).labels(**lbl)
        g_pfx = obs.gauge(
            "serving_prefix_cache_blocks",
            "prefix-cache block census: kind=cached (trie-indexed) | "
            "shared (refcount >= 2); tenant='*' is the all-tenants "
            "total, per-tenant children carry kind=cached only "
            "(cardinality bounded by the TenantRegistry)",
            labels=("engine", "kind", "tenant"), unit="blocks")
        self._f_prefix_blocks = g_pfx
        self._g_prefix_cached = g_pfx.labels(kind="cached", tenant="*",
                                             **lbl)
        self._g_prefix_shared = g_pfx.labels(kind="shared", tenant="*",
                                             **lbl)
        self._g_prefix_tenant: Dict[str, object] = {}
        # hierarchical tiering (docs/serving.md "Hierarchical KV-cache
        # tiering"): per-tier residency, demote/promote lifecycle
        # counters and the promotion-latency histogram
        g_tier = obs.gauge(
            "serving_prefix_tier_blocks",
            "prefix-cache residency per tier: device (trie-indexed HBM "
            "blocks) | host (demoted host-RAM payloads)",
            labels=("engine", "tier"), unit="blocks")
        self._g_tier_device = g_tier.labels(tier="device", **lbl)
        self._g_tier_host = g_tier.labels(tier="host", **lbl)
        self._c_demotions = obs.counter(
            "serving_tier_demotions_total",
            "device->host spills (demote-instead-of-free evictions)",
            labels=("engine",)).labels(**lbl)
        pr = obs.counter(
            "serving_tier_promotions_total",
            "host->device promotion attempts by outcome: hit (filled, "
            "digest verified) | timeout (killed/over budget/pool hot — "
            "entry stays resident) | integrity (sha256 mismatch, "
            "dropped) | raced (store evicted first, dropped)",
            labels=("engine", "outcome"))
        self._promotions = {o: pr.labels(outcome=o, **lbl)
                            for o in ("hit", "timeout",
                                      "integrity", "raced")}
        self._promote_hist = obs.histogram(
            "serving_tier_promote_seconds",
            "wall time of one host->device promotion run (all blocks "
            "promoted for one request probe)",
            labels=("engine",), unit="seconds").labels(**lbl)

    # -------------------------------------------------- record helpers
    def observe_ttft(self, dt: float) -> None:
        self._ttft.observe(dt)

    def observe_token_gap(self, dt: float) -> None:
        self._token_gap.observe(dt)

    def observe_latency(self, dt: float) -> None:
        self._latency.observe(dt)

    def observe_step(self, dt: float) -> None:
        self._step.observe(dt)

    def set_step_gauges(self, running: int, waiting: int,
                        blocks_used: int, blocks_free: int) -> None:
        self._g_running.set(running)
        self._g_waiting.set(waiting)
        self._g_blocks_used.set(blocks_used)
        self._g_blocks_free.set(blocks_free)

    def set_prefill_spend(self, tokens: int) -> None:
        self._g_prefill_spend.set(tokens)

    def observe_decode_chunk(self, dt: float) -> None:
        self._decode_chunk.observe(dt)

    def inc_prefill_chunks(self, n: int = 1) -> None:
        self._c_prefill_chunks.inc(n)

    def prefill_chunks(self) -> int:
        return int(self._c_prefill_chunks.value)

    def set_padding_waste(self, v: float) -> None:
        self._g_padding_waste.set(v)

    def padding_waste(self) -> float:
        return self._g_padding_waste.value

    def inc_host_sync(self, phase: str) -> None:
        self._syncs[phase].inc()

    def host_syncs(self, phase: str) -> int:
        """Exact sync count (the chunked-decode acceptance test pins
        decode syncs == number of chunks, not tokens)."""
        return int(self._syncs[phase].value)

    def set_syncs_per_token(self, v: float) -> None:
        self._g_syncs_per_token.set(v)

    def host_syncs_per_token(self) -> float:
        return self._g_syncs_per_token.value

    def record_prefix(self, ps: dict) -> None:
        """Publish one prefix-cache snapshot (PagedKVCache.prefix_stats)
        — counters advance by delta (they are lifetime-monotone on the
        cache side), gauges overwrite."""
        for k, child in self._prefix_counters.items():
            delta = ps[k] - child.value
            if delta > 0:
                child.inc(delta)
        self._g_prefix_ratio.set(ps["cached_tokens_ratio"])
        self._g_prefix_cached.set(ps["cached_blocks"])
        self._g_prefix_shared.set(ps["shared_blocks"])
        # per-tenant cached-block census (multi-tenant stacks): children
        # are created lazily but never retired — a tenant that drops to
        # zero blocks must REPORT zero, not go silently stale
        tb = ps.get("tenant_blocks") or {}
        for t in tb:
            if t not in self._g_prefix_tenant:
                self._g_prefix_tenant[t] = self._f_prefix_blocks.labels(
                    kind="cached", tenant=t, engine=self.label)
        for t, child in self._g_prefix_tenant.items():
            child.set(tb.get(t, 0))
        delta = ps["tier_demotions"] - self._c_demotions.value
        if delta > 0:
            self._c_demotions.inc(delta)
        for o, child in self._promotions.items():
            delta = ps[f"promote_{o}"] - child.value
            if delta > 0:
                child.inc(delta)
        self._g_tier_device.set(ps["cached_blocks"])
        self._g_tier_host.set(ps["host_blocks"])

    def prefix_counter(self, kind: str) -> int:
        """Exact published counter value (kind='hits'|'misses'|
        'evictions') — tests pin these against the cache's own
        counters."""
        return int(self._prefix_counters[kind].value)

    def prefix_tenant_blocks(self, tenant: str) -> int:
        """Published per-tenant cached-block gauge (reconciliation tests
        pin this against the trie's lifetime counters)."""
        child = self._g_prefix_tenant.get(tenant)
        return int(child.value) if child is not None else 0

    def observe_promote(self, dt: float) -> None:
        self._promote_hist.observe(dt)

    def promote_quantile(self, q: float) -> float:
        """Exact promotion-latency quantile (tiered_prefix reports
        p99 here)."""
        return self._promote_hist.quantile(q)

    def tier_demotions(self) -> int:
        return int(self._c_demotions.value)

    def promotion_counter(self, outcome: str) -> int:
        """Published promotion count for one outcome ('hit'|'timeout'|
        'integrity'|'raced') — tests pin these against the cache."""
        return int(self._promotions[outcome].value)

    def ttft_quantile(self, q: float) -> float:
        """Exact TTFT quantile (bench / load suite read p50/p99 here)."""
        return self._ttft.quantile(q)

    def token_gap_quantile(self, q: float) -> float:
        """Exact inter-token-gap quantile (load suite decode_heavy
        reports p99 here)."""
        return self._token_gap.quantile(q)

    def as_dict(self) -> dict:
        d = {f: getattr(self, f) for f in _STAT_EVENTS}
        for f in _STAT_PHASES:
            d[f] = getattr(self, f)
        for f in _STAT_REQ_SUMS:
            d[f] = getattr(self, f)
        done = max(self.completed, 1)
        d["avg_ttft_s"] = self.ttft_sum / done
        d["avg_request_latency_s"] = self.latency_sum / done
        busy = self.time_prefill + self.time_decode
        d["decode_tokens_per_sec"] = (
            self.generated_tokens / busy if busy > 0 else 0.0)
        d["host_syncs_per_token"] = (
            self.host_syncs("decode") / self.generated_tokens
            if self.generated_tokens else 0.0)
        return d


def _stats_property(table: str, f: str, as_int: bool):
    """Generated accessor pair: reads pull the registry child's value,
    writes inc() the monotonic delta — so the historical `stats.x += 1`
    call sites keep working verbatim against counter-backed storage."""

    def _get(self):
        v = getattr(self, table)[f].value
        return int(v) if as_int else v

    def _set(self, new):
        child = getattr(self, table)[f]
        delta = new - child.value
        if delta:
            child.inc(delta)             # counters refuse to go down

    return property(_get, _set)


for _f in _STAT_EVENTS:
    setattr(EngineStats, _f, _stats_property("_events", _f, as_int=True))
for _f in _STAT_PHASES:
    setattr(EngineStats, _f, _stats_property("_phases", _f, as_int=False))
for _f in _STAT_REQ_SUMS:
    setattr(EngineStats, _f, _stats_property("_req_sums", _f,
                                             as_int=False))
del _f


def _bucket(n: int, cap: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return min(b, cap)


class LLMEngine:
    """Continuous-batching engine over (params, geom) — the pure-JAX
    decode substrate of models.generation, served paged.

    Thread contract (checked by ptlint PT-C001 via _GUARDED_BY): the
    fields below are shared between the serving loop (step/run) and
    intake threads (add_request/cancel) and are only touched under
    self._lock. Public entry points take the lock; internal helpers are
    @holds_lock("_lock") — called only from a locked frame. Lock order
    is engine → scheduler (the engine calls scheduler methods while
    locked, never the reverse), so the pair cannot deadlock."""

    _GUARDED_BY = {
        "_requests": "_lock",
        "_rngs": "_lock",
        "_next_id": "_lock",
        "_next_trace": "_lock",
        "_pending_outputs": "_lock",
        "_flights": "_lock",
        "stats": "_lock",
        "_step_start": "_lock",
    }

    def __init__(self, params, geom, config: EngineConfig = None,
                 faults=None):
        config = config or EngineConfig()
        L, H, D, S = geom
        if S % config.block_size != 0:
            # divisibility keeps the gathered context bitwise-identical
            # to the dense cache layout (and write_prefill rectangular)
            raise ValueError(
                f"block_size {config.block_size} must divide "
                f"max_seq_len {S}")
        if config.decode_chunk_size < 1:
            raise ValueError(
                f"decode_chunk_size must be >= 1, got "
                f"{config.decode_chunk_size}")
        self.params = params
        self.geom = geom
        self.config = config
        self.max_blocks_per_seq = S // config.block_size
        self.cache = PagedKVCache(
            L, H, D, config.num_blocks, config.block_size,
            enable_prefix_cache=config.enable_prefix_cache,
            host_tier_blocks=config.host_tier_blocks,
            promote_timeout_s=config.promote_timeout_s,
            kv_cache_dtype=config.kv_cache_dtype)
        cost_model = config.prefill_cost_model
        if cost_model == "auto":
            # committed-plan admission pricing; a repo without a plan
            # file degrades to the flat token budget
            from ...analysis import jaxplan
            cost_model = jaxplan.default_admission_model()
        self.scheduler = Scheduler(
            SchedulerConfig(
                max_num_seqs=config.max_num_seqs,
                max_prefill_tokens=config.max_prefill_tokens,
                decode_chunk_size=config.decode_chunk_size,
                max_waiting=config.max_waiting,
                admission_policy=config.admission_policy,
                cache_high_watermark=config.cache_high_watermark,
                prefill_cost_model=cost_model,
                prefill_chunk_threshold=config.prefill_chunk_threshold,
                tenants=config.tenants),
            self.cache)
        # RLock: step() holds it across the whole iteration and the
        # helpers it calls re-enter (e.g. _emit under _recover)
        self._lock = threading.RLock()
        self.stats = EngineStats(config.obs_label)
        # (model, revision) event tag (serving/deploy.py): emission and
        # terminal events carry the serving revision so the causality
        # checker can prove no token was emitted by a revision other
        # than the one the request was admitted under (invariant 8).
        # Default-keyed engines stay untagged — pre-deploy dump schema.
        self._rev_tag: Optional[Dict[str, str]] = None
        if (config.model, config.revision) != ("default", "r0"):
            self._rev_tag = {"model": config.model,
                             "revision": config.revision}
        self._requests: Dict[str, Request] = {}
        self._rngs: Dict[str, np.random.RandomState] = {}
        self._next_id = 0
        self._next_trace = 0
        self._pending_outputs: List[RequestOutput] = []
        self._flights: List[tuple] = []   # deferred flight-recorder dumps
        self._step_start = 0.0
        if faults is None:
            # env-driven (PADDLE_TPU_SERVE_FAULTS), inert without a spec
            # — same unconditional-call contract as training's
            # FaultInjector. Lazy import: testing pulls the op harness.
            from ...testing.faults import ServingFaultInjector
            faults = ServingFaultInjector()
        self.faults = faults
        # ptlint: disable=PT-C004  fault injector (see step())
        self.cache.arm_tier_faults(self.faults, 0)

    @classmethod
    def from_model(cls, model, config: EngineConfig = None, faults=None):
        cfg = model.cfg
        geom = (cfg.num_layers, cfg.num_heads,
                cfg.hidden_size // cfg.num_heads, cfg.max_seq_len)
        return cls(gen.extract_params(model), geom, config, faults=faults)

    # ------------------------------------------------------------ intake
    def add_request(self, prompt_ids, sampling: SamplingParams = None,
                    request_id: str = None, arrival_time: float = None,
                    arrival: int = None, resume_tokens=None,
                    readmit: bool = False,
                    trace_id: str = None) -> str:
        """Queue one request. Raises EngineOverloaded when the bounded
        waiting queue is full under admission_policy='reject'; under
        'shed_oldest' the oldest waiting request is evicted instead
        (terminal RequestOutput with finish_reason='shed', streamed from
        the next step()).

        The keyword extensions are the replica-failover re-admission
        surface (router.py; docs/serving.md "Multi-replica serving"):
        `arrival_time`/`arrival` carry the request's ORIGINAL wall-clock
        arrival and FCFS ticket across engines — deadline_s/queue_ttl_s
        stay measured from the original arrival (a re-admitted request
        that already blew its deadline finishes as 'timeout', never as a
        silent retry), and the requeue keeps its original place in line.
        `resume_tokens` seeds the output log with the tokens the failed
        replica already streamed, so re-prefill continues the SAME token
        stream (sampling keys depend only on request progress) and
        max_tokens accounting never restarts. `readmit=True` inserts
        arrival-ordered and bypasses the max_waiting bound (backpressure
        applies to new arrivals, not to recovered in-flight work).
        `trace_id` is the per-request causal-trace id (obs/reqtrace.py);
        the router mints one and passes it through dispatch so a
        failover hop stays ONE timeline — a standalone engine mints its
        own (`tr-<engine-label>-N`)."""
        sampling = sampling or SamplingParams()
        ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        if ids.size == 0:
            raise ValueError("empty prompt")
        S = self.geom[3]
        if ids.size + sampling.max_tokens > S:
            raise ValueError(
                f"prompt {ids.size} + max_tokens {sampling.max_tokens} "
                f"exceeds max_seq_len {S}")
        tenants = self.config.tenants
        if tenants is not None:
            # unknown tenant ids are caller bugs, refused loudly before
            # any engine state is touched
            tenants.resolve(sampling.tenant)
        with self._lock:
            if request_id is None:
                request_id = f"req-{self._next_id}"
                self._next_id += 1
            old = self._requests.get(request_id)
            if old is not None and old.state != RequestState.MIGRATED:
                # a migrated-out tombstone does NOT block re-admission:
                # a request can legitimately come back to an engine it
                # once left (failover after its new home died, drain
                # round trip) — only a live or truly-terminal record is
                # a duplicate
                raise ValueError(f"duplicate request_id {request_id!r}")
            now = time.perf_counter()
            req = Request(request_id=request_id, prompt_ids=ids,
                          params=sampling,
                          arrival_time=now if arrival_time is None
                          else arrival_time)
            if arrival is not None:
                req.arrival = arrival
            if trace_id is None:
                trace_id = f"tr-{self.stats.label}-{self._next_trace}"
                self._next_trace += 1
            req.trace_id = trace_id
            if tenants is not None:
                # bind the tenant to the trace so EVERY subsequent event
                # on this timeline auto-carries the tag (ring-level map;
                # single-tenant stacks without a registry stay untagged)
                obs.reqtrace.bind_tenant(req.tid, sampling.tenant)
            if resume_tokens is not None and len(resume_tokens):
                req.output_ids = [int(t) for t in resume_tokens]
                # TTFT was already observed on the replica that emitted
                # the first token; the re-admitting engine records only
                # token gaps (from now) for the resumed stream
                req.first_token_time = req.arrival_time
                req.last_token_time = now
            charged = 0
            if tenants is not None and not readmit:
                # sliding-window token quota, charged for the WORST CASE
                # (prompt + max_tokens) before any engine state commits;
                # readmissions never re-charge — failover must not burn
                # quota twice for one request
                try:
                    # ptlint: disable=PT-C004  TenantRegistry sits
                    # BELOW LLMEngine in lockgraph.json; charge() takes
                    # only the registry lock, no re-entry
                    tenants.charge(sampling.tenant,
                                   ids.size + sampling.max_tokens,
                                   model=self.config.model)
                except EngineOverloaded as e:
                    self.stats.rejected += 1
                    obs.reqtrace.record(
                        "rejected", req.tid, request_id, reason="quota",
                        tenant=sampling.tenant, spent=e.depth,
                        quota=e.limit, retry_after_s=e.retry_after_s)
                    e.request_id = request_id
                    raise
                charged = ids.size + sampling.max_tokens
            try:
                if readmit:
                    self.scheduler.readmit(req)
                    shed = []
                else:
                    shed = self.scheduler.add(req)  # validates pool fit
            except EngineOverloaded:
                if charged:
                    # ptlint: disable=PT-C004  registry call below the
                    # engine lock in lockgraph.json (see charge above)
                    tenants.refund(sampling.tenant, charged,
                                   model=self.config.model)
                self.stats.rejected += 1
                raise
            except ValueError:
                if charged:
                    # ptlint: disable=PT-C004  same as refund above
                    tenants.refund(sampling.tenant, charged,
                                   model=self.config.model)
                raise
            for victim in shed:
                victim.finish_time = time.perf_counter()
                self.stats.shed += 1
                self._pending_outputs.append(RequestOutput(
                    victim.request_id, None, list(victim.output_ids),
                    True, "shed"))
                obs.reqtrace.record("finish", victim.tid,
                                    victim.request_id, reason="shed",
                                    **(self._rev_tag or {}))
            self._requests[request_id] = req
            self._rngs[request_id] = np.random.RandomState(
                sampling.seed & 0x7FFFFFFF)
            obs.reqtrace.record(
                "engine_admit", req.tid, request_id,
                engine=self.stats.label, arrival=req.arrival,
                readmit=bool(readmit), resume=len(req.output_ids),
                waiting=self.scheduler.num_waiting(),
                **(self._rev_tag or {}))
            if self.cache.host_tier is not None:
                # enqueue-time prefetch: promote the host-resident
                # prefix while the request queues, overlapping the fill
                # with queue wait instead of serialising it into the
                # admission step
                self._prefetch_promote(req)
            return request_id

    @holds_lock("_lock")
    def _prefetch_promote(self, req: Request) -> None:
        """Asynchronous-in-spirit host→device prefetch at enqueue (the
        scheduler's admission probe is the retry for anything this run
        leaves behind). Never raises: ensure_promoted degrades every
        failure to re-prefill of the missing suffix."""
        tokens = req.all_token_ids()
        host = self.cache.host_match_len(tokens)
        if not host:
            return
        cached = self.cache.match_len(tokens)
        obs.reqtrace.record("prefix_match", req.tid, req.request_id,
                            cached_tokens=cached, host_tokens=host,
                            probe=cached)
        with RecordEvent("serving.promote", cat="promote") as ev:
            promo = self.cache.ensure_promoted(tokens)
            ev.args = {"request_id": req.request_id,
                       "host_tokens": host,
                       "promoted": 0 if promo is None
                       else promo["promoted_blocks"],
                       "outcomes": [] if promo is None
                       else promo["outcomes"]}
        record_promotion_events(req.tid, req.request_id, promo)

    def cancel(self, request_id: str) -> bool:
        with self._lock:
            ok = self.scheduler.cancel(request_id)
            if ok:
                self.stats.cancelled += 1
                req = self._requests[request_id]
                req.finish_time = time.perf_counter()
                self._pending_outputs.append(RequestOutput(
                    request_id, None, list(req.output_ids), True,
                    "cancelled"))
                obs.reqtrace.record("finish", req.tid, request_id,
                                    reason="cancelled")
            return ok

    def has_unfinished(self) -> bool:
        return self.scheduler.has_unfinished()

    def get_request(self, request_id: str) -> Request:
        with self._lock:
            return self._requests[request_id]

    # ----------------------------------------------- router-facing surface
    def shed_oldest_waiting(self) -> Optional[str]:
        """Evict this engine's oldest waiting request (the router's
        cross-replica 'shed_oldest' acts on whichever replica holds the
        globally-oldest waiting request). Streams the terminal 'shed'
        output from the next step(); returns the shed request_id or
        None when nothing waits."""
        with self._lock:
            victim = self.scheduler.shed_oldest()
            if victim is None:
                return None
            victim.finish_time = time.perf_counter()
            self.stats.shed += 1
            self._pending_outputs.append(RequestOutput(
                victim.request_id, None, list(victim.output_ids),
                True, "shed"))
            obs.reqtrace.record("finish", victim.tid, victim.request_id,
                                reason="shed")
            return victim.request_id

    def oldest_waiting_arrival(self) -> Optional[int]:
        return self.scheduler.oldest_waiting_arrival()

    def load_info(self) -> dict:
        """Host-side load snapshot the ReplicaSet balances on:
        free_blocks MINUS the engine's outstanding block demand is the
        effective headroom, prefill_cost prices the queued re-prefills
        with the committed cost model (docs/serving.md "Multi-replica
        serving")."""
        with self._lock:
            info = self.scheduler.backlog()
            info["free_blocks"] = self.cache.num_free()
            info["running"] = self.scheduler.num_running()
            return info

    def waiting_by_tenant(self) -> dict:
        """Per-tenant queue depth (autoscaler pressure signal)."""
        return self.scheduler.waiting_by_tenant()

    # ------------------------------------------- block migration surface
    # (serving/migration.py; docs/serving.md "Disaggregated serving and
    # block migration"). All four methods run at step boundaries only —
    # the BlockMigration coordinator calls them through the owning
    # replica's lock from the router's step frame, where the per-request
    # invariant holds that every reserved cache slot has written KV.

    def migratable_requests(self, decode_only: bool = True) -> List[str]:
        """Request ids safe to export at this step boundary: RUNNING and
        unfinished. `decode_only=True` (handoff/rebalance) keeps only
        requests PAST prefill — the prefill→decode handoff point;
        `decode_only=False` (drain) also includes mid-prefill rows,
        whose committed prefix migrates and finishes prefilling at the
        destination."""
        with self._lock:
            out = []
            for req in self.scheduler.running_requests():
                if req.finished:
                    continue
                if decode_only and req.pf_target \
                        and req.prefill_pos < req.pf_target:
                    continue
                out.append(req.request_id)
            return out

    def export_request(self, request_id: str) -> dict:
        """Snapshot one RUNNING request for migration: the full request
        record (prompt, params, token log, FCFS ticket, deadline clock,
        prefill progress, trace id) plus its KV payload gathered from
        the pool (PagedKVCache.export_blocks — a COPY; source state is
        untouched, so a failed migration just keeps running here).
        Sampling needs no extra state: in-scan keys are
        fold_in(seed, tokens_generated), a function of progress the
        snapshot already carries."""
        with self._lock:
            req = self._requests[request_id]
            if req.state != RequestState.RUNNING:
                raise ValueError(
                    f"export_request: {request_id!r} is {req.state}, "
                    f"not running")
            payload, num_tokens = self.cache.export_blocks(request_id)
            if req.pf_target and req.prefill_pos < req.pf_target:
                valid = req.prefill_pos
            else:
                valid = len(req.prompt_ids) \
                    + max(0, len(req.output_ids) - 1)
            if num_tokens != valid:
                # only clean step boundaries satisfy written-KV == length
                raise ValueError(
                    f"export_request: {request_id!r} cache length "
                    f"{num_tokens} != written KV {valid} — not at a "
                    f"clean step boundary")
            return {
                "request_id": request_id,
                # (model, revision) key: the destination's
                # admit_migrated refuses a payload keyed for different
                # weights — KV is only valid under the parameters that
                # wrote it, so it must never cross a rollout boundary
                "model": self.config.model,
                "revision": self.config.revision,
                "prompt_ids": np.array(req.prompt_ids, np.int32),
                "params": req.params,
                "arrival": req.arrival,
                "arrival_time": req.arrival_time,
                "first_token_time": req.first_token_time,
                "last_token_time": req.last_token_time,
                "output_ids": list(req.output_ids),
                "pf_target": req.pf_target,
                "prefill_pos": req.prefill_pos,
                "trace_id": req.trace_id,
                "payload": payload,
                "num_tokens": num_tokens,
                "blocks": len(self.cache.block_table(request_id)),
                "bytes": self.cache.payload_bytes(payload),
            }

    def admit_migrated(self, snap: dict) -> str:
        """Destination half of a migration: import the KV payload into
        fresh private blocks, register its clean prefix into this
        engine's trie (hit rates survive the hop), and adopt the
        request straight into the RUNNING set — no re-prefill, no
        waiting-queue pass, FCFS ticket and deadline clock preserved.
        Raises CacheExhausted with NO side effects when the pool can't
        hold the table (the coordinator aborts; the request keeps
        running at the source)."""
        rid = snap["request_id"]
        key = (snap.get("model", self.config.model),
               snap.get("revision", self.config.revision))
        if key != (self.config.model, self.config.revision):
            # cross-revision refusal (serving/deploy.py): KV written by
            # other weights is garbage under these — raised BEFORE any
            # state is touched, so the coordinator aborts cleanly and
            # the request keeps running at its source
            raise ValueError(
                f"admit_migrated: {rid!r} payload is keyed "
                f"{key} but this engine serves "
                f"{(self.config.model, self.config.revision)} — "
                f"cross-revision KV refused")
        with self._lock:
            old = self._requests.get(rid)
            if old is not None and not old.finished:
                raise ValueError(
                    f"admit_migrated: {rid!r} already live here")
            req = Request(request_id=rid,
                          prompt_ids=snap["prompt_ids"],
                          params=snap["params"],
                          arrival_time=snap["arrival_time"])
            req.arrival = snap["arrival"]
            req.trace_id = snap["trace_id"]
            req.output_ids = list(snap["output_ids"])
            req.pf_target = snap["pf_target"]
            req.prefill_pos = snap["prefill_pos"]
            # TTFT was observed (once) wherever the first token was
            # emitted; preserving the stamps keeps the gap histograms
            # honest — the next emission's gap includes migration time
            req.first_token_time = snap["first_token_time"]
            req.last_token_time = snap["last_token_time"]
            worst = len(req.prompt_ids) + req.params.max_tokens
            if self.cache.blocks_needed(worst) > self.cache.num_blocks:
                raise ValueError(
                    f"admit_migrated: {rid!r} can never fit this pool "
                    f"({self.cache.blocks_needed(worst)} blocks at its "
                    f"longest vs {self.cache.num_blocks} total)")
            # the decode packing is a FIXED max_num_seqs rows — adopting
            # past it would index off the end of the batch, so sequence
            # slots exhaust with the same clean-abort signal as blocks
            live = sum(1 for r in self.scheduler.running
                       if not r.finished)
            if live >= self.config.max_num_seqs:
                raise CacheExhausted(rid, 1, 0,
                                     self.config.max_num_seqs,
                                     what="sequence slot")
            self.cache.import_blocks(rid, snap["payload"],
                                     snap["num_tokens"])
            self.scheduler.adopt_running(req)
            if self.cache.prefix_index is not None \
                    and snap["num_tokens"]:
                self.cache.register_prefix(
                    rid, req.all_token_ids()[:snap["num_tokens"]])
            self._requests[rid] = req
            self._rngs[rid] = np.random.RandomState(
                req.params.seed & 0x7FFFFFFF)
            return self.stats.label

    def release_migrated(self, request_id: str) -> None:
        """Source half, called only AFTER the destination committed:
        detach the request (state MIGRATED — terminal for this engine,
        no finish output) and free its blocks through the normal
        completion path, registering the clean prefix so the SOURCE
        trie keeps its entries and shared blocks just drop one
        reference."""
        with self._lock:
            req = self._requests[request_id]
            if req.state != RequestState.RUNNING:
                raise ValueError(
                    f"release_migrated: {request_id!r} is {req.state}, "
                    f"not running")
            self.scheduler.release_running(req)
            self._rngs.pop(request_id, None)

    def abort_migrated(self, request_id: str) -> None:
        """Destination rollback for a migration that failed AFTER
        admit_migrated (source died before releasing): drop the adopted
        request and free its imported blocks. The router re-admits the
        victim from its authoritative token log via the failover path —
        zero blocks leak on either end."""
        with self._lock:
            req = self._requests.pop(request_id, None)
            self._rngs.pop(request_id, None)
            if req is not None and req.state == RequestState.RUNNING:
                self.scheduler.abort_adopted(req)

    def release_waiting(self, request_id: str) -> Optional[Request]:
        """Drain evacuation of QUEUED work: pull a waiting request out
        without a terminal output (it has no KV to migrate — the router
        re-dispatches it to another replica from its token log).
        Returns the request, or None when it is not waiting."""
        with self._lock:
            req = self.scheduler.remove_waiting(request_id)
            if req is not None:
                self._rngs.pop(request_id, None)
            return req

    # --------------------------------------------- peer prefix fetch
    # (docs/serving.md "Hierarchical KV-cache tiering": a replica
    # missing a prefix pulls its blocks from a peer that holds them —
    # a BlockMigration-shaped transactional pull — before falling back
    # to re-prefill, so prefix-affinity routing degrades gracefully
    # after rebalance/failover instead of cliff-ing into cold caches.)

    def prefix_probe(self, prompt_ids) -> int:
        """Leading tokens of `prompt_ids` this engine could serve from
        its prefix cache, across BOTH tiers (device match + promotable
        host run) — the router compares probes to pick the donor."""
        with self._lock:
            toks = np.asarray(prompt_ids, np.int32).reshape(-1)
            return self.cache.match_len(toks) \
                + self.cache.host_match_len(toks)

    def export_prefix(self, prompt_ids) -> Optional[dict]:
        """Donor half of a peer prefix fetch: snapshot the longest
        cached full-block prefix of `prompt_ids` (both tiers, digests
        included), keyed by this engine's (model, revision). Read-only;
        None when nothing matches."""
        with self._lock:
            snap = self.cache.export_prefix(
                np.asarray(prompt_ids, np.int32).reshape(-1))
            if snap is not None:
                snap["model"] = self.config.model
                snap["revision"] = self.config.revision
            return snap

    def admit_prefix(self, prompt_ids, blocks, model: str = None,
                     revision: str = None) -> int:
        """Receiver half: verify and install a peer's prefix snapshot
        as locally cached (evictable) blocks. Raises ValueError on an
        integrity mismatch OR on a payload keyed for a different
        (model, revision) — stale prefix KV must never serve another
        revision's requests — and CacheExhausted when the pool cannot
        hold it; all with atomic-abort semantics (nothing mutated)."""
        key = (self.config.model if model is None else model,
               self.config.revision if revision is None else revision)
        if key != (self.config.model, self.config.revision):
            raise ValueError(
                f"admit_prefix: payload keyed {key} but this engine "
                f"serves {(self.config.model, self.config.revision)} — "
                f"cross-revision prefix refused")
        with self._lock:
            return self.cache.admit_prefix(
                np.asarray(prompt_ids, np.int32).reshape(-1), blocks)

    # ---------------------------------------------------------- sampling
    @holds_lock("_lock")
    def _sample(self, req: Request, logits: np.ndarray) -> int:
        p = req.params
        if p.temperature <= 0.0:
            return int(np.argmax(logits))
        lg = logits.astype(np.float64) / p.temperature
        if p.top_k:
            kth = np.sort(lg)[-p.top_k]
            lg = np.where(lg < kth, -np.inf, lg)
        if 0.0 < p.top_p < 1.0:
            srt = np.sort(lg)[::-1]
            probs = np.exp(srt - srt.max())
            probs /= probs.sum()
            excl = np.cumsum(probs) - probs
            kth = srt[int((excl < p.top_p).sum()) - 1]
            lg = np.where(lg < kth, -np.inf, lg)
        probs = np.exp(lg - lg.max())
        probs /= probs.sum()
        return int(self._rngs[req.request_id].choice(len(probs), p=probs))

    @holds_lock("_lock")
    def _emit(self, req: Request, tok: int, outs: List[RequestOutput]):
        """Record one sampled token, handle completion, stream it out."""
        now = time.perf_counter()
        if req.first_token_time is None:
            req.first_token_time = now
            # TTFT is recorded HERE, exactly once per request at its
            # first token (tests/test_observability.py pins once-ness);
            # ttft_sum below stays the completed-only accumulator
            self.stats.observe_ttft(now - req.arrival_time)
            obs.reqtrace.record("first_token", req.tid, req.request_id,
                                ttft_s=now - req.arrival_time,
                                **(self._rev_tag or {}))
        else:
            # per-token latency: gap since this request's previous token
            self.stats.observe_token_gap(now - req.last_token_time)
        req.last_token_time = now
        req.output_ids.append(tok)
        self.stats.generated_tokens += 1
        finished, reason = False, None
        if req.params.eos_token_id is not None \
                and tok == req.params.eos_token_id:
            finished, reason = True, "stop"
            state = RequestState.FINISHED_STOPPED
        elif len(req.output_ids) >= req.params.max_tokens:
            finished, reason = True, "length"
            state = RequestState.FINISHED_LENGTH
        if finished:
            self.scheduler.finish(req, state)
            req.finish_time = now
            self.stats.completed += 1
            self.stats.ttft_sum += req.first_token_time - req.arrival_time
            self.stats.latency_sum += now - req.arrival_time
            self.stats.observe_latency(now - req.arrival_time)
            obs.reqtrace.record("finish", req.tid, req.request_id,
                                reason=reason,
                                tokens=len(req.output_ids),
                                **(self._rev_tag or {}))
        outs.append(RequestOutput(req.request_id, tok,
                                  list(req.output_ids), finished, reason))

    # --------------------------------------------- robustness primitives
    def _finish_abnormal(self, req: Request, state: str, reason: str,
                         outs: List[RequestOutput], scrub: bool = False):
        """Terminal path for timeout/shed/error: detach (freeing blocks
        iff running), stamp, stream the terminal RequestOutput."""
        if req.state == RequestState.RUNNING:
            self.scheduler.finish(req, state, scrub=scrub)
        else:
            req.state = state
        req.finish_time = time.perf_counter()
        outs.append(RequestOutput(req.request_id, None,
                                  list(req.output_ids), True, reason))
        obs.reqtrace.record("finish", req.tid, req.request_id,
                            reason=reason, tokens=len(req.output_ids),
                            **(self._rev_tag or {}))

    @holds_lock("_lock")
    def _expire_and_abort(self, outs: List[RequestOutput]):
        """Step-boundary deadline enforcement: expire queued requests
        past queue_ttl_s/deadline_s, abort running ones past
        deadline_s."""
        now = time.perf_counter()
        for req in self.scheduler.expire_waiting(now):
            self.stats.expired += 1
            req.finish_time = now
            outs.append(RequestOutput(req.request_id, None,
                                      list(req.output_ids), True,
                                      "timeout"))
            obs.reqtrace.record("finish", req.tid, req.request_id,
                                reason="timeout",
                                **(self._rev_tag or {}))
        for req in self.scheduler.overdue_running(now):
            self.stats.timeouts += 1
            self._finish_abnormal(req, RequestState.FINISHED_TIMEOUT,
                                  "timeout", outs)

    @holds_lock("_lock")
    def _wedged(self) -> bool:
        """Watchdog check at phase boundaries: has this step overrun its
        step_timeout_s budget? (A hard device hang blocks Python
        entirely — that is what the elastic supervisor's heartbeat
        catches; this watchdog handles the soft case where a phase
        returns but has already blown the step's latency budget.)"""
        t = self.config.step_timeout_s
        return t is not None and \
            (time.perf_counter() - self._step_start) > t

    @holds_lock("_lock")
    def _quarantine(self, req: Request, outs: List[RequestOutput],
                    why: str):
        """One poisoned/wedged request costs one request: error-terminal,
        blocks scrubbed (NaN survives the attention mask) and freed."""
        self.stats.errors += 1
        obs.reqtrace.record("quarantine", req.tid, req.request_id,
                            why=why, engine=self.stats.label)
        self._finish_abnormal(req, RequestState.FINISHED_ERROR, "error",
                              outs, scrub=True)
        # flight recorder: a quarantine is a postmortem trigger — when
        # armed, ship the victim's full timeline + registry snapshot.
        # The dump is file I/O, so it is only QUEUED here; step() writes
        # it after the engine lock is released (PT-C003) — a slow disk
        # must not stall intake threads mid-step.
        self._flights.append((
            "quarantine", [req.tid],
            {"why": why, "engine": self.stats.label,
             "request_id": req.request_id}))

    @holds_lock("_lock")
    def _recover(self, decode: List[Request], offenders: List[Request],
                 outs: List[RequestOutput], why: str):
        """Crash recovery for a poisoned/wedged decode step: the step's
        outputs are already discarded (nothing was emitted); quarantine
        the offenders and rebuild every surviving decode request by
        scrub-freeing its blocks and requeueing it (arrival-ordered) for
        re-prefill from its token log — proven bitwise-equivalent to an
        unfaulted run for the survivors (tests/test_serving_robustness)."""
        self.stats.recoveries += 1
        for req in offenders:
            self._quarantine(req, outs, why)
        survivors = [r for r in decode if r not in offenders]
        for req in survivors:
            self.scheduler.requeue_for_recovery(req)
            self.stats.rebuilt += 1

    # -------------------------------------------------------------- step
    def step(self) -> List[RequestOutput]:
        """One engine iteration: expire/abort overdue requests, schedule,
        prefill admitted requests, decode every running sequence, stream
        the new tokens — under the anomaly guard + watchdog (module
        docstring)."""
        from ...distributed import elastic
        elastic.heartbeat()                  # no-op when unsupervised
        with self._lock:
            outs = self._step_locked()
            flights, self._flights = self._flights, []
        # flight-recorder dumps queued by _quarantine are written here,
        # AFTER the engine lock is released (PT-C003). In fleet mode
        # this still rides under the owning replica's lock — that lock
        # is per-replica, so the blast radius of slow disk I/O is one
        # replica, not the router or its siblings.
        for reason, ids, extra in flights:
            obs.reqtrace.maybe_flight(reason, ids, extra=extra)
        return outs

    @holds_lock("_lock")
    def _step_locked(self) -> List[RequestOutput]:
        outs: List[RequestOutput] = list(self._pending_outputs)
        self._pending_outputs.clear()
        self.stats.steps += 1
        step_no = self.stats.steps
        self._step_start = time.perf_counter()
        with RecordEvent("serving.engine_step", cat="serving") as step_ev:
            # ptlint: disable=PT-C004  fault injector: inert no-op in
            # production (env-gated); chaos tests NEED it inside the lock
            # to corrupt state at the exact point a real fault would
            self.faults.corrupt_cache(step_no, self.cache)
            # ptlint: disable=PT-C004  fault injector (see above)
            self.faults.corrupt_host_block(step_no, self.cache)
            # re-arm the cache's demote/promote fault hooks at this
            # step so kill_promotion/kill_demotion specs fire on the
            # engine-step clock like every other serving fault
            # ptlint: disable=PT-C004  fault injector (see above)
            self.cache.arm_tier_faults(self.faults, step_no)
            self._expire_and_abort(outs)
            t0 = time.perf_counter()
            with RecordEvent("serving.schedule", cat="schedule") as ev:
                batch = self.scheduler.schedule()
                ev.args = {"prefill": len(batch.prefill),
                           "decode": len(batch.decode),
                           "preempted": len(batch.preempted),
                           "waiting": self.scheduler.num_waiting(),
                           "free_blocks": self.cache.num_free()}
            self.stats.preemptions += len(batch.preempted)
            self.stats.time_schedule += time.perf_counter() - t0

            prefill_spend = 0
            for req in batch.prefill:
                t0 = time.perf_counter()
                tokens = req.all_token_ids()
                with RecordEvent("serving.prefill", cat="prefill") as ev:
                    ev.args = {"request_id": req.request_id,
                               "tokens": int(tokens.size)}
                    try:
                        logits = self._prefill(req, tokens)
                    except Exception as e:
                        self._quarantine(req, outs, f"prefill raised: {e}")
                        continue
                self.stats.prefill_tokens += int(tokens.size)
                prefill_spend += int(tokens.size)
                self.stats.time_prefill += time.perf_counter() - t0
                # ptlint: disable=PT-C004  fault injector (see step())
                logits = self.faults.poison_logits(step_no, logits)
                # logits are already host numpy (_prefill fetched them);
                # the host-side check avoids re-uploading them through a
                # jnp reduction every step (ptlint PT-T002's defect
                # class: a device round-trip per prefill)
                if anomaly.any_not_finite_host(logits):
                    self._quarantine(req, outs,
                                     "non-finite prefill logits")
                    continue
                obs.reqtrace.record("prefill", req.tid, req.request_id,
                                    tokens=int(tokens.size))
                self._emit(req, self._sample(req, logits), outs)
                if not req.finished and self._wedged():
                    # prefill attribution is exact: the request whose
                    # forward blew the budget is the one in hand
                    self.stats.watchdog_trips += 1
                    self._quarantine(req, outs, "wedged prefill")

            # requests finished right at prefill release their blocks
            # before the decode gather builds its tables
            decode = [r for r in batch.decode if not r.finished]
            if decode:
                t0 = time.perf_counter()
                k = self.config.decode_chunk_size
                with RecordEvent("serving.decode", cat="decode") as ev:
                    ev.args = {"num_seqs": len(decode), "chunk": k}
                    # ptlint: disable=PT-C004  fault injector: stalls ON
                    # PURPOSE under the lock to exercise the watchdog
                    self.faults.stall(step_no)
                    try:
                        toks, bad = self._decode_chunk(decode, k)
                    except Exception as e:
                        toks = None
                        self._recover(decode, [decode[0]], outs,
                                      f"decode raised: {e}")
                dt = time.perf_counter() - t0
                self.stats.time_decode += dt
                self.stats.observe_decode_chunk(dt)
                if toks is not None:
                    # the not-finite flags were computed IN-SCAN and
                    # arrived with the chunk fetch — anomaly attribution
                    # costs no extra sync (and no host re-reduction)
                    # ptlint: disable=PT-C004  fault injector (see step())
                    bad = self.faults.poison_chunk(step_no, bad)
                    if bad.any():
                        # a bad row poisons the whole chunk: every
                        # emission is discarded, offenders quarantined,
                        # survivors requeued — replay is bitwise because
                        # sampling keys depend only on request progress
                        self._recover(
                            decode,
                            [r for i, r in enumerate(decode) if bad[i]],
                            outs, "non-finite decode logits in chunk")
                    elif self._wedged():
                        # a wedged batched chunk cannot be attributed;
                        # quarantine its head (deterministic) and rebuild
                        # the rest — the whole chunk's tokens are dropped
                        # so survivors stay bitwise on the replay
                        self.stats.watchdog_trips += 1
                        self._recover(decode, [decode[0]], outs,
                                      "wedged decode chunk (watchdog)")
                    else:
                        # step-major drain of the fetched chunk: row j of
                        # toks is scan step j; -1 marks a frozen row.
                        # _emit re-derives eos/max_tokens terminals on
                        # host — the same conditions the device froze on
                        # — so telemetry and finish_reason stay exact.
                        emitted: Dict[str, int] = {}
                        for j in range(toks.shape[0]):
                            for i, req in enumerate(decode):
                                t = int(toks[j, i])
                                if t >= 0 and not req.finished:
                                    self._emit(req, t, outs)
                                    emitted[req.request_id] = \
                                        emitted.get(req.request_id, 0) + 1
                        # chunk-boundary trace events: tokens emitted
                        # per row + the finish latch (host values only)
                        for req in decode:
                            n_emit = emitted.get(req.request_id, 0)
                            if n_emit:
                                obs.reqtrace.record(
                                    "decode_chunk", req.tid,
                                    req.request_id, n=n_emit,
                                    total=len(req.output_ids),
                                    finished=req.finished,
                                    **(self._rev_tag or {}))
            step_ev.args = {"step": step_no, "outputs": len(outs),
                            "errors": self.stats.errors,
                            "expired": self.stats.expired,
                            "shed": self.stats.shed,
                            "recoveries": self.stats.recoveries}
        # per-step telemetry: all host values already in hand (scheduler
        # counters, cache free lists) — recording adds no device work
        step_dt = time.perf_counter() - self._step_start
        self.stats.observe_step(step_dt)
        # feed the measured service rate to the scheduler's deadline
        # early-reject estimator (inert without a tenant registry)
        self.scheduler.note_step_seconds(step_dt)
        self.stats.set_prefill_spend(prefill_spend)
        if self.stats.generated_tokens:
            self.stats.set_syncs_per_token(
                self.stats.host_syncs("decode")
                / self.stats.generated_tokens)
        self.stats.set_step_gauges(
            running=self.scheduler.num_running(),
            waiting=self.scheduler.num_waiting(),
            blocks_used=self.cache.num_used(),
            blocks_free=self.cache.num_free())
        if self.cache.prefix_index is not None:
            self.stats.record_prefix(self.cache.prefix_stats())
            for dt in self.cache.drain_promote_seconds():
                self.stats.observe_promote(dt)
        return outs

    @holds_lock("_lock")
    def _prefill(self, req: Request, tokens: np.ndarray) -> np.ndarray:
        """Dense prefill (shared jitted program with generate()),
        scattered into the sequence's blocks. One upload (the prompt),
        one fetch (the last-position logits [V]) — already the minimal
        host/device traffic for a prompt forward."""
        logits, dense_cache = gen.prefill(
            self.params, jnp.asarray(tokens[None], jnp.int32), self.geom)
        self.cache.write_prefill(req.request_id, dense_cache, tokens.size)
        if self.cache.prefix_index is not None:
            # every prompt position's KV is now written — index the
            # full blocks immediately so template siblings queued
            # behind this request already hit
            self.cache.register_prefix(req.request_id, tokens)
        out = np.asarray(logits[0])
        self.stats.inc_host_sync("prefill")
        return out

    @holds_lock("_lock")
    def _decode_chunk(self, reqs: List[Request], k: int):
        """Fused k-token device-resident decode for all running
        sequences — padded to the ONE fixed max_num_seqs width under the
        default ragged kernel (dead rows cost zero kernel work, so a
        single compilation covers every batch mix), or to the power-of-
        two bucket under kernel="bucketed". The per-sequence control
        state (last token, position, sampling knobs, prefill feed, block
        table) travels as ONE packed int32 upload; the result — k
        sampled tokens per row plus the finished and not-finite masks —
        comes back in ONE fetch. Mid-prefill rows (chunked prefill) get
        their next min(k, remaining-prompt) tokens packed into the feed
        columns and advance prefill_pos iff the chunk came back clean.
        Returns (tokens [k, len(reqs)] int32 with -1 on frozen rows,
        bad [len(reqs)] bool)."""
        ragged = self.config.kernel == "ragged"
        n = self.config.max_num_seqs if ragged \
            else _bucket(len(reqs), self.config.max_num_seqs)
        mb = self.max_blocks_per_seq
        packed = np.zeros((n, PACK_COLS + k + mb), np.int32)
        fed = []                             # (req, tokens consumed)
        for i, req in enumerate(reqs):
            p = req.params
            packed[i, 0] = req.last_token
            packed[i, 1] = req.slot[2]       # first reserved position
            packed[i, 2] = 1                 # active (padding rows: 0)
            packed[i, 3] = len(req.output_ids)
            packed[i, 4] = p.max_tokens
            packed[i, 5] = -1 if p.eos_token_id is None \
                else int(p.eos_token_id)
            packed[i, 6] = pack_f32(p.temperature)
            packed[i, 7] = int(p.top_k)
            packed[i, 8] = pack_f32(p.top_p)
            packed[i, 9] = p.seed & 0x7FFFFFFF
            if req.prefill_pos < req.pf_target:
                pf_rem = req.pf_target - req.prefill_pos
                f = min(k, pf_rem)
                packed[i, 10] = f
                packed[i, 11] = 1 if pf_rem > k else 0
                prompt = req.all_token_ids()
                packed[i, PACK_COLS:PACK_COLS + f] = \
                    prompt[req.prefill_pos:req.prefill_pos + f]
                fed.append((req, f))
            table = self.cache.block_table(req.request_id)
            packed[i, PACK_COLS + k:PACK_COLS + k + len(table)] = table
        out, pools = fused_decode_chunk(
            self.params, self.cache.pools, jnp.asarray(packed),
            self.geom, k, self.config.kernel)
        self.cache.pools = pools
        fetched = np.asarray(out)            # the chunk's ONE host sync
        self.stats.inc_host_sync("decode")
        live = len(reqs)
        # padded-vs-live telemetry: the bucketed fallback burns compute
        # on its dead rows; the ragged kernel's length gating skips them
        self.stats.set_padding_waste(0.0 if ragged else (n - live) / n)
        if fed:
            self.stats.inc_prefill_chunks(len(fed))
        bad = fetched[k + 1, :live].astype(bool)
        if not bad.any():
            # a bad chunk is discarded wholesale (offenders quarantined,
            # survivors requeued with pf state reset), so prefill
            # progress only commits on a clean fetch
            for req, f in fed:
                req.prefill_pos += f
                obs.reqtrace.record(
                    "prefill_chunk", req.tid, req.request_id, fed=f,
                    pos=req.prefill_pos, target=req.pf_target)
                if self.cache.prefix_index is not None:
                    # committed prefill progress is valid KV: index the
                    # newly completed full blocks so concurrent template
                    # siblings share them while this row still prefills
                    self.cache.register_prefix(
                        req.request_id,
                        req.all_token_ids()[:req.prefill_pos])
        return fetched[:k, :live], bad

    # ------------------------------------------------------- convenience
    def run(self, max_steps: int = None) -> Dict[str, np.ndarray]:
        """Drive every queued request to completion; returns
        {request_id: np.ndarray of generated token ids}."""
        steps = 0
        # NOTE: the drain loop itself runs unlocked — each step() takes
        # the lock for one iteration, so intake threads (add_request /
        # cancel) interleave at step boundaries instead of blocking for
        # the whole drain
        while self.has_unfinished():
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                raise RuntimeError(
                    f"engine did not drain within {max_steps} steps")
        with self._lock:
            # MIGRATED tombstones hold a PARTIAL stream — the request's
            # real output finishes on its destination engine (the router
            # record is the place to read it)
            return {rid: np.asarray(r.output_ids, np.int64)
                    for rid, r in self._requests.items()
                    if r.state not in (RequestState.CANCELLED,
                                       RequestState.MIGRATED)}


class ServingPredictor:
    """Paddle-parity predictor facade over LLMEngine (the serving twin
    of inference.Predictor, dispatched by create_predictor when
    Config.enable_llm_engine was called — mirroring how
    AnalysisPredictor picks its engine off config flags).

    IO surface: input 'input_ids' [B, T] (right-padded) + optional
    'prompt_lens' [B]; output 'sequences' [B, T_out] right-padded with
    the pad token (eos when set, else 0).
    """

    def __init__(self, config):
        model = getattr(config, "_llm_model", None)
        if model is None:
            raise ValueError(
                "Config.enable_llm_engine(model=...) must receive the "
                "model object; serving runs live parameters, not a "
                "serialized artifact")
        opts = dict(getattr(config, "_llm_options", {}) or {})
        self._sampling = SamplingParams(**{
            k: opts.pop(k) for k in list(opts)
            if k in SamplingParams.__dataclass_fields__})
        self.engine = LLMEngine.from_model(model, EngineConfig(**opts))
        from .. import Tensor
        self._inputs = {n: Tensor(n)
                        for n in ("input_ids", "prompt_lens")}
        self._outputs = {}

    def get_input_names(self):
        return ["input_ids", "prompt_lens"]

    def get_input_handle(self, name):
        return self._inputs[name]

    def get_output_names(self):
        return ["sequences"]

    def get_output_handle(self, name):
        return self._outputs[name]

    def run(self, inputs: Optional[list] = None):
        from ...distributed import elastic
        elastic.heartbeat()                  # no-op when unsupervised
        if inputs is not None:
            self._inputs["input_ids"].copy_from_cpu(
                np.asarray(inputs[0]))
            if len(inputs) > 1:
                self._inputs["prompt_lens"].copy_from_cpu(
                    np.asarray(inputs[1]))
        ids_h = self._inputs["input_ids"]
        if ids_h._arr is None:
            raise RuntimeError("input 'input_ids' not set")
        ids = np.asarray(ids_h._arr)
        lens_h = self._inputs["prompt_lens"]
        lens = (np.asarray(lens_h._arr).astype(int).reshape(-1)
                if lens_h._arr is not None
                else np.full(ids.shape[0], ids.shape[1]))
        rids = [self.engine.add_request(ids[b, :lens[b]], self._sampling)
                for b in range(ids.shape[0])]
        results = self.engine.run()
        pad = self._sampling.eos_token_id
        pad = 0 if pad is None else int(pad)
        width = max(int(lens[b]) + len(results[r].tolist())
                    for b, r in enumerate(rids))
        out = np.full((ids.shape[0], width), pad, np.int64)
        for b, rid in enumerate(rids):
            seq = np.concatenate([ids[b, :lens[b]].astype(np.int64),
                                  results[rid]])
            out[b, :seq.size] = seq
        from .. import Tensor
        t = Tensor("sequences")
        t._arr = jnp.asarray(out)
        self._outputs = {"sequences": t}
        if inputs is not None:
            return [out]
        return None

    # Predictor-surface parity no-ops
    def clear_intermediate_tensor(self):
        pass

    def try_shrink_memory(self):
        pass
