"""LLMEngine: continuous-batching serving loop over the paged KV cache.

The serving analogue of the reference inference layer's
AnalysisPredictor::Run — but instead of one synchronous batch per call,
requests stream in (add_request), the engine interleaves prefill and
decode per step() under the scheduler's FCFS/preemption policy, and
outputs stream back token by token.

Device work per step:
- prefill: models.generation.prefill (the SAME jitted program the dense
  generate() path uses — one compilation per prompt-length bucket),
  scattered into the sequence's blocks (PagedKVCache.write_prefill);
- decode: serving.attention.paged_decode_step over ALL running
  sequences at once, padded to a power-of-two bucket capped at
  max_num_seqs, so XLA compiles once per bucket and never recompiles
  per request mix.

Sampling is host-side numpy (greedy argmax / temperature + top-k/top-p)
with a per-request RNG: continuous batching must not change results, so
greedy engine output token-matches models.generation.generate
(tests/test_serving.py pins this end to end, preemptions included).

Every phase runs under a profiler.RecordEvent span (cat="serving") so a
serving trace exported with profiler.export_chrome_tracing shows
schedule/prefill/decode per engine step, with request counts in args.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np
import jax.numpy as jnp

from ...models import generation as gen
from ...profiler import RecordEvent
from .attention import paged_decode_step
from .paged_cache import PagedKVCache
from .scheduler import (Request, RequestState, SamplingParams,
                        ScheduledBatch, Scheduler, SchedulerConfig)

__all__ = ["EngineConfig", "EngineStats", "LLMEngine", "RequestOutput",
           "ServingPredictor"]


@dataclass
class EngineConfig:
    block_size: int = 16
    num_blocks: int = 256
    max_num_seqs: int = 8
    max_prefill_tokens: int = 2048


@dataclass
class RequestOutput:
    """One streamed step result for one request."""
    request_id: str
    new_token: Optional[int]
    token_ids: List[int]                 # all generated tokens so far
    finished: bool
    finish_reason: Optional[str] = None  # 'stop' | 'length' | 'cancelled'


@dataclass
class EngineStats:
    steps: int = 0
    prefill_tokens: int = 0
    generated_tokens: int = 0
    preemptions: int = 0
    completed: int = 0
    cancelled: int = 0
    time_schedule: float = 0.0
    time_prefill: float = 0.0
    time_decode: float = 0.0
    ttft_sum: float = 0.0                # time-to-first-token accumulator
    latency_sum: float = 0.0             # request wall time accumulator

    def as_dict(self) -> dict:
        d = dict(self.__dict__)
        done = max(self.completed, 1)
        d["avg_ttft_s"] = self.ttft_sum / done
        d["avg_request_latency_s"] = self.latency_sum / done
        busy = self.time_prefill + self.time_decode
        d["decode_tokens_per_sec"] = (
            self.generated_tokens / busy if busy > 0 else 0.0)
        return d


def _bucket(n: int, cap: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return min(b, cap)


class LLMEngine:
    """Continuous-batching engine over (params, geom) — the pure-JAX
    decode substrate of models.generation, served paged."""

    def __init__(self, params, geom, config: EngineConfig = None):
        config = config or EngineConfig()
        L, H, D, S = geom
        if S % config.block_size != 0:
            # divisibility keeps the gathered context bitwise-identical
            # to the dense cache layout (and write_prefill rectangular)
            raise ValueError(
                f"block_size {config.block_size} must divide "
                f"max_seq_len {S}")
        self.params = params
        self.geom = geom
        self.config = config
        self.max_blocks_per_seq = S // config.block_size
        self.cache = PagedKVCache(L, H, D, config.num_blocks,
                                  config.block_size)
        self.scheduler = Scheduler(
            SchedulerConfig(max_num_seqs=config.max_num_seqs,
                            max_prefill_tokens=config.max_prefill_tokens),
            self.cache)
        self.stats = EngineStats()
        self._requests: Dict[str, Request] = {}
        self._rngs: Dict[str, np.random.RandomState] = {}
        self._next_id = 0

    @classmethod
    def from_model(cls, model, config: EngineConfig = None):
        cfg = model.cfg
        geom = (cfg.num_layers, cfg.num_heads,
                cfg.hidden_size // cfg.num_heads, cfg.max_seq_len)
        return cls(gen.extract_params(model), geom, config)

    # ------------------------------------------------------------ intake
    def add_request(self, prompt_ids, sampling: SamplingParams = None,
                    request_id: str = None) -> str:
        sampling = sampling or SamplingParams()
        ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        if ids.size == 0:
            raise ValueError("empty prompt")
        S = self.geom[3]
        if ids.size + sampling.max_tokens > S:
            raise ValueError(
                f"prompt {ids.size} + max_tokens {sampling.max_tokens} "
                f"exceeds max_seq_len {S}")
        if request_id is None:
            request_id = f"req-{self._next_id}"
            self._next_id += 1
        if request_id in self._requests:
            raise ValueError(f"duplicate request_id {request_id!r}")
        req = Request(request_id=request_id, prompt_ids=ids,
                      params=sampling, arrival_time=time.perf_counter())
        self.scheduler.add(req)              # validates pool fit
        self._requests[request_id] = req
        self._rngs[request_id] = np.random.RandomState(
            sampling.seed & 0x7FFFFFFF)
        return request_id

    def cancel(self, request_id: str) -> bool:
        ok = self.scheduler.cancel(request_id)
        if ok:
            self.stats.cancelled += 1
            req = self._requests[request_id]
            req.finish_time = time.perf_counter()
        return ok

    def has_unfinished(self) -> bool:
        return self.scheduler.has_unfinished()

    def get_request(self, request_id: str) -> Request:
        return self._requests[request_id]

    # ---------------------------------------------------------- sampling
    def _sample(self, req: Request, logits: np.ndarray) -> int:
        p = req.params
        if p.temperature <= 0.0:
            return int(np.argmax(logits))
        lg = logits.astype(np.float64) / p.temperature
        if p.top_k:
            kth = np.sort(lg)[-p.top_k]
            lg = np.where(lg < kth, -np.inf, lg)
        if 0.0 < p.top_p < 1.0:
            srt = np.sort(lg)[::-1]
            probs = np.exp(srt - srt.max())
            probs /= probs.sum()
            excl = np.cumsum(probs) - probs
            kth = srt[int((excl < p.top_p).sum()) - 1]
            lg = np.where(lg < kth, -np.inf, lg)
        probs = np.exp(lg - lg.max())
        probs /= probs.sum()
        return int(self._rngs[req.request_id].choice(len(probs), p=probs))

    def _emit(self, req: Request, tok: int, outs: List[RequestOutput]):
        """Record one sampled token, handle completion, stream it out."""
        now = time.perf_counter()
        if req.first_token_time is None:
            req.first_token_time = now
        req.output_ids.append(tok)
        self.stats.generated_tokens += 1
        finished, reason = False, None
        if req.params.eos_token_id is not None \
                and tok == req.params.eos_token_id:
            finished, reason = True, "stop"
            state = RequestState.FINISHED_STOPPED
        elif len(req.output_ids) >= req.params.max_tokens:
            finished, reason = True, "length"
            state = RequestState.FINISHED_LENGTH
        if finished:
            self.scheduler.finish(req, state)
            req.finish_time = now
            self.stats.completed += 1
            self.stats.ttft_sum += req.first_token_time - req.arrival_time
            self.stats.latency_sum += now - req.arrival_time
        outs.append(RequestOutput(req.request_id, tok,
                                  list(req.output_ids), finished, reason))

    # -------------------------------------------------------------- step
    def step(self) -> List[RequestOutput]:
        """One engine iteration: schedule, prefill admitted requests,
        decode every running sequence, stream the new tokens."""
        outs: List[RequestOutput] = []
        self.stats.steps += 1
        with RecordEvent("serving.engine_step", cat="serving") as step_ev:
            t0 = time.perf_counter()
            with RecordEvent("serving.schedule", cat="serving") as ev:
                batch = self.scheduler.schedule()
                ev.args = {"prefill": len(batch.prefill),
                           "decode": len(batch.decode),
                           "preempted": len(batch.preempted),
                           "free_blocks": self.cache.num_free()}
            self.stats.preemptions += len(batch.preempted)
            self.stats.time_schedule += time.perf_counter() - t0

            for req in batch.prefill:
                t0 = time.perf_counter()
                tokens = req.all_token_ids()
                with RecordEvent("serving.prefill", cat="serving") as ev:
                    ev.args = {"request_id": req.request_id,
                               "tokens": int(tokens.size)}
                    logits = self._prefill(req, tokens)
                self.stats.prefill_tokens += int(tokens.size)
                self.stats.time_prefill += time.perf_counter() - t0
                self._emit(req, self._sample(req, logits), outs)

            # requests finished right at prefill release their blocks
            # before the decode gather builds its tables
            decode = [r for r in batch.decode if not r.finished]
            if decode:
                t0 = time.perf_counter()
                with RecordEvent("serving.decode", cat="serving") as ev:
                    ev.args = {"num_seqs": len(decode)}
                    logits = self._decode(decode)
                self.stats.time_decode += time.perf_counter() - t0
                for i, req in enumerate(decode):
                    self._emit(req, self._sample(req, logits[i]), outs)
            step_ev.args = {"step": self.stats.steps,
                            "outputs": len(outs)}
        return outs

    def _prefill(self, req: Request, tokens: np.ndarray) -> np.ndarray:
        """Dense prefill (shared jitted program with generate()),
        scattered into the sequence's blocks. Returns last-position
        logits [V]."""
        logits, dense_cache = gen.prefill(
            self.params, jnp.asarray(tokens[None], jnp.int32), self.geom)
        self.cache.write_prefill(req.request_id, dense_cache, tokens.size)
        return np.asarray(logits[0])

    def _decode(self, reqs: List[Request]) -> np.ndarray:
        """Ragged paged decode for all running sequences, padded to the
        power-of-two bucket. Returns logits [len(reqs), V]."""
        n = _bucket(len(reqs), self.config.max_num_seqs)
        mb, nb = self.max_blocks_per_seq, self.config.num_blocks
        tokens = np.zeros(n, np.int32)
        positions = np.zeros(n, np.int32)
        tables = np.zeros((n, mb), np.int32)
        # padded rows scatter out of bounds -> dropped by the kernel
        slot_blocks = np.full(n, nb, np.int32)
        slot_offsets = np.zeros(n, np.int32)
        for i, req in enumerate(reqs):
            block, offset, pos = req.slot
            tokens[i] = req.last_token
            positions[i] = pos
            slot_blocks[i] = block
            slot_offsets[i] = offset
            table = self.cache.block_table(req.request_id)
            tables[i, :len(table)] = table
        logits, pools = paged_decode_step(
            self.params, self.cache.pools, jnp.asarray(tokens),
            jnp.asarray(positions), jnp.asarray(tables),
            jnp.asarray(slot_blocks), jnp.asarray(slot_offsets),
            self.geom)
        self.cache.pools = pools
        return np.asarray(logits)[:len(reqs)]

    # ------------------------------------------------------- convenience
    def run(self, max_steps: int = None) -> Dict[str, np.ndarray]:
        """Drive every queued request to completion; returns
        {request_id: np.ndarray of generated token ids}."""
        steps = 0
        while self.has_unfinished():
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                raise RuntimeError(
                    f"engine did not drain within {max_steps} steps")
        return {rid: np.asarray(r.output_ids, np.int64)
                for rid, r in self._requests.items()
                if r.state != RequestState.CANCELLED}


class ServingPredictor:
    """Paddle-parity predictor facade over LLMEngine (the serving twin
    of inference.Predictor, dispatched by create_predictor when
    Config.enable_llm_engine was called — mirroring how
    AnalysisPredictor picks its engine off config flags).

    IO surface: input 'input_ids' [B, T] (right-padded) + optional
    'prompt_lens' [B]; output 'sequences' [B, T_out] right-padded with
    the pad token (eos when set, else 0).
    """

    def __init__(self, config):
        model = getattr(config, "_llm_model", None)
        if model is None:
            raise ValueError(
                "Config.enable_llm_engine(model=...) must receive the "
                "model object; serving runs live parameters, not a "
                "serialized artifact")
        opts = dict(getattr(config, "_llm_options", {}) or {})
        self._sampling = SamplingParams(**{
            k: opts.pop(k) for k in list(opts)
            if k in SamplingParams.__dataclass_fields__})
        self.engine = LLMEngine.from_model(model, EngineConfig(**opts))
        from .. import Tensor
        self._inputs = {n: Tensor(n)
                        for n in ("input_ids", "prompt_lens")}
        self._outputs = {}

    def get_input_names(self):
        return ["input_ids", "prompt_lens"]

    def get_input_handle(self, name):
        return self._inputs[name]

    def get_output_names(self):
        return ["sequences"]

    def get_output_handle(self, name):
        return self._outputs[name]

    def run(self, inputs: Optional[list] = None):
        if inputs is not None:
            self._inputs["input_ids"].copy_from_cpu(
                np.asarray(inputs[0]))
            if len(inputs) > 1:
                self._inputs["prompt_lens"].copy_from_cpu(
                    np.asarray(inputs[1]))
        ids_h = self._inputs["input_ids"]
        if ids_h._arr is None:
            raise RuntimeError("input 'input_ids' not set")
        ids = np.asarray(ids_h._arr)
        lens_h = self._inputs["prompt_lens"]
        lens = (np.asarray(lens_h._arr).astype(int).reshape(-1)
                if lens_h._arr is not None
                else np.full(ids.shape[0], ids.shape[1]))
        rids = [self.engine.add_request(ids[b, :lens[b]], self._sampling)
                for b in range(ids.shape[0])]
        results = self.engine.run()
        pad = self._sampling.eos_token_id
        pad = 0 if pad is None else int(pad)
        width = max(int(lens[b]) + len(results[r].tolist())
                    for b, r in enumerate(rids))
        out = np.full((ids.shape[0], width), pad, np.int64)
        for b, rid in enumerate(rids):
            seq = np.concatenate([ids[b, :lens[b]].astype(np.int64),
                                  results[rid]])
            out[b, :seq.size] = seq
        from .. import Tensor
        t = Tensor("sequences")
        t._arr = jnp.asarray(out)
        self._outputs = {"sequences": t}
        if inputs is not None:
            return [out]
        return None

    # Predictor-surface parity no-ops
    def clear_intermediate_tensor(self):
        pass

    def try_shrink_memory(self):
        pass
