"""Host-RAM tier for demoted prefix-cache blocks.

`HostTierStore` is the spill target behind `PrefixCacheIndex` (docs/
serving.md "Hierarchical KV-cache tiering"): when the device pool runs
dry, `PagedKVCache._evict_cached` no longer destroys the LRU trie leaf
— it *demotes* the block's KV payload here (per-layer numpy copies of
the `export_blocks`-shaped per-block slab, plus a sha256 digest taken
at spill time) and retags the trie node host-resident. A later match
promotes the payload back into a fresh device block after re-verifying
the digest; a mismatch (torn host RAM, an injected
`corrupt_host_block`) drops the entry and the request re-prefills.

The store knows nothing about tries, pools or requests — it is a
bounded LRU dict of opaque payloads keyed by monotonically minted host
ids, so the cache's invariants ("every resident entry has exactly one
trie node pointing at it") stay auditable from the outside
(`PagedKVCache.check_integrity` cross-tier keys). Its lock is a LEAF
in the declared order (lockgraph.json): nothing is called out of the
store while `_lock` is held — no metrics, no reqtrace, no callbacks —
so it can be taken from any serving frame (scheduler admission, engine
prefetch, peer-fetch export) without ordering hazards.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ...analysis import holds_lock

__all__ = ["HostTierStore"]


class HostTierStore:
    """Bounded host-RAM block store with LRU eviction.

    One entry per demoted block: ``{"payload": L-tuple of (k, v) numpy
    arrays [block_size, H, D], "digest": sha256 hex taken at spill
    time, "touch": LRU clock}``. Capacity is counted in blocks; `put`
    evicts the oldest entries to fit and returns their ids so the
    owning cache can unlink the orphaned trie nodes."""

    _GUARDED_BY = {
        "_entries": "_lock", "_clock": "_lock", "_next_id": "_lock",
        "puts": "_lock", "drops": "_lock",
        "capacity_evictions": "_lock", "poisoned": "_lock",
    }

    def __init__(self, capacity_blocks: int):
        if capacity_blocks <= 0:
            raise ValueError("host tier capacity must be positive, got "
                             f"{capacity_blocks}")
        self.capacity = int(capacity_blocks)
        self._lock = threading.RLock()
        self._entries: Dict[int, dict] = {}
        self._next_id = 0
        self._clock = 0
        self.puts = 0
        self.drops = 0
        self.capacity_evictions = 0
        self.poisoned = 0

    # ------------------------------------------------------------- core
    def put(self, payload, digest: str) -> Tuple[int, List[int]]:
        """Admit one block payload; returns ``(host_id, evicted_ids)``.
        ``evicted_ids`` are entries LRU-dropped to respect capacity —
        the caller must unlink their trie nodes."""
        with self._lock:
            evicted: List[int] = []
            while len(self._entries) >= self.capacity:
                victim = min(self._entries,
                             key=lambda h: self._entries[h]["touch"])
                del self._entries[victim]
                self.capacity_evictions += 1
                self.drops += 1
                evicted.append(victim)
            hid = self._next_id
            self._next_id += 1
            self._clock += 1
            self._entries[hid] = {"payload": payload, "digest": digest,
                                  "touch": self._clock}
            self.puts += 1
            return hid, evicted

    def get(self, hid: int) -> Optional[dict]:
        """The entry for ``hid`` (LRU-touched), or None if it was
        dropped — the caller treats that as a raced eviction."""
        with self._lock:
            entry = self._entries.get(hid)
            if entry is not None:
                self._clock += 1
                entry["touch"] = self._clock
            return entry

    def drop(self, hid: int) -> bool:
        with self._lock:
            if hid not in self._entries:
                return False
            del self._entries[hid]
            self.drops += 1
            return True

    def poison(self, hid: int) -> bool:
        """Drop a host copy whose content is no longer trusted (a
        scrub-taint raised while the blocks were host-resident): the
        entry must never be promoted, so it is removed immediately and
        counted separately from ordinary drops."""
        with self._lock:
            if hid not in self._entries:
                return False
            del self._entries[hid]
            self.drops += 1
            self.poisoned += 1
            return True

    # ------------------------------------------------------ maintenance
    def corrupt_oldest(self) -> bool:
        """Test support (``corrupt_host_block`` fault): flip one value
        in the LRU-oldest entry's layer-0 K payload WITHOUT updating
        its digest — models torn host RAM / a bad DMA, caught by the
        sha256 check on the next fill."""
        with self._lock:
            if not self._entries:
                return False
            hid = min(self._entries,
                      key=lambda h: self._entries[h]["touch"])
            k0 = self._entries[hid]["payload"][0][0]
            k0.flat[0] = k0.flat[0] + 1.0
            return True

    def ids(self) -> List[int]:
        with self._lock:
            return list(self._entries)

    def clear(self) -> int:
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self.drops += n
            return n

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @holds_lock("_lock")
    def _resident_bytes_locked(self) -> int:
        total = 0
        for entry in self._entries.values():
            for k, v in entry["payload"]:
                total += k.nbytes + v.nbytes
        return total

    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity_blocks": self.capacity,
                "resident_blocks": len(self._entries),
                "resident_bytes": self._resident_bytes_locked(),
                "puts": self.puts,
                "drops": self.drops,
                "capacity_evictions": self.capacity_evictions,
                "poisoned": self.poisoned,
            }
