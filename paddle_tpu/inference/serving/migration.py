"""Live KV-block migration between serving replicas.

The disaggregated-serving primitive (docs/serving.md "Disaggregated
serving and block migration"): move one in-flight request — its KV
blocks, its token log, its FCFS ticket, its deadline clock — from one
replica's paged pool to another's WITHOUT re-prefilling and without
perturbing the token stream. One primitive pays three times:

- HANDOFF: prefill-tier replicas push every request that finishes
  prefill onto the decode tier (`ReplicaSet` roles, router step loop);
- REBALANCE: `ReplicaSet.rebalance()` moves the coldest decode requests
  off a pool running past a high watermark;
- DRAIN: `ReplicaSet.drain(index, recompute=False)` evacuates a
  replica's live work before a restart/deploy instead of recomputing it.

Transfer mechanics: the source pool GATHERS the request's blocks into a
contiguous payload (`PagedKVCache.export_blocks` — a device-to-device
copy on TPU, an array copy on CPU; the source is untouched), the
destination allocates fresh physical blocks, scatters the payload in,
rewrites the block table, and registers the request's clean prefix into
its own trie so prefix-cache hit rates survive the hop. Prefix-shared
blocks under refcount are therefore **copied, never stolen**: the
source trie keeps its cached entry (release registers the prefix back,
exactly like request completion), the destination gets a private,
freshly-registered copy, and `check_integrity` passes on both ends at
every step.

Bitwise invariance: decode sampling keys are
``fold_in(seed, tokens_generated)`` — a pure function of progress the
snapshot carries — and the ragged kernels mask stale block-tail
positions to exact zeros, so greedy output after a migration is
bitwise-identical to the same request served unmigrated.

The protocol is TRANSACTIONAL, ordered so every failure leaves both
ends clean:

1. EXPORT from the source (pure copy; aborting costs nothing);
2. ADMIT at the destination — fresh blocks, adopted straight into the
   RUNNING set. ``CacheExhausted`` here aborts the whole migration with
   no side effects and NO trace events: the request keeps decoding at
   the source as if nothing happened;
3. the mid-migration fault window (`kill_migration`): a source that
   dies here rolls the destination back (`abort_migrated`) and raises
   ``ReplicaCrashed`` — the router's failover re-prefills the victim
   from its authoritative token log, so a half-migrated request is
   never half-served;
4. COMMIT: record ``migrate_out``, release the source copy (state
   MIGRATED — terminal for that engine, no finish event), record
   ``migrate_in``.

Thread contract (ptlint PT-C001 via _GUARDED_BY): the coordinator runs
in the router's locked step frame and serializes migrations under its
own lock, slotted into the declared order as
router → **migration** → replica → engine → scheduler
(lockgraph.json). It acquires ONE replica's lock at a time — source and
destination locks are never held together, so the cross-pool copy can
never deadlock against a concurrent migration in the other direction.
"""
from __future__ import annotations

import threading
import time
from typing import Optional

from ... import obs
from ...analysis import holds_lock
from .paged_cache import CacheExhausted
from .replica import EngineReplica, ReplicaCrashed

__all__ = ["BlockMigration", "MIGRATION_REASONS"]

MIGRATION_REASONS = ("handoff", "rebalance", "drain")


class BlockMigration:
    """Migration coordinator for one ReplicaSet (module docstring).
    Owns the migration counters and the obs families; one instance per
    router, driven from the router's locked step frame."""

    _GUARDED_BY = {
        "migrations": "_lock",
        "aborted": "_lock",
        "rolled_back": "_lock",
        "revision_refused": "_lock",
        "bytes_moved": "_lock",
        "prefix_fetches": "_lock",
        "prefix_aborted": "_lock",
        "prefix_bytes": "_lock",
    }

    def __init__(self, router_label: str):
        self.label = router_label
        self._lock = threading.RLock()
        self.migrations = 0               # committed
        self.aborted = 0                  # destination pool full
        self.rolled_back = 0              # source died mid-migration
        self.revision_refused = 0         # cross-(model,revision) blocked
        self.bytes_moved = 0
        self.prefix_fetches = 0           # committed peer prefix pulls
        self.prefix_aborted = 0           # dst full / digest mismatch
        self.prefix_bytes = 0
        self._c_migrations = obs.counter(
            "serving_migrations_total",
            "committed KV-block migrations by reason "
            "(handoff|rebalance|drain)", labels=("router", "reason"))
        self._h_seconds = obs.histogram(
            "serving_migration_seconds",
            "export -> committed wall time per migration",
            labels=("router",), unit="seconds").labels(
                router=router_label)
        self._h_bytes = obs.histogram(
            "serving_migration_bytes",
            "KV payload size per migration (all layers, k and v)",
            labels=("router",), unit="bytes").labels(
                router=router_label)
        self._c_peer_fetch = obs.counter(
            "serving_peer_fetches_total",
            "peer prefix pulls by outcome (hit|aborted); an abort "
            "leaves the destination pool untouched and the request "
            "re-prefills", labels=("router", "outcome"))
        self._c_rev_refused = obs.counter(
            "serving_revision_refusals_total",
            "KV transfers refused because source and destination serve "
            "different (model, revision) keys — stale KV never crosses "
            "a weight rollout (serving/deploy.py)", labels=("router",))

    def migrate(self, src: EngineReplica, dst: EngineReplica,
                request_id: str, reason: str, router_step: int = 0,
                faults=None) -> Optional[dict]:
        """Move one request src → dst (module-docstring protocol).
        Returns the committed migration's stats dict, or None when the
        destination pool could not hold it (clean abort — the request
        keeps running at the source). Raises ReplicaCrashed when the
        `kill_migration` fault fires in the commit window; the caller
        (router) fails the SOURCE replica over, and the destination has
        already been rolled back here."""
        if reason not in MIGRATION_REASONS:
            raise ValueError(
                f"migration reason {reason!r} not in "
                f"{MIGRATION_REASONS}")
        if src is dst:
            raise ValueError(
                f"cannot migrate {request_id!r} onto its own replica "
                f"{src.index}")
        with self._lock:
            return self._migrate_locked(src, dst, request_id, reason,
                                        router_step, faults)

    @holds_lock("_lock")
    def _migrate_locked(self, src: EngineReplica, dst: EngineReplica,
                        request_id: str, reason: str,
                        router_step: int, faults) -> Optional[dict]:
        t0 = time.perf_counter()
        if src.revision_key() != dst.revision_key():
            # cross-revision refusal (serving/deploy.py): KV written by
            # one revision's weights must never serve another's
            # requests. Clean abort before any copy — the request keeps
            # running at the source; the router routes the drain/
            # rebalance to a same-revision destination instead.
            self.revision_refused += 1
            self._c_rev_refused.labels(router=self.label).inc()
            return None
        snap = src.export_request(request_id)
        try:
            dst_engine = dst.admit_migrated(snap)
        except CacheExhausted:
            # abort with no side effects and no trace events: export
            # was a pure copy, the destination rejected atomically
            self.aborted += 1
            return None
        if faults is not None \
                and faults.kill_migration(router_step, src.index):
            # source died between destination-admit and source-release:
            # roll the destination back and let the router's failover
            # re-prefill the victim from its authoritative token log
            dst.abort_migrated(request_id)
            self.rolled_back += 1
            raise ReplicaCrashed(
                f"replica {src.index} killed mid-migration of "
                f"{request_id!r} at router step {router_step}")
        prefilled = not snap["pf_target"] \
            or snap["prefill_pos"] >= snap["pf_target"]
        trace_id = snap["trace_id"] or request_id
        obs.reqtrace.record(
            "migrate_out", trace_id, request_id,
            replica=src.index, to_replica=dst.index, reason=reason,
            blocks=snap["blocks"], bytes=snap["bytes"],
            resume_pos=snap["num_tokens"], arrival=snap["arrival"])
        src.release_migrated(request_id)
        obs.reqtrace.record(
            "migrate_in", trace_id, request_id,
            replica=dst.index, from_replica=src.index, reason=reason,
            engine=dst_engine, blocks=snap["blocks"],
            bytes=snap["bytes"], resume_pos=snap["num_tokens"],
            arrival=snap["arrival"], prefilled=prefilled)
        dt = time.perf_counter() - t0
        self.migrations += 1
        self.bytes_moved += snap["bytes"]
        self._c_migrations.labels(router=self.label,
                                  reason=reason).inc()
        self._h_seconds.observe(dt)
        self._h_bytes.observe(snap["bytes"])
        return {"request_id": request_id, "src": src.index,
                "dst": dst.index, "reason": reason,
                "blocks": snap["blocks"], "bytes": snap["bytes"],
                "resume_pos": snap["num_tokens"], "seconds": dt}

    # --------------------------------------------------- peer prefix pull
    def fetch_prefix(self, src: EngineReplica, dst: EngineReplica,
                     request_id: str, trace_id: str, prompt_ids,
                     router_step: int = 0) -> Optional[dict]:
        """Transactional peer prefix pull (docs/serving.md "Hierarchical
        KV-cache tiering"): a replica missing a prompt's prefix copies
        the cached blocks from a peer that holds them instead of
        re-prefilling. Same shape and same atomic-abort semantics as
        `migrate`: the source export is a pure copy (host-resident
        blocks are integrity-checked against their spill digests during
        export), and the destination's `admit_prefix` re-verifies EVERY
        per-block digest before claiming a single block — a pool-full
        `CacheExhausted` or a digest-mismatch `ValueError` aborts with
        the destination untouched and the request degrades to ordinary
        re-prefill. Blocks are copied, never stolen: the source trie
        keeps its entry. Returns the committed pull's stats dict, or
        None on abort / nothing-to-pull."""
        if src is dst:
            raise ValueError(
                f"cannot pull prefix for {request_id!r} from its own "
                f"replica {src.index}")
        with self._lock:
            return self._fetch_prefix_locked(src, dst, request_id,
                                             trace_id, prompt_ids,
                                             router_step)

    @holds_lock("_lock")
    def _fetch_prefix_locked(self, src: EngineReplica,
                             dst: EngineReplica, request_id: str,
                             trace_id: str, prompt_ids,
                             router_step: int) -> Optional[dict]:
        t0 = time.perf_counter()
        if src.revision_key() != dst.revision_key():
            # same refusal as _migrate_locked: a peer serving different
            # weights holds no prefix worth pulling — its KV is garbage
            # under this revision's parameters
            self.revision_refused += 1
            self._c_rev_refused.labels(router=self.label).inc()
            return None
        snap = src.export_prefix(prompt_ids)
        if snap is None:
            return None                   # peer held nothing after all
        tid = trace_id or request_id
        try:
            added = dst.admit_prefix(prompt_ids, snap["blocks"],
                                     model=snap.get("model"),
                                     revision=snap.get("revision"))
        except (CacheExhausted, ValueError):
            # atomic abort: admit_prefix verifies all digests BEFORE
            # claiming blocks and CacheExhausted claims nothing — the
            # destination pool is untouched either way, and the request
            # re-prefills its missing suffix like any cache miss
            self.prefix_aborted += 1
            self._c_peer_fetch.labels(router=self.label,
                                      outcome="aborted").inc()
            obs.reqtrace.record(
                "peer_fetch", tid, request_id, outcome="aborted",
                from_replica=src.index, to_replica=dst.index,
                blocks=len(snap["blocks"]), bytes=snap["bytes"],
                step=router_step)
            return None
        dt = time.perf_counter() - t0
        self.prefix_fetches += 1
        self.prefix_bytes += snap["bytes"]
        self._c_peer_fetch.labels(router=self.label,
                                  outcome="hit").inc()
        obs.reqtrace.record(
            "peer_fetch", tid, request_id, outcome="hit",
            from_replica=src.index, to_replica=dst.index, blocks=added,
            tokens=len(snap["tokens"]), bytes=snap["bytes"],
            step=router_step, seconds=round(dt, 6))
        return {"request_id": request_id, "src": src.index,
                "dst": dst.index, "blocks": added,
                "tokens": len(snap["tokens"]), "bytes": snap["bytes"],
                "seconds": dt}

    def stats(self) -> dict:
        with self._lock:
            return {"migrations": self.migrations,
                    "aborted": self.aborted,
                    "rolled_back": self.rolled_back,
                    "revision_refused": self.revision_refused,
                    "bytes_moved": self.bytes_moved,
                    "prefix_fetches": self.prefix_fetches,
                    "prefix_aborted": self.prefix_aborted,
                    "prefix_bytes": self.prefix_bytes}

    def seconds_quantile(self, q: float) -> float:
        """Migration latency quantile (export -> committed wall time)
        from this router's serving_migration_seconds series; NaN when
        no migration has committed yet."""
        return self._h_seconds.quantile(q)
