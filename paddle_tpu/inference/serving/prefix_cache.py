"""Radix-trie prefix index over paged KV blocks (prefix caching).

Serving traffic from millions of users is template-shaped: system
prompts, few-shot preambles and multi-turn history repeat across
requests, yet without sharing every request re-prefills from token
zero. The paged block tables (serving/paged_cache.py) already give the
indirection that makes sharing pure bookkeeping: if two prompts agree
on their first k*block_size tokens, the KV content of those k blocks
is identical bit for bit (causal attention: position p's KV depends
only on tokens <= p), so the SAME physical blocks can appear in both
sequences' tables.

This module owns the content index; PagedKVCache owns the physical
side (refcounts, free list, copy-on-write forks, eviction). The index
is a radix trie at FULL-BLOCK granularity: one node per cached block,
keyed by the tuple of block_size token ids that block holds, child
edges extending the prefix by one block. Matching a prompt walks the
trie greedily; divergence INSIDE a block surfaces as a partial match
(node, m) that the cache materialises as a copy-on-write fork.

Invariants (audited by PagedKVCache.check_integrity):
- a physical block appears at most once in the trie;
- a node's depth equals its block's position range: node at depth d
  (root = 0) holds token positions [(d-1)*bs, d*bs);
- every trie block is OFF the free list (cached blocks with refcount 0
  are retained-but-evictable, not free);
- last_touch clocks are monotone root-ward (children are only touched
  through their parents), so LRU leaf eviction never strands a
  recently-used descendant.

Tiering (docs/serving.md "Hierarchical KV-cache tiering"): nodes carry
a tier tag. A "device" node owns a physical block (`block >= 0`, in
`_by_block`); a "host" node's payload was demoted to the owning
cache's HostTierStore (`block == -1`, `host_id` in `_by_host`). Along
any root-to-leaf path the tiers read device* host* — demotion works
leaf-ward (only frontier nodes whose children are all host demote),
promotion works root-ward, and `insert` stops at a host child — so a
match is always a device prefix followed by a contiguous promotable
host run (`match_tiered`).

Host-side only: the index never touches device arrays. See
docs/serving.md "Prefix caching".
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["PrefixCacheIndex", "PrefixNode"]


class PrefixNode:
    """One cached block: `key` is the tuple of block_size token ids the
    block holds, `block` the physical block id, `last_touch` the
    index's logical clock at the last match through this node. `tier`
    is "device" (owns `block`) or "host" (`block == -1`; `host_id`
    names the spilled payload in the cache's HostTierStore)."""

    __slots__ = ("key", "block", "parent", "children", "last_touch",
                 "tier", "host_id", "tenant")

    def __init__(self, key: Optional[tuple], block: int,
                 parent: Optional["PrefixNode"], touch: int = 0,
                 tenant: str = "default"):
        self.key = key
        self.block = block
        self.parent = parent
        self.children: Dict[tuple, "PrefixNode"] = {}
        self.last_touch = touch
        self.tier = "device"
        self.host_id: Optional[int] = None
        # tenant whose sequence WROTE this block (first-wins, like the
        # block content itself) — the unit of share-weighted eviction
        self.tenant = tenant

    def __repr__(self):                      # debugging aid only
        return (f"PrefixNode(block={self.block}, tier={self.tier}, "
                f"children={len(self.children)})")


class PrefixCacheIndex:
    """Token-id radix trie mapping full-block prefixes to block ids.

    Thread contract: owned by a PagedKVCache and mutated only under its
    owning engine's lock (the cache itself has no lock — same contract
    as the block tables).
    """

    def __init__(self, block_size: int):
        if block_size <= 0:
            raise ValueError("block_size must be positive")
        self.block_size = block_size
        self.root = PrefixNode(None, -1, None)
        self._by_block: Dict[int, PrefixNode] = {}
        self._by_host: Dict[int, PrefixNode] = {}
        self._clock = 0
        # ----------------------------------------- lifetime counters
        self.hits = 0                 # admissions with cached_len > 0
        self.misses = 0               # admissions matching nothing
        self.evictions = 0            # blocks reclaimed under pressure
        self.cow_forks = 0            # mid-block divergence forks
        self.inserted_blocks = 0      # trie insertions (first-wins)
        self.cached_tokens_total = 0  # prompt tokens served from cache
        self.prompt_tokens_total = 0  # prompt tokens seen at admission
        # per-tenant lifetime node counters: every insertion and every
        # unlink is attributed, so for each tenant
        #   tenant_inserted - tenant_removed == live census
        # (both tiers; demote/promote retag without creating/removing).
        # check_integrity pins this reconciliation under churn.
        self.tenant_inserted: Dict[str, int] = {}
        self.tenant_removed: Dict[str, int] = {}

    # -------------------------------------------------------- queries
    def __len__(self) -> int:
        return len(self._by_block)

    def blocks(self):
        """View of every cached physical block id."""
        return self._by_block.keys()

    def node_of(self, block: int) -> Optional[PrefixNode]:
        return self._by_block.get(block)

    def node_of_host(self, host_id: int) -> Optional[PrefixNode]:
        return self._by_host.get(host_id)

    def host_ids(self):
        """View of every host-resident node's store id."""
        return self._by_host.keys()

    # ------------------------------------------------------- matching
    def match(self, tokens: List[int], touch: bool = True
              ) -> Tuple[List[PrefixNode],
                         Optional[Tuple[PrefixNode, int]]]:
        """Longest cached prefix of `tokens`: the list of full-block
        nodes matched in order, plus an optional partial match
        (child_node, m) when 1 <= m < block_size leading tokens of the
        NEXT block agree with a cached child — the copy-on-write
        candidate. `touch=False` is the scheduler's pricing probe (no
        LRU side effects); the real attach touches the matched path so
        eviction age reflects use.

        Device-resident only: the walk stops at a host-tier child and
        the partial scan skips host children (a COW donor must own a
        physical block). `match_tiered` sees the host run."""
        bs = self.block_size
        if touch:
            self._clock += 1
        node, path = self.root, []
        i = 0
        while i + bs <= len(tokens):
            child = node.children.get(tuple(tokens[i:i + bs]))
            if child is None or child.tier != "device":
                break
            if touch:
                child.last_touch = self._clock
            path.append(child)
            node = child
            i += bs
        # mid-block divergence: the best partially-agreeing child
        rest = tokens[i:]
        best: Optional[Tuple[PrefixNode, int]] = None
        if rest:
            for key, child in node.children.items():
                if child.tier != "device":
                    continue
                m = 0
                for a, b in zip(rest, key):
                    if a != b:
                        break
                    m += 1
                if m >= 1 and (best is None or m > best[1]):
                    best = (child, m)
            if best is not None and touch:
                best[0].last_touch = self._clock
        return path, best

    def match_tiered(self, tokens: List[int]
                     ) -> Tuple[List[PrefixNode], List[PrefixNode]]:
        """Tier-aware probe, no LRU side effects: the device-resident
        full-block path plus the contiguous HOST-resident run extending
        it (the promotable suffix — tiers along a path are always
        device* host*). The scheduler prices a prompt from both halves;
        `PagedKVCache.ensure_promoted` fills the host run back in."""
        bs = self.block_size
        node, dev = self.root, []
        i = 0
        while i + bs <= len(tokens):
            child = node.children.get(tuple(tokens[i:i + bs]))
            if child is None or child.tier != "device":
                break
            dev.append(child)
            node = child
            i += bs
        host: List[PrefixNode] = []
        while i + bs <= len(tokens):
            child = node.children.get(tuple(tokens[i:i + bs]))
            if child is None or child.tier != "host":
                break
            host.append(child)
            node = child
            i += bs
        return dev, host

    # ------------------------------------------------------ insertion
    def insert(self, tokens: List[int], blocks: List[int],
               skip: Optional[Callable[[int], bool]] = None,
               tenant: str = "default") -> int:
        """Register `blocks` (block i holding tokens[i*bs:(i+1)*bs]) as
        cached prefixes. First-wins dedupe: where a node already exists
        the existing physical block is kept and `blocks[i]` stays a
        private duplicate (freed normally with its table). `skip(b)`
        vetoes individual blocks (tainted content must never be
        re-matched); a vetoed or already-indexed block STOPS the walk —
        a deeper insertion would orphan its children. Returns the
        number of newly indexed blocks.

        A HOST-tier child also stops the walk: indexing a device block
        beneath it would break the device*-host* path invariant, and
        the host copy already holds this content — the next match
        promotes it instead."""
        bs = self.block_size
        self._clock += 1
        node, added = self.root, 0
        for i, b in enumerate(blocks):
            key = tuple(tokens[i * bs:(i + 1) * bs])
            child = node.children.get(key)
            if child is not None:
                if child.tier != "device":
                    break
                child.last_touch = self._clock
                node = child
                continue
            if (skip is not None and skip(b)) or b in self._by_block:
                break
            child = PrefixNode(key, b, node, self._clock, tenant=tenant)
            node.children[key] = child
            self._by_block[b] = child
            added += 1
            node = child
        self.inserted_blocks += added
        if added:
            self.tenant_inserted[tenant] = \
                self.tenant_inserted.get(tenant, 0) + added
        return added

    # ------------------------------------------------ tier transitions
    def demote(self, node: PrefixNode, host_id: int) -> None:
        """Retag a device node host-resident: its payload now lives in
        the host store under `host_id` and the physical block is the
        caller's to free. Only frontier nodes (no device children) may
        demote — the path stays device* host*."""
        if node.tier != "device":
            raise ValueError(f"node for host id {node.host_id} is "
                             "already host-resident")
        if any(c.tier == "device" for c in node.children.values()):
            raise ValueError(f"cannot demote block {node.block}: it "
                             "still has device-resident children")
        del self._by_block[node.block]
        node.block = -1
        node.tier = "host"
        node.host_id = host_id
        self._by_host[host_id] = node

    def promote(self, node: PrefixNode, block: int) -> None:
        """Retag a host node device-resident in `block` (the caller
        filled it from the store payload). Fresh last_touch: a just-
        promoted prefix must not be the next demotion victim."""
        if node.tier != "host":
            raise ValueError(f"node for block {node.block} is already "
                             "device-resident")
        if node.parent is not None and node.parent.key is not None \
                and node.parent.tier != "device":
            raise ValueError("cannot promote below a host-resident "
                             "parent (promotion works root-ward)")
        del self._by_host[node.host_id]
        node.host_id = None
        node.tier = "device"
        node.block = block
        node.last_touch = self._clock
        self._by_block[block] = node

    # ------------------------------------------------------- eviction
    def _note_removed(self, node: PrefixNode) -> None:
        """Attribute one unlinked node to its tenant's removal counter
        (every removal path funnels through here so the per-tenant
        inserted/removed/census reconciliation stays exact)."""
        self.tenant_removed[node.tenant] = \
            self.tenant_removed.get(node.tenant, 0) + 1

    def remove(self, node: PrefixNode) -> None:
        """Unlink one LEAF node (raises on internal nodes — removing
        them would orphan the subtree; use remove_subtree)."""
        if node.children:
            raise ValueError(
                f"cannot remove internal prefix node for block "
                f"{node.block} ({len(node.children)} children)")
        del node.parent.children[node.key]
        if node.tier == "device":
            del self._by_block[node.block]
        else:
            del self._by_host[node.host_id]
        node.parent = None
        self._note_removed(node)

    def remove_subtree(self, node: PrefixNode) -> List[PrefixNode]:
        """Unlink `node` and its whole subtree (distrust on scrub,
        host-entry loss: the content must not be re-matched, and a
        removed parent would orphan its children anyway). Returns the
        removed nodes, `node` first — the cache reconciles each by
        tier (free/taint the device block, drop the host entry)."""
        del node.parent.children[node.key]
        node.parent = None
        removed: List[PrefixNode] = []
        stack = [node]
        while stack:
            n = stack.pop()
            removed.append(n)
            if n.tier == "device":
                del self._by_block[n.block]
            else:
                del self._by_host[n.host_id]
            self._note_removed(n)
            stack.extend(n.children.values())
            n.children.clear()
        return removed

    def pop_lru_leaf(self, evictable: Callable[[int], bool],
                     among: Optional[set] = None) -> Optional[PrefixNode]:
        """Remove and return the least-recently-touched leaf whose
        block satisfies `evictable` (the cache passes refcount == 0),
        or None. Clocks are monotone root-ward, so evicting the oldest
        leaf frees the coldest extremity of the trie first. `among`
        restricts candidates to the given TENANTS (share-weighted
        eviction: the cache first charges tenants over their share,
        then falls back to the global LRU sweep with among=None)."""
        best: Optional[PrefixNode] = None
        for node in self._by_block.values():
            if node.children or not evictable(node.block):
                continue
            if among is not None and node.tenant not in among:
                continue
            if best is None or node.last_touch < best.last_touch:
                best = node
        if best is not None:
            self.remove(best)
        return best

    def lru_demotable(self, evictable: Callable[[int], bool],
                      skip=frozenset(), pending=frozenset(),
                      among: Optional[set] = None
                      ) -> Optional[PrefixNode]:
        """The least-recently-touched node on the DEMOTION FRONTIER —
        a device node with no device-resident children whose block
        satisfies `evictable` — or None. Unlike pop_lru_leaf the node
        is NOT unlinked: the caller spills its payload and calls
        `demote`. `skip` excludes nodes on a promotion path in
        progress (demoting a node's parent mid-promotion would break
        the device*-host* invariant). `pending` holds nodes the caller
        has SELECTED but not yet spilled (batched demotion): they are
        not re-selected, and they count as demoted for their parent's
        frontier eligibility — the selection sequence matches the
        one-at-a-time loop exactly. `among` restricts candidates to the
        given tenants (share-weighted eviction, as pop_lru_leaf)."""
        best: Optional[PrefixNode] = None
        for node in self._by_block.values():
            if node in skip or node in pending:
                continue
            if among is not None and node.tenant not in among:
                continue
            if any(c.tier == "device" and c not in pending
                   for c in node.children.values()):
                continue
            if not evictable(node.block):
                continue
            if best is None or node.last_touch < best.last_touch:
                best = node
        return best

    def clear(self) -> List[int]:
        """Drop the entire index; returns every DEVICE block id it held
        (the cache reconciles them back to the free list / tables and
        clears its host store separately)."""
        blocks = list(self._by_block)
        for node in self._by_block.values():
            self._note_removed(node)
        for node in self._by_host.values():
            self._note_removed(node)
        self._by_block.clear()
        self._by_host.clear()
        self.root.children.clear()
        return blocks

    def tenant_census(self) -> Dict[str, int]:
        """Live trie nodes per tenant, BOTH tiers (demotion keeps the
        node) — the reconciliation counterpart of tenant_inserted/
        tenant_removed and the per-tenant block gauge source."""
        out: Dict[str, int] = {}
        for node in self._by_block.values():
            out[node.tenant] = out.get(node.tenant, 0) + 1
        for node in self._by_host.values():
            out[node.tenant] = out.get(node.tenant, 0) + 1
        return out

    def tenant_device_blocks(self) -> Dict[str, int]:
        """Device-resident blocks per tenant (the share the weighted
        eviction arbitrates — host payloads hold no HBM)."""
        out: Dict[str, int] = {}
        for node in self._by_block.values():
            out[node.tenant] = out.get(node.tenant, 0) + 1
        return out

    # --------------------------------------------------------- audits
    def audit(self) -> int:
        """Structural self-check, returns the number of violations:
        key widths, parent/child links, by-block/by-host map coverage,
        block uniqueness (one trie slot per physical block) and tier
        layering (no device node beneath a host node)."""
        bad = 0
        seen: Dict[int, int] = {}
        seen_host: Dict[int, int] = {}
        stack = [self.root]
        while stack:
            node = stack.pop()
            for key, child in node.children.items():
                if child.key != key or len(key) != self.block_size:
                    bad += 1
                if child.parent is not node:
                    bad += 1
                if child.tier == "device":
                    if self._by_block.get(child.block) is not child:
                        bad += 1
                    if child.host_id is not None:
                        bad += 1
                    if node.key is not None and node.tier != "device":
                        bad += 1    # device below host: unreachable
                    seen[child.block] = seen.get(child.block, 0) + 1
                else:
                    if self._by_host.get(child.host_id) is not child:
                        bad += 1
                    if child.block != -1:
                        bad += 1
                    seen_host[child.host_id] = \
                        seen_host.get(child.host_id, 0) + 1
                stack.append(child)
        bad += sum(c - 1 for c in seen.values() if c > 1)
        bad += len(set(self._by_block) - set(seen))
        bad += sum(c - 1 for c in seen_host.values() if c > 1)
        bad += len(set(self._by_host) - set(seen_host))
        return bad

    def stats(self) -> dict:
        total = self.prompt_tokens_total
        return {
            "cached_blocks": len(self._by_block),
            "host_blocks": len(self._by_host),
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "cow_forks": self.cow_forks,
            "inserted_blocks": self.inserted_blocks,
            "cached_tokens_total": self.cached_tokens_total,
            "prompt_tokens_total": total,
            "cached_tokens_ratio":
                self.cached_tokens_total / total if total else 0.0,
        }
