"""Radix-trie prefix index over paged KV blocks (prefix caching).

Serving traffic from millions of users is template-shaped: system
prompts, few-shot preambles and multi-turn history repeat across
requests, yet without sharing every request re-prefills from token
zero. The paged block tables (serving/paged_cache.py) already give the
indirection that makes sharing pure bookkeeping: if two prompts agree
on their first k*block_size tokens, the KV content of those k blocks
is identical bit for bit (causal attention: position p's KV depends
only on tokens <= p), so the SAME physical blocks can appear in both
sequences' tables.

This module owns the content index; PagedKVCache owns the physical
side (refcounts, free list, copy-on-write forks, eviction). The index
is a radix trie at FULL-BLOCK granularity: one node per cached block,
keyed by the tuple of block_size token ids that block holds, child
edges extending the prefix by one block. Matching a prompt walks the
trie greedily; divergence INSIDE a block surfaces as a partial match
(node, m) that the cache materialises as a copy-on-write fork.

Invariants (audited by PagedKVCache.check_integrity):
- a physical block appears at most once in the trie;
- a node's depth equals its block's position range: node at depth d
  (root = 0) holds token positions [(d-1)*bs, d*bs);
- every trie block is OFF the free list (cached blocks with refcount 0
  are retained-but-evictable, not free);
- last_touch clocks are monotone root-ward (children are only touched
  through their parents), so LRU leaf eviction never strands a
  recently-used descendant.

Host-side only: the index never touches device arrays. See
docs/serving.md "Prefix caching".
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["PrefixCacheIndex", "PrefixNode"]


class PrefixNode:
    """One cached block: `key` is the tuple of block_size token ids the
    block holds, `block` the physical block id, `last_touch` the
    index's logical clock at the last match through this node."""

    __slots__ = ("key", "block", "parent", "children", "last_touch")

    def __init__(self, key: Optional[tuple], block: int,
                 parent: Optional["PrefixNode"], touch: int = 0):
        self.key = key
        self.block = block
        self.parent = parent
        self.children: Dict[tuple, "PrefixNode"] = {}
        self.last_touch = touch

    def __repr__(self):                      # debugging aid only
        return (f"PrefixNode(block={self.block}, "
                f"children={len(self.children)})")


class PrefixCacheIndex:
    """Token-id radix trie mapping full-block prefixes to block ids.

    Thread contract: owned by a PagedKVCache and mutated only under its
    owning engine's lock (the cache itself has no lock — same contract
    as the block tables).
    """

    def __init__(self, block_size: int):
        if block_size <= 0:
            raise ValueError("block_size must be positive")
        self.block_size = block_size
        self.root = PrefixNode(None, -1, None)
        self._by_block: Dict[int, PrefixNode] = {}
        self._clock = 0
        # ----------------------------------------- lifetime counters
        self.hits = 0                 # admissions with cached_len > 0
        self.misses = 0               # admissions matching nothing
        self.evictions = 0            # blocks reclaimed under pressure
        self.cow_forks = 0            # mid-block divergence forks
        self.inserted_blocks = 0      # trie insertions (first-wins)
        self.cached_tokens_total = 0  # prompt tokens served from cache
        self.prompt_tokens_total = 0  # prompt tokens seen at admission

    # -------------------------------------------------------- queries
    def __len__(self) -> int:
        return len(self._by_block)

    def blocks(self):
        """View of every cached physical block id."""
        return self._by_block.keys()

    def node_of(self, block: int) -> Optional[PrefixNode]:
        return self._by_block.get(block)

    # ------------------------------------------------------- matching
    def match(self, tokens: List[int], touch: bool = True
              ) -> Tuple[List[PrefixNode],
                         Optional[Tuple[PrefixNode, int]]]:
        """Longest cached prefix of `tokens`: the list of full-block
        nodes matched in order, plus an optional partial match
        (child_node, m) when 1 <= m < block_size leading tokens of the
        NEXT block agree with a cached child — the copy-on-write
        candidate. `touch=False` is the scheduler's pricing probe (no
        LRU side effects); the real attach touches the matched path so
        eviction age reflects use."""
        bs = self.block_size
        if touch:
            self._clock += 1
        node, path = self.root, []
        i = 0
        while i + bs <= len(tokens):
            child = node.children.get(tuple(tokens[i:i + bs]))
            if child is None:
                break
            if touch:
                child.last_touch = self._clock
            path.append(child)
            node = child
            i += bs
        # mid-block divergence: the best partially-agreeing child
        rest = tokens[i:]
        best: Optional[Tuple[PrefixNode, int]] = None
        if rest:
            for key, child in node.children.items():
                m = 0
                for a, b in zip(rest, key):
                    if a != b:
                        break
                    m += 1
                if m >= 1 and (best is None or m > best[1]):
                    best = (child, m)
            if best is not None and touch:
                best[0].last_touch = self._clock
        return path, best

    # ------------------------------------------------------ insertion
    def insert(self, tokens: List[int], blocks: List[int],
               skip: Optional[Callable[[int], bool]] = None) -> int:
        """Register `blocks` (block i holding tokens[i*bs:(i+1)*bs]) as
        cached prefixes. First-wins dedupe: where a node already exists
        the existing physical block is kept and `blocks[i]` stays a
        private duplicate (freed normally with its table). `skip(b)`
        vetoes individual blocks (tainted content must never be
        re-matched); a vetoed or already-indexed block STOPS the walk —
        a deeper insertion would orphan its children. Returns the
        number of newly indexed blocks."""
        bs = self.block_size
        self._clock += 1
        node, added = self.root, 0
        for i, b in enumerate(blocks):
            key = tuple(tokens[i * bs:(i + 1) * bs])
            child = node.children.get(key)
            if child is not None:
                child.last_touch = self._clock
                node = child
                continue
            if (skip is not None and skip(b)) or b in self._by_block:
                break
            child = PrefixNode(key, b, node, self._clock)
            node.children[key] = child
            self._by_block[b] = child
            added += 1
            node = child
        self.inserted_blocks += added
        return added

    # ------------------------------------------------------- eviction
    def remove(self, node: PrefixNode) -> None:
        """Unlink one LEAF node (raises on internal nodes — removing
        them would orphan the subtree; use remove_subtree)."""
        if node.children:
            raise ValueError(
                f"cannot remove internal prefix node for block "
                f"{node.block} ({len(node.children)} children)")
        del node.parent.children[node.key]
        del self._by_block[node.block]
        node.parent = None

    def remove_subtree(self, node: PrefixNode) -> List[int]:
        """Unlink `node` and its whole subtree (distrust on scrub:
        tainted content must not be re-matched, and a removed parent
        would orphan its children anyway). Returns the removed block
        ids, node first."""
        del node.parent.children[node.key]
        node.parent = None
        removed: List[int] = []
        stack = [node]
        while stack:
            n = stack.pop()
            removed.append(n.block)
            del self._by_block[n.block]
            stack.extend(n.children.values())
            n.children.clear()
        return removed

    def pop_lru_leaf(self, evictable: Callable[[int], bool]
                     ) -> Optional[PrefixNode]:
        """Remove and return the least-recently-touched leaf whose
        block satisfies `evictable` (the cache passes refcount == 0),
        or None. Clocks are monotone root-ward, so evicting the oldest
        leaf frees the coldest extremity of the trie first."""
        best: Optional[PrefixNode] = None
        for node in self._by_block.values():
            if node.children or not evictable(node.block):
                continue
            if best is None or node.last_touch < best.last_touch:
                best = node
        if best is not None:
            self.remove(best)
        return best

    def clear(self) -> List[int]:
        """Drop the entire index; returns every block id it held (the
        cache reconciles them back to the free list / tables)."""
        blocks = list(self._by_block)
        self._by_block.clear()
        self.root.children.clear()
        return blocks

    # --------------------------------------------------------- audits
    def audit(self) -> int:
        """Structural self-check, returns the number of violations:
        key widths, parent/child links, by-block map coverage and
        block uniqueness (one trie slot per physical block)."""
        bad = 0
        seen: Dict[int, int] = {}
        stack = [self.root]
        while stack:
            node = stack.pop()
            for key, child in node.children.items():
                if child.key != key or len(key) != self.block_size:
                    bad += 1
                if child.parent is not node:
                    bad += 1
                if self._by_block.get(child.block) is not child:
                    bad += 1
                seen[child.block] = seen.get(child.block, 0) + 1
                stack.append(child)
        bad += sum(c - 1 for c in seen.values() if c > 1)
        bad += len(set(self._by_block) - set(seen))
        return bad

    def stats(self) -> dict:
        total = self.prompt_tokens_total
        return {
            "cached_blocks": len(self._by_block),
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "cow_forks": self.cow_forks,
            "inserted_blocks": self.inserted_blocks,
            "cached_tokens_total": self.cached_tokens_total,
            "prompt_tokens_total": total,
            "cached_tokens_ratio":
                self.cached_tokens_total / total if total else 0.0,
        }
