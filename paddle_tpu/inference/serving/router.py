"""ReplicaSet: N supervised LLMEngine replicas behind one front-end.

ROADMAP item 3: one dense replica cannot serve heavy traffic. The
ReplicaSet runs N data-parallel engine replicas (replica.py supervises
each one the way distributed/elastic.py supervises trainers) behind a
single `add_request` / `step` / streaming surface, and promotes the
engine-level crash recovery of PR 3 to REPLICA-LEVEL failover:

- ADMISSION routes each request to the replica with the most effective
  headroom: free blocks MINUS the replica's outstanding block demand
  (worst-case growth of everything admitted + queued), tie-broken by
  the smallest queued re-prefill cost as priced by the PR-8 jaxplan
  prefill cost model. Under skewed prompt lengths this beats
  round-robin (kept as `balance="round_robin"` for A/B) because a long
  prompt's demand lands on one replica's score immediately.
  `balance="prefix_affinity"` (docs/serving.md "Prefix caching") adds
  a prefix-affinity tier on top: the leading blocks of the prompt are
  rendezvous-hashed (highest-random-weight over replica indices) to a
  deterministic preferred replica, so every request sharing a template
  prefix lands where that prefix's KV blocks already live and the
  cache hit rate survives scale-out instead of dying by 1/N.
  Rendezvous keys, not cache probes, make the policy stateless and
  failover-stable (a key re-hashes to the same survivor set minus the
  dead replica); a preferred replica without block headroom for the
  request falls back to the free-block ranking, so affinity can skew
  load but never wedge admission.
- FAILOVER: a replica that crashes (step raises — kill_replica fault,
  unrecoverable engine error) or wedges (heartbeat stale past
  `heartbeat_timeout_s` while holding work) is quarantined: its engine
  object is dropped UNREAD (the router scrub-frees nothing it can't
  reach — a dead engine's pool died with it), and every one of its
  in-flight and queued requests is re-admitted to survivors in
  ORIGINAL arrival order with its original arrival_time/FCFS ticket
  and the tokens already streamed (re-prefill — exactly the PR-3
  requeue discipline, crossing engines). A seeded kill therefore loses
  ZERO requests, and requests on untouched replicas stay
  bitwise-identical to an unfaulted run. Deadlines keep counting from
  the ORIGINAL arrival: a re-admitted request that already blew
  deadline_s finishes as 'timeout', never as a silent retry.
- RECOVERY: failed replicas restart with capped backoff
  (distributed.elastic.BackoffPolicy) and rejoin only after a warmup
  probe serves a token end-to-end on the fresh engine; a replica that
  exhausts max_restarts parks FAILED. If NO survivor is up at failover
  time, recovered requests wait in the router's orphan queue (arrival
  order) and re-admit the moment a replica rejoins — only when every
  replica is permanently FAILED do they terminalize as 'error'.
- BACKPRESSURE spans replicas: `max_waiting` bounds the TOTAL waiting
  depth across up replicas; policy 'reject' raises EngineOverloaded
  carrying a `retry_after_s` hint (drain-rate estimate from the
  router's step-time EWMA, or the earliest pending restart), policy
  'shed_oldest' sheds the GLOBALLY-oldest waiting request from
  whichever replica holds it.
- MULTI-MODEL (PR 18, serving/deploy.py): with `config.models` set to
  a ModelRegistry, each replica belongs to ONE model's pool (its
  engine config's `model`) and `SamplingParams.model` picks the pool —
  admission, failover re-admission and migration never cross pools,
  and per-model revision route weights (set_route_weights) split a
  pool's traffic across checkpoint revisions for A/B and rolling
  deploys. Every request is PINNED to the revision that admitted it
  (invariant 8 in obs/reqtrace.py): migrated KV only moves between
  replicas sharing the (model, revision) key, and the only legal
  revision crossing is a full re-dispatch/re-prefill, which records a
  fresh `admitted` event re-pinning the trace.

Observability (docs/observability.md): `serving_replica_up{router,
replica}` gauge, `serving_failovers_total{router,replica,reason}`,
`serving_requeued_total{router}`, `serving_router_ttft_seconds{router}`
(first token as the CLIENT sees it, across failovers) and
`serving_failover_recovery_seconds{router}` (quarantine → back UP);
per-replica token/TTFT/latency families come for free through each
engine's existing `engine` label (one per replica incarnation).

The router is host-side orchestration only — it owns no device
programs and adds no host syncs; all device work stays inside the
engines it supervises.

Thread contract (ptlint PT-C001 via _GUARDED_BY): router tables are
shared between the serving loop (step/run) and intake threads
(add_request/cancel); public entry points take self._lock, helpers are
@holds_lock. Lock order: router → replica → engine → scheduler, never
the reverse.
"""
from __future__ import annotations

import hashlib
import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ... import obs
from ...analysis import holds_lock
from ...distributed.elastic import BackoffPolicy
from .migration import BlockMigration
from .replica import EngineReplica, ReplicaCrashed, ReplicaState
from .scheduler import EngineOverloaded, SamplingParams
from .tenancy import TenantQuotaExceeded
from .engine import RequestOutput

__all__ = ["BALANCE_POLICIES", "ReplicaSet", "RouterConfig",
           "RouterRequest"]

BALANCE_POLICIES = ("free_blocks", "round_robin", "prefix_affinity")

_ROUTER_IDS = itertools.count()


@dataclass
class RouterConfig:
    num_replicas: int = 2
    balance: str = "free_blocks"         # BALANCE_POLICIES
    # heartbeat-based wedge detection (None disables — crash failover
    # still works; wedges then surface only through engine watchdogs)
    heartbeat_timeout_s: Optional[float] = None
    # replica restart policy (distributed.elastic.BackoffPolicy)
    max_restarts: int = 3
    backoff_base: float = 0.25
    backoff_factor: float = 2.0
    backoff_max: float = 30.0
    backoff_jitter: float = 0.25
    backoff_seed: Optional[int] = None
    # router-level backpressure spanning replicas: TOTAL waiting bound
    max_waiting: Optional[int] = None
    admission_policy: str = "reject"     # 'reject' | 'shed_oldest'
    # prefix-affinity key width (balance="prefix_affinity"): how many
    # leading FULL blocks of the prompt feed the rendezvous hash. Wide
    # enough to separate templates, narrow enough that one template's
    # requests share a key whatever their unique suffixes
    affinity_prefix_blocks: int = 4
    # warmup probe for rejoining replicas (token ids; must be < vocab)
    probe_prompt: tuple = (1,)
    # disaggregated tiers (docs/serving.md "Disaggregated serving and
    # block migration"): one role per replica, 'prefill' | 'decode' |
    # 'mixed'. None keeps the homogeneous all-'mixed' fleet. New
    # prompts admit to the prefill/mixed tier; a prefill replica hands
    # every request that completes prefill off to the decode tier via
    # live KV-block migration (serving/migration.py)
    roles: Optional[tuple] = None
    # peer prefix fetch (docs/serving.md "Hierarchical KV-cache
    # tiering"): at dispatch, if a PEER replica holds at least one more
    # full block of the prompt's prefix than the chosen home, pull the
    # blocks over (BlockMigration.fetch_prefix — transactional, abort
    # leaves the destination untouched) before the request prefills.
    # Off by default: with balance="prefix_affinity" requests already
    # land where their prefix lives; this flag pays under round_robin /
    # free_blocks routing and after failovers scatter a template's
    # working set
    peer_prefix_fetch: bool = False
    # multi-model fleet (serving/deploy.py): a ModelRegistry resolving
    # SamplingParams.model to its published revisions. When set, each
    # replica belongs to ONE model's pool (its engine config's `model`),
    # admission/routing/failover stay inside that pool, and per-model
    # revision weights (set_route_weights / DeployController) split
    # traffic across revisions for A/B and rolling deploys. None keeps
    # the single-model fleet untagged and bit-identical.
    models: Optional[object] = None
    obs_label: Optional[str] = None


@dataclass
class RouterRequest:
    """Router-side record of one request: the authoritative copy of
    everything failover needs — prompt, params, ORIGINAL arrival
    stamps, and the token log as streamed to the client (the router
    never reads recovery state out of a dead engine)."""
    request_id: str
    prompt_ids: np.ndarray
    params: SamplingParams
    arrival_time: float
    arrival: int                         # global FCFS ticket
    replica: Optional[int]               # current home (None = orphaned)
    tokens: List[int] = field(default_factory=list)
    finished: bool = False
    finish_reason: Optional[str] = None
    requeues: int = 0                    # failover re-admissions
    first_token_time: Optional[float] = None
    # causal tracing (obs/reqtrace.py): the stable trace id minted at
    # router admission, and the replica a failover orphaned it from —
    # the re-admission event names its predecessor with it
    trace_id: str = ""
    prev_replica: Optional[int] = None
    # multi-model fleets (serving/deploy.py): the model pool this
    # request belongs to and the revision it is currently PINNED to
    # (the revision of the replica that admitted it — tokens may only
    # come from that revision; a re-pin records a fresh `admitted`)
    model: str = "default"
    revision: Optional[str] = None


class ReplicaSet:
    """N supervised engine replicas behind one serving surface (module
    docstring)."""

    _GUARDED_BY = {
        "_requests": "_lock",
        "_next_id": "_lock",
        "_next_trace": "_lock",
        "_readmit_seq": "_lock",
        "_rr_next": "_lock",
        "_orphans": "_lock",
        "_pending": "_lock",
        "_flights": "_lock",
        "_steps": "_lock",
        "_step_ewma": "_lock",
        "recovery_times": "_lock",
        "_route_weights": "_lock",
    }

    def __init__(self, engine_factory, config: RouterConfig = None,
                 faults=None):
        """`engine_factory(replica_index, incarnation) -> LLMEngine`
        builds each replica incarnation; `from_model` wires the common
        case. `faults` is a ServingFaultInjector shared by the router
        (kill_replica/wedge_replica hooks) and — when the factory passes
        it through, as from_model does — by every engine (the
        engine-level nan/stall/corrupt hooks keep working unchanged in
        multi-replica runs)."""
        config = config or RouterConfig()
        if config.num_replicas < 1:
            raise ValueError(
                f"num_replicas must be >= 1, got {config.num_replicas}")
        if config.balance not in BALANCE_POLICIES:
            raise ValueError(
                f"balance must be one of {BALANCE_POLICIES}, got "
                f"{config.balance!r}")
        if config.admission_policy not in ("reject", "shed_oldest"):
            raise ValueError(
                f"admission_policy must be 'reject' or 'shed_oldest', "
                f"got {config.admission_policy!r}")
        roles = config.roles
        if roles is not None:
            if len(roles) != config.num_replicas:
                raise ValueError(
                    f"roles has {len(roles)} entries for "
                    f"{config.num_replicas} replicas")
            bad = [r for r in roles if r not in EngineReplica.ROLES]
            if bad:
                raise ValueError(
                    f"unknown replica roles {bad}; expected one of "
                    f"{EngineReplica.ROLES}")
            if "prefill" in roles and not any(
                    r in ("decode", "mixed") for r in roles):
                raise ValueError(
                    "a prefill tier needs at least one decode or mixed "
                    "replica to hand off to")
        else:
            roles = ("mixed",) * config.num_replicas
        self.config = config
        self.label = f"{config.obs_label or 'router'}-{next(_ROUTER_IDS)}"
        if faults is None:
            from ...testing.faults import ServingFaultInjector
            faults = ServingFaultInjector()
        self.faults = faults
        backoff = BackoffPolicy(base=config.backoff_base,
                                factor=config.backoff_factor,
                                max_delay=config.backoff_max,
                                jitter=config.backoff_jitter,
                                seed=config.backoff_seed)
        # the replica list itself is immutable after construction (each
        # EngineReplica carries its own lock); router tables below are
        # the shared-mutable state under self._lock
        self.replicas = [
            EngineReplica(i, engine_factory, backoff,
                          max_restarts=config.max_restarts,
                          heartbeat_timeout=config.heartbeat_timeout_s,
                          probe_prompt=config.probe_prompt,
                          role=roles[i])
            for i in range(config.num_replicas)]
        # migration coordinator: one per router, immutable after
        # construction (it carries its own lock — see lockgraph.json)
        self.migrator = BlockMigration(self.label)
        self._lock = threading.RLock()
        self._requests: Dict[str, RouterRequest] = {}
        # per-model revision routing weights (A/B splits and canary
        # ramps, serving/deploy.py). Empty → every model routes to its
        # registry-active revision. Only consulted when config.models
        # is set; single-model fleets never look here.
        self._route_weights: Dict[str, Dict[str, float]] = {}
        self._next_id = 0
        self._next_trace = 0              # trace-id mint (reqtrace)
        self._readmit_seq = 0             # failover re-admission batches
        self._rr_next = 0                 # round_robin cursor
        self._orphans: List[RouterRequest] = []
        self._pending: List[RequestOutput] = []
        self._flights: List[tuple] = []   # deferred flight-recorder dumps
        self._steps = 0
        self._step_ewma = 0.05            # drain-rate estimate seed (s)
        self.recovery_times: List[float] = []
        lbl = dict(router=self.label)
        self._g_up = obs.gauge(
            "serving_replica_up",
            "1 while the replica accepts admissions, 0 while draining/"
            "down/failed", labels=("router", "replica"))
        self._c_failovers = obs.counter(
            "serving_failovers_total",
            "replica-level failovers by reason (crash|wedge)",
            labels=("router", "replica", "reason"))
        self._c_requeued = obs.counter(
            "serving_requeued_total",
            "requests re-admitted to a survivor (or orphaned) after "
            "their replica failed", labels=("router",)).labels(**lbl)
        self._h_ttft = obs.histogram(
            "serving_router_ttft_seconds",
            "client-visible time to first token across replicas and "
            "failovers", labels=("router",), unit="seconds").labels(**lbl)
        self._h_recovery = obs.histogram(
            "serving_failover_recovery_seconds",
            "quarantine -> rejoined-UP wall time per replica restart",
            labels=("router",), unit="seconds").labels(**lbl)
        g_role = obs.gauge(
            "serving_replica_role",
            "1 for the replica's assigned tier (prefill|decode|mixed)",
            labels=("router", "replica", "role"))
        for r in self.replicas:
            self._set_up_gauge(r)
            g_role.labels(router=self.label, replica=str(r.index),
                          role=r.role).set(1)

    @classmethod
    def from_model(cls, model, config: RouterConfig = None,
                   engine_config=None, faults=None):
        """Build a ReplicaSet of identical engines over one model's
        live parameters (each replica gets its own paged pool and a
        per-replica obs label `<router>-r<i>`)."""
        import dataclasses
        from .engine import EngineConfig, LLMEngine
        config = config or RouterConfig()
        ecfg = engine_config or EngineConfig()
        if faults is None:
            from ...testing.faults import ServingFaultInjector
            faults = ServingFaultInjector()
        base_label = config.obs_label or "router"

        def factory(index, incarnation):
            cfg_i = dataclasses.replace(
                ecfg, obs_label=f"{base_label}-r{index}")
            return LLMEngine.from_model(model, cfg_i, faults=faults)

        return cls(factory, config, faults=faults)

    @classmethod
    def from_registry(cls, registry, assignments, config: RouterConfig
                      = None, faults=None):
        """Build a multi-model fleet over a ModelRegistry
        (serving/deploy.py): `assignments[i]` names the model replica i
        serves, each pinned to the model's revision ACTIVE AT BUILD
        TIME (a restart rebuilds the same revision bit-for-bit; only a
        DeployController swap moves a slot forward). The registry rides
        on config.models so admission, failover and migration stay
        inside each model's pool."""
        import dataclasses
        config = config or RouterConfig()
        if len(assignments) != config.num_replicas:
            raise ValueError(
                f"assignments names {len(assignments)} replicas but "
                f"num_replicas={config.num_replicas}")
        # one pinned factory per slot, resolved NOW: a later restart
        # (which runs the factory under EngineReplica._lock) rebuilds
        # the same revision without re-entering the registry
        pinned = tuple(registry.engine_factory(m, registry.active(m))
                       for m in assignments)
        config = dataclasses.replace(config, models=registry)

        def factory(index, incarnation):
            return pinned[index](index, incarnation)

        return cls(factory, config, faults=faults)

    # ------------------------------------------------------------ intake
    def add_request(self, prompt_ids, sampling: SamplingParams = None,
                    request_id: str = None) -> str:
        """Route one request to the best replica. Raises
        EngineOverloaded (with a retry_after_s hint) when no replica is
        up, or when the router-level waiting bound is hit under policy
        'reject'; under 'shed_oldest' the globally-oldest waiting
        request is shed instead."""
        sampling = sampling or SamplingParams()
        with self._lock:
            if request_id is None:
                request_id = f"rr-{self._next_id}"
                self._next_id += 1
            if request_id in self._requests:
                raise ValueError(f"duplicate request_id {request_id!r}")
            model = sampling.model
            registry = self.config.models
            # ptlint: disable=PT-C004  ModelRegistry sits BELOW
            # ReplicaSet in the declared order; pure locked reads
            if registry is not None and not registry.has_model(model):
                # a caller bug, not an overload: unknown models never
                # become routable by waiting
                raise ValueError(
                    f"unknown model {model!r}; registry serves "
                    f"{sorted(registry.models())}")  # ptlint: disable=PT-C004  registry read down the order
            ups = self._admission_candidates(model=model)
            if not ups:
                raise EngineOverloaded(
                    request_id, 0, 0,
                    retry_after_s=self._retry_after())
            limit = self.config.max_waiting
            if limit is not None:
                total = sum(r.load_info()["waiting"] for r in ups)
                if total >= limit:
                    if self.config.admission_policy == "reject":
                        raise EngineOverloaded(
                            request_id, total, limit,
                            retry_after_s=self._retry_after())
                    self._shed_globally_oldest(ups)
            ids = np.asarray(prompt_ids, np.int32).reshape(-1)
            trace_id = f"tr-{self.label}-{self._next_trace}"
            self._next_trace += 1
            # revision steering (A/B weights / canary ramp): prefer the
            # picked revision's replicas, but availability beats the
            # split — the admitted event records the revision the
            # request actually LANDED on, which is what pins it
            want_rev = self._pick_revision(model, request_id)
            if want_rev is not None:
                ups = [r for r in ups if r.revision == want_rev] or ups
            last_exc = None
            for rep in self._rank(ups, prompt_ids=ids,
                                  demand=self._worst_demand(
                                      ids.size + sampling.max_tokens,
                                      ups)):
                try:
                    arrival, arrival_time = rep.dispatch(
                        prompt_ids, sampling, request_id,
                        trace_id=trace_id)
                except TenantQuotaExceeded:
                    # the quota verdict is TENANT-global, not a property
                    # of this replica — every peer shares the registry
                    # and would refuse identically, so surface it now
                    # with its own retry_after_s (window expiry)
                    raise
                except EngineOverloaded as e:
                    last_exc = e          # per-replica bound; try next
                    continue
                self._rr_next = (rep.index + 1) % len(self.replicas)
                self._requests[request_id] = RouterRequest(
                    request_id=request_id, prompt_ids=ids,
                    params=sampling, arrival_time=arrival_time,
                    arrival=arrival, replica=rep.index,
                    trace_id=trace_id, model=model,
                    revision=rep.revision)
                # balance decision, recorded with the chosen replica's
                # post-dispatch headroom (host-side load snapshot).
                # Multi-model fleets stamp the resolved (model,
                # revision) — invariant 8 pins every later token to it;
                # single-model fleets stay untagged (byte-identical
                # dumps).
                info = rep.load_info()
                rev_tag = {} if registry is None else {
                    "model": model, "revision": rep.revision}
                obs.reqtrace.record(
                    "admitted", trace_id, request_id,
                    router=self.label, replica=rep.index,
                    policy=self.config.balance,
                    headroom=info["free_blocks"] - info["block_demand"],
                    waiting=info["waiting"], **rev_tag)
                self._maybe_peer_fetch(rep, request_id, trace_id, ids)
                return request_id
            # every up replica refused at ITS bound: surface overload
            # with the strongest hint we have — a replica-supplied
            # retry_after_s (deadline early-reject estimate) beats the
            # router's drain-rate guess
            hint = last_exc.retry_after_s if last_exc is not None \
                and last_exc.retry_after_s is not None \
                else self._retry_after()
            raise EngineOverloaded(
                request_id, last_exc.depth if last_exc else 0,
                last_exc.limit if last_exc else 0,
                retry_after_s=hint)

    def cancel(self, request_id: str) -> bool:
        with self._lock:
            rec = self._requests.get(request_id)
            if rec is None or rec.finished:
                return False
            if rec.replica is None:       # orphaned: cancel router-side
                self._orphans = [o for o in self._orphans
                                 if o.request_id != request_id]
                self._terminal(rec, "cancelled")
                return True
            ok = self.replicas[rec.replica].cancel(request_id)
            if ok:
                # the engine's cancel already recorded the terminal
                # trace event; don't double-record it router-side
                self._terminal(rec, "cancelled", record=False)
            return ok

    def get_request(self, request_id: str) -> RouterRequest:
        with self._lock:
            return self._requests[request_id]

    def has_unfinished(self) -> bool:
        with self._lock:
            return any(not rec.finished
                       for rec in self._requests.values())

    # ------------------------------------------------------------ routing
    @holds_lock("_lock")
    def _admission_candidates(self, model: str = None
                              ) -> List[EngineReplica]:
        """New prompts (and failover re-prefills) are prefill work:
        they admit to the prefill/mixed tier. Falls back to EVERY
        accepting replica when that whole tier is down — availability
        beats tiering, and a decode replica can still prefill, just not
        at its sized-for roofline. In a multi-model fleet the request's
        model pool is a HARD filter applied first — a request never
        lands on another model's weights, whatever is down."""
        ups = [r for r in self.replicas if r.accepts_admissions()]
        if model is not None and self.config.models is not None:
            ups = [r for r in ups if r.model == model]
        tier = [r for r in ups if r.role in ("prefill", "mixed")]
        return tier or ups

    @holds_lock("_lock")
    def _pick_revision(self, model: str, seed: str) -> Optional[str]:
        """Deterministic weighted revision choice for one request:
        hash (model, request_id) onto the model's route weights —
        stateless, replayable, and a 90/10 split is 90/10 for any
        request population. No weights → the registry's active
        revision; no registry → None (single-model fleet, no
        steering)."""
        weights = self._route_weights.get(model)
        if not weights:
            reg = self.config.models
            # ptlint: disable=PT-C004  registry read down the order
            return reg.active(model) if reg is not None else None
        total = sum(weights.values())
        h = int.from_bytes(hashlib.sha256(
            f"{model}/{seed}".encode()).digest()[:8], "big")
        x = (h / 2.0 ** 64) * total
        for rev in sorted(weights):
            x -= weights[rev]
            if x < 0:
                return rev
        return sorted(weights)[-1]

    @holds_lock("_lock")
    def _repin(self, rec: RouterRequest, rep: EngineReplica) -> None:
        """Re-pin a re-dispatched request to its new home's revision.
        Crossing revisions is legal ONLY because re-dispatch re-prefills
        from the router's token log (migrated KV never crosses — the
        migrator refuses); the fresh `admitted` event re-pins the trace
        so invariant 8 holds the request's FUTURE tokens to the new
        revision."""
        if self.config.models is None:
            rec.revision = rep.revision
            return
        if rec.revision == rep.revision:
            return
        rec.revision = rep.revision
        obs.reqtrace.record(
            "admitted", rec.trace_id or rec.request_id,
            rec.request_id, router=self.label, replica=rep.index,
            policy="repin", model=rec.model, revision=rep.revision)

    @holds_lock("_lock")
    def _rank(self, candidates: List[EngineReplica],
              prompt_ids=None, demand: int = 0):
        """Dispatch preference order. free_blocks: descending effective
        headroom (free - outstanding demand), then cheapest queued
        re-prefill backlog (jaxplan-priced when the engines carry a
        cost model), then lowest index. round_robin: rotate.
        prefix_affinity: the prompt's rendezvous-preferred replica
        first IF its effective headroom covers the request's worst-case
        `demand` blocks, then the free_blocks order — affinity steers,
        headroom decides."""
        if self.config.balance == "round_robin":
            n = len(self.replicas)
            return sorted(candidates,
                          key=lambda r: (r.index - self._rr_next) % n)

        def score(rep):
            info = rep.load_info()
            return (info["free_blocks"] - info["block_demand"],
                    -info["prefill_cost"], -rep.index)

        by_headroom = sorted(candidates, key=score, reverse=True)
        if self.config.balance == "prefix_affinity" \
                and prompt_ids is not None:
            key = self._affinity_key(prompt_ids)
            if key is not None:
                pref = max(candidates,
                           key=lambda r: self._affinity_weight(key,
                                                               r.index))
                info = pref.load_info()
                if info["free_blocks"] - info["block_demand"] >= demand:
                    return [pref] + [r for r in by_headroom
                                     if r is not pref]
        return by_headroom

    @holds_lock("_lock")
    def _affinity_key(self, prompt_ids) -> Optional[tuple]:
        """Routing key: the prompt's leading full blocks, capped at
        affinity_prefix_blocks, mirroring what the engine-side prefix
        trie can actually share (full-block granularity over the first
        len-1 tokens). None when the prompt spans no full block — such
        prompts carry nothing shareable and route purely on headroom."""
        ups = [r for r in self.replicas if r.engine is not None]
        if not ups:
            return None
        bs = ups[0].engine.cache.block_size
        toks = [int(t) for t in
                np.asarray(prompt_ids, np.int32).reshape(-1)]
        nb = min(max(len(toks) - 1, 0) // bs,
                 self.config.affinity_prefix_blocks)
        if nb <= 0:
            return None
        return tuple(toks[:nb * bs])

    @staticmethod
    def _affinity_weight(key: tuple, index: int) -> int:
        """Highest-random-weight (rendezvous) hash: every router ranks
        (key, replica) identically, keys spread uniformly, and removing
        a replica only remaps the keys it owned — failover moves a
        template's traffic to ONE deterministic survivor instead of
        scattering it."""
        h = hashlib.sha256(repr((key, index)).encode()).digest()
        return int.from_bytes(h[:8], "big")

    @holds_lock("_lock")
    def _worst_demand(self, n_tokens: int, ups: List[EngineReplica]
                      ) -> int:
        """Worst-case block footprint of a request (prompt + full
        max_tokens budget), in the fleet's common block geometry — the
        headroom bar a prefix-affinity preferred replica must clear."""
        eng = next((r.engine for r in ups if r.engine is not None), None)
        return eng.cache.blocks_needed(n_tokens) if eng is not None \
            else 0

    @holds_lock("_lock")
    def _shed_globally_oldest(self, ups: List[EngineReplica]) -> None:
        oldest, victim_rep = None, None
        for rep in ups:
            a = rep.oldest_waiting_arrival()
            if a is not None and (oldest is None or a < oldest):
                oldest, victim_rep = a, rep
        if victim_rep is not None:
            victim_rep.shed_oldest_waiting()
            # terminal 'shed' output streams from that replica's next
            # step and lands in the router record via _absorb

    @holds_lock("_lock")
    def _retry_after(self) -> float:
        """Client backoff hint: the earliest pending replica restart if
        the fleet is (partially) down, else one drain step's EWMA."""
        now = time.monotonic()
        waits = [max(r.restart_at - now, 0.0) for r in self.replicas
                 if r.restart_at is not None
                 and r.state == ReplicaState.DOWN]
        base = max(self._step_ewma, 0.01)
        return round(max(min(waits), base), 3) if waits \
            else round(base, 3)

    # -------------------------------------------------------------- step
    def step(self) -> List[RequestOutput]:
        """One router iteration: restart due replicas (warmup-probed),
        re-admit orphans, step every serving replica under crash
        supervision, then run the heartbeat wedge check. Returns the
        merged streamed outputs."""
        with self._lock:
            outs = self._step_locked()
            flights, self._flights = self._flights, []
        # flight-recorder dumps are file I/O — run them AFTER releasing
        # the router lock (PT-C003) so a slow disk cannot stall intake
        # threads or the whole fleet's step loop
        for reason, ids, extra in flights:
            obs.reqtrace.maybe_flight(reason, ids, extra=extra)
        return outs

    @holds_lock("_lock")
    def _step_locked(self) -> List[RequestOutput]:
        outs: List[RequestOutput] = list(self._pending)
        self._pending.clear()
        self._steps += 1
        step_no = self._steps
        t0 = time.perf_counter()
        with obs.span("serving.router_step", cat="serving",
                      annotate=False,
                      args={"router": self.label, "step": step_no}):
            for rep in self.replicas:
                if rep.restart_due():
                    before = rep.failed_at
                    if rep.restart():
                        self._set_up_gauge(rep)
                        dt = time.monotonic() - before
                        self.recovery_times.append(dt)
                        self._h_recovery.observe(dt)
            self._readmit_orphans(outs)
            for rep in self.replicas:
                if not rep.is_serving():
                    continue
                try:
                    r_outs = rep.step(step_no, self.faults)
                except ReplicaCrashed as e:
                    self._failover(rep, "crash", str(e), outs)
                    continue
                self._absorb(r_outs, outs)
                rep.maybe_drained()
            self._handoffs(step_no, outs)
            for rep in self.replicas:
                if rep.wedged():
                    self._failover(rep, "wedge",
                                   "heartbeat stale past "
                                   f"{self.config.heartbeat_timeout_s}s",
                                   outs)
        dt = time.perf_counter() - t0
        self._step_ewma = 0.8 * self._step_ewma + 0.2 * dt
        return outs

    # --------------------------------------------------------- peer fetch
    @holds_lock("_lock")
    def _maybe_peer_fetch(self, rep: EngineReplica, request_id: str,
                          trace_id: str, prompt_ids) -> None:
        """After dispatching to `rep`: if a serving peer holds at least
        one more FULL block of this prompt's prefix (device- or
        host-resident) than `rep` does, pull those blocks over
        (BlockMigration.fetch_prefix) before the request schedules. An
        aborted pull costs nothing — the request re-prefills exactly as
        if the peer had held nothing."""
        if not self.config.peer_prefix_fetch:
            return
        eng = next((r.engine for r in self.replicas
                    if r.engine is not None), None)
        if eng is None:
            return
        local = rep.prefix_probe(prompt_ids)
        best, best_len = None, local
        for peer in self.replicas:
            # prefix KV is revision-keyed: a peer on other weights
            # holds nothing this replica may serve
            if peer is rep or not peer.is_serving() \
                    or peer.revision_key() != rep.revision_key():
                continue
            n = peer.prefix_probe(prompt_ids)
            if n > best_len:
                best, best_len = peer, n
        if best is None or best_len - local < eng.cache.block_size:
            return                        # nothing a full block better
        self.migrator.fetch_prefix(best, rep, request_id, trace_id,
                                   prompt_ids, router_step=self._steps)

    # ---------------------------------------------------------- migration
    @holds_lock("_lock")
    def _migration_targets(self, exclude: EngineReplica,
                           decode_phase: bool = True
                           ) -> List[EngineReplica]:
        """Destination preference for one migration: UP replicas other
        than the source, decode tier first for decode-phase requests
        (that is what the tier is sized for, and the router's tier
        filter keeps prompts off it), then descending effective
        headroom, mid-prefill migrations prefer prefill/mixed instead
        (their remaining chunks are prefill work). Candidates must
        share the source's (model, revision) key — KV blocks never
        cross a weight rollout (the migrator refuses anyway; filtering
        here avoids burning export attempts on guaranteed refusals)."""
        key = exclude.revision_key()
        cands = [r for r in self.replicas
                 if r is not exclude and r.accepts_admissions()
                 and r.revision_key() == key]
        if decode_phase:
            cands = [r for r in cands if r.role != "prefill"] \
                or cands

        def score(rep):
            info = rep.load_info()
            return (rep.role == "decode" if decode_phase
                    else rep.role != "decode",
                    info["free_blocks"] - info["block_demand"],
                    -rep.index)

        return sorted(cands, key=score, reverse=True)

    @holds_lock("_lock")
    def _handoffs(self, step_no: int, outs) -> None:
        """Prefill→decode tier handoff, run once per router step:
        every request that COMPLETED prefill on a prefill-role replica
        migrates to the decode tier before its next decode chunk. No
        decode-tier headroom → the request simply keeps decoding where
        it is (tiering degrades to mixed, never wedges); a source that
        dies mid-migration fails over like any other crash."""
        for rep in self.replicas:
            if rep.role != "prefill" or not rep.is_serving():
                continue
            try:
                rids = rep.migratable_requests(decode_only=True)
            except ReplicaCrashed:  # pragma: no cover - defensive
                continue
            for rid in rids:
                rec = self._requests.get(rid)
                if rec is None or rec.finished:
                    continue          # warmup probe / already terminal
                targets = self._migration_targets(rep)
                if not targets:
                    break             # no decode tier up: decode here
                try:
                    info = self.migrator.migrate(
                        rep, targets[0], rid, "handoff",
                        router_step=step_no, faults=self.faults)
                except ReplicaCrashed as e:
                    self._failover(rep, "crash", str(e), outs)
                    break             # source gone; victims re-admit
                if info is None:
                    break             # tier full this step; retry next
                rec.replica = targets[0].index

    @holds_lock("_lock")
    def _evacuate(self, rep: EngineReplica, outs) -> int:
        """drain(recompute=False) body: move every live request's KV
        off `rep` (arrival order — FCFS fairness at the destinations),
        then re-dispatch its queued requests from the router's token
        log. Anything no survivor can hold stays behind and finishes
        under classic drain. Returns the number of requests moved."""
        moved = 0
        live = sorted(
            (self._requests[rid] for rid
             in rep.migratable_requests(decode_only=False)
             if rid in self._requests),
            key=lambda rec: rec.arrival)
        for rec in live:
            if rec.finished:
                continue
            # streamed tokens ⇒ past prefill ⇒ decode-tier preference
            targets = self._migration_targets(
                rep, decode_phase=bool(rec.tokens))
            done = None
            for target in targets:
                try:
                    done = self.migrator.migrate(
                        rep, target, rec.request_id, "drain",
                        router_step=self._steps, faults=self.faults)
                except ReplicaCrashed as e:
                    self._failover(rep, "crash", str(e), outs)
                    return moved
                if done is not None:
                    rec.replica = target.index
                    moved += 1
                    break
        # queued work second: no KV exists yet, so this is a plain
        # re-dispatch — the first prefill at the new home recomputes
        # nothing. The migrate_out event (blocks=0, queued) closes the
        # request's timeline on this replica; the dispatch's
        # engine_admit opens it on the next.
        queued = sorted(
            (rec for rec in self._requests.values()
             if rec.replica == rep.index and not rec.finished),
            key=lambda rec: rec.arrival)
        for rec in queued:
            # excludes the DRAINING rep; stays in the model pool, and
            # prefers the revision the request is pinned to (crossing
            # is legal for queued work — it never prefilled — but a
            # same-revision home keeps old-revision traffic bitwise on
            # old weights through a rolling deploy)
            ups = self._admission_candidates(model=rec.model)
            if not ups:
                break
            if rec.revision is not None:
                ups = [r for r in ups
                       if r.revision == rec.revision] or ups
            if rep.release_waiting(rec.request_id) is None:
                continue      # running but unmovable: finishes here
            target = self._rank(
                ups, prompt_ids=rec.prompt_ids,
                demand=self._worst_demand(
                    rec.prompt_ids.size + rec.params.max_tokens,
                    ups))[0]
            obs.reqtrace.record(
                "migrate_out", rec.trace_id or rec.request_id,
                rec.request_id, replica=rep.index,
                to_replica=target.index, reason="drain",
                blocks=0, bytes=0, resume_pos=0, arrival=rec.arrival,
                queued=True)
            try:
                target.dispatch(rec.prompt_ids, rec.params,
                                rec.request_id,
                                arrival_time=rec.arrival_time,
                                arrival=rec.arrival,
                                resume_tokens=rec.tokens, readmit=True,
                                trace_id=rec.trace_id or None)
            except ValueError:
                # can never fit any pool — terminal, loud (the same
                # contract as failover re-admission)
                self._terminal(rec, "error")
                outs.append(self._pending.pop())
                continue
            rec.replica = target.index
            self._repin(rec, target)
            moved += 1
        return moved

    @holds_lock("_lock")
    def _absorb(self, replica_outputs, outs) -> None:
        """Fold one replica's streamed outputs into the router tables.
        token_ids is authoritative (it includes resumed tokens, so the
        router log can only move forward)."""
        now = time.perf_counter()
        for o in replica_outputs:
            rec = self._requests.get(o.request_id)
            if rec is None:
                continue                  # warmup probe etc.
            rec.tokens = list(o.token_ids)
            if rec.first_token_time is None and o.new_token is not None:
                rec.first_token_time = now
                self._h_ttft.observe(now - rec.arrival_time)
            if o.finished:
                rec.finished = True
                rec.finish_reason = o.finish_reason
            outs.append(o)

    @holds_lock("_lock")
    def _terminal(self, rec: RouterRequest, reason: str,
                  record: bool = True) -> None:
        """Router-side terminal (cancel of an orphan, orphans with no
        fleet left): synthesize the terminal output the engines would
        have streamed. `record=False` when an engine already emitted
        the terminal trace event (exactly-one-terminal invariant)."""
        rec.finished = True
        rec.finish_reason = reason
        self._pending.append(RequestOutput(
            rec.request_id, None, list(rec.tokens), True, reason))
        if record:
            obs.reqtrace.record("finish", rec.trace_id or rec.request_id,
                                rec.request_id, reason=reason,
                                tokens=len(rec.tokens))

    # ----------------------------------------------------------- failover
    @holds_lock("_lock")
    def _failover(self, rep: EngineReplica, reason: str, detail: str,
                  outs) -> None:
        """Quarantine a crashed/wedged replica and re-admit its
        non-terminal requests to survivors in original arrival order
        (module docstring). The router's own record is the recovery
        source — nothing is read from the failed engine."""
        self._c_failovers.labels(router=self.label,
                                 replica=str(rep.index),
                                 reason=reason).inc()
        rep.quarantine(f"{reason}: {detail}")
        self._set_up_gauge(rep)
        victims = sorted(
            (rec for rec in self._requests.values()
             if not rec.finished and rec.replica == rep.index),
            key=lambda rec: rec.arrival)
        for rec in victims:
            rec.prev_replica = rep.index
            rec.replica = None
            rec.requeues += 1
            self._c_requeued.inc()
            obs.reqtrace.record(
                "failover", rec.trace_id or rec.request_id,
                rec.request_id, replica=rep.index, reason=reason,
                arrival=rec.arrival, tokens_streamed=len(rec.tokens))
        self._orphans.extend(victims)
        self._orphans.sort(key=lambda rec: rec.arrival)
        self._readmit_orphans(outs)
        # flight recorder: a failover is a postmortem trigger — when
        # armed, dump the victims' timelines (incl. the re-admission
        # hops just recorded) plus the registry snapshot. The dump is
        # file I/O, so it is only QUEUED here; step() writes it after
        # the router lock is released (PT-C003).
        self._flights.append((
            "failover",
            [rec.trace_id or rec.request_id for rec in victims],
            {"router": self.label, "replica": rep.index,
             "reason": reason, "detail": detail,
             "victims": [rec.request_id for rec in victims]}))

    @holds_lock("_lock")
    def _readmit_orphans(self, outs) -> None:
        """Re-admit orphaned requests (original arrival order) to up
        replicas; with the whole fleet permanently FAILED they
        terminalize as 'error' — loudly, never silently dropped."""
        if not self._orphans:
            return
        if all(r.state == ReplicaState.FAILED for r in self.replicas):
            for rec in self._orphans:
                self._terminal(rec, "error")
                outs.append(self._pending.pop())
            self._orphans.clear()
            return
        remaining: List[RouterRequest] = []
        self._readmit_seq += 1
        batch_id = self._readmit_seq
        for rec in self._orphans:
            ups = self._admission_candidates(model=rec.model)
            if not ups:
                remaining.append(rec)
                continue
            # same-revision survivors first: a failover mid-deploy must
            # not silently promote old-revision requests onto new
            # weights while an old-revision home exists (re-admission
            # DOES cross revisions as a last resort — it re-prefills
            # from the token log, and _repin records the fresh
            # `admitted` that makes it legal under invariant 8)
            if rec.revision is not None:
                ups = [r for r in ups
                       if r.revision == rec.revision] or ups
            # affinity-aware re-admission: the rendezvous key re-ranks
            # over the SURVIVOR set, so a dead replica's template
            # traffic converges on one deterministic survivor and
            # rebuilds its prefix working set there once
            target = self._rank(
                ups, prompt_ids=rec.prompt_ids,
                demand=self._worst_demand(
                    rec.prompt_ids.size + rec.params.max_tokens,
                    ups))[0]
            try:
                target.dispatch(rec.prompt_ids, rec.params,
                                rec.request_id,
                                arrival_time=rec.arrival_time,
                                arrival=rec.arrival,
                                resume_tokens=rec.tokens, readmit=True,
                                trace_id=rec.trace_id or None)
            except ValueError:
                # can never fit the survivor's pool — terminal, loud
                self._terminal(rec, "error")
                outs.append(self._pending.pop())
                continue
            rec.replica = target.index
            obs.reqtrace.record(
                "readmit", rec.trace_id or rec.request_id,
                rec.request_id, to_replica=target.index,
                from_replica=rec.prev_replica, arrival=rec.arrival,
                resume=len(rec.tokens), requeues=rec.requeues,
                batch=batch_id)
            self._repin(rec, target)
            # the dead replica's prefix working set may survive on a
            # peer — pull it before the re-prefill recomputes it
            self._maybe_peer_fetch(target, rec.request_id,
                                   rec.trace_id, rec.prompt_ids)
        self._orphans[:] = remaining

    @holds_lock("_lock")
    def _set_up_gauge(self, rep: EngineReplica) -> None:
        self._g_up.labels(router=self.label,
                          replica=str(rep.index)).set(
            1 if rep.accepts_admissions() else 0)

    # ------------------------------------------------------------ control
    def rebalance(self, watermark: float = 0.85) -> int:
        """Move the COLDEST decode requests off every pool running past
        `watermark` occupancy (used / total blocks) until it drops back
        under. Coldest = latest arrival: under pressure those are
        exactly the requests the FCFS preemption rule would recompute
        anyway, so moving them is strictly cheaper than losing them.
        Returns the number of requests migrated."""
        if not 0.0 < watermark <= 1.0:
            raise ValueError(
                f"watermark must be in (0, 1], got {watermark}")
        with self._lock:
            moved = 0
            outs: List[RequestOutput] = []
            for rep in self.replicas:
                if not rep.is_serving() or rep.engine is None:
                    continue
                total = rep.engine.cache.num_blocks
                victims = sorted(
                    (self._requests[rid] for rid
                     in rep.migratable_requests(decode_only=True)
                     if rid in self._requests),
                    key=lambda rec: rec.arrival, reverse=True)
                for rec in victims:
                    info = rep.load_info()
                    if (total - info["free_blocks"]) / total \
                            <= watermark:
                        break
                    targets = [t for t in self._migration_targets(rep)
                               if t.engine is not None
                               and (t.engine.cache.num_blocks
                                    - t.load_info()["free_blocks"])
                               / t.engine.cache.num_blocks < watermark]
                    if not targets:
                        break     # nowhere under the bar: stop moving
                    try:
                        done = self.migrator.migrate(
                            rep, targets[0], rec.request_id,
                            "rebalance", router_step=self._steps,
                            faults=self.faults)
                    except ReplicaCrashed as e:
                        self._failover(rep, "crash", str(e), outs)
                        break
                    if done is None:
                        break
                    rec.replica = targets[0].index
                    moved += 1
            self._pending.extend(outs)
            return moved

    def drain(self, index: int, recompute: bool = True) -> None:
        """Stop routing new work to replica `index`; it parks DRAINED
        once empty (undrain() to rejoin). `recompute=True` (classic)
        lets it finish everything it holds in place.
        `recompute=False` EVACUATES it instead: live requests (decode
        AND mid-prefill) migrate their KV blocks to the other replicas
        — zero re-prefilled tokens — and queued requests re-dispatch
        from the router's token log (they never prefilled, so their
        first prefill elsewhere recomputes nothing). Work that no
        survivor can hold stays and finishes here under the classic
        drain semantics."""
        with self._lock:
            rep = self.replicas[index]
            rep.drain()
            self._set_up_gauge(rep)
            if recompute or rep.engine is None:
                return
            outs: List[RequestOutput] = []
            self._evacuate(rep, outs)
            self._pending.extend(outs)

    def undrain(self, index: int) -> None:
        with self._lock:
            self.replicas[index].undrain()
            self._set_up_gauge(self.replicas[index])

    def evict(self, index: int, reason: str = "evict",
              detail: str = "") -> int:
        """Forced failover of one replica through the exact machinery a
        crash takes: quarantine, requeue every non-terminal request in
        original arrival order, re-admit to survivors immediately. The
        deploy controller uses this on rollback to clear a swapped
        slot's live work before restoring the previous revision's warm
        engine — restore_revision replaces the engine object, so any
        request still decoding there would otherwise be silently
        stranded. Terminal outputs synthesized during re-admission (no
        survivor fits) are delivered by the next step(). Returns the
        number of requeued requests."""
        with self._lock:
            rep = self.replicas[index]
            victims = sum(1 for rec in self._requests.values()
                          if not rec.finished and rec.replica == index)
            outs: List[RequestOutput] = []
            self._failover(rep, reason, detail, outs)
            self._pending.extend(outs)
            return victims

    def set_route_weights(self, model: str,
                          weights: Dict[str, float] = None) -> None:
        """Set (or with None/empty: clear) the revision traffic split
        for `model` — {"sha256:abc...": 0.9, "sha256:def...": 0.1}.
        Cleared → requests route to the registry-active revision.
        DeployController drives this to shift traffic onto swapped
        replicas mid-rollout and to snap it back on rollback."""
        if weights:
            if any(w < 0 for w in weights.values()) \
                    or sum(weights.values()) <= 0:
                raise ValueError(
                    f"route weights must be non-negative with a "
                    f"positive sum, got {weights}")
        with self._lock:
            if weights:
                self._route_weights[model] = dict(weights)
            else:
                self._route_weights.pop(model, None)

    def route_weights(self, model: str) -> Dict[str, float]:
        with self._lock:
            return dict(self._route_weights.get(model, {}))

    def pool(self, model: str) -> List[int]:
        """Replica indices currently serving `model` (any revision)."""
        with self._lock:
            return [r.index for r in self.replicas if r.model == model]

    def probe_grow(self, index: int) -> bool:
        """Return a PARKED (DRAINED) replica to rotation through a
        warmup-probe rejoin (autoscaler grow path, docs/serving.md):
        unlike undrain(), which trusts the warm engine blindly, the
        slot must serve a 1-token greedy probe end-to-end before real
        traffic routes there — the same gate a restarted incarnation
        passes. A failed probe quarantines the slot (normal
        restart/backoff machinery takes over) and returns False."""
        with self._lock:
            rep = self.replicas[index]
            ok = rep.probe_rejoin()
            self._set_up_gauge(rep)
            return ok

    # ------------------------------------------------------------- audits
    def check_integrity(self) -> dict:
        """Per-replica zero-leak audit (chaos gate): every live pool's
        free list + tables must exactly partition it. Replicas whose
        slot holds no engine (DOWN/FAILED) audit as None — their pools
        are unreachable."""
        return {r.index: r.check_integrity() for r in self.replicas}

    def prefix_stats(self) -> dict:
        """Fleet-level prefix-cache telemetry: per-replica snapshots
        plus the aggregate hit rate the 3-replica affinity SLO gates on
        (cached tokens / prompt tokens summed across LIVE replicas —
        dead replicas' counters died with their engines)."""
        with self._lock:
            per = {}
            agg = {"hits": 0, "misses": 0, "evictions": 0,
                   "cow_forks": 0, "cached_tokens_total": 0,
                   "prompt_tokens_total": 0}
            for r in self.replicas:
                eng = r.engine
                if eng is None:
                    per[r.index] = None
                    continue
                ps = eng.cache.prefix_stats()
                per[r.index] = ps
                for k in agg:
                    agg[k] += ps[k]
            total = agg["prompt_tokens_total"]
            agg["cached_tokens_ratio"] = \
                agg["cached_tokens_total"] / total if total else 0.0
            agg["replicas"] = per
            return agg

    def states(self) -> dict:
        return {r.index: r.state for r in self.replicas}

    def num_up(self) -> int:
        return sum(1 for r in self.replicas if r.accepts_admissions())

    def ttft_quantile(self, q: float) -> float:
        return self._h_ttft.quantile(q)

    def router_stats(self) -> dict:
        with self._lock:
            recs = list(self._requests.values())
            by_reason: Dict[str, int] = {}
            for rec in recs:
                if rec.finished:
                    key = rec.finish_reason or "unknown"
                    by_reason[key] = by_reason.get(key, 0) + 1
            pools: Dict[str, Dict[str, List[int]]] = {}
            for r in self.replicas:
                pools.setdefault(r.model, {}).setdefault(
                    r.revision, []).append(r.index)
            return {
                "steps": self._steps,
                "requests": len(recs),
                "unfinished": sum(1 for r in recs if not r.finished),
                "generated_tokens": sum(len(r.tokens) for r in recs),
                "requeues": sum(r.requeues for r in recs),
                "migrations": self.migrator.stats(),
                "finish_reasons": by_reason,
                "replica_states": {r.index: r.state
                                   for r in self.replicas},
                "pools": pools,
                "route_weights": {m: dict(w) for m, w
                                  in self._route_weights.items()},
                "recovery_times_s": [round(t, 4)
                                     for t in self.recovery_times],
            }

    # ------------------------------------------------------- convenience
    def run(self, max_steps: int = None) -> Dict[str, np.ndarray]:
        """Drive every queued request to a terminal state; returns
        {request_id: generated token ids} for normally-completed
        requests. Idles briefly while the only pending work is a
        replica restart backoff, so the drain loop doesn't spin."""
        steps = 0
        while self.has_unfinished():
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                raise RuntimeError(
                    f"router did not drain within {max_steps} steps")
            if not any(r.has_unfinished() for r in self.replicas) \
                    and self.has_unfinished():
                time.sleep(0.002)         # waiting on a restart backoff
        with self._lock:
            return {rid: np.asarray(rec.tokens, np.int64)
                    for rid, rec in self._requests.items()
                    if rec.finish_reason in ("stop", "length")}
