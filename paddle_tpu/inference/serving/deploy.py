"""Multi-model registry and chaos-gated zero-downtime weight rollouts.

PR 18: one ReplicaSet can now serve SEVERAL models, each with a line of
published checkpoint REVISIONS, and move a model's pool from one
revision to the next replica-by-replica without dropping a request or
ever letting stale KV serve new weights. Two classes:

- `ModelRegistry`: model id -> published revisions. A revision is a
  sha256-MANIFEST checkpoint artifact: its id is the digest of the
  per-array checksum manifest (incubate/checkpoint.py writes one next
  to every snapshot; publishing from an artifact directory with no
  `checksums.json` is a HARD error — a deploy never loads weights it
  cannot verify), so two byte-identical weight sets publish as the
  SAME revision and any drift publishes as a different one. Each
  revision carries its own jaxplan-priced prefill cost model, so
  admission pricing rolls forward with the weights. The registry rides
  `RouterConfig.models` into the ReplicaSet: `SamplingParams.model`
  resolves here, pools never mix models, and the registry-ACTIVE
  revision is where un-weighted traffic routes.

- `DeployController`: a tick-based state machine that rolls one
  model's pool to a new revision one replica at a time:

      drain(recompute=False)  evacuating drain: live KV migrates to
                              same-revision peers, queued work
                              re-dispatches — zero lost, zero recompute
      swap_revision           new revision's engine installed on the
                              parked slot + warmup probe; the OLD
                              engine/factory stay warm for rollback
      [kill_deploy window]    the chaos fault fires HERE — after swap,
                              before the canary gate
      canary parity gate      greedy outputs on pinned prompts vs the
                              OLD revision's reference outputs;
                              mismatches beyond the committed tolerance
                              abort the deploy
      probe_rejoin            the slot rejoins rotation only through
                              the same warmup-probe gate a restart uses
      route-weight shift      new admissions steer to the swapped
                              revision in proportion to pool progress

  Any failure — drain stuck, swap/probe failure, canary mismatch, a
  replica killed in the window — rolls EVERY swapped slot back to the
  warm old engine (restore_revision, newest first) and snaps route
  weights to the old revision: rollback is instant and re-prefill-free
  because the old pools were evacuated empty. Commit releases the warm
  standbys, flips the registry-active revision, and clears the weights.

Revision safety is enforced below this module, not promised by it:
engines stamp (model, revision) on every exported KV payload and
REFUSE mismatched admits (`export_blocks`/`admit_migrated`/
`fetch_prefix` — engine.py, migration.py), the router only migrates
between same-key replicas, and reqtrace invariant 8 (obs/reqtrace.py)
proves post-hoc that no token was emitted by a revision other than the
one the request was admitted under. Old-revision in-flight requests
finish BITWISE on old weights: their KV never crosses, and a crossing
re-dispatch (full re-prefill) records a fresh `admitted` that re-pins
the trace.

Observability (docs/observability.md): `serving_deploys_total{router,
outcome}` (committed|rolled_back|aborted), `serving_model_revision
{router,model,revision}` per-pool active gauge,
`serving_canary_mismatches_total{router}`, deploy-cat spans, and the
deploy event kinds (`deploy_start`/`replica_swap`/`canary`/`rollback`/
`deploy_commit`) on one `deploy-<model>-N` trace per rollout.

Thread contract (ptlint PT-C001 via _GUARDED_BY):
`DeployController._lock` is the OUTERMOST serving lock (lockgraph.json
— above even the Autoscaler: a tick drives router control surfaces the
same way the autoscaler does, plus replica rollout primitives).
`ModelRegistry._lock` sits between EngineReplica and LLMEngine: the
router resolves the active revision under its own lock, and a replica
swap builds the new engine through the registry under the replica
lock; the registry itself only ever takes metric-registry locks (engine
construction registers stats families).
"""
from __future__ import annotations

import dataclasses
import hashlib
import itertools
import json
import os
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from ... import obs
from ...analysis import holds_lock
from .scheduler import SamplingParams

__all__ = ["DeployConfig", "DeployController", "ModelRegistry",
           "Revision"]

_DEPLOY_IDS = itertools.count()

# deploy outcomes, the serving_deploys_total label set
OUTCOMES = ("committed", "rolled_back", "aborted")


@dataclass(frozen=True)
class Revision:
    """One published (model, revision): verified weights + the pricing
    that ships with them."""
    model: str
    revision: str                 # "sha256:<manifest digest prefix>"
    weights: object               # the live model object engines build from
    manifest: Dict[str, str]      # array path -> sha256 (checkpoint.py)
    cost_model: Optional[object]  # jaxplan.PrefillCostModel or None
    engine_config: object         # base EngineConfig template


def _manifest_from_artifact(artifact_dir: str) -> Dict[str, str]:
    """Load the sha256 manifest of a checkpoint artifact directory.
    Missing manifest is a HARD error — the strict half of
    AutoCheckpointManager(require_manifest=True): an unverifiable
    artifact cannot become a revision."""
    from ...incubate.checkpoint import CHECKSUM_FILE
    path = os.path.join(artifact_dir, CHECKSUM_FILE)
    if not os.path.exists(path):
        raise IOError(
            f"artifact {artifact_dir!r} has no {CHECKSUM_FILE} manifest "
            f"— unverifiable weights cannot be published as a revision")
    with open(path) as f:
        manifest = json.load(f)
    if not isinstance(manifest, dict) or not manifest:
        raise IOError(
            f"artifact {artifact_dir!r}: {CHECKSUM_FILE} is empty or "
            f"malformed")
    return {str(k): str(v) for k, v in manifest.items()}


def _manifest_from_weights(weights) -> Dict[str, str]:
    """sha256 manifest computed directly from a live model's parameter
    tree (host-side bytes) — the in-memory publish path, digest-
    compatible with what checkpoint.py writes for the same arrays."""
    from ...incubate.checkpoint import _array_manifest, _to_host
    from ...models import generation as gen
    return _array_manifest(_to_host(gen.extract_params(weights)))


def _engine_from_revision(rev: "Revision", index: int,
                          label: str = None):
    """Build one engine from an already-resolved Revision. Registry
    lock-free on purpose: the pinned factories replica slots install
    run under EngineReplica._lock (swap_revision, restart), and the
    resolved Revision is immutable, so nothing here needs — or may
    take — ModelRegistry._lock."""
    from .engine import LLMEngine
    cfg = dataclasses.replace(
        rev.engine_config, model=rev.model, revision=rev.revision,
        prefill_cost_model=rev.cost_model,
        obs_label=label or f"{rev.model}-r{index}")
    return LLMEngine.from_model(rev.weights, cfg)


def _revision_id(manifest: Dict[str, str]) -> str:
    digest = hashlib.sha256(
        json.dumps(manifest, sort_keys=True).encode()).hexdigest()
    return f"sha256:{digest[:12]}"


class ModelRegistry:
    """model id -> published checkpoint revisions (module docstring).

    `version` increments on every publish/activation so consumers can
    cache derived views and refresh only on change — the same contract
    as TenantRegistry."""

    _GUARDED_BY = {
        "_revisions": "_lock",
        "_active": "_lock",
        "version": "_lock",
    }

    def __init__(self):
        self._lock = threading.RLock()
        # model -> revision id -> Revision, insertion-ordered (publish
        # order is the rollback lineage)
        self._revisions: Dict[str, Dict[str, Revision]] = {}
        self._active: Dict[str, str] = {}
        self.version = 1

    # ----------------------------------------------------------- publish
    def publish(self, model_id: str, weights, engine_config=None,
                cost_model="auto", artifact_dir: Optional[str] = None,
                activate: Optional[bool] = None) -> str:
        """Publish one revision of `model_id` and return its id.

        The revision id is the sha256 of the checkpoint manifest:
        loaded from `artifact_dir/checksums.json` when an artifact
        directory is given (missing manifest = hard IOError), computed
        from the live parameter tree otherwise. Re-publishing identical
        weights is idempotent — same manifest, same id, no new entry.

        `cost_model="auto"` prices admission with the committed jaxplan
        prefill model (falls back to the flat token budget when no plan
        is committed); pass an explicit PrefillCostModel to pin a
        revision's own pricing, or None to force the flat budget.
        `activate=None` activates only the model's FIRST revision (new
        revisions of a live model go live through a DeployController,
        never by publish)."""
        manifest = (_manifest_from_artifact(artifact_dir)
                    if artifact_dir is not None
                    else _manifest_from_weights(weights))
        rev_id = _revision_id(manifest)
        if cost_model == "auto":
            from ...analysis import jaxplan
            cost_model = jaxplan.default_admission_model()
        if engine_config is None:
            from .engine import EngineConfig
            engine_config = EngineConfig()
        with self._lock:
            revs = self._revisions.setdefault(model_id, {})
            if rev_id not in revs:
                revs[rev_id] = Revision(
                    model=model_id, revision=rev_id, weights=weights,
                    manifest=dict(manifest), cost_model=cost_model,
                    engine_config=engine_config)
                self.version += 1
            if activate or (activate is None
                            and model_id not in self._active):
                self._active[model_id] = rev_id
                self.version += 1
            return rev_id

    def set_active(self, model_id: str, revision: str) -> None:
        """Flip the model's active revision (DeployController commit)."""
        with self._lock:
            self._resolve(model_id, revision)
            self._active[model_id] = revision
            self.version += 1

    # ------------------------------------------------------------ lookup
    @holds_lock("_lock")
    def _resolve(self, model_id: str, revision: Optional[str]
                 ) -> Revision:
        revs = self._revisions.get(model_id)
        if not revs:
            raise ValueError(
                f"unknown model {model_id!r}; published: "
                f"{sorted(self._revisions)}")
        rev_id = self._active[model_id] if revision is None else revision
        rev = revs.get(rev_id)
        if rev is None:
            raise ValueError(
                f"model {model_id!r} has no revision {rev_id!r}; "
                f"published: {sorted(revs)}")
        return rev

    def has_model(self, model_id: str) -> bool:
        with self._lock:
            return model_id in self._revisions

    def models(self) -> Tuple[str, ...]:
        with self._lock:
            return tuple(sorted(self._revisions))

    def revisions(self, model_id: str) -> Tuple[str, ...]:
        """Publish-ordered revision ids (the rollback lineage)."""
        with self._lock:
            revs = self._revisions.get(model_id)
            if revs is None:
                raise ValueError(f"unknown model {model_id!r}")
            return tuple(revs)

    def active(self, model_id: str) -> str:
        with self._lock:
            rev = self._active.get(model_id)
            if rev is None:
                raise ValueError(
                    f"unknown model {model_id!r}; published: "
                    f"{sorted(self._revisions)}")
            return rev

    def manifest(self, model_id: str, revision: str = None
                 ) -> Dict[str, str]:
        with self._lock:
            return dict(self._resolve(model_id, revision).manifest)

    def cost_model(self, model_id: str, revision: str = None):
        with self._lock:
            return self._resolve(model_id, revision).cost_model

    def describe(self) -> dict:
        """Telemetry snapshot: per model, the publish lineage and the
        active revision."""
        with self._lock:
            return {m: {"revisions": list(revs),
                        "active": self._active.get(m)}
                    for m, revs in sorted(self._revisions.items())}

    # ------------------------------------------------------------- build
    def build_engine(self, model_id: str, revision: Optional[str],
                     index: int, incarnation: int, label: str = None):
        """Build one engine of `model_id` at `revision` (None: active)
        for replica slot `index`. The config template is stamped with
        the (model, revision) key — every KV payload the engine exports
        carries it — and the revision's own prefill cost model."""
        with self._lock:
            rev = self._resolve(model_id, revision)
        return _engine_from_revision(rev, index, label=label)

    def engine_factory(self, model_id: str, revision: str) -> Callable:
        """The `engine_factory(index, incarnation)` a replica slot
        installs at swap_revision — pinned to ONE resolved Revision
        OBJECT, right here, so the closure never re-enters the
        registry: restarts of the swapped incarnation rebuild the same
        weights even after the registry moves on, and a swap or
        restart (which runs the factory under EngineReplica._lock)
        takes no ModelRegistry._lock."""
        with self._lock:
            rev = self._resolve(model_id, revision)

        def factory(index, incarnation):
            return _engine_from_revision(rev, index)

        return factory


# --------------------------------------------------------------- deploys
@dataclass
class DeployConfig:
    # pinned canary prompt set: greedy outputs on these must match the
    # OLD revision's within `canary_tolerance` mismatching prompts. The
    # defaults use tiny token ids so any test-sized vocab covers them;
    # production pins real regression prompts here.
    canary_prompts: tuple = ((1, 2, 3, 4), (2, 4, 6), (5, 1, 5, 1, 5))
    canary_max_tokens: int = 8
    # committed tolerance: how many of the canary prompts may diverge
    # from the old revision before the deploy aborts. 0 = the revisions
    # must agree greedily on every pinned prompt (a weight-format
    # migration); raise it only for deploys that INTEND output drift.
    canary_tolerance: int = 0
    # ticks a single replica may spend draining before the deploy gives
    # up and rolls back (the harness steps the router between ticks, so
    # one tick ~ one router step of drain progress)
    drain_wait_ticks: int = 600
    # steer new admissions toward the swapped revision in proportion to
    # pool progress (False: traffic follows the registry-active
    # revision until commit — a dark launch)
    shift_weights: bool = True

    def __post_init__(self):
        if not self.canary_prompts:
            raise ValueError("canary_prompts must not be empty")
        if self.canary_max_tokens < 1:
            raise ValueError("canary_max_tokens must be >= 1")
        if self.canary_tolerance < 0:
            raise ValueError("canary_tolerance must be >= 0")
        if self.drain_wait_ticks < 1:
            raise ValueError("drain_wait_ticks must be >= 1")


def _greedy_outputs(engine, prompts, max_tokens: int,
                    max_steps_each: int = 256) -> List[List[int]]:
    """Reference half of the canary parity gate: greedy decode of the
    pinned prompts on a PRIVATE engine (never in rotation), returning
    the emitted token lists. Every prompt must run to its full token
    budget — a reference that cannot serve is a failed deploy
    precondition, not a tolerable mismatch."""
    outs: List[List[int]] = []
    for i, prompt in enumerate(prompts):
        rid = engine.add_request(
            list(prompt),
            SamplingParams(max_tokens=max_tokens, temperature=0.0),
            request_id=f"canary-ref-p{i}")
        for _ in range(max_steps_each):
            engine.step()
            if engine.get_request(rid).finished:
                break
        req = engine.get_request(rid)
        if req.state != "finished_length":
            raise RuntimeError(
                f"canary reference {rid!r} ended {req.state!r} instead "
                f"of serving its tokens")
        outs.append([int(t) for t in req.output_ids])
    return outs


class DeployController:
    """Rolling revision deploy over one model's replica pool (module
    docstring). Usage:

        ctl = DeployController(rs, "chat", new_rev)
        ctl.start()
        while not ctl.done():
            rs.step()            # traffic keeps flowing
            ctl.tick()           # one bounded rollout action
        assert ctl.outcome == "committed"

    `tick()` performs at most ONE phase action (wait-for-drain, swap,
    canary, rejoin, commit/rollback) so the caller interleaves rollout
    progress with live traffic — the zero-downtime property is the
    interleaving, not a background thread."""

    _GUARDED_BY = {
        "phase": "_lock",
        "outcome": "_lock",
        "error": "_lock",
        "ticks": "_lock",
        "_queue": "_lock",
        "_pos": "_lock",
        "_swapped": "_lock",
        "_reference": "_lock",
        "_drain_waited": "_lock",
    }

    # phase machine: idle -> drain -> swap -> canary -> rejoin -> (next
    # slot: drain) ... -> committed | rolled_back | aborted
    TERMINAL = ("committed", "rolled_back", "aborted")

    def __init__(self, rs, model: str, revision: str,
                 config: DeployConfig = None, faults=None):
        registry = rs.config.models
        if registry is None:
            raise ValueError(
                "DeployController needs a ReplicaSet built over a "
                "ModelRegistry (RouterConfig.models)")
        self.rs = rs
        self.registry = registry
        self.model = model
        self.to_revision = revision
        self.from_revision = registry.active(model)
        if self.from_revision == revision:
            raise ValueError(
                f"model {model!r} is already at {revision!r}")
        registry.engine_factory(model, revision)   # must be published
        self.config = config or DeployConfig()
        self.faults = faults if faults is not None else rs.faults
        self.deploy_id = f"deploy-{model}-{next(_DEPLOY_IDS)}"
        self._lock = threading.RLock()
        self.phase = "idle"
        self.outcome: Optional[str] = None
        self.error: Optional[str] = None
        self.ticks = 0
        self._queue: List[int] = []
        self._pos = 0
        self._swapped: List[int] = []
        self._reference: Optional[List[List[int]]] = None
        self._drain_waited = 0
        self._c_deploys = obs.counter(
            "serving_deploys_total",
            "weight rollouts by outcome (committed|rolled_back|"
            "aborted)", labels=("router", "outcome"))
        self._c_canary = obs.counter(
            "serving_canary_mismatches_total",
            "canary prompts whose greedy output diverged from the old "
            "revision during a deploy", labels=("router",)).labels(
                router=rs.label)
        self._g_rev = obs.gauge(
            "serving_model_revision",
            "1 for the revision a model's pool is actively serving "
            "(flips at deploy commit, snaps back on rollback)",
            labels=("router", "model", "revision"))

    # ------------------------------------------------------------ control
    def start(self) -> None:
        """Validate the rollout and build the canary reference outputs
        from a PRIVATE old-revision engine (never in rotation — replica
        engines keep serving while the reference decodes). Records
        deploy_start; the first tick() begins draining."""
        with self._lock:
            if self.phase != "idle":
                raise ValueError(
                    f"deploy {self.deploy_id} already {self.phase}")
            # ptlint: disable=PT-C004  DeployController._lock is the
            # OUTERMOST serving lock (lockgraph.json); everything below
            # never calls back up into the controller
            pool = self.rs.pool(self.model)
            if not pool:
                self._finish("aborted", "empty_pool")
                return
            span = obs.span("deploy.start", cat="deploy",
                            annotate=False,
                            args={"deploy": self.deploy_id})
            span.begin()
            try:
                ref_engine = self.registry.build_engine(
                    self.model, self.from_revision, 0, 0,
                    label=f"{self.deploy_id}-ref")
                self._reference = _greedy_outputs(
                    ref_engine, self.config.canary_prompts,
                    self.config.canary_max_tokens)
            except Exception as e:          # noqa: BLE001 — a deploy
                # that cannot build its reference aborts cleanly, it
                # does not crash the serving loop driving it
                self._finish("aborted", f"reference_failed: {e}")
                return
            finally:
                span.end()
            self._queue = list(pool)
            obs.reqtrace.record(
                "deploy_start", self.deploy_id, self.deploy_id,
                router=self.rs.label, model=self.model,
                from_revision=self.from_revision,
                to_revision=self.to_revision, replicas=len(pool))
            self._g_rev.labels(router=self.rs.label, model=self.model,
                               revision=self.from_revision).set(1)
            self._g_rev.labels(router=self.rs.label, model=self.model,
                               revision=self.to_revision).set(0)
            self.phase = "drain"
            self._drain_waited = 0
            # ptlint: disable=PT-C004  outermost-lock call down the
            # declared order (ReplicaSet sits BELOW DeployController)
            self.rs.drain(self._queue[0], recompute=False)

    def done(self) -> bool:
        with self._lock:
            return self.phase in self.TERMINAL

    def status(self) -> dict:
        with self._lock:
            return {"deploy_id": self.deploy_id, "phase": self.phase,
                    "outcome": self.outcome, "error": self.error,
                    "model": self.model,
                    "from_revision": self.from_revision,
                    "to_revision": self.to_revision,
                    "swapped": list(self._swapped),
                    "pool": list(self._queue), "ticks": self.ticks}

    def tick(self) -> dict:
        """Advance the rollout by at most one bounded action; returns
        status(). Call interleaved with rs.step() — a tick never blocks
        on traffic, it only observes drain progress the router steps
        make."""
        with self._lock:
            if self.phase in self.TERMINAL:
                return self.status()
            if self.phase == "idle":
                raise ValueError("tick() before start()")
            self.ticks += 1
            span = obs.span("deploy.tick", cat="deploy", annotate=False,
                            args={"deploy": self.deploy_id,
                                  "phase": self.phase,
                                  "tick": self.ticks})
            span.begin()
            try:
                # ptlint: disable=PT-C004  outermost-lock calls down
                # the declared order (start() above)
                if self.phase == "drain":
                    self._tick_drain()
                elif self.phase == "swap":
                    self._tick_swap()
                elif self.phase == "canary":
                    self._tick_canary()
                elif self.phase == "rejoin":
                    self._tick_rejoin()
            finally:
                span.end()
            return self.status()

    # ------------------------------------------------------------- phases
    @holds_lock("_lock")
    def _current(self):
        return self.rs.replicas[self._queue[self._pos]]

    @holds_lock("_lock")
    def _tick_drain(self) -> None:
        from .replica import ReplicaState
        rep = self._current()
        if rep.state == ReplicaState.DRAINED:
            self.phase = "swap"
            return
        if rep.state in (ReplicaState.FAILED, ReplicaState.DOWN):
            # the slot died while draining (chaos): its requests
            # already failed over; roll the deploy back
            self._rollback(f"replica {rep.index} died while draining")
            return
        self._drain_waited += 1
        if self._drain_waited > self.config.drain_wait_ticks:
            # undrain so the slot rejoins rotation as-was
            # ptlint: disable=PT-C004  outermost-lock call down the order
            self.rs.undrain(rep.index)
            self._rollback(
                f"replica {rep.index} still draining after "
                f"{self.config.drain_wait_ticks} ticks")

    @holds_lock("_lock")
    def _tick_swap(self) -> None:
        rep = self._current()
        factory = self.registry.engine_factory(self.model,
                                               self.to_revision)
        if not rep.swap_revision(factory):
            self._rollback(
                f"replica {rep.index}: new revision failed to build "
                f"or probe")
            return
        self._swapped.append(rep.index)
        obs.reqtrace.record(
            "replica_swap", self.deploy_id, self.deploy_id,
            router=self.rs.label, replica=rep.index, model=self.model,
            revision=self.to_revision)
        # chaos window: the new engine is installed and probed but the
        # canary gate has NOT run — a kill here must roll back cleanly
        # (the swapped slot never served, so there is nothing to lose)
        # ptlint: disable=PT-C004  deterministic lock-free test hook
        # (ServingFaultInjector), same contract as every other fault gate
        if self.faults is not None and self.faults.kill_deploy(
                self.ticks, rep.index):
            rep.quarantine("kill_deploy: died between swap and canary")
            self._rollback(
                f"replica {rep.index} killed in the swap->canary "
                f"window")
            return
        self.phase = "canary"

    @holds_lock("_lock")
    def _tick_canary(self) -> None:
        rep = self._current()
        try:
            outs = rep.canary_outputs(
                self.config.canary_prompts,
                max_tokens=self.config.canary_max_tokens)
        except Exception as e:              # noqa: BLE001 — a canary
            # that cannot serve is a failed candidate revision
            obs.reqtrace.record(
                "canary", self.deploy_id, self.deploy_id,
                router=self.rs.label, replica=rep.index,
                mismatches=-1, passed=False)
            self._rollback(f"replica {rep.index}: canary failed: {e}")
            return
        mism = sum(1 for got, want in zip(outs, self._reference)
                   if got != want)
        passed = mism <= self.config.canary_tolerance
        obs.reqtrace.record(
            "canary", self.deploy_id, self.deploy_id,
            router=self.rs.label, replica=rep.index, mismatches=mism,
            passed=passed)
        if mism:
            self._c_canary.inc(mism)
        if not passed:
            self._rollback(
                f"replica {rep.index}: {mism} canary prompts diverged "
                f"(tolerance {self.config.canary_tolerance})")
            return
        self.phase = "rejoin"

    @holds_lock("_lock")
    def _tick_rejoin(self) -> None:
        rep = self._current()
        # ptlint: disable=PT-C004  outermost-lock call down the order
        if not self.rs.probe_grow(rep.index):
            self._rollback(
                f"replica {rep.index}: swapped slot failed its rejoin "
                f"probe")
            return
        self._pos += 1
        if self.config.shift_weights:
            done, total = self._pos, len(self._queue)
            weights = {self.to_revision: float(done)}
            if total - done:
                weights[self.from_revision] = float(total - done)
            # ptlint: disable=PT-C004  outermost-lock call down the order
            self.rs.set_route_weights(self.model, weights)
        if self._pos == len(self._queue):
            self._commit()
            return
        self.phase = "drain"
        self._drain_waited = 0
        # ptlint: disable=PT-C004  outermost-lock call down the order
        self.rs.drain(self._queue[self._pos], recompute=False)

    # ---------------------------------------------------------- terminal
    @holds_lock("_lock")
    def _commit(self) -> None:
        self.registry.set_active(self.model, self.to_revision)
        for idx in self._swapped:
            self.rs.replicas[idx].commit_revision()
        # active now IS the new revision: explicit weights come off
        # ptlint: disable=PT-C004  outermost-lock call down the order
        self.rs.set_route_weights(self.model, None)
        obs.reqtrace.record(
            "deploy_commit", self.deploy_id, self.deploy_id,
            router=self.rs.label, model=self.model,
            revision=self.to_revision, replicas=len(self._swapped))
        self._g_rev.labels(router=self.rs.label, model=self.model,
                           revision=self.to_revision).set(1)
        self._g_rev.labels(router=self.rs.label, model=self.model,
                           revision=self.from_revision).set(0)
        self._finish("committed", None)

    @holds_lock("_lock")
    def _rollback(self, reason: str) -> None:
        """Atomic rollback: every swapped slot restores its warm
        old-revision engine (newest swap first — the reverse of the
        rollout), rejoins through the probe gate, and the route weights
        snap back to the old revision. A swapped slot that already
        rejoined rotation may hold live new-revision requests; those
        evacuate through the router's zero-lost failover FIRST
        (rs.evict — re-admission re-prefills from the token log and
        _repin records the fresh `admitted` that keeps invariant 8
        honest), because restore_revision replaces the engine object
        and would strand them. Slots that never swapped were never
        touched beyond a drain, which undrain/probe_grow reverses."""
        restored = 0
        for idx in reversed(self._swapped):
            rep = self.rs.replicas[idx]
            if rep.is_serving() and rep.has_unfinished():
                # ptlint: disable=PT-C004  outermost-lock call down the
                # declared order (router failover under ReplicaSet._lock)
                self.rs.evict(idx, "rollback",
                              f"{self.deploy_id}: {reason}")
            if rep.restore_revision():
                restored += 1
                # ptlint: disable=PT-C004  outermost-lock call down the
                # order
                self.rs.probe_grow(idx)
        # a mid-drain slot (never swapped) rejoins as-was
        if self._pos < len(self._queue):
            rep = self._current()
            from .replica import ReplicaState
            if rep.state == ReplicaState.DRAINING:
                # ptlint: disable=PT-C004  outermost-lock call down the
                # order
                self.rs.undrain(rep.index)
            elif rep.state == ReplicaState.DRAINED \
                    and rep.index not in self._swapped:
                # ptlint: disable=PT-C004  outermost-lock call down the
                # order
                self.rs.probe_grow(rep.index)
        # ptlint: disable=PT-C004  outermost-lock call down the order
        self.rs.set_route_weights(self.model, None)
        obs.reqtrace.record(
            "rollback", self.deploy_id, self.deploy_id,
            router=self.rs.label, model=self.model,
            reason=reason, restored=restored,
            revision=self.from_revision)
        self._g_rev.labels(router=self.rs.label, model=self.model,
                           revision=self.from_revision).set(1)
        self._g_rev.labels(router=self.rs.label, model=self.model,
                           revision=self.to_revision).set(0)
        self._finish("rolled_back" if self._swapped else "aborted",
                     reason)

    @holds_lock("_lock")
    def _finish(self, outcome: str, error: Optional[str]) -> None:
        self.phase = outcome
        self.outcome = outcome
        self.error = error
        self._c_deploys.labels(router=self.rs.label,
                               outcome=outcome).inc()

    # -------------------------------------------------------- convenience
    def run(self, max_ticks: int = 5000) -> dict:
        """Drive the rollout to a terminal state, stepping the router
        between ticks (tests and offline deploys; live callers
        interleave tick() with their own serving loop)."""
        with self._lock:
            idle = self.phase == "idle"
        if idle:
            self.start()
        ticks = 0
        while not self.done() and ticks < max_ticks:
            self.rs.step()
            self.tick()
            ticks += 1
        if not self.done():
            with self._lock:
                self._rollback(f"deploy incomplete after {max_ticks} "
                               f"ticks")
        return self.status()
