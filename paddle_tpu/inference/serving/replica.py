"""EngineReplica: one supervised LLMEngine slot inside a ReplicaSet.

The replica supervisor is the serving twin of the trainer supervision in
distributed/elastic.py — the same three signals, one per failure mode:

- CRASH: the engine's step raises (a kill_replica fault, a device error
  the engine-level recovery could not contain). The exception IS the
  signal, like a nonzero exit code to ElasticSupervisor.
- WEDGE: the engine stops making progress without raising — a hung
  device call. Detected the way elastic detects trainer hangs: each
  successful step beats a heartbeat timestamp, and a replica holding
  unfinished work whose beat goes stale past `heartbeat_timeout` counts
  as wedged (`ReplicaSet` runs the check; a wedged step here returns
  without beating, which is exactly what a hung engine looks like from
  the router's thread).
- DRAIN: operator-initiated; the replica finishes its admitted work but
  receives nothing new, then parks DRAINED until undrained.

A failed replica's engine object is DISCARDED untouched — the router
scrub-frees nothing it can't reach, because a dead engine's device state
is gone and a wedged one's is untrustworthy; the blocks die with the
pool. Restarts follow elastic's capped-backoff policy
(distributed.elastic.BackoffPolicy — literally the same class), and a
restarted replica rejoins rotation only after a WARMUP PROBE: a 1-token
greedy request must complete on the fresh engine before any real traffic
routes there (a replica that crashes on its probe goes straight back to
backoff).

Thread contract (ptlint PT-C001 via _GUARDED_BY): replica state is
shared between the router's step loop and intake threads; public methods
take self._lock, helpers are @holds_lock. Lock order is
router → replica → engine → scheduler, never the reverse.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from ...analysis import holds_lock
from ...distributed.elastic import BackoffPolicy
from .scheduler import SamplingParams

__all__ = ["EngineReplica", "ReplicaCrashed", "ReplicaState"]


class ReplicaCrashed(RuntimeError):
    """A replica's engine died mid-step (the serving analogue of a
    nonzero worker exit code); the router quarantines the replica and
    fails its requests over to survivors."""


class ReplicaState:
    STARTING = "starting"    # fresh engine up, warmup probe pending
    UP = "up"                # serving; eligible for admissions
    DRAINING = "draining"    # finishing admitted work; no new admissions
    DRAINED = "drained"      # drained empty; parked until undrain()
    DOWN = "down"            # quarantined; backing off before restart
    FAILED = "failed"        # restart budget exhausted; never rejoins

    SERVING = (UP, DRAINING)  # states whose engine steps


class EngineReplica:
    """One supervised engine slot (module docstring). The ReplicaSet is
    the only caller; every public method is safe from the router's
    locked frame (lock order router → replica)."""

    _GUARDED_BY = {
        "engine": "_lock",
        "state": "_lock",
        "restarts": "_lock",
        "restart_at": "_lock",
        "last_beat": "_lock",
        "last_step_end": "_lock",
        "_wedged": "_lock",
        "history": "_lock",
        "failed_at": "_lock",
        "probe_tokens": "_lock",
        "_probe_seq": "_lock",
        "_factory": "_lock",
        "model": "_lock",
        "revision": "_lock",
        "_prev_engine": "_lock",
        "_prev_factory": "_lock",
    }

    ROLES = ("prefill", "decode", "mixed")

    def __init__(self, index: int, engine_factory: Callable,
                 backoff: BackoffPolicy, max_restarts: int = 3,
                 heartbeat_timeout: Optional[float] = None,
                 probe_prompt=(1,), probe_timeout_steps: int = 64,
                 role: str = "mixed"):
        if role not in self.ROLES:
            raise ValueError(f"replica role {role!r} not in {self.ROLES}")
        self.index = index
        # tier assignment (docs/serving.md): immutable after construction
        # — "prefill" replicas take new prompts and hand finished
        # prefills off, "decode" replicas only receive migrations,
        # "mixed" does both (the homogeneous default)
        self.role = role
        self._factory = engine_factory
        self._backoff = backoff
        self.max_restarts = int(max_restarts)
        self.heartbeat_timeout = heartbeat_timeout
        self.probe_prompt = list(probe_prompt)
        self.probe_timeout_steps = int(probe_timeout_steps)
        self._lock = threading.RLock()
        self.engine = engine_factory(index, 0)
        # first incarnation starts UP unprobed — the same trust a
        # single-engine deployment extends to a freshly built LLMEngine;
        # the warmup probe gates REJOIN after a failure, where the
        # previous incarnation just proved the slot can go bad
        self.state = ReplicaState.UP
        self.restarts = 0                 # incarnations spent (0 = first)
        self.restart_at: Optional[float] = None
        self.last_beat = time.monotonic()
        self.last_step_end = self.last_beat
        self._wedged = False
        self.failed_at: Optional[float] = None  # quarantine timestamp
        self.history: List[tuple] = []    # [(incarnation, reason)]
        self.probe_tokens = 0             # warmup tokens spent (telemetry)
        self._probe_seq = 0               # probes run on THIS incarnation
        # (model, revision) identity (serving/deploy.py): cached OFF the
        # engine so routing/autoscaling can still group a quarantined
        # slot (engine None) with its pool. Updated whenever an engine
        # is (re)built; swap_revision changes it, quarantine keeps it.
        with self._lock:
            self.model, self.revision = self._engine_key()
        # warm standby for instant rollback: the previous revision's
        # engine + factory, held from swap_revision until the deploy
        # commits (commit_revision) or rolls back (restore_revision)
        self._prev_engine = None
        self._prev_factory: Optional[Callable] = None

    @holds_lock("_lock")
    def _engine_key(self) -> tuple:
        """(model, revision) the current engine serves (engine configs
        default to ("default", "r0") on single-model stacks)."""
        cfg = self.engine.config
        return (getattr(cfg, "model", "default"),
                getattr(cfg, "revision", "r0"))

    # ------------------------------------------------------------ queries
    def revision_key(self) -> tuple:
        """(model, revision) this slot serves — the key every KV payload
        carries and every admit path checks (cross-revision refusal)."""
        with self._lock:
            return (self.model, self.revision)

    def is_serving(self) -> bool:
        with self._lock:
            return self.state in ReplicaState.SERVING

    def accepts_admissions(self) -> bool:
        with self._lock:
            return self.state == ReplicaState.UP

    def has_unfinished(self) -> bool:
        with self._lock:
            return self.state in ReplicaState.SERVING \
                and self.engine.has_unfinished()

    def load_info(self) -> dict:
        with self._lock:
            return self.engine.load_info()

    def check_integrity(self):
        """Zero-leak audit of THIS replica's live pool (None while the
        slot holds no engine — a quarantined incarnation's pool is
        unreachable by definition)."""
        with self._lock:
            if self.engine is None:
                return None
            # ptlint: disable=PT-C003  postmortem-only I/O: the flight
            # dump inside check_integrity fires IFF the pool is corrupt,
            # right before the raise condemns this replica anyway
            return self.engine.cache.check_integrity()

    # ------------------------------------------------------------ intake
    def dispatch(self, prompt_ids, sampling, request_id,
                 arrival_time=None, arrival=None, resume_tokens=None,
                 readmit: bool = False, trace_id=None):
        """Admit one request to this replica's engine (router-only
        entry; the dispatch beats the heartbeat so an idle replica's
        clock starts when work lands). `trace_id` rides through to the
        engine so the router-minted causal timeline (obs/reqtrace.py)
        survives the hop. Returns the engine-stamped
        (arrival ticket, arrival_time)."""
        with self._lock:
            self.engine.add_request(prompt_ids, sampling,
                                    request_id=request_id,
                                    arrival_time=arrival_time,
                                    arrival=arrival,
                                    resume_tokens=resume_tokens,
                                    readmit=readmit,
                                    trace_id=trace_id)
            self.last_beat = time.monotonic()
            req = self.engine.get_request(request_id)
            return req.arrival, req.arrival_time

    def oldest_waiting_arrival(self) -> Optional[int]:
        with self._lock:
            return self.engine.oldest_waiting_arrival()

    def shed_oldest_waiting(self) -> Optional[str]:
        with self._lock:
            return self.engine.shed_oldest_waiting()

    def cancel(self, request_id: str) -> bool:
        with self._lock:
            if self.engine is None:
                return False
            return self.engine.cancel(request_id)

    # --------------------------------------------------------------- step
    def step(self, router_step: int, faults=None) -> list:
        """One engine step under supervision. Raises ReplicaCrashed when
        a kill fault (or any engine-level exception) fires; a wedged
        replica returns [] WITHOUT beating the heartbeat — from the
        router's perspective indistinguishable from a hung device call,
        which is the point."""
        with self._lock:
            if faults is not None \
                    and faults.kill_replica(router_step, self.index):
                raise ReplicaCrashed(
                    f"replica {self.index} killed by fault injection at "
                    f"router step {router_step}")
            if faults is not None \
                    and faults.wedge_replica(router_step, self.index):
                self._wedged = True
            if self._wedged:
                self.last_step_end = time.monotonic()
                return []
            try:
                # ptlint: disable=PT-C003  engine.step flushes its OWN
                # deferred flight dumps outside the ENGINE lock; here
                # that tail rides under this replica's lock — per-replica
                # blast radius, bounded by the ring's flight budget
                outs = self.engine.step()
            except Exception as e:
                raise ReplicaCrashed(
                    f"replica {self.index} engine step raised: {e}") from e
            now = time.monotonic()
            self.last_beat = now
            self.last_step_end = now
            return outs

    def beat(self) -> None:
        """Reset the heartbeat baseline (the router beats on dispatch so
        a request added to a momentarily-idle replica can't trip the
        stale-beat check before its first step)."""
        with self._lock:
            self.last_beat = time.monotonic()

    def wedged(self) -> bool:
        """Heartbeat-based wedge verdict: serving, holding unfinished
        work, and silent past heartbeat_timeout. The staleness baseline
        is the replica's OWN last step-return time, not wall clock — a
        healthy step always beats at its end, so last_step_end and
        last_beat advance together and a slow-but-progressing step
        (fresh-engine compile, a long stall that completes) can never
        false-trip the check; only steps that return WITHOUT beating —
        the wedge signature — let last_step_end drift ahead. An IDLE
        wedged replica is caught on its first admission: the dispatch
        beat starts the clock and no step beat ever follows."""
        with self._lock:
            if self.heartbeat_timeout is None \
                    or self.state not in ReplicaState.SERVING:
                return False
            if not self.engine.has_unfinished():
                return False
            return (self.last_step_end - self.last_beat) \
                > self.heartbeat_timeout

    # ----------------------------------------------------------- failover
    def quarantine(self, reason: str) -> None:
        """Take the replica out of rotation after a crash/wedge verdict.
        The engine object is dropped UNREAD — nothing it owns can be
        trusted (and for a real dead process nothing is reachable), so
        there is no scrub, no free: the pool dies with the engine. A
        fresh incarnation gets a fresh pool."""
        with self._lock:
            self.history.append((self.restarts, reason))
            self.engine = None
            self._wedged = False
            self.failed_at = time.monotonic()
            if self.restarts >= self.max_restarts:
                self.state = ReplicaState.FAILED
                self.restart_at = None
            else:
                self.state = ReplicaState.DOWN
                self.restart_at = time.monotonic() \
                    + self._backoff.delay(self.restarts)
                self.restarts += 1

    def restart_due(self, now: float = None) -> bool:
        with self._lock:
            now = time.monotonic() if now is None else now
            return self.state == ReplicaState.DOWN \
                and self.restart_at is not None and now >= self.restart_at

    def restart(self) -> bool:
        """Build a fresh engine incarnation and run the warmup probe.
        Returns True when the replica is back UP; a probe failure sends
        it straight back to quarantine (counting against the restart
        budget, with escalated backoff)."""
        with self._lock:
            self.state = ReplicaState.STARTING
            try:
                # ptlint: disable=PT-C004  restart MUST swap the engine
                # atomically under the replica lock — a half-built engine
                # visible to dispatch() is worse than a slow factory (the
                # router tolerates a slow restart; it routes around DOWN)
                self.engine = self._factory(self.index, self.restarts)
                self.model, self.revision = self._engine_key()
                self._probe()
            except Exception as e:          # noqa: BLE001 — any probe
                # failure is a failed incarnation, not a router crash
                self.quarantine(f"warmup probe failed: {e}")
                return False
            self.state = ReplicaState.UP
            self.last_beat = time.monotonic()
            return True

    @holds_lock("_lock")
    def _probe(self) -> None:
        """Warmup probe: one greedy token end-to-end on the fresh engine
        (prefill → paged decode → terminal). Any raise or a non-'length'
        terminal fails the probe; the probe request never reaches the
        router's tables."""
        eng = self.engine
        # the -p sequence keeps probe ids unique when one incarnation is
        # probed more than once (restart probe, then autoscaler rejoin
        # probes after each park) — engines reject duplicate request ids
        self._probe_seq += 1
        rid = eng.add_request(
            self.probe_prompt,
            SamplingParams(max_tokens=1, temperature=0.0),
            request_id=(f"warmup-probe-r{self.index}-i{self.restarts}"
                        f"-p{self._probe_seq}"))
        for _ in range(self.probe_timeout_steps):
            # ptlint: disable=PT-C003  warmup probe of a PRIVATE engine
            # not yet published to dispatch(); nothing else can contend
            eng.step()
            req = eng.get_request(rid)
            if req.finished:
                break
        req = eng.get_request(rid)
        if req.state != "finished_length":
            raise RuntimeError(
                f"warmup probe ended {req.state!r} instead of serving "
                f"its token")
        self.probe_tokens += len(req.output_ids)

    # ----------------------------------------------------------- migration
    # Locked pass-throughs for the BlockMigration coordinator
    # (serving/migration.py). The coordinator runs in the router's step
    # frame and acquires ONE replica's lock at a time — never source and
    # destination together (lock order: BlockMigration._lock →
    # EngineReplica._lock; two same-named locks held at once would be a
    # witnessed self-cycle).

    def migratable_requests(self, decode_only: bool = True) -> List[str]:
        with self._lock:
            if self.state not in ReplicaState.SERVING \
                    or self.engine is None:
                return []
            return self.engine.migratable_requests(decode_only=decode_only)

    def export_request(self, request_id: str) -> dict:
        with self._lock:
            return self.engine.export_request(request_id)

    def admit_migrated(self, snap: dict) -> str:
        """Destination admission; beats the heartbeat like dispatch()
        does, so a migration landing on an idle decode replica can't
        trip the stale-beat wedge check before its first step. Returns
        the destination engine's obs label (migrate_in event home)."""
        with self._lock:
            label = self.engine.admit_migrated(snap)
            self.last_beat = time.monotonic()
            return label

    def release_migrated(self, request_id: str) -> None:
        with self._lock:
            self.engine.release_migrated(request_id)

    def abort_migrated(self, request_id: str) -> None:
        with self._lock:
            if self.engine is not None:
                self.engine.abort_migrated(request_id)

    def release_waiting(self, request_id: str):
        with self._lock:
            return self.engine.release_waiting(request_id)

    # ------------------------------------------------------- prefix tier
    # Peer prefix-fetch pass-throughs (docs/serving.md "Hierarchical
    # KV-cache tiering"). Same discipline as the migration block above:
    # the BlockMigration coordinator touches ONE replica's lock at a
    # time, so a fetch in each direction between two replicas can never
    # deadlock. A slot with no engine probes 0 / exports None — a dead
    # peer simply holds no prefix.

    def prefix_probe(self, prompt_ids) -> int:
        with self._lock:
            if self.engine is None:
                return 0
            return self.engine.prefix_probe(prompt_ids)

    def export_prefix(self, prompt_ids):
        with self._lock:
            if self.engine is None:
                return None
            return self.engine.export_prefix(prompt_ids)

    def admit_prefix(self, prompt_ids, blocks, model: str = None,
                     revision: str = None) -> int:
        with self._lock:
            if self.engine is None:
                return 0
            return self.engine.admit_prefix(prompt_ids, blocks,
                                            model=model,
                                            revision=revision)

    # ------------------------------------------------------------ draining
    def drain(self) -> None:
        with self._lock:
            if self.state == ReplicaState.UP:
                self.state = ReplicaState.DRAINING

    def maybe_drained(self) -> bool:
        """DRAINING → DRAINED once the engine has nothing unfinished
        (router polls this each step). True when parked."""
        with self._lock:
            if self.state == ReplicaState.DRAINING \
                    and not self.engine.has_unfinished():
                self.state = ReplicaState.DRAINED
            return self.state == ReplicaState.DRAINED

    def undrain(self) -> None:
        with self._lock:
            if self.state in (ReplicaState.DRAINING, ReplicaState.DRAINED):
                self.state = ReplicaState.UP

    def probe_rejoin(self) -> bool:
        """Warmup-probe rejoin for a PARKED replica (autoscaler grow
        path, docs/serving.md): a DRAINED slot has been idle for an
        unbounded time, so before it takes traffic again it must prove
        the warm engine still serves — the same 1-token greedy probe
        that gates rejoin after a restart. Only DRAINED slots qualify:
        the probe loop steps the engine and discards outputs, which
        would eat live requests' tokens on any serving state. A probe
        failure quarantines the incarnation (the slot just proved it
        went bad while parked), handing recovery to the normal
        restart/backoff machinery. Returns True when the replica is
        back UP."""
        with self._lock:
            if self.state != ReplicaState.DRAINED:
                return False
            if self.engine is None or self.engine.has_unfinished():
                return False
            try:
                self._probe()
            except Exception as e:          # noqa: BLE001 — a failed
                # rejoin probe is a failed incarnation, not a crash
                self.quarantine(f"rejoin probe failed: {e}")
                return False
            self.state = ReplicaState.UP
            self.last_beat = time.monotonic()
            return True

    # ------------------------------------------------- revision rollout
    # The DeployController's per-replica primitives (serving/deploy.py).
    # A rollout touches one PARKED (DRAINED, evacuated-empty) slot at a
    # time: swap_revision installs the new revision's engine and runs
    # the warmup probe, canary_outputs drives the parity gate, and the
    # slot only rejoins rotation via the normal probe_rejoin. The OLD
    # engine + factory are kept warm until the whole deploy commits
    # (commit_revision) so restore_revision is an instant, re-prefill-
    # free rollback — the drained old pool is empty, nothing is stale.

    def swap_revision(self, engine_factory: Callable) -> bool:
        """Replace a PARKED slot's engine with a new revision's, probe
        it, and park again (the canary gate and probe_rejoin stand
        between the swap and real traffic). A factory/probe failure
        reinstates the old incarnation and returns False — the slot is
        exactly as before the call."""
        with self._lock:
            if self.state != ReplicaState.DRAINED:
                raise ValueError(
                    f"swap_revision: replica {self.index} is "
                    f"{self.state!r}, not drained")
            if self.engine.has_unfinished():
                raise ValueError(
                    f"swap_revision: replica {self.index} still holds "
                    f"unfinished work")
            self._prev_engine = self.engine
            self._prev_factory = self._factory
            self._factory = engine_factory
            self.state = ReplicaState.STARTING
            try:
                # ptlint: disable=PT-C004  same contract as restart():
                # the swap must be atomic under the replica lock — a
                # half-built engine visible to dispatch() would serve
                # unverified weights
                self.engine = engine_factory(self.index, self.restarts)
                self.model, self.revision = self._engine_key()
                self._probe()
            except Exception:               # noqa: BLE001 — a failed
                # swap is a failed CANDIDATE, not a failed slot: the
                # old incarnation comes straight back
                self._factory = self._prev_factory
                self.engine = self._prev_engine
                self._prev_engine = None
                self._prev_factory = None
                self.model, self.revision = self._engine_key()
                self.state = ReplicaState.DRAINED
                return False
            self.state = ReplicaState.DRAINED
            return True

    def restore_revision(self) -> bool:
        """Instant rollback: reinstate the warm previous-revision engine
        and factory saved by swap_revision. Works whether the swapped
        incarnation is still parked or was quarantined mid-deploy (the
        chaos window) — the slot parks DRAINED either way and rejoins
        via probe_rejoin. Returns False when there is nothing to
        restore."""
        with self._lock:
            if self._prev_factory is None:
                return False
            self._factory = self._prev_factory
            old, self._prev_engine = self._prev_engine, None
            self._prev_factory = None
            if old is None:                  # pragma: no cover - the
                # warm engine is only dropped by commit_revision, which
                # also clears the factory; restart() rebuilds old weights
                return False
            self.engine = old
            self.model, self.revision = self._engine_key()
            self._wedged = False
            self.restart_at = None
            self.state = ReplicaState.DRAINED
            return True

    def commit_revision(self) -> None:
        """Release the warm standby once the deploy commits — rollback
        past this point is a fresh deploy of the old revision."""
        with self._lock:
            self._prev_engine = None
            self._prev_factory = None

    def canary_outputs(self, prompts, max_tokens: int = 8,
                       max_steps_each: int = 256) -> List[List[int]]:
        """Greedy decode of the pinned canary prompt set on a PARKED
        slot's engine — the deploy parity gate's measurement half. Runs
        each prompt end-to-end (prefill → decode → 'length' terminal)
        and returns the emitted token lists; any raise or an unfinished
        canary fails the gate. Only DRAINED slots qualify, for the same
        reason as probe_rejoin: the loop steps the engine and a serving
        state would lose live requests' tokens."""
        with self._lock:
            if self.state != ReplicaState.DRAINED:
                raise ValueError(
                    f"canary_outputs: replica {self.index} is "
                    f"{self.state!r}, not drained")
            eng = self.engine
            outs: List[List[int]] = []
            for prompt in prompts:
                self._probe_seq += 1
                rid = eng.add_request(
                    list(prompt),
                    SamplingParams(max_tokens=max_tokens,
                                   temperature=0.0),
                    request_id=(f"canary-r{self.index}-i{self.restarts}"
                                f"-p{self._probe_seq}"))
                for _ in range(max_steps_each):
                    # ptlint: disable=PT-C003  canary probe of a PARKED
                    # engine not reachable from dispatch(); same
                    # contention-free contract as _probe
                    eng.step()
                    if eng.get_request(rid).finished:
                        break
                req = eng.get_request(rid)
                if req.state != "finished_length":
                    raise RuntimeError(
                        f"canary {rid!r} ended {req.state!r} instead of "
                        f"serving its tokens")
                self.probe_tokens += len(req.output_ids)
                outs.append([int(t) for t in req.output_ids])
            return outs
