"""Telemetry-driven role-aware autoscaler over a ReplicaSet.

ROADMAP item 3 / docs/serving.md "Multi-tenant scheduling and
autoscaling": a fixed fleet wastes accelerators at 3am and sheds
latency-tenant traffic at noon. The Autoscaler closes the loop between
the serving observability the stack already emits and the replica
lifecycle the router already implements — it invents no new mechanism,
it just decides WHEN to use the existing ones:

- SHRINK parks a replica through the PR-15 evacuating drain
  (`ReplicaSet.drain(index, recompute=False)`): live KV blocks migrate
  to survivors, queued requests re-dispatch from the router's token
  log, zero tokens are recomputed and zero requests are lost. The slot
  parks DRAINED with its engine warm.
- GROW returns a parked slot through a warmup-probe rejoin
  (`ReplicaSet.probe_grow(index)`): the slot must serve a 1-token
  greedy probe end-to-end before real traffic routes there, the same
  gate a restarted incarnation passes — a slot that went bad while
  parked quarantines instead of eating live requests.

Because the router's replica list is immutable after construction, the
autoscaler scales the ACTIVE set over a max-provisioned fleet: build
the ReplicaSet at `max_replicas`, let the autoscaler park what the
load doesn't need. A parked replica holds no admitted work (the drain
evacuated it) and steps for free (`is_serving()` is False), so the
only cost of a parked slot is its idle pool memory.

Scaling signals (AutoscalerPolicy.decide, pure and unit-testable):

- queue pressure: total waiting across serving replicas, per replica
  (the per-tenant split from `waiting_by_tenant` rides along in the
  signal dict for telemetry and tie-breaks);
- block headroom: aggregate free-block fraction across live pools;
- TTFT-p99 trend: the router histogram's p99 vs the configured SLO.

Role-awareness: the fleet may mix prefill/decode/mixed tiers
(disaggregated serving, PR 16). The measured phase split — summed
`time_prefill` vs `time_decode` across serving engines — picks WHICH
role to grow or shrink: when prefill dominates, grow prefill-capable
slots first and shrink decode slots first; when decode dominates, the
reverse. Mixed slots are always eligible on both sides.

Model-awareness (multi-model fleets, PR 18 / serving/deploy.py): the
signal snapshot carries a per-model breakdown, growth lands in the
HOTTEST model's pool (highest waiting per serving replica, among
models with a parked slot), shrink drains the COLDEST — and never a
model's last serving replica, so no pool ever scales to zero while
registered. Single-model fleets see identical decisions to before.

Thread contract (ptlint PT-C001 via _GUARDED_BY): `Autoscaler._lock`
is the OUTERMOST lock in the serving stack — step() holds it while
calling into ReplicaSet control surfaces, which take the router lock
and then replica/engine/scheduler locks (lockgraph.json order:
Autoscaler -> ReplicaSet -> ... -> Scheduler). Nothing in the serving
stack ever calls back into the autoscaler, so the edge is one-way.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional

from ... import obs
from ...analysis import holds_lock
from .replica import ReplicaState

__all__ = ["Autoscaler", "AutoscalerConfig", "AutoscalerPolicy"]


@dataclass
class AutoscalerConfig:
    # fleet bounds on the ACTIVE (admission-eligible) set
    min_replicas: int = 1
    max_replicas: Optional[int] = None   # None: the provisioned fleet
    # queue pressure thresholds, in waiting requests per serving replica
    target_waiting_per_replica: float = 8.0   # grow above this
    low_waiting_per_replica: float = 1.0      # shrink below this
    # grow when the aggregate free-block fraction across live pools
    # drops below this (admission is about to hit watermark holds)
    min_headroom_frac: float = 0.10
    # grow when router TTFT p99 breaches this (None: ignore TTFT)
    ttft_p99_slo_s: Optional[float] = None
    # steps to hold after any action (probe + evacuation both perturb
    # the very signals the policy reads; don't chase the transient)
    cooldown_steps: int = 8
    # phase-split fraction above which prefill is "the bottleneck"
    prefill_heavy_frac: float = 0.55

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas is not None \
                and self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas < min_replicas")
        if self.low_waiting_per_replica > self.target_waiting_per_replica:
            raise ValueError(
                "low_waiting_per_replica must not exceed "
                "target_waiting_per_replica")
        if not 0.0 <= self.min_headroom_frac < 1.0:
            raise ValueError("min_headroom_frac must be in [0, 1)")
        if self.cooldown_steps < 0:
            raise ValueError("cooldown_steps must be >= 0")


class AutoscalerPolicy:
    """Pure decision function: signals in, verdict out. Stateless so
    tests drive it with synthetic signal dicts and the Autoscaler's
    locking/cooldown machinery stays out of the picture."""

    def __init__(self, config: AutoscalerConfig):
        self.config = config

    def decide(self, signals: dict) -> dict:
        """Map one signal snapshot to {action, reason, role_pref}.

        `signals` keys (Autoscaler.collect_signals builds them):
          up             serving replica count (admission-eligible)
          parked         parked (DRAINED) replica count
          waiting_total  waiting requests across serving replicas
          free_frac      aggregate free-block fraction (1.0 when no
                         live pool is visible)
          ttft_p99       router TTFT p99 seconds (0.0 before data)
          prefill_frac   time_prefill / (time_prefill + time_decode)
                         across serving engines (0.5 before data)
        """
        cfg = self.config
        up = signals["up"]
        pressure_role = "prefill" \
            if signals.get("prefill_frac", 0.5) >= cfg.prefill_heavy_frac \
            else "decode"
        per = signals["waiting_total"] / max(up, 1)
        if up < cfg.min_replicas:
            return {"action": "grow", "reason": "below_min",
                    "role_pref": pressure_role}
        cap = cfg.max_replicas
        can_grow = signals["parked"] > 0 and (cap is None or up < cap)
        if can_grow:
            if per > cfg.target_waiting_per_replica:
                return {"action": "grow", "reason": "queue_pressure",
                        "role_pref": pressure_role}
            if signals["free_frac"] < cfg.min_headroom_frac:
                return {"action": "grow", "reason": "block_headroom",
                        "role_pref": pressure_role}
            if cfg.ttft_p99_slo_s is not None \
                    and signals["ttft_p99"] > cfg.ttft_p99_slo_s:
                return {"action": "grow", "reason": "ttft_slo",
                        "role_pref": pressure_role}
        if up > cfg.min_replicas \
                and per < cfg.low_waiting_per_replica \
                and signals["free_frac"] >= cfg.min_headroom_frac \
                and (cfg.ttft_p99_slo_s is None
                     or signals["ttft_p99"] <= cfg.ttft_p99_slo_s):
            # shrink the role the measured split says is OVER-provided:
            # prefill-heavy load keeps prefill slots, sheds decode
            shed = "decode" if pressure_role == "prefill" else "prefill"
            return {"action": "shrink", "reason": "idle_capacity",
                    "role_pref": shed}
        return {"action": "hold", "reason": "steady",
                "role_pref": pressure_role}


class Autoscaler:
    """Closed-loop fleet sizing over one ReplicaSet (module docstring).
    Drive `step()` from the serving loop — typically once per router
    step or per intake batch; it is cheap (host-side reads) and
    rate-limits itself through the cooldown."""

    _GUARDED_BY = {
        "steps": "_lock",
        "cooldown": "_lock",
        "grow_events": "_lock",
        "shrink_events": "_lock",
        "last_decision": "_lock",
    }

    def __init__(self, rs, config: AutoscalerConfig = None):
        self.rs = rs
        self.config = config or AutoscalerConfig()
        self.policy = AutoscalerPolicy(self.config)
        self._lock = threading.RLock()
        self.steps = 0
        self.cooldown = 0
        self.grow_events = 0
        self.shrink_events = 0
        self.last_decision: dict = {"action": "hold", "reason": "init",
                                    "role_pref": "decode"}
        lbl = dict(router=rs.label)
        self._g_active = obs.gauge(
            "serving_fleet_active",
            "replicas currently accepting admissions (autoscaler-"
            "managed active set)", labels=("router",)).labels(**lbl)
        self._c_events = obs.counter(
            "serving_autoscale_events_total",
            "autoscaler actions enacted, by direction (grow|shrink)",
            labels=("router", "direction"))
        self._g_active.set(rs.num_up())

    # ----------------------------------------------------------- signals
    def collect_signals(self) -> dict:
        """One host-side snapshot of the scaling inputs. Reads take the
        router/replica locks INSIDE this frame (lock order: Autoscaler
        outermost), never the reverse."""
        rs = self.rs
        up = 0
        parked = 0
        waiting_total = 0
        waiting_by_tenant: Dict[str, int] = {}
        free = 0
        total = 0
        t_prefill = 0.0
        t_decode = 0.0
        by_model: Dict[str, Dict[str, int]] = {}
        for rep in rs.replicas:
            ent = by_model.setdefault(
                rep.model, {"up": 0, "parked": 0, "waiting": 0})
            if rep.state == ReplicaState.DRAINED:
                parked += 1
                ent["parked"] += 1
            if not rep.accepts_admissions():
                continue
            up += 1
            ent["up"] += 1
            eng = rep.engine
            if eng is None:
                continue
            info = rep.load_info()
            waiting_total += info["waiting"]
            ent["waiting"] += info["waiting"]
            free += info["free_blocks"]
            total += eng.cache.num_blocks
            for t, n in eng.waiting_by_tenant().items():
                waiting_by_tenant[t] = waiting_by_tenant.get(t, 0) + n
            t_prefill += eng.stats.time_prefill
            t_decode += eng.stats.time_decode
        busy = t_prefill + t_decode
        return {
            "up": up,
            "parked": parked,
            "waiting_total": waiting_total,
            "waiting_by_tenant": waiting_by_tenant,
            "free_frac": free / total if total else 1.0,
            # ptlint: disable=PT-C004  ReplicaSet sits BELOW Autoscaler
            # in lockgraph.json; a lock-free histogram read besides
            "ttft_p99": rs.ttft_quantile(0.99),
            "prefill_frac": t_prefill / busy if busy else 0.5,
            # per-model pool pressure (multi-model fleets): which pool
            # growth should land in / shrink should drain from
            "by_model": by_model,
        }

    # -------------------------------------------------------------- step
    def step(self) -> dict:
        """One control-loop tick: snapshot signals, decide, enact.
        Returns the decision dict (action/reason/role_pref plus an
        `enacted` flag and the chosen replica index, or None)."""
        with self._lock:
            self.steps += 1
            # ptlint: disable=PT-C004  snapshot reads run down the
            # declared lock order (collect_signals docstring)
            signals = self.collect_signals()
            if self.cooldown > 0:
                self.cooldown -= 1
                out = {"action": "hold", "reason": "cooldown",
                       "role_pref": None, "enacted": False,
                       "replica": None, "signals": signals}
                self.last_decision = out
                return out
            verdict = self.policy.decide(signals)
            out = dict(verdict)
            out["signals"] = signals
            out["enacted"] = False
            out["replica"] = None
            if verdict["action"] == "grow":
                idx = self._pick_grow(verdict["role_pref"],
                                      model=self._hot_model(signals))
                # ptlint: disable=PT-C004  Autoscaler._lock is the
                # OUTERMOST serving lock (lockgraph.json); control
                # surfaces below never call back up into the autoscaler
                if idx is not None and self.rs.probe_grow(idx):
                    self.grow_events += 1
                    self.cooldown = self.config.cooldown_steps
                    self._c_events.labels(
                        router=self.rs.label, direction="grow").inc()
                    out["enacted"] = True
                    out["replica"] = idx
            elif verdict["action"] == "shrink":
                idx = self._pick_shrink(verdict["role_pref"],
                                        model=self._cold_model(signals))
                if idx is not None:
                    # evacuating drain: live blocks migrate, queued
                    # work re-dispatches — nothing recomputes or drops
                    # ptlint: disable=PT-C004  outermost-lock call down
                    # the declared order, as probe_grow above
                    self.rs.drain(idx, recompute=False)
                    self.shrink_events += 1
                    self.cooldown = self.config.cooldown_steps
                    self._c_events.labels(
                        router=self.rs.label, direction="shrink").inc()
                    out["enacted"] = True
                    out["replica"] = idx
            # ptlint: disable=PT-C004  locked replica-state read down
            # the declared order, as probe_grow above
            self._g_active.set(self.rs.num_up())
            self.last_decision = out
            return out

    # --------------------------------------------------------- selection
    @holds_lock("_lock")
    def _hot_model(self, signals: dict) -> Optional[str]:
        """The model pool growth should land in: highest waiting per
        serving replica among models that HAVE a parked slot to give
        back. None in single-model fleets (no preference)."""
        by = signals.get("by_model") or {}
        if len(by) < 2:
            return None
        cands = {m: e for m, e in by.items() if e["parked"] > 0}
        if not cands:
            return None
        return max(sorted(cands),
                   key=lambda m: cands[m]["waiting"]
                   / max(cands[m]["up"], 1))

    @holds_lock("_lock")
    def _cold_model(self, signals: dict) -> Optional[str]:
        """The model pool shrink should drain from: lowest waiting per
        serving replica among models that keep >= 1 serving replica
        after the drain. None in single-model fleets."""
        by = signals.get("by_model") or {}
        if len(by) < 2:
            return None
        cands = {m: e for m, e in by.items() if e["up"] > 1}
        if not cands:
            return None
        return min(sorted(cands),
                   key=lambda m: cands[m]["waiting"] / cands[m]["up"])

    @holds_lock("_lock")
    def _pick_grow(self, role_pref: str, model: str = None
                   ) -> Optional[int]:
        """Parked slot to rejoin: preferred model pool first (hottest —
        multi-model fleets), then preferred role, then mixed, then
        whatever is parked — availability beats tiering, same rule the
        router's admission fallback uses."""
        parked = [r for r in self.rs.replicas
                  if r.state == ReplicaState.DRAINED]
        if model is not None:
            parked = [r for r in parked if r.model == model] or parked
        for want in (role_pref, "mixed"):
            for rep in parked:
                if rep.role == want:
                    return rep.index
        return parked[0].index if parked else None

    @holds_lock("_lock")
    def _pick_shrink(self, role_pref: str, model: str = None
                     ) -> Optional[int]:
        """Active slot to park: among UP replicas (never touch DRAINING
        — one evacuation at a time), prefer the cold model's pool, then
        the shed role, then mixed; within a role, drain the emptiest
        slot (cheapest evacuation). Refuses to take the active set
        below min_replicas, and never parks a model's LAST serving
        replica (a registered pool must stay routable)."""
        ups = [r for r in self.rs.replicas
               if r.state == ReplicaState.UP]
        if len(ups) <= self.config.min_replicas:
            return None
        serving_by_model: Dict[str, int] = {}
        for r in self.rs.replicas:
            if r.accepts_admissions():
                serving_by_model[r.model] = \
                    serving_by_model.get(r.model, 0) + 1
        if len(serving_by_model) > 1:
            ups = [r for r in ups
                   if serving_by_model.get(r.model, 0) > 1]
            if not ups:
                return None
        if model is not None:
            ups = [r for r in ups if r.model == model] or ups
        def emptiest(reps: List) -> Optional[int]:
            best, best_load = None, None
            for rep in reps:
                info = rep.load_info()
                load = info["waiting"] + info["running"]
                if best_load is None or load < best_load:
                    best, best_load = rep.index, load
            return best
        for want in (role_pref, "mixed"):
            cand = [r for r in ups if r.role == want]
            # keep at least one slot of a dedicated role serving: a
            # disaggregated fleet with zero prefill (or zero decode)
            # capacity wedges that phase entirely
            if want != "mixed" and len(cand) <= 1:
                continue
            if cand:
                return emptiest(cand)
        return emptiest(ups)
