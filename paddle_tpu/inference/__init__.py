"""paddle.inference — the deployment API.

Reference: /root/reference/paddle/fluid/inference/api/analysis_predictor.cc
(AnalysisPredictor::Run, ZeroCopyTensor handles) + paddle_inference_api.h
(Config/create_predictor/Predictor), python surface
python/paddle/inference/__init__.py.

TPU-native: the serialized artifact is StableHLO (jax.export) produced by
paddle.jit.save or paddle.static.save_inference_model; "analysis passes"
collapse into XLA compilation at load time. The Config knobs that steer
CUDA/TensorRT/MKLDNN keep their API shape and record state (introspectable
via summary()) but the execution engine is always the XLA backend in this
build.
"""
from __future__ import annotations

import os
import pickle
from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["Config", "Predictor", "create_predictor", "Tensor",
           "PredictorPool", "get_version"]


def get_version() -> str:
    from .. import __version__
    return __version__


class Config:
    """reference: paddle_analysis_config.h AnalysisConfig."""

    def __init__(self, prog_file: str = None, params_file: str = None):
        # accept (model_dir) or (prog_file, params_file) like the reference
        self._model_dir = None
        self._prog_file = None
        self._params_file = None
        if prog_file is not None and params_file is None:
            if os.path.isdir(prog_file):
                self._model_dir = prog_file
            else:
                self._prog_file = prog_file
        else:
            self._prog_file = prog_file
            self._params_file = params_file
        self._use_gpu = False
        self._use_tpu = True
        self._device_id = 0
        self._ir_optim = True
        self._memory_optim = True
        self._cpu_math_threads = 1
        self._enable_profile = False
        self._glog_info = True
        self._llm_engine = False
        self._llm_model = None
        self._llm_options: Dict = {}

    # --------------------------------------------------------------- model
    def set_model(self, prog_file: str, params_file: str = None):
        if params_file is None:
            self._model_dir = prog_file
        else:
            self._prog_file = prog_file
            self._params_file = params_file

    def set_prog_file(self, path: str):
        self._prog_file = path

    def set_params_file(self, path: str):
        self._params_file = path

    def model_dir(self) -> Optional[str]:
        return self._model_dir

    def prog_file(self) -> Optional[str]:
        return self._prog_file

    def params_file(self) -> Optional[str]:
        return self._params_file

    def _artifact_prefix(self) -> str:
        if self._prog_file:
            return self._prog_file[:-len(".pdmodel")] \
                if self._prog_file.endswith(".pdmodel") else self._prog_file
        if self._model_dir:
            for name in sorted(os.listdir(self._model_dir)):
                if name.endswith(".pdmodel"):
                    return os.path.join(self._model_dir,
                                        name[:-len(".pdmodel")])
            raise ValueError(
                f"no .pdmodel artifact in {self._model_dir}")
        raise ValueError("Config: no model set")

    # -------------------------------------------------------------- device
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_gpu = True
        self._device_id = device_id

    def disable_gpu(self):
        self._use_gpu = False

    def use_gpu(self) -> bool:
        return self._use_gpu

    def enable_xpu(self, *a, **k):
        pass

    def gpu_device_id(self) -> int:
        return self._device_id

    # ------------------------------------------------------ engine options
    def switch_ir_optim(self, flag: bool = True):
        self._ir_optim = flag

    def ir_optim(self) -> bool:
        return self._ir_optim

    def enable_memory_optim(self, flag: bool = True):
        self._memory_optim = flag

    def set_cpu_math_library_num_threads(self, n: int):
        self._cpu_math_threads = n

    def enable_mkldnn(self):
        pass  # XLA:CPU owns codegen in this build

    def enable_tensorrt_engine(self, *a, **k):
        raise NotImplementedError(
            "TensorRT subgraph offload does not exist on the TPU backend; "
            "the whole model is one XLA computation already")

    def enable_profile(self):
        self._enable_profile = True

    def disable_glog_info(self):
        self._glog_info = False

    def enable_llm_engine(self, model=None, **options):
        """Route create_predictor to the continuous-batching LLM serving
        engine (inference/serving/) instead of the one-shot artifact
        Predictor — the dispatch mirror of enable_tensorrt_engine on the
        reference AnalysisConfig, for the engine that DOES exist here.

        model: a models.gpt.GPT-shaped Layer (live parameters; serving
        decodes through models.generation math, not a serialized
        artifact). options: EngineConfig fields (block_size, num_blocks,
        max_num_seqs, max_prefill_tokens) + default SamplingParams
        fields (max_tokens, temperature, top_k, top_p, eos_token_id,
        seed). See docs/serving.md."""
        self._llm_engine = True
        self._llm_model = model
        self._llm_options = dict(options)

    def llm_engine_enabled(self) -> bool:
        return self._llm_engine

    def summary(self) -> str:
        if self._llm_engine:
            prefix = "<llm serving engine>"
        else:
            prefix = self._artifact_prefix()
        lines = ["----- paddle_tpu inference config -----",
                 f"model prefix: {prefix}",
                 f"backend: {jax.default_backend()}",
                 f"ir_optim (XLA): {self._ir_optim}",
                 f"memory_optim: {self._memory_optim}",
                 f"profiling: {self._enable_profile}"]
        if self._llm_engine:
            lines.append(f"llm engine: {self._llm_options}")
        return "\n".join(lines)


class Tensor:
    """Zero-copy-style IO handle (reference: ZeroCopyTensor,
    paddle_tensor.h). copy_from_cpu stages the input; copy_to_cpu fetches
    the output after run()."""

    def __init__(self, name: str):
        self._name = name
        self._arr: Optional[jax.Array] = None

    def name(self) -> str:
        return self._name

    def reshape(self, shape):
        if self._arr is not None:
            self._arr = self._arr.reshape(shape)

    def copy_from_cpu(self, data: np.ndarray):
        self._arr = jnp.asarray(data)

    def copy_to_cpu(self) -> np.ndarray:
        if self._arr is None:
            raise RuntimeError("output not populated; call predictor.run()")
        return np.asarray(self._arr)

    def shape(self):
        return list(self._arr.shape) if self._arr is not None else []

    def type(self):
        return str(self._arr.dtype) if self._arr is not None else "unset"


class Predictor:
    """reference: AnalysisPredictor — load artifact, bind IO handles,
    run one compiled executable."""

    def __init__(self, config: Config):
        self._config = config
        prefix = config._artifact_prefix()
        with open(prefix + ".pdmodel", "rb") as f:
            self._exported = jax.export.deserialize(f.read())
        state = None
        meta: Dict = {}
        params_path = prefix + ".pdiparams"
        if os.path.exists(params_path):
            with open(params_path, "rb") as f:
                blob = pickle.load(f)
            if isinstance(blob, dict) and "feed_names" in blob:
                meta = blob          # static save_inference_model artifact
            else:
                state = jax.tree_util.tree_map(jnp.asarray, blob)
        self._state = state          # jit.save artifact closes over params
        n_state = len(jax.tree_util.tree_leaves(state)) if state else 0
        n_inputs = len(self._exported.in_avals) - n_state
        self._input_names = meta.get("feed_names") or [
            f"x{i}" for i in range(n_inputs)]
        self._output_names = meta.get("fetch_names") or None
        self._inputs = {n: Tensor(n) for n in self._input_names}
        self._outputs: Dict[str, Tensor] = {}

    # ------------------------------------------------------------------ io
    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> Tensor:
        return self._inputs[name]

    def get_output_names(self) -> List[str]:
        if self._output_names is None:
            return [f"out{i}" for i in range(len(self._outputs))] \
                if self._outputs else ["out0"]
        return list(self._output_names)

    def get_output_handle(self, name: str) -> Tensor:
        return self._outputs[name]

    # ----------------------------------------------------------------- run
    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """reference: AnalysisPredictor::ZeroCopyRun (handle style) and
        Run(inputs) (list style)."""
        if inputs is not None:
            for n, a in zip(self._input_names, inputs):
                self._inputs[n].copy_from_cpu(np.asarray(a))
        args = []
        for n in self._input_names:
            h = self._inputs[n]
            if h._arr is None:
                raise RuntimeError(f"input '{n}' not set; use "
                                   "get_input_handle(name).copy_from_cpu")
            args.append(h._arr)
        if self._state is not None:
            outs = self._exported.call(self._state, *args)
        else:
            outs = self._exported.call(*args)
        flat = jax.tree_util.tree_leaves(outs)
        names = self._output_names or [f"out{i}" for i in range(len(flat))]
        self._outputs = {}
        for n, a in zip(names, flat):
            t = Tensor(n)
            t._arr = a
            self._outputs[n] = t
        if inputs is not None:
            return [np.asarray(a) for a in flat]
        return None

    def clear_intermediate_tensor(self):
        pass

    def try_shrink_memory(self):
        pass


def create_predictor(config: Config):
    """reference: paddle_infer::CreatePredictor. Dispatches on config
    flags like AnalysisPredictor: enable_llm_engine() routes to the
    continuous-batching serving engine (inference/serving/), else the
    one-shot StableHLO artifact Predictor."""
    if config.llm_engine_enabled():
        from .serving import ServingPredictor
        return ServingPredictor(config)
    return Predictor(config)


class PredictorPool:
    """reference: paddle_infer::services::PredictorPool."""

    def __init__(self, config: Config, size: int = 1):
        self._preds = [Predictor(config) for _ in range(size)]

    def retrieve(self, idx: int) -> Predictor:
        return self._preds[idx]


def capi_so_path() -> str:
    """Path to the C predictor shared library (built on demand).
    Reference: inference/capi/pd_predictor.cc — PD_NewPredictor /
    PD_PredictorRun / PD_GetOutput; see tests/test_inference.py for the
    ctypes binding pattern (Go/Rust/C bind the same symbols)."""
    from ..native import capi_so_path as _p
    return _p()


class DataType:
    """reference inference/api/paddle_api.h PaddleDType enum surface."""
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6


class PlaceType:
    """reference paddle_api.h PaddlePlace."""
    UNK = -1
    CPU = 0
    GPU = 1
    XPU = 2
    TPU = 2  # the accelerator here


class PrecisionType:
    """reference paddle_analysis_config.h Precision."""
    Float32 = 0
    Int8 = 1
    Half = 2
    Bfloat16 = 3


_DTYPE_BYTES = {DataType.FLOAT32: 4, DataType.INT64: 8, DataType.INT32: 4,
                DataType.UINT8: 1, DataType.INT8: 1, DataType.FLOAT16: 2,
                DataType.BFLOAT16: 2}


def get_num_bytes_of_data_type(dtype):
    """reference pybind inference_api.cc get_num_bytes_of_data_type."""
    try:
        return _DTYPE_BYTES[dtype]
    except KeyError:
        raise ValueError(f"unknown inference DataType {dtype!r}")
