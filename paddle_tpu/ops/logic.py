"""Comparison / logical / bitwise ops.

TPU-native analogue of /root/reference/paddle/fluid/operators/controlflow/
compare_op.cc, logical_op.cc, and bitwise kernels; Python surface
python/paddle/tensor/logic.py.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.dispatch import op
from ..core.tensor import Tensor, to_tensor


def _wrap(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _cmp(name, fn):
    wrapped = op(name, differentiable=False)(fn)

    def api(x, y, name=None):
        return wrapped(_wrap(x), _wrap(y))
    api.__name__ = name
    return api


equal = _cmp("equal", lambda x, y: jnp.equal(x, y))
not_equal = _cmp("not_equal", lambda x, y: jnp.not_equal(x, y))
greater_than = _cmp("greater_than", lambda x, y: jnp.greater(x, y))
greater_equal = _cmp("greater_equal", lambda x, y: jnp.greater_equal(x, y))
less_than = _cmp("less_than", lambda x, y: jnp.less(x, y))
less_equal = _cmp("less_equal", lambda x, y: jnp.less_equal(x, y))
logical_and = _cmp("logical_and", lambda x, y: jnp.logical_and(x, y))
logical_or = _cmp("logical_or", lambda x, y: jnp.logical_or(x, y))
logical_xor = _cmp("logical_xor", lambda x, y: jnp.logical_xor(x, y))
bitwise_and = _cmp("bitwise_and", lambda x, y: jnp.bitwise_and(x, y))
bitwise_or = _cmp("bitwise_or", lambda x, y: jnp.bitwise_or(x, y))
bitwise_xor = _cmp("bitwise_xor", lambda x, y: jnp.bitwise_xor(x, y))


@op("logical_not", differentiable=False)
def _logical_not(x):
    return jnp.logical_not(x)


@op("bitwise_not", differentiable=False)
def _bitwise_not(x):
    return jnp.bitwise_not(x)


def logical_not(x, name=None):
    return _logical_not(_wrap(x))


def bitwise_not(x, name=None):
    return _bitwise_not(_wrap(x))


def equal_all(x, y, name=None):
    x, y = _wrap(x), _wrap(y)
    if tuple(x.shape) != tuple(y.shape):
        return Tensor(jnp.asarray(False))
    return Tensor(jnp.array_equal(x._value, y._value))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    x, y = _wrap(x), _wrap(y)
    return Tensor(jnp.allclose(x._value, y._value, rtol=rtol, atol=atol,
                               equal_nan=equal_nan))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    x, y = _wrap(x), _wrap(y)
    return Tensor(jnp.isclose(x._value, y._value, rtol=rtol, atol=atol,
                              equal_nan=equal_nan))


def is_empty(x, name=None):
    return Tensor(jnp.asarray(int(np.prod(x.shape)) == 0))


def is_tensor(x):
    return isinstance(x, Tensor)
