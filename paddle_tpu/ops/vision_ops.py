"""Vision/detection ops.

Reference: operators/detection/ (~18k LoC: prior_box_op, box_coder_op,
yolo_box_op, multiclass_nms_op, matrix_nms_op, bipartite_match_op,
iou_similarity_op, roi_align/roi_pool ops), affine_grid_op, grid_sampler_op,
temporal_shift_op, pixel_shuffle/unshuffle, fold/unfold, shuffle_channel_op.

TPU-native split: dense, fixed-shape ops (roi_align, grid_sample,
affine_grid, prior_box, box_coder, yolo_box, iou, temporal_shift, fold,
pixel_unshuffle, shuffle_channel) are pure jnp and jit/shard cleanly; NMS
variants have data-dependent output sizes and run on host eagerly — exactly
the reference's split (its NMS kernels are CPU too,
multiclass_nms_op.cc uses no CUDA kernel).
"""
from __future__ import annotations

from typing import List

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import op
from ..core.tensor import Tensor, to_tensor

__all__ = ["roi_align", "roi_pool", "grid_sample", "affine_grid",
           "prior_box", "box_coder", "yolo_box", "box_iou",
           "multiclass_nms", "matrix_nms", "nms", "bipartite_match",
           "temporal_shift", "pixel_unshuffle", "fold", "shuffle_channel",
           "channel_shuffle"]


def _wrap(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


# ---------------------------------------------------------------- roi ops
@op("roi_align")
def _roi_align(x, boxes, boxes_num, out_h, out_w, spatial_scale,
               sampling_ratio, aligned):
    """reference: roi_align_op.cc — bilinear-sampled average per bin."""
    N, C, H, W = x.shape
    R = boxes.shape[0]
    offset = 0.5 if aligned else 0.0
    b = boxes * spatial_scale - offset
    x0, y0, x1, y1 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    rw = jnp.maximum(x1 - x0, 1e-6 if aligned else 1.0)
    rh = jnp.maximum(y1 - y0, 1e-6 if aligned else 1.0)
    bin_h = rh / out_h
    bin_w = rw / out_w
    s = sampling_ratio if sampling_ratio > 0 else 2
    # sample grid: [R, out_h*s, out_w*s]
    iy = (jnp.arange(out_h * s) + 0.5) / s
    ix = (jnp.arange(out_w * s) + 0.5) / s
    ys = y0[:, None] + bin_h[:, None] * iy[None, :]
    xs = x0[:, None] + bin_w[:, None] * ix[None, :]

    # boxes_num: rois per image, cumulative mapping
    img_of_roi = jnp.searchsorted(jnp.cumsum(boxes_num), jnp.arange(R),
                                  side="right")

    def bilinear(img, yy, xx):
        y0i = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, H - 1)
        x0i = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, W - 1)
        y1i = jnp.clip(y0i + 1, 0, H - 1)
        x1i = jnp.clip(x0i + 1, 0, W - 1)
        ly = jnp.clip(yy - y0i, 0.0, 1.0)
        lx = jnp.clip(xx - x0i, 0.0, 1.0)
        v00 = img[:, y0i, x0i]
        v01 = img[:, y0i, x1i]
        v10 = img[:, y1i, x0i]
        v11 = img[:, y1i, x1i]
        return (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx
                + v10 * ly * (1 - lx) + v11 * ly * lx)

    def per_roi(r):
        img = x[img_of_roi[r]]
        yy, xx = jnp.meshgrid(ys[r], xs[r], indexing="ij")
        samp = bilinear(img, yy, xx)          # [C, out_h*s, out_w*s]
        samp = samp.reshape(C, out_h, s, out_w, s)
        return samp.mean(axis=(2, 4))

    return jax.vmap(per_roi)(jnp.arange(R))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    return _roi_align(_wrap(x), _wrap(boxes), _wrap(boxes_num),
                      int(output_size[0]), int(output_size[1]),
                      float(spatial_scale), int(sampling_ratio),
                      bool(aligned))


@op("roi_pool")
def _roi_pool(x, boxes, boxes_num, out_h, out_w, spatial_scale):
    """reference: roi_pool_op.cc — max pool per quantized bin (approximated
    on a fixed sample grid for static shapes)."""
    N, C, H, W = x.shape
    R = boxes.shape[0]
    b = jnp.round(boxes * spatial_scale)
    img_of_roi = jnp.searchsorted(jnp.cumsum(boxes_num), jnp.arange(R),
                                  side="right")
    s = 4  # samples per bin edge

    def per_roi(r):
        x0, y0, x1, y1 = b[r, 0], b[r, 1], b[r, 2], b[r, 3]
        rh = jnp.maximum(y1 - y0 + 1, 1.0)
        rw = jnp.maximum(x1 - x0 + 1, 1.0)
        iy = y0 + (jnp.arange(out_h * s) + 0.5) * rh / (out_h * s)
        ix = x0 + (jnp.arange(out_w * s) + 0.5) * rw / (out_w * s)
        yi = jnp.clip(iy.astype(jnp.int32), 0, H - 1)
        xi = jnp.clip(ix.astype(jnp.int32), 0, W - 1)
        img = x[img_of_roi[r]]
        samp = img[:, yi[:, None], xi[None, :]]
        samp = samp.reshape(C, out_h, s, out_w, s)
        return samp.max(axis=(2, 4))

    return jax.vmap(per_roi)(jnp.arange(R))


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    return _roi_pool(_wrap(x), _wrap(boxes), _wrap(boxes_num),
                     int(output_size[0]), int(output_size[1]),
                     float(spatial_scale))


# ------------------------------------------------------------ grid sample
@op("grid_sampler")
def _grid_sample(x, grid, mode, padding_mode, align_corners):
    """reference: grid_sampler_op.cc (NCHW, grid in [-1, 1])."""
    N, C, H, W = x.shape
    gx, gy = grid[..., 0], grid[..., 1]
    if align_corners:
        fx = (gx + 1) * (W - 1) / 2
        fy = (gy + 1) * (H - 1) / 2
    else:
        fx = ((gx + 1) * W - 1) / 2
        fy = ((gy + 1) * H - 1) / 2

    def sample_one(img, fy_, fx_):
        if mode == "nearest":
            yi = jnp.clip(jnp.round(fy_).astype(jnp.int32), 0, H - 1)
            xi = jnp.clip(jnp.round(fx_).astype(jnp.int32), 0, W - 1)
            out = img[:, yi, xi]
            if padding_mode == "zeros":
                valid = ((fy_ >= -0.5) & (fy_ <= H - 0.5)
                         & (fx_ >= -0.5) & (fx_ <= W - 0.5))
                out = out * valid[None].astype(img.dtype)
            return out
        y0 = jnp.floor(fy_)
        x0 = jnp.floor(fx_)
        ly, lx = fy_ - y0, fx_ - x0
        vals = 0
        for dy, wy in ((0, 1 - ly), (1, ly)):
            for dx, wx in ((0, 1 - lx), (1, lx)):
                yi = (y0 + dy).astype(jnp.int32)
                xi = (x0 + dx).astype(jnp.int32)
                yc = jnp.clip(yi, 0, H - 1)
                xc = jnp.clip(xi, 0, W - 1)
                v = img[:, yc, xc]
                if padding_mode == "zeros":
                    inside = ((yi >= 0) & (yi < H) & (xi >= 0) & (xi < W))
                    v = v * inside[None].astype(img.dtype)
                vals = vals + v * (wy * wx)[None].astype(img.dtype)
        return vals

    return jax.vmap(sample_one)(x, fy, fx)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    return _grid_sample(_wrap(x), _wrap(grid), mode, padding_mode,
                        bool(align_corners))


@op("affine_grid")
def _affine_grid(theta, n, h, w, align_corners):
    """reference: affine_grid_op.cc — sampling grid from 2x3 affine."""
    if align_corners:
        ys = jnp.linspace(-1, 1, h)
        xs = jnp.linspace(-1, 1, w)
    else:
        ys = (jnp.arange(h) * 2 + 1) / h - 1
        xs = (jnp.arange(w) * 2 + 1) / w - 1
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1).reshape(-1, 3)  # [h*w, 3]
    out = jnp.einsum("hk,nck->nhc", base, theta)              # [n, h*w, 2]
    return out.reshape(n, h, w, 2).astype(theta.dtype)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    shp = [int(s) for s in (out_shape.tolist()
                            if isinstance(out_shape, Tensor) else out_shape)]
    n, _, h, w = shp
    return _affine_grid(_wrap(theta), n, h, w, bool(align_corners))


# -------------------------------------------------------------- box ops
@op("prior_box", differentiable=False)
def _prior_box(feat_h, feat_w, img_h, img_w, min_sizes, max_sizes,
               aspect_ratios, variances, flip, clip, step_w, step_h,
               offset, min_max_aspect_ratios_order, dtype):
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    boxes_per_cell = []
    for ms in min_sizes:
        for ar in ars:
            boxes_per_cell.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        if max_sizes:
            for mx in max_sizes:
                s = np.sqrt(ms * mx)
                boxes_per_cell.append((s, s))
    sw = step_w or img_w / feat_w
    sh = step_h or img_h / feat_h
    cx = (jnp.arange(feat_w) + offset) * sw
    cy = (jnp.arange(feat_h) + offset) * sh
    gx, gy = jnp.meshgrid(cx, cy, indexing="xy")
    outs = []
    for bw, bh in boxes_per_cell:
        box = jnp.stack([(gy * 0 + gx - bw / 2) / img_w,
                         (gy - bh / 2) / img_h,
                         (gx + bw / 2) / img_w,
                         (gy + bh / 2) / img_h], axis=-1)
        outs.append(box)
    out = jnp.stack(outs, axis=2)  # [H, W, nboxes, 4]
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, dtype), out.shape)
    return out.astype(dtype), var


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """reference: detection/prior_box_op.cc (SSD anchors)."""
    x, im = _wrap(input), _wrap(image)
    return _prior_box(x._value.shape[2], x._value.shape[3],
                      im._value.shape[2], im._value.shape[3],
                      [float(s) for s in min_sizes],
                      [float(s) for s in (max_sizes or [])],
                      tuple(aspect_ratios), tuple(variance), bool(flip),
                      bool(clip), float(steps[0]), float(steps[1]),
                      float(offset), bool(min_max_aspect_ratios_order),
                      "float32")


@op("box_coder")
def _box_coder(prior, prior_var, target, code_type, normalized):
    """reference: detection/box_coder_op.cc (encode/decode_center_size)."""
    pw = prior[:, 2] - prior[:, 0] + (0.0 if normalized else 1.0)
    ph = prior[:, 3] - prior[:, 1] + (0.0 if normalized else 1.0)
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    if code_type == "encode_center_size":
        tw = target[:, 2] - target[:, 0] + (0.0 if normalized else 1.0)
        th = target[:, 3] - target[:, 1] + (0.0 if normalized else 1.0)
        tcx = target[:, 0] + tw * 0.5
        tcy = target[:, 1] + th * 0.5
        out = jnp.stack([(tcx - pcx) / pw, (tcy - pcy) / ph,
                         jnp.log(tw / pw), jnp.log(th / ph)], axis=1)
        return out / prior_var if prior_var is not None else out
    # decode
    t = target * prior_var if prior_var is not None else target
    ocx = t[..., 0] * pw + pcx
    ocy = t[..., 1] * ph + pcy
    ow = jnp.exp(t[..., 2]) * pw
    oh = jnp.exp(t[..., 3]) * ph
    return jnp.stack([ocx - ow / 2, ocy - oh / 2,
                      ocx + ow / 2 - (0.0 if normalized else 1.0),
                      ocy + oh / 2 - (0.0 if normalized else 1.0)], axis=-1)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    pv = None
    if prior_box_var is not None:
        pv = _wrap(prior_box_var)
    return _box_coder(_wrap(prior_box), pv, _wrap(target_box),
                      code_type.lower(), bool(box_normalized))


@op("yolo_box")
def _yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample,
              clip_bbox, scale_x_y):
    """reference: detection/yolo_box_op.cc."""
    N, C, H, W = x.shape
    na = len(anchors) // 2
    x = x.reshape(N, na, 5 + class_num, H, W)
    gx, gy = jnp.meshgrid(jnp.arange(W), jnp.arange(H), indexing="xy")
    bias = (scale_x_y - 1) / 2
    sx = jax.nn.sigmoid(x[:, :, 0]) * scale_x_y - bias
    sy = jax.nn.sigmoid(x[:, :, 1]) * scale_x_y - bias
    cx = (gx[None, None] + sx) / W
    cy = (gy[None, None] + sy) / H
    aw = jnp.asarray(anchors[0::2], x.dtype).reshape(1, na, 1, 1)
    ah = jnp.asarray(anchors[1::2], x.dtype).reshape(1, na, 1, 1)
    bw = jnp.exp(x[:, :, 2]) * aw / (downsample * W)
    bh = jnp.exp(x[:, :, 3]) * ah / (downsample * H)
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    imh = img_size[:, 0].reshape(N, 1, 1, 1).astype(x.dtype)
    imw = img_size[:, 1].reshape(N, 1, 1, 1).astype(x.dtype)
    x0 = (cx - bw / 2) * imw
    y0 = (cy - bh / 2) * imh
    x1 = (cx + bw / 2) * imw
    y1 = (cy + bh / 2) * imh
    boxes = jnp.stack([x0, y0, x1, y1], axis=-1)
    if clip_bbox:
        boxes = jnp.stack([jnp.clip(x0, 0, imw - 1),
                           jnp.clip(y0, 0, imh - 1),
                           jnp.clip(x1, 0, imw - 1),
                           jnp.clip(y1, 0, imh - 1)], axis=-1)
    mask = (conf > conf_thresh).astype(x.dtype)
    boxes = boxes * mask[..., None]
    boxes = boxes.reshape(N, na * H * W, 4)
    scores = (probs * mask[:, :, None]).transpose(0, 1, 3, 4, 2).reshape(
        N, na * H * W, class_num)
    return boxes, scores


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0, name=None):
    return _yolo_box(_wrap(x), _wrap(img_size), list(anchors),
                     int(class_num), float(conf_thresh),
                     int(downsample_ratio), bool(clip_bbox), float(scale_x_y))


@op("iou_similarity")
def _box_iou(a, b):
    """reference: detection/iou_similarity_op.cc — pairwise IoU."""
    area_a = jnp.maximum(a[:, 2] - a[:, 0], 0) * \
        jnp.maximum(a[:, 3] - a[:, 1], 0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0], 0) * \
        jnp.maximum(b[:, 3] - b[:, 1], 0)
    x0 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    y0 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    x1 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    y1 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    inter = jnp.maximum(x1 - x0, 0) * jnp.maximum(y1 - y0, 0)
    return inter / jnp.maximum(area_a[:, None] + area_b[None, :] - inter,
                               1e-10)


def box_iou(boxes1, boxes2, name=None):
    return _box_iou(_wrap(boxes1), _wrap(boxes2))


iou_similarity = box_iou


# ------------------------------------------------------------------- NMS
def _nms_host(boxes, scores, threshold):
    order = np.argsort(-scores)
    keep = []
    sup = np.zeros(len(boxes), bool)
    for i in order:
        if sup[i]:
            continue
        keep.append(i)
        xx0 = np.maximum(boxes[i, 0], boxes[:, 0])
        yy0 = np.maximum(boxes[i, 1], boxes[:, 1])
        xx1 = np.minimum(boxes[i, 2], boxes[:, 2])
        yy1 = np.minimum(boxes[i, 3], boxes[:, 3])
        inter = np.maximum(xx1 - xx0, 0) * np.maximum(yy1 - yy0, 0)
        a = np.maximum(boxes[:, 2] - boxes[:, 0], 0) * \
            np.maximum(boxes[:, 3] - boxes[:, 1], 0)
        iou = inter / np.maximum(a[i] + a - inter, 1e-10)
        sup |= iou > threshold
        sup[i] = True
    return np.asarray(keep, np.int64)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """reference: detection NMS family — kept-index form (host eager, like
    the reference CPU kernel; data-dependent output size)."""
    b = np.asarray(_wrap(boxes)._value)
    s = np.asarray(_wrap(scores)._value) if scores is not None \
        else np.arange(len(b), 0, -1, dtype=np.float32)
    if category_idxs is not None:
        cats = np.asarray(_wrap(category_idxs)._value)
        keep_all = []
        for c in (categories if categories is not None
                  else np.unique(cats)):
            idx = np.nonzero(cats == c)[0]
            if idx.size == 0:
                continue
            kept = _nms_host(b[idx], s[idx], iou_threshold)
            keep_all.append(idx[kept])
        keep = np.concatenate(keep_all) if keep_all else np.zeros(0, np.int64)
        keep = keep[np.argsort(-s[keep])]
    else:
        keep = _nms_host(b, s, iou_threshold)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=1000,
                   keep_top_k=100, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, return_index=False,
                   rois_num=None, name=None):
    """reference: detection/multiclass_nms_op.cc (host; returns
    [M, 6] = label, score, x0, y0, x1, y1)."""
    b = np.asarray(_wrap(bboxes)._value)   # [N, M, 4]
    s = np.asarray(_wrap(scores)._value)   # [N, C, M]
    outs, idxs, nums = [], [], []
    for n in range(b.shape[0]):
        dets = []
        for c in range(s.shape[1]):
            if c == background_label:
                continue
            sc = s[n, c]
            m = sc > score_threshold
            if not m.any():
                continue
            cand = np.nonzero(m)[0]
            cand = cand[np.argsort(-sc[cand])][:nms_top_k]
            kept = _nms_host(b[n, cand], sc[cand], nms_threshold)
            for k in cand[kept]:
                dets.append([c, sc[k], *b[n, k]])
        dets = np.asarray(dets, np.float32) if dets else \
            np.zeros((0, 6), np.float32)
        if len(dets) > keep_top_k:
            dets = dets[np.argsort(-dets[:, 1])][:keep_top_k]
        outs.append(dets)
        nums.append(len(dets))
    out = np.concatenate(outs) if outs else np.zeros((0, 6), np.float32)
    res = Tensor(jnp.asarray(out))
    nums_t = Tensor(jnp.asarray(np.asarray(nums, np.int32)))
    if return_index:
        return res, Tensor(jnp.asarray(np.zeros((len(out), 1), np.int64))), \
            nums_t
    return res, nums_t


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """reference: detection/matrix_nms_op.cc — soft suppression by decayed
    scores (host)."""
    b = np.asarray(_wrap(bboxes)._value)
    s = np.asarray(_wrap(scores)._value)
    outs, nums = [], []
    for n in range(b.shape[0]):
        dets = []
        for c in range(s.shape[1]):
            if c == background_label:
                continue
            sc = s[n, c].copy()
            m = sc > score_threshold
            if not m.any():
                continue
            cand = np.nonzero(m)[0]
            cand = cand[np.argsort(-sc[cand])][:nms_top_k]
            bb = b[n, cand]
            ss = sc[cand]
            # pairwise IoU of sorted candidates
            x0 = np.maximum(bb[:, None, 0], bb[None, :, 0])
            y0 = np.maximum(bb[:, None, 1], bb[None, :, 1])
            x1 = np.minimum(bb[:, None, 2], bb[None, :, 2])
            y1 = np.minimum(bb[:, None, 3], bb[None, :, 3])
            inter = np.maximum(x1 - x0, 0) * np.maximum(y1 - y0, 0)
            ar = np.maximum(bb[:, 2] - bb[:, 0], 0) * \
                np.maximum(bb[:, 3] - bb[:, 1], 0)
            iou = inter / np.maximum(ar[:, None] + ar[None, :] - inter,
                                     1e-10)
            iou = np.triu(iou, 1)
            comp = iou.max(axis=0)  # max IoU with any higher-scored box
            if use_gaussian:
                decay = np.exp(-(iou ** 2 - comp[None, :] ** 2)
                               / gaussian_sigma).min(axis=0)
            else:
                decay = ((1 - iou) / np.maximum(1 - comp[None, :], 1e-10)
                         ).min(axis=0)
            ss = ss * decay
            keep = ss > post_threshold
            for k in range(len(cand)):
                if keep[k]:
                    dets.append([c, ss[k], *bb[k]])
        dets = np.asarray(dets, np.float32) if dets else \
            np.zeros((0, 6), np.float32)
        if len(dets) > keep_top_k:
            dets = dets[np.argsort(-dets[:, 1])][:keep_top_k]
        outs.append(dets)
        nums.append(len(dets))
    out = np.concatenate(outs) if outs else np.zeros((0, 6), np.float32)
    ret = [Tensor(jnp.asarray(out))]
    if return_index:
        ret.append(Tensor(jnp.asarray(np.zeros((len(out), 1), np.int64))))
    if return_rois_num:
        ret.append(Tensor(jnp.asarray(np.asarray(nums, np.int32))))
    return tuple(ret) if len(ret) > 1 else ret[0]


def bipartite_match(dist_matrix, match_type="bipartite", dist_threshold=0.5,
                    name=None):
    """reference: detection/bipartite_match_op.cc — greedy max matching
    (host)."""
    d = np.asarray(_wrap(dist_matrix)._value).copy()
    rows, cols = d.shape
    match_idx = np.full(cols, -1, np.int64)
    match_dist = np.zeros(cols, np.float32)
    used_r = np.zeros(rows, bool)
    used_c = np.zeros(cols, bool)
    while True:
        masked = np.where(used_r[:, None] | used_c[None, :], -np.inf, d)
        r, c = np.unravel_index(np.argmax(masked), d.shape)
        if not np.isfinite(masked[r, c]) or masked[r, c] <= 0:
            break
        match_idx[c] = r
        match_dist[c] = d[r, c]
        used_r[r] = True
        used_c[c] = True
    if match_type == "per_prediction":
        for c in range(cols):
            if match_idx[c] == -1:
                r = int(np.argmax(d[:, c]))
                if d[r, c] >= dist_threshold:
                    match_idx[c] = r
                    match_dist[c] = d[r, c]
    return Tensor(jnp.asarray(match_idx[None])), \
        Tensor(jnp.asarray(match_dist[None]))


# -------------------------------------------------------- layout/shift ops
@op("temporal_shift")
def _temporal_shift(x, seg_num, shift_ratio):
    """reference: temporal_shift_op.cc — shift channels across time."""
    NT, C, H, W = x.shape
    N = NT // seg_num
    v = x.reshape(N, seg_num, C, H, W)
    c1 = int(C * shift_ratio)
    c2 = int(C * 2 * shift_ratio)
    fwd = jnp.concatenate([v[:, 1:, :c1], jnp.zeros_like(v[:, :1, :c1])], 1)
    bwd = jnp.concatenate([jnp.zeros_like(v[:, :1, c1:c2]),
                           v[:, :-1, c1:c2]], 1)
    keep = v[:, :, c2:]
    return jnp.concatenate([fwd, bwd, keep], axis=2).reshape(NT, C, H, W)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    t = _wrap(x)
    if data_format == "NHWC":
        t = Tensor(jnp.transpose(t._value, (0, 3, 1, 2)))
        out = _temporal_shift(t, int(seg_num), float(shift_ratio))
        return Tensor(jnp.transpose(out._value, (0, 2, 3, 1)))
    return _temporal_shift(t, int(seg_num), float(shift_ratio))


@op("pixel_unshuffle")
def _pixel_unshuffle(x, factor):
    """reference: pixel_unshuffle (inverse of pixel_shuffle_op.cc)."""
    N, C, H, W = x.shape
    r = factor
    v = x.reshape(N, C, H // r, r, W // r, r)
    return v.transpose(0, 1, 3, 5, 2, 4).reshape(
        N, C * r * r, H // r, W // r)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    return _pixel_unshuffle(_wrap(x), int(downscale_factor))


@op("fold")
def _fold(x, out_h, out_w, kh, kw, sh, sw, ph, pw, dh, dw):
    """reference: fold_op.cc (col2im) — inverse of unfold: scatter-add
    patches back into the image."""
    N, CKK, L = x.shape
    C = CKK // (kh * kw)
    nh = (out_h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    nw = (out_w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    v = x.reshape(N, C, kh, kw, nh, nw)
    out = jnp.zeros((N, C, out_h + 2 * ph, out_w + 2 * pw), x.dtype)
    for i in range(kh):
        for j in range(kw):
            out = out.at[:, :, i * dh:i * dh + nh * sh:sh,
                         j * dw:j * dw + nw * sw:sw].add(v[:, :, i, j])
    return out[:, :, ph:ph + out_h, pw:pw + out_w]


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    def pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    oh, ow = pair(output_sizes)
    kh, kw = pair(kernel_sizes)
    sh, sw = pair(strides)
    ph, pw = pair(paddings) if not (isinstance(paddings, (list, tuple))
                                    and len(paddings) == 4) else \
        (paddings[0], paddings[1])
    dh, dw = pair(dilations)
    return _fold(_wrap(x), oh, ow, kh, kw, sh, sw, ph, pw, dh, dw)


@op("shuffle_channel")
def _shuffle_channel(x, group):
    """reference: shuffle_channel_op.cc."""
    N, C, H, W = x.shape
    return x.reshape(N, group, C // group, H, W).transpose(
        0, 2, 1, 3, 4).reshape(N, C, H, W)


def shuffle_channel(x, group, name=None):
    return _shuffle_channel(_wrap(x), int(group))


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    t = _wrap(x)
    if data_format == "NHWC":
        t = Tensor(jnp.transpose(t._value, (0, 3, 1, 2)))
        out = _shuffle_channel(t, int(groups))
        return Tensor(jnp.transpose(out._value, (0, 2, 3, 1)))
    return _shuffle_channel(t, int(groups))


# ---------------------------------------------------------------------------
# round-3 vision tail

@op("psroi_pool")
def _psroi_pool(x, rois, roi_batch_id, out_c, out_h, out_w, spatial_scale):
    """reference: psroi_pool_op.cc — position-sensitive RoI average pool:
    bin (ph, pw) reads channel group ph*out_w+pw."""
    N, C, H, W = x.shape
    R = rois.shape[0]

    def per_roi(r):
        box = rois[r] * spatial_scale
        x1, y1, x2, y2 = box[0], box[1], box[2], box[3]
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_h = rh / out_h
        bin_w = rw / out_w
        img = x[roi_batch_id[r]]
        ys = jnp.arange(H, dtype=jnp.float32)
        xs = jnp.arange(W, dtype=jnp.float32)
        out = jnp.zeros((out_c, out_h, out_w), x.dtype)
        for ph in range(out_h):
            for pw in range(out_w):
                hstart = y1 + ph * bin_h
                hend = y1 + (ph + 1) * bin_h
                wstart = x1 + pw * bin_w
                wend = x1 + (pw + 1) * bin_w
                m = ((ys[:, None] >= jnp.floor(hstart))
                     & (ys[:, None] < jnp.ceil(hend))
                     & (xs[None, :] >= jnp.floor(wstart))
                     & (xs[None, :] < jnp.ceil(wend)))
                cnt = jnp.maximum(jnp.sum(m), 1.0)
                grp = img[(ph * out_w + pw) * out_c:(ph * out_w + pw + 1)
                          * out_c]
                out = out.at[:, ph, pw].set(
                    jnp.sum(jnp.where(m[None], grp, 0.0), axis=(1, 2))
                    / cnt)
        return out

    return jax.vmap(per_roi)(jnp.arange(R))


def psroi_pool(x, boxes, boxes_num, output_channels, spatial_scale,
               pooled_height, pooled_width, name=None):
    """reference: operators/psroi_pool_op.cc."""
    xt, bt = _wrap(x), _wrap(boxes)
    num = _wrap(boxes_num)
    rid = jnp.asarray(np.repeat(np.arange(num.shape[0]),
                                np.asarray(num.numpy())))
    return _psroi_pool(xt, bt, Tensor(rid), int(output_channels),
                       int(pooled_height), int(pooled_width),
                       float(spatial_scale))


@op("prroi_pool")
def _prroi_pool(x, rois, roi_batch_id, out_h, out_w, spatial_scale):
    """reference: prroi_pool_op.cc — Precise RoI pooling: exact integral of
    the bilinear surface over each bin (here a dense 4x supersampled
    midpoint quadrature of that integral — differentiable wrt both input
    and roi coords like the reference)."""
    N, C, H, W = x.shape
    R = rois.shape[0]
    S = 4

    def bilinear(img, yy, xx):
        y0 = jnp.floor(yy)
        x0 = jnp.floor(xx)
        y0i = jnp.clip(y0.astype(jnp.int32), 0, H - 1)
        x0i = jnp.clip(x0.astype(jnp.int32), 0, W - 1)
        y1i = jnp.clip(y0i + 1, 0, H - 1)
        x1i = jnp.clip(x0i + 1, 0, W - 1)
        ly = jnp.clip(yy - y0, 0.0, 1.0)
        lx = jnp.clip(xx - x0, 0.0, 1.0)
        return (img[:, y0i, x0i] * (1 - ly) * (1 - lx)
                + img[:, y0i, x1i] * (1 - ly) * lx
                + img[:, y1i, x0i] * ly * (1 - lx)
                + img[:, y1i, x1i] * ly * lx)

    def per_roi(r):
        box = rois[r] * spatial_scale
        x1, y1, x2, y2 = box[0], box[1], box[2], box[3]
        rw = jnp.maximum(x2 - x1, 1e-6)
        rh = jnp.maximum(y2 - y1, 1e-6)
        iy = (jnp.arange(out_h * S) + 0.5) / S
        ix = (jnp.arange(out_w * S) + 0.5) / S
        ys = y1 + rh / out_h * iy
        xs = x1 + rw / out_w * ix
        yy, xx = jnp.meshgrid(ys, xs, indexing="ij")
        samp = bilinear(x[roi_batch_id[r]], yy, xx)
        return samp.reshape(C, out_h, S, out_w, S).mean(axis=(2, 4))

    return jax.vmap(per_roi)(jnp.arange(R))


def prroi_pool(x, boxes, boxes_num, pooled_height, pooled_width,
               spatial_scale=1.0, name=None):
    xt, bt = _wrap(x), _wrap(boxes)
    num = _wrap(boxes_num)
    rid = jnp.asarray(np.repeat(np.arange(num.shape[0]),
                                np.asarray(num.numpy())))
    return _prroi_pool(xt, bt, Tensor(rid), int(pooled_height),
                       int(pooled_width), float(spatial_scale))


@op("deformable_conv")
def _deformable_conv(x, offset, mask, weight, stride, padding, dilation,
                     groups, deformable_groups):
    """reference: deformable_conv_op.cc (v2, modulated) / deformable_conv
    _v1: for each kernel tap k and output site p, sample the input at
    p*stride - pad + k*dilation + offset_k(p) bilinearly, scale by the
    modulation mask, then contract taps x channels with the weight — the
    im2col-free TPU formulation (gathers + one einsum on the MXU)."""
    N, C, H, W = x.shape
    out_c, in_c_per_g, kh, kw = weight.shape
    _, _, out_h, out_w = offset.shape  # offset [N, 2*dg*kh*kw, oh, ow]
    dg = deformable_groups
    off = offset.reshape(N, dg, kh * kw, 2, out_h, out_w)
    msk = (jnp.ones((N, dg, kh * kw, out_h, out_w), x.dtype)
           if mask is None else mask.reshape(N, dg, kh * kw, out_h, out_w))
    base_y = (jnp.arange(out_h) * stride[0] - padding[0])[:, None]
    base_x = (jnp.arange(out_w) * stride[1] - padding[1])[None, :]
    cpg = C // dg

    def sample(img, yy, xx):
        # img [C', H, W]; yy/xx [oh, ow] float. Out-of-bounds corners
        # contribute zero (per-corner masking, matching the reference's
        # DmcnIm2colBilinear boundary handling).
        y0 = jnp.floor(yy)
        x0 = jnp.floor(xx)
        ly = yy - y0
        lx = xx - x0
        acc = 0.0
        for dy, wy in ((0, (1 - ly)), (1, ly)):
            for dx, wx in ((0, (1 - lx)), (1, lx)):
                yi = y0.astype(jnp.int32) + dy
                xi = x0.astype(jnp.int32) + dx
                ok = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
                yc = jnp.clip(yi, 0, H - 1)
                xc = jnp.clip(xi, 0, W - 1)
                acc = acc + jnp.where(ok[None], img[:, yc, xc], 0.0) \
                    * (wy * wx)[None]
        return acc

    def per_image(n):
        cols = []
        for g in range(dg):
            img = x[n, g * cpg:(g + 1) * cpg]
            taps = []
            for k in range(kh * kw):
                ky, kx = divmod(k, kw)
                yy = base_y + ky * dilation[0] + off[n, g, k, 0]
                xx = base_x + kx * dilation[1] + off[n, g, k, 1]
                taps.append(sample(img, yy, xx) * msk[n, g, k][None])
            cols.append(jnp.stack(taps, axis=1))  # [C', K, oh, ow]
        return jnp.concatenate(cols, axis=0)      # [C, K, oh, ow]

    col = jax.vmap(per_image)(jnp.arange(N))      # [N, C, K, oh, ow]
    wg = weight.reshape(groups, out_c // groups, in_c_per_g, kh * kw)
    colg = col.reshape(N, groups, in_c_per_g, kh * kw, out_h, out_w)
    out = jnp.einsum("goik,ngikhw->ngohw", wg, colg)
    return out.reshape(N, out_c, out_h, out_w)


def deformable_conv(x, offset, weight, mask=None, bias=None, stride=1,
                    padding=0, dilation=1, deformable_groups=1, groups=1,
                    name=None):
    """reference: operators/deformable_conv_op.cc (+ _v1 when mask=None)."""
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    d = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    out = _deformable_conv(_wrap(x), _wrap(offset),
                           None if mask is None else _wrap(mask),
                           _wrap(weight), s, p, d, int(groups),
                           int(deformable_groups))
    if bias is not None:
        out = Tensor(_wrap(out)._value
                     + _wrap(bias)._value.reshape(1, -1, 1, 1))
    return out


@op("deformable_psroi_pooling")
def _deform_psroi(x, rois, trans, roi_batch_id, out_c, out_h, out_w,
                  spatial_scale, trans_std):
    """Per-BIN deformation: bin (ph, pw) is shifted by its own normalized
    offset trans[r, :, part_y, part_x] * trans_std * roi_size
    (deformable_psroi_pooling_op.cu DeformablePSROIPoolForwardKernel)."""
    N, C, H, W = x.shape
    R = rois.shape[0]
    part_h, part_w = trans.shape[2], trans.shape[3]

    def per_roi(r):
        box = rois[r] * spatial_scale
        x1, y1 = box[0], box[1]
        rw = jnp.maximum(box[2] - box[0], 0.1)
        rh = jnp.maximum(box[3] - box[1], 0.1)
        bin_h = rh / out_h
        bin_w = rw / out_w
        img = x[roi_batch_id[r]]
        ys = jnp.arange(H, dtype=jnp.float32)
        xs = jnp.arange(W, dtype=jnp.float32)
        out = jnp.zeros((out_c, out_h, out_w), x.dtype)
        for ph in range(out_h):
            for pw in range(out_w):
                py = min(ph * part_h // out_h, part_h - 1)
                px = min(pw * part_w // out_w, part_w - 1)
                dy = trans[r, 0, py, px] * trans_std * rh
                dx = trans[r, 1, py, px] * trans_std * rw
                hstart = y1 + ph * bin_h + dy
                hend = hstart + bin_h
                wstart = x1 + pw * bin_w + dx
                wend = wstart + bin_w
                m = ((ys[:, None] >= jnp.floor(hstart))
                     & (ys[:, None] < jnp.ceil(hend))
                     & (xs[None, :] >= jnp.floor(wstart))
                     & (xs[None, :] < jnp.ceil(wend)))
                cnt = jnp.maximum(jnp.sum(m), 1.0)
                grp = img[(ph * out_w + pw) * out_c:(ph * out_w + pw + 1)
                          * out_c]
                out = out.at[:, ph, pw].set(
                    jnp.sum(jnp.where(m[None], grp, 0.0), axis=(1, 2))
                    / cnt)
        return out

    return jax.vmap(per_roi)(jnp.arange(R))


def deformable_psroi_pooling(x, rois, trans, boxes_num=None, no_trans=False,
                             spatial_scale=1.0, output_channels=None,
                             group_size=1, pooled_height=7, pooled_width=7,
                             part_size=None, sample_per_part=4,
                             trans_std=0.1, name=None):
    """reference: operators/deformable_psroi_pooling_op.cc — PS RoI pooling
    whose bins are shifted by learned normalized offsets (trans)."""
    xt = _wrap(x)
    rt = _wrap(rois)
    R = int(rt.shape[0])
    C = int(xt.shape[1])
    oc = output_channels or C // (pooled_height * pooled_width)
    if boxes_num is None:
        rid = jnp.zeros((R,), jnp.int32)
    else:
        num = _wrap(boxes_num)
        rid = jnp.asarray(np.repeat(np.arange(num.shape[0]),
                                    np.asarray(num.numpy())))
    if no_trans or trans is None:
        return _psroi_pool(xt, rt, Tensor(rid), oc, pooled_height,
                           pooled_width, float(spatial_scale))
    return _deform_psroi(xt, rt, _wrap(trans), Tensor(rid), oc,
                         pooled_height, pooled_width, float(spatial_scale),
                         float(trans_std))


def random_crop(x, shape, seed=None, name=None):
    """reference: operators/random_crop_op.cc — crop the trailing dims to
    `shape` at a random offset."""
    from ..core import random as _random
    xt = _wrap(x)
    key = _random.next_key()
    nd = len(shape)
    lead = xt.shape[:xt._value.ndim - nd]
    maxs = [int(xt.shape[xt._value.ndim - nd + i]) - int(shape[i])
            for i in range(nd)]
    keys = jax.random.split(key, nd)
    starts = [jax.random.randint(keys[i], (), 0, m + 1) for i, m in
              enumerate(maxs)]
    out = jax.lax.dynamic_slice(
        xt._value,
        [0] * len(lead) + [s for s in starts],
        list(lead) + [int(s) for s in shape])
    return Tensor(out)


def spp(x, pyramid_height=3, pool_type="max", name=None):
    """reference: operators/spp_op.cc — spatial pyramid pooling: levels
    0..h-1 pool to (2^l x 2^l) bins, flattened and concatenated."""
    from ..nn.functional.pooling import adaptive_avg_pool2d, \
        adaptive_max_pool2d
    xt = _wrap(x)
    N, C = int(xt.shape[0]), int(xt.shape[1])
    outs = []
    for level in range(pyramid_height):
        bins = 2 ** level
        pooled = (adaptive_max_pool2d(xt, bins) if pool_type == "max"
                  else adaptive_avg_pool2d(xt, bins))
        outs.append(_wrap(pooled)._value.reshape(N, C * bins * bins))
    return Tensor(jnp.concatenate(outs, axis=1))
