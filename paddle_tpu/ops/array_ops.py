"""Array/meta ops: shape/size/rank, unique family, meshgrid, unbind,
TensorArray (LoDTensorArray analogue), assign_value, crop, pad variants.

Reference: operators/shape_op.cc, size_op.cc, unique_op.cc (+
unique_consecutive_op.cc, unique_with_counts_op.cc), meshgrid_op.cc,
unbind_op.cc, assign_value_op.cc, crop_tensor_op.cc, lod_array_length_op.cc
/ array_read/array_write (controlflow/tensor_array_read_write_op.cc).

Note on unique: XLA needs static shapes, so the compiled path cannot return
a data-dependent-length tensor. Eagerly (tape mode) we return the exact
result like the reference; under jit tracing `unique` raises with guidance
to use masks — the honest TPU contract.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import op
from ..core.tensor import Tensor, to_tensor

__all__ = ["shape", "size", "rank", "unique", "unique_consecutive",
           "meshgrid", "unbind", "assign_value", "crop",
           "create_array", "array_write", "array_read", "array_length",
           "TensorArray", "broadcast_tensors", "numel"]


def _wrap(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


@op("shape", differentiable=False)
def _shape(x):
    return jnp.asarray(x.shape, jnp.int64)


def shape(input, name=None):
    """paddle.shape → int64 1-D tensor (reference: shape_op.cc)."""
    return _shape(_wrap(input))


@op("size", differentiable=False)
def _size(x):
    return jnp.asarray(np.prod(x.shape, dtype=np.int64))


def size(x, name=None):
    return _size(_wrap(x))


numel = size


def rank(input, name=None):
    return Tensor(jnp.asarray(_wrap(input)._value.ndim, jnp.int32))


# ---------------------------------------------------------------- unique
@op("unique", differentiable=False)
def _unique_sorted(x, axis):
    # static-shape-safe pieces only (sorted unique with padding is possible,
    # but the public API contract below keeps exact semantics eagerly)
    return jnp.unique(x, axis=axis)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    """reference: unique_op.cc. Exact (data-dependent shape) — eager only;
    inside jit use sort+mask patterns instead."""
    t = _wrap(x)
    if isinstance(t._value, jax.core.Tracer):
        raise RuntimeError(
            "paddle.unique produces a data-dependent shape and cannot run "
            "inside jit/to_static on TPU; compute it eagerly or use "
            "sort/searchsorted + mask with a static bound.")
    arr = np.asarray(t._value)
    out = np.unique(arr, return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(out, tuple):
        return Tensor(jnp.asarray(out))
    outs = [Tensor(jnp.asarray(o if i == 0 else o.astype(dtype)))
            for i, o in enumerate(out)]
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, dtype="int64", name=None):
    """reference: unique_consecutive_op.cc — dedup only adjacent repeats."""
    t = _wrap(x)
    if isinstance(t._value, jax.core.Tracer):
        raise RuntimeError(
            "paddle.unique_consecutive has a data-dependent output shape; "
            "run it eagerly (outside jit).")
    arr = np.asarray(t._value)
    if axis is None:
        flat = arr.reshape(-1)
        keep = np.empty(flat.shape, bool)
        keep[:1] = True
        keep[1:] = flat[1:] != flat[:-1]
        vals = flat[keep]
        inverse = np.cumsum(keep) - 1
        counts = np.diff(np.append(np.nonzero(keep)[0], flat.size))
    else:
        sl = [slice(None)] * arr.ndim
        sl[axis] = slice(1, None)
        sl2 = [slice(None)] * arr.ndim
        sl2[axis] = slice(None, -1)
        diff = (arr[tuple(sl)] != arr[tuple(sl2)])
        red = tuple(i for i in range(arr.ndim) if i != axis)
        keep = np.concatenate([[True], diff.any(axis=red)])
        vals = np.compress(keep, arr, axis=axis)
        inverse = np.cumsum(keep) - 1
        counts = np.diff(np.append(np.nonzero(keep)[0], arr.shape[axis]))
    outs = [Tensor(jnp.asarray(vals))]
    if return_inverse:
        outs.append(Tensor(jnp.asarray(inverse.astype(dtype))))
    if return_counts:
        outs.append(Tensor(jnp.asarray(counts.astype(dtype))))
    return outs[0] if len(outs) == 1 else tuple(outs)


# ------------------------------------------------------------- meshgrid etc
@op("meshgrid")
def _meshgrid(xs):
    return tuple(jnp.meshgrid(*xs, indexing="ij"))


def meshgrid(*args, **kwargs):
    """reference: meshgrid_op.cc ('ij' indexing, paddle semantics)."""
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    return list(_meshgrid([_wrap(a) for a in args]))


@op("unbind")
def _unbind(x, axis):
    n = x.shape[axis]
    parts = jnp.split(x, n, axis=axis)
    return tuple(jnp.squeeze(p, axis=axis) for p in parts)


def unbind(input, axis=0, name=None):
    """reference: unbind_op.cc."""
    return list(_unbind(_wrap(input), axis))


@op("assign_value")
def _assign_value(values, dtype):
    return jnp.asarray(values, dtype=dtype)


def assign_value(shape, dtype, values, name=None):
    """reference: assign_value_op.cc."""
    out = _assign_value(np.asarray(values), dtype)
    return out.reshape(shape) if shape else out


@op("crop_tensor")
def _crop(x, offsets, crop_shape):
    sl = tuple(slice(o, o + s) for o, s in zip(offsets, crop_shape))
    return x[sl]


def crop(x, shape=None, offsets=None, name=None):
    """reference: crop_tensor_op.cc."""
    t = _wrap(x)
    if offsets is None:
        offsets = [0] * t._value.ndim
    offsets = [int(o) for o in (offsets.tolist()
                                if isinstance(offsets, Tensor) else offsets)]
    shp = [int(s) for s in (shape.tolist()
                            if isinstance(shape, Tensor) else shape)]
    shp = [t._value.shape[i] - offsets[i] if s == -1 else s
           for i, s in enumerate(shp)]
    return _crop(t, tuple(offsets), tuple(shp))


@op("broadcast_tensors")
def _broadcast_tensors(xs):
    shape = jnp.broadcast_shapes(*[x.shape for x in xs])
    return tuple(jnp.broadcast_to(x, shape) for x in xs)


def broadcast_tensors(input, name=None):
    return list(_broadcast_tensors([_wrap(x) for x in input]))


# ----------------------------------------------------------- TensorArray
class TensorArray(list):
    """LoDTensorArray analogue (reference: pybind LoDTensorArray +
    controlflow/tensor_array_read_write_op.cc). A Python list of Tensors —
    under jit, prefer lax.scan; this exists for API/eager parity."""

    def append(self, t):
        super().append(_wrap(t))
        return self


def create_array(dtype="float32", initialized_list=None):
    """reference: fluid/layers/control_flow.py create_array."""
    arr = TensorArray()
    for t in (initialized_list or []):
        arr.append(t)
    return arr


def array_write(x, i, array=None):
    """reference: array_write op — write x at index i (extends like the
    reference when i == len)."""
    if array is None:
        array = TensorArray()
    idx = int(i.numpy()) if isinstance(i, Tensor) else int(i)
    if idx < len(array):
        array[idx] = _wrap(x)
    else:
        while len(array) < idx:
            array.append(Tensor(jnp.zeros_like(_wrap(x)._value)))
        array.append(x)
    return array


def array_read(array, i):
    idx = int(i.numpy()) if isinstance(i, Tensor) else int(i)
    return array[idx]


def array_length(array):
    return Tensor(jnp.asarray(len(array), jnp.int64))


@op("tensor_array_to_tensor")
def _taro(xs, axis, use_stack):
    return (jnp.stack(xs, axis) if use_stack else
            jnp.concatenate(xs, axis))


def tensor_array_to_tensor(input, axis=0, use_stack=False, name=None):
    """reference: operators/tensor_array_to_tensor_op.cc — concat (or
    stack) a TensorArray into one tensor; also returns the per-item sizes
    along axis (the OutIndex output)."""
    xs = [_wrap(x) for x in input]
    sizes = Tensor(jnp.asarray(
        [1 if use_stack else x.shape[axis] for x in xs], jnp.int64))
    return _taro(xs, int(axis), bool(use_stack)), sizes


def array_to_lod_tensor(x, table):
    """reference: operators/array_to_lod_tensor_op.cc — concatenate a
    TensorArray of per-sequence rows back into a LoDTensor whose level-0
    lengths come from `table` (the rank-table lengths)."""
    from ..core.lod import LoDTensor
    lens = [int(v) for v in np.asarray(_wrap(table).numpy()).reshape(-1)]
    flat = jnp.concatenate([_wrap(t)._value for t in x], axis=0)
    off = [0]
    for n in lens:
        off.append(off[-1] + n)
    return LoDTensor(Tensor(flat), [off])


def lod_tensor_to_array(x, table=None):
    """reference: operators/lod_tensor_to_array_op.cc — split a LoDTensor
    into a TensorArray of per-sequence row blocks (level-0)."""
    from ..core.lod import LoDTensor
    if isinstance(x, LoDTensor):
        offsets = x.lod()[-1]
        data = x.data
    else:
        lens = [int(v) for v in np.asarray(_wrap(table).numpy()).reshape(-1)]
        offsets = [0]
        for n in lens:
            offsets.append(offsets[-1] + n)
        data = _wrap(x)
    arr = TensorArray()
    for a, b in zip(offsets[:-1], offsets[1:]):
        arr.append(Tensor(data._value[a:b]))
    return arr
