"""AMP ops: check_finite_and_unscale, update_loss_scaling.

Reference: operators/amp/check_finite_and_unscale_op.cc (scan grads for
NaN/Inf, unscale by 1/loss_scaling, set found_inf flag) and
update_loss_scaling_op.cc (the dynamic loss-scale state machine:
good_steps/incr_every_n/decr_every_n). The GradScaler class
(paddle_tpu.amp) drives these; the op forms compile into jitted steps.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import op
from ..core.tensor import Tensor, to_tensor

__all__ = ["check_finite_and_unscale", "update_loss_scaling"]


def _wrap(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


@op("check_finite_and_unscale", differentiable=False)
def _check_finite_and_unscale(xs, scale):
    inv = 1.0 / scale
    found = jnp.asarray(False)
    outs = []
    for x in xs:
        found = jnp.logical_or(found, ~jnp.isfinite(x).all())
        outs.append(x * inv.astype(x.dtype))
    return tuple(outs), found


def check_finite_and_unscale(x, scale, name=None):
    """reference: check_finite_and_unscale_op.cc. x: list of grads.
    Returns (unscaled_grads, found_inf)."""
    outs, found = _check_finite_and_unscale([_wrap(t) for t in x],
                                            _wrap(scale))
    return list(outs), found


@op("update_loss_scaling", differentiable=False)
def _update_loss_scaling(scale, good_steps, bad_steps, found_inf,
                         incr_every_n, decr_every_n, incr_ratio, decr_ratio):
    def on_inf(_):
        new_bad = bad_steps + 1

        def decay(_):
            # reference clamps the decayed scale to 1 so a run of bad
            # steps can't drive it to 0 (whose 1/scale unscale is inf)
            return (jnp.maximum(scale * decr_ratio, 1.0),
                    jnp.zeros_like(good_steps), jnp.zeros_like(bad_steps))

        def hold(_):
            return scale, jnp.zeros_like(good_steps), new_bad
        return jax.lax.cond(new_bad >= decr_every_n, decay, hold, None)

    def on_ok(_):
        new_good = good_steps + 1

        def bump(_):
            # reference keeps the previous scale if the bump overflows
            grown = scale * incr_ratio
            return (jnp.where(jnp.isfinite(grown), grown, scale),
                    jnp.zeros_like(good_steps), jnp.zeros_like(bad_steps))

        def keep(_):
            return scale, new_good, jnp.zeros_like(bad_steps)
        return jax.lax.cond(new_good >= incr_every_n, bump, keep, None)

    return jax.lax.cond(found_inf, on_inf, on_ok, None)


def update_loss_scaling(x, found_inf, prev_loss_scaling, num_good_steps,
                        num_bad_steps=None, incr_every_n_steps=2000,
                        decr_every_n_nan_or_inf=1, incr_ratio=2.0,
                        decr_ratio=0.5, stop_update=False, name=None):
    """reference: update_loss_scaling_op.cc — the full state machine:
    decay only after `decr_every_n_nan_or_inf` consecutive bad steps (the
    bad count is reset by any good step), bump after `incr_every_n_steps`
    consecutive good ones; the decayed scale is floored at 1 and an
    overflowing bump holds the previous scale, both per the reference
    kernel (update_loss_scaling_op.h). Returns (new_scale, new_good_steps)
    when
    num_bad_steps is None, else (new_scale, new_good_steps, new_bad_steps).
    `x` (grads) kept in the signature for parity; the reference zeroes
    them on overflow, which the scaler does by skipping the step."""
    bad = _wrap(0 if num_bad_steps is None else num_bad_steps)
    scale, good, bad = _update_loss_scaling(
        _wrap(prev_loss_scaling), _wrap(num_good_steps), bad,
        _wrap(found_inf), int(incr_every_n_steps),
        int(decr_every_n_nan_or_inf), float(incr_ratio), float(decr_ratio))
    if num_bad_steps is None:
        return scale, good
    return scale, good, bad
