"""paddle.fft — spectral ops.

Reference: python/paddle/fft.py + operators/spectral_op.cc (cuFFT/MKL
backed). Here each transform is one jnp.fft call — XLA lowers to its own
FFT HLO, which the TPU backend executes natively.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import op
from ..core.tensor import Tensor, to_tensor

__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
           "fft2", "ifft2", "rfft2", "irfft2",
           "fftn", "ifftn", "rfftn", "irfftn",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def _wrap(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _norm(norm):
    # paddle uses 'backward'/'ortho'/'forward' like numpy
    return norm if norm in ("backward", "ortho", "forward") else "backward"


def _make1d(name, fn):
    wrapped = op(name)(
        lambda x, n, axis, norm: fn(x, n=n, axis=axis, norm=norm))

    def api(x, n=None, axis=-1, norm="backward", name=None):
        return wrapped(_wrap(x), n, axis, _norm(norm))
    api.__name__ = name
    return api


fft = _make1d("fft_c2c", jnp.fft.fft)
ifft = _make1d("fft_c2c_inv", jnp.fft.ifft)
rfft = _make1d("fft_r2c", jnp.fft.rfft)
irfft = _make1d("fft_c2r", jnp.fft.irfft)
hfft = _make1d("fft_c2r_h", jnp.fft.hfft)
ihfft = _make1d("fft_r2c_ih", jnp.fft.ihfft)


def _make2d(name, fn):
    wrapped = op(name)(
        lambda x, s, axes, norm: fn(x, s=s, axes=axes, norm=norm))

    def api(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return wrapped(_wrap(x), s, tuple(axes), _norm(norm))
    api.__name__ = name
    return api


fft2 = _make2d("fft2_c2c", jnp.fft.fft2)
ifft2 = _make2d("fft2_c2c_inv", jnp.fft.ifft2)
rfft2 = _make2d("fft2_r2c", jnp.fft.rfft2)
irfft2 = _make2d("fft2_c2r", jnp.fft.irfft2)


def _maken(name, fn):
    wrapped = op(name)(
        lambda x, s, axes, norm: fn(x, s=s, axes=axes, norm=norm))

    def api(x, s=None, axes=None, norm="backward", name=None):
        return wrapped(_wrap(x), s, None if axes is None else tuple(axes),
                       _norm(norm))
    api.__name__ = name
    return api


fftn = _maken("fftn_c2c", jnp.fft.fftn)
ifftn = _maken("fftn_c2c_inv", jnp.fft.ifftn)
rfftn = _maken("fftn_r2c", jnp.fft.rfftn)
irfftn = _maken("fftn_c2r", jnp.fft.irfftn)


@op("fft_shift")
def _fftshift(x, axes):
    return jnp.fft.fftshift(x, axes=axes)


@op("fft_ishift")
def _ifftshift(x, axes):
    return jnp.fft.ifftshift(x, axes=axes)


def fftshift(x, axes=None, name=None):
    return _fftshift(_wrap(x), None if axes is None else tuple(axes))


def ifftshift(x, axes=None, name=None):
    return _ifftshift(_wrap(x), None if axes is None else tuple(axes))


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype or "float32"))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype or "float32"))
